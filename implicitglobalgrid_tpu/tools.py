"""Global-grid queries: sizes, coordinates, timing.

TPU-native re-design of the reference's `src/tools.jl`:

- ``nx_g/ny_g/nz_g`` — implicit global sizes, with per-array overloads for
  staggered fields (`tools.jl:24-59`).
- ``x_g/y_g/z_g`` — global coordinate of a local index, including the
  staggering offset and the periodic ghost-cell shift/wrap
  (`tools.jl:98-107`; the math is subtle and ported exactly).
- vectorized coordinate builders (``x_g_vec``/``coords_g``) — the TPU-native
  way to build initial conditions: instead of per-rank comprehensions
  (reference `examples/diffusion3D_multigpu_CuArrays_novis.jl:35-38`), build
  the full stacked coordinate array once and use jnp broadcasts.
- ``tic/toc`` — wall-clock with a device/process barrier (`tools.jl:230-236`).

Coordinate conventions: indices here are 0-based (Python); the reference is
1-based Julia. `x_g(ix, ...)` here takes a 0-based local index and returns the
same coordinate the reference returns for `ix+1`.
"""

from __future__ import annotations

import numpy as np

from .parallel.topology import (
    NDIMS, check_initialized, global_grid,
)
from .ops.fields import local_shape_of
from .utils.exceptions import InvalidArgumentError

__all__ = [
    "nx_g", "ny_g", "nz_g", "x_g", "y_g", "z_g",
    "x_g_vec", "y_g_vec", "z_g_vec", "coords_g",
]


def _shape_of(A):
    if A is None:
        return None
    if hasattr(A, "shape"):
        return tuple(int(s) for s in A.shape)
    raise InvalidArgumentError(f"Expected an array, got {type(A)}.")


def _n_g(dim: int, A=None, layout=None) -> int:
    """Global size along ``dim``; with an array, the array's own global size
    including staggering (reference `tools.jl:45-59`:
    ``nx_g(A) = nx_g() + (size(A,1) - nx)``)."""
    gg = global_grid()
    if A is None:
        return int(gg.nxyz_g[dim])
    shape = _shape_of(A)
    loc = local_shape_of(shape, layout)
    size_d = loc[dim] if dim < len(loc) else 1
    return int(gg.nxyz_g[dim]) + (size_d - int(gg.nxyz[dim]))


def nx_g(A=None, *, layout=None) -> int:
    """Size of the global grid in dimension x; ``nx_g(A)`` for array ``A``'s
    global size (staggered arrays differ; reference `tools.jl:24,45`).
    ``layout`` ("local"/"stacked") disambiguates small blocks."""
    return _n_g(0, A, layout)


def ny_g(A=None, *, layout=None) -> int:
    """Size of the global grid in dimension y (reference `tools.jl:31,52`)."""
    return _n_g(1, A, layout)


def nz_g(A=None, *, layout=None) -> int:
    """Size of the global grid in dimension z (reference `tools.jl:38,59`)."""
    return _n_g(2, A, layout)


def _coord_g(i0, dim: int, dcoord, size_d: int, coord):
    """Global coordinate math (reference `tools.jl:98-107`), for scalar or
    vector ``i0`` (0-based local index) and scalar or traced ``coord``.

    x0 shifts staggered arrays; the periodic branch shifts everything left by
    one cell (the first global cell is a ghost cell) and wraps into
    ``[0, nxyz_g*d)`` (reference `tools.jl:102-104`).
    """
    import jax.numpy as jnp

    gg = global_grid()
    n = int(gg.nxyz[dim])
    olp = int(gg.overlaps[dim])
    n_gl = int(gg.nxyz_g[dim])
    x0 = 0.5 * (n - size_d) * dcoord
    x = (coord * (n - olp) + i0) * dcoord + x0
    if bool(gg.periods[dim]):
        x = x - dcoord
        if np.isscalar(x) or isinstance(x, (int, float, np.generic)):
            if x > (n_gl - 1) * dcoord:
                x = x - n_gl * dcoord
            if x < 0:
                x = x + n_gl * dcoord
        else:
            x = jnp.where(x > (n_gl - 1) * dcoord, x - n_gl * dcoord, x)
            x = jnp.where(x < 0, x + n_gl * dcoord, x)
    return x


def _x_g(ix, dcoord, A, dim: int, coords=None, layout=None):
    """Scalar/per-index global coordinate for local index ``ix`` (0-based) of
    array ``A`` along ``dim``.

    - For a stacked/global array, ``ix`` is the stacked index: the shard
      coordinate and local index are derived statically.
    - For a local block: pass ``coords`` (shard coordinate, scalar or the
      full 3-tuple) explicitly, or call inside `shard_map` where the mesh
      coordinate is taken from `lax.axis_index` (the analog of the reference
      reading the rank's `coords`, `tools.jl:100`).
    - ``layout`` ("local"/"stacked") overrides the stacked-vs-local shape
      inference for ambiguous block sizes (see `local_shape_of`).
    """
    check_initialized()
    gg = global_grid()
    shape = _shape_of(A)
    loc = local_shape_of(shape, layout)
    size_d = loc[dim] if dim < len(loc) else 1
    shape_d = shape[dim] if dim < len(shape) else 1
    if layout is None:
        stacked = shape_d != size_d or int(gg.dims[dim]) == 1
    else:
        stacked = layout == "stacked" or int(gg.dims[dim]) == 1

    if stacked and coords is None:
        coord, i_local = divmod(int(ix), size_d)
        return _coord_g(i_local, dim, dcoord, size_d, coord)

    if coords is not None:
        coord = coords[dim] if np.iterable(coords) else coords
        return _coord_g(ix, dim, dcoord, size_d, int(coord))

    # Local block, no explicit coords: use the traced mesh coordinate.
    from jax import lax
    from .parallel.topology import AXIS_NAMES

    try:
        coord = lax.axis_index(AXIS_NAMES[dim])
    except NameError as e:
        raise InvalidArgumentError(
            "x_g/y_g/z_g on a local block outside shard_map requires the shard "
            "coordinate: pass coords=<mesh coordinate(s)>."
        ) from e
    return _coord_g(ix, dim, dcoord, size_d, coord)


def x_g(ix, dx, A, coords=None, *, layout=None):
    """Global x-coordinate of 0-based local index ``ix`` in array ``A``
    (reference `tools.jl:98-107`).

    Examples (run as doctests, like the reference's doctested API docs,
    `tools.jl:67-96`):

    >>> import implicitglobalgrid_tpu as igg
    >>> _ = igg.init_global_grid(4, 4, 4, dimx=2, dimy=1, dimz=1,
    ...                          quiet=True)
    >>> igg.nx_g()          # 2*(4-2) + 2: the implicit-global-size formula
    6
    >>> A = igg.zeros_g()   # stacked global array: shape (8, 4, 4)
    >>> float(igg.x_g(0, 0.5, A))   # first cell of the left shard
    0.0
    >>> float(igg.x_g(4, 0.5, A))   # right shard overlaps by 2 cells
    1.0
    >>> igg.finalize_global_grid()
    """
    return _x_g(ix, dx, A, 0, coords, layout)


def y_g(iy, dy, A, coords=None, *, layout=None):
    """Global y-coordinate (reference `tools.jl:146-155`)."""
    return _x_g(iy, dy, A, 1, coords, layout)


def z_g(iz, dz, A, coords=None, *, layout=None):
    """Global z-coordinate (reference `tools.jl:194-203`)."""
    return _x_g(iz, dz, A, 2, coords, layout)


def _x_g_vec(dcoord, A, dim: int, layout=None):
    """Stacked 1-D coordinate vector along ``dim`` for array/shape ``A``:
    entry ``i`` is the global coordinate of stacked index ``i``. Host-computed
    numpy (init-time only)."""
    check_initialized()
    shape = _shape_of(A) if hasattr(A, "shape") else tuple(A)
    loc = local_shape_of(shape, layout)
    gg = global_grid()
    size_d = loc[dim] if dim < len(loc) else 1
    n_stack = int(gg.dims[dim]) * size_d if dim < NDIMS else size_d
    idx = np.arange(n_stack)
    coord, i_local = idx // size_d, idx % size_d
    return _coord_g(i_local.astype(np.float64), dim, dcoord, size_d, coord.astype(np.float64))


def x_g_vec(dx, A, *, layout=None):
    """Vector of global x-coordinates for every stacked index of ``A``."""
    return _x_g_vec(dx, A, 0, layout)


def y_g_vec(dy, A, *, layout=None):
    return _x_g_vec(dy, A, 1, layout)


def z_g_vec(dz, A, *, layout=None):
    return _x_g_vec(dz, A, 2, layout)


def _cli(argv=None) -> int:
    """``python -m implicitglobalgrid_tpu.tools`` — operator CLI.

    Subcommands:

    - ``report <run.jsonl> [--trace DIR] [--run-id ID] [--indent N]
      [--no-metrics]`` — print the unified `telemetry.run_report` for a
      flight-recorder stream (post-hoc: works on a file from a run that
      died hours ago; ``--trace`` merges a profiler capture's
      overlap/op-breakdown numbers).
    - ``prom`` — print the current process's Prometheus metrics snapshot
      (mostly useful under ``python -i`` / notebook sessions; scrapers of
      a LIVE run export `prometheus_snapshot()` themselves).
    - ``snapshots <root>`` — list the COMMITTED snapshots under a
      `SnapshotWriter` root: step, path, fields, implicit-global shapes,
      on-disk bytes. Host-only (numpy meta reads, no grid, no
      accelerator).
    - ``probe <root|snapshot> <field> i [j [k]]`` — read one
      implicit-global cell from every snapshot under a root (a point
      time-series: ``step value`` lines) or from a single snapshot
      directory; O(one shard block) per snapshot via
      `io.Snapshot.read_point`, never the global array.
    - ``aggregate <dir|files...>`` — merge per-process flight streams
      (`telemetry.aggregate_flight`): prints the alignment summary
      (processes, clock offsets, per-process event/chunk counts);
      ``--out merged.jsonl`` additionally writes the merged, clock-
      corrected event sequence as one JSONL.
    - ``trace <dir|files...> [-o trace.json]`` — export the merged
      stream as Chrome/Perfetto trace-event JSON
      (`telemetry.export_chrome_trace`); open at
      https://ui.perfetto.dev.
    - ``stragglers <dir|files...>`` — the cross-process straggler &
      imbalance report (`telemetry.straggler_report`): per-chunk
      barrier-arrival spreads, slowest-process attribution, persistent-
      straggler flags, wait/compute imbalance.
    - ``watch <flight_dir>`` — the LIVE terminal dashboard
      (`telemetry.LiveAggregate`, docs/observability.md "Live plane"):
      tails the directory's flight streams incrementally and redraws a
      per-job table (state, step, warm p50/p90 step time, robust z,
      deadline slack, guard trips, snapshot queue) plus active alerts
      every ``--interval`` seconds; ``--once`` polls and prints a single
      frame (scripts/tests), ``--json`` emits the raw snapshot instead.
    - ``alerts <flight_dir>`` — list the alert transitions journaled in
      a flight directory (rule, severity, state, job, when) with their
      ack state; ``--ack RULE[:JOB]`` acknowledges an alert in the
      side file ``alerts_ack.json`` (journals are append-only and
      seq-validated — acks never touch them).
    - ``perfdb add <bench.json> --db HISTORY.jsonl`` — append a bench
      run (BENCH_ALL.json shape) to the perf-history database;
      ``perfdb check <bench.json> --db HISTORY.jsonl`` gates it against
      the trailing window (`telemetry.perfdb_check`) and EXITS 1 on a
      regression — the CI hook that makes the bench trajectory gate
      itself.
    - ``calibrate [--out profile.json] [--cpu]`` — measure this machine's
      profile (`telemetry.calibrate_machine`: achieved memory bandwidth,
      FLOP rate, per-mesh-axis link bandwidth/latency) on a
      self-initialized grid and print/persist the JSON the cost model
      (`telemetry.predict_step`) consumes.
    - ``tune <model> [--profile profile.json] [--out tuned.json]
      [--cpu] [--nx N] [--no-measure]`` — the closed-loop auto-tuner
      (`telemetry.tune_config`): search `predict_step` over per-axis
      ``comm_every`` x per-axis ``wire_dtype`` x coalesce x overlap x
      ensemble E, validate the top candidates with short measured
      calibration runs, print (and persist) the winning `TunedConfig`
      JSON — the file ``jobs submit`` applies per job via the ``tuned``
      run knob. ``tune show <tuned.json>`` inspects a persisted config
      host-only.
    - ``audit [model ...] [--hlo FILE] [--json]`` — static analysis of
      compiled programs (`analysis.audit_model` / `audit_program`):
      compile each model's step on a self-initialized grid (``--cpu`` for
      the 8-device virtual mesh), check it against its plan-derived
      collective contract + the implicit-grid lints, and cross-check the
      perf oracle's collective pricing; or parse a captured HLO/StableHLO
      dump host-only (``--hlo``, optionally against a ``--contract``
      JSON). EXITS 1 when any error-severity finding survives — the CI
      hook that makes the wire contract gate itself.
    - ``jobs submit|list|status|cancel|drain|resize`` — the multi-run
      scheduler's operator surface (`service.MeshScheduler`,
      docs/service.md): ``submit QUEUE.json`` runs a JSON-described job
      queue through one persistent-mesh scheduler (exit 1 unless every
      job finishes), ``list``/``status`` inspect a service flight
      directory post-hoc from its journal, ``cancel``/``drain`` file
      control requests a LIVE scheduler consumes at its next
      chunk-granular slice boundary, and ``resize DIR NAME 1,2,2``
      files an elastic-resize request: the scheduler re-blocks the
      job's state onto the new dims at its next slice boundary
      (HBM-to-HBM when possible, checkpoint-elastic fallback) and
      journals ``job_resized``.
    - ``reshard plan|run`` — the on-device elastic resharding subsystem
      (`implicitglobalgrid_tpu.reshard`, docs/resilience.md): ``plan``
      prints the (src_dims -> dst_dims) transfer plan host-only
      (scheduled ppermute rounds, byte accounting, the
      `predict_reshard` static price); ``run`` executes the collective
      re-block on a self-initialized grid, audits the compiled program
      against its plan-derived contract, verifies the moved state
      bit-identical to the host oracle, and EXITS 1 on a contract
      violation or mismatch — the CI hook for the reshard wire
      contract.
    """
    import argparse
    import json
    import os
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m implicitglobalgrid_tpu.tools",
        description="implicitglobalgrid_tpu operator tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    jp = sub.add_parser(
        "jobs", help="multi-run scheduler: submit a job queue, inspect "
                     "or control a service flight directory")
    jobs_sub = jp.add_subparsers(dest="jobs_cmd", required=True)
    js = jobs_sub.add_parser(
        "submit", help="run a JSON-described job queue through one "
                       "MeshScheduler (exit 1 unless every job finishes)")
    js.add_argument("spec", help="queue JSON: {policy?, jobs: [{name, "
                                 "model, nt, grid?, dtype?, priority?, "
                                 "deadline_s?, run?}]}")
    js.add_argument("--flight-dir", default=None,
                    help="journal + per-job flight JSONLs land here "
                         "(enables list/status/report afterwards)")
    js.add_argument("--policy", default=None,
                    help="override the spec's policy (fifo | round_robin "
                         "| fair)")
    js.add_argument("--metrics-port", type=int, default=None,
                    help="serve the scheduler-owned /metrics + /healthz "
                         "for the duration (0 = ephemeral)")
    js.add_argument("--cpu", action="store_true",
                    help="run on the 8-device virtual CPU mesh (the "
                         "bench scripts' convention)")
    js.add_argument("--json", action="store_true")
    jl = jobs_sub.add_parser(
        "list", help="jobs of a service flight directory (post-hoc, "
                     "from the journal alone)")
    jl.add_argument("flight_dir")
    jl.add_argument("--json", action="store_true")
    jst = jobs_sub.add_parser(
        "status", help="one job's record (exit 3 when unknown)")
    jst.add_argument("flight_dir")
    jst.add_argument("name")
    jst.add_argument("--indent", type=int, default=2)
    jc = jobs_sub.add_parser(
        "cancel", help="file a cancel request a LIVE scheduler consumes "
                       "at its next slice boundary (exit 3 unknown job, "
                       "4 already finished)")
    jc.add_argument("flight_dir")
    jc.add_argument("name")
    jd = jobs_sub.add_parser(
        "drain", help="file a drain request: cancel queued jobs, finish "
                      "running ones")
    jd.add_argument("flight_dir")
    jrs = jobs_sub.add_parser(
        "resize", help="file an elastic-resize request a LIVE scheduler "
                       "applies at the job's next slice boundary "
                       "(HBM-to-HBM re-block, checkpoint-elastic "
                       "fallback; exit 3 unknown job, 4 already "
                       "finished)")
    jrs.add_argument("flight_dir")
    jrs.add_argument("name")
    jrs.add_argument("dims", help="new decomposition, e.g. 1,2,2")
    jrs.add_argument("--via", default="auto",
                     choices=["auto", "device", "checkpoint"],
                     help="force the on-device or checkpoint path "
                          "(default: device with fallback)")
    rp = sub.add_parser("report", help="unified run report from a "
                                       "flight-recorder JSONL stream")
    rp.add_argument("jsonl", help="flight-recorder .jsonl file")
    rp.add_argument("--trace", default=None,
                    help="profiler capture dir to merge "
                         "(overlap_stats/op_breakdown)")
    rp.add_argument("--run-id", default=None,
                    help="run id when the file holds several runs "
                         "(default: the last run)")
    rp.add_argument("--indent", type=int, default=2)
    rp.add_argument("--no-metrics", action="store_true",
                    help="omit the (empty, post-hoc) registry snapshot")
    sub.add_parser("prom", help="Prometheus text-format metrics snapshot")
    sp = sub.add_parser("snapshots",
                        help="list committed snapshots under a root")
    sp.add_argument("root", help="SnapshotWriter root directory")
    sp.add_argument("--json", action="store_true",
                    help="one JSON object per snapshot instead of a table")
    pp = sub.add_parser(
        "probe", help="point time-series from snapshots (O(1 block) "
                      "reads, no grid, no gather)")
    pp.add_argument("path", help="snapshot root (time series over every "
                                 "snapshot) or a single snapshot dir")
    pp.add_argument("field", help="field name in the snapshots")
    pp.add_argument("index", nargs="+", type=int,
                    help="implicit-global cell index (one per dimension)")
    pp.add_argument("--json", action="store_true")
    agp = sub.add_parser(
        "aggregate", help="merge per-process flight streams into one "
                          "clock-aligned mesh-wide sequence")
    agp.add_argument("src", nargs="+",
                     help="directory of flight_p*.jsonl streams, or the "
                          "stream files themselves")
    agp.add_argument("--run-id", default=None)
    agp.add_argument("--out", default=None,
                     help="also write the merged event sequence as JSONL")
    agp.add_argument("--indent", type=int, default=2)
    tp = sub.add_parser(
        "trace", help="Chrome/Perfetto trace-event JSON from per-process "
                      "flight streams (open at ui.perfetto.dev)")
    tp.add_argument("src", nargs="+",
                    help="directory of flight_p*.jsonl streams, or the "
                         "stream files themselves")
    tp.add_argument("-o", "--out", default="trace.json")
    tp.add_argument("--run-id", default=None)
    tp.add_argument("--otlp", action="store_true",
                    help="emit OTLP/HTTP JSON ResourceSpans (the span-"
                         "tree view any OpenTelemetry collector ingests) "
                         "instead of Perfetto trace-event JSON")
    tp.add_argument("--trace-id", default=None,
                    help="filter to ONE distributed trace (32-hex id "
                         "from a traceparent) — the causal slice of a "
                         "single request")
    tp.add_argument("--job", default=None,
                    help="with --otlp: filter to one job's spans")
    stp = sub.add_parser(
        "stragglers", help="cross-process straggler & imbalance report")
    stp.add_argument("src", nargs="+",
                     help="directory of flight_p*.jsonl streams, or the "
                          "stream files themselves")
    stp.add_argument("--run-id", default=None)
    stp.add_argument("--window", type=int, default=8,
                     help="rolling window (chunks) for persistent-"
                          "straggler flags")
    stp.add_argument("--share", type=float, default=0.5,
                     help="slowest-share above which a window flags")
    stp.add_argument("--indent", type=int, default=2)
    wp = sub.add_parser(
        "watch", help="live terminal dashboard over a flight directory "
                      "(incremental tail, rolling derived signals, "
                      "active alerts)")
    wp.add_argument("flight_dir",
                    help="directory of per-run flight JSONLs (a live "
                         "run's flight dir or a scheduler's service dir)")
    wp.add_argument("--interval", type=float, default=2.0,
                    help="seconds between polls/redraws")
    wp.add_argument("--window", type=int, default=16,
                    help="rolling window (boundaries) for the derived "
                         "signals")
    wp.add_argument("--once", action="store_true",
                    help="poll once, print one frame, exit (no screen "
                         "clear — the scripting/test mode)")
    wp.add_argument("--json", action="store_true",
                    help="emit the raw snapshot JSON instead of the "
                         "table")
    al = sub.add_parser(
        "alerts", help="list journaled alert transitions of a flight "
                       "directory; acknowledge with --ack")
    al.add_argument("flight_dir")
    al.add_argument("--ack", default=None, metavar="RULE[:JOB]",
                    help="acknowledge an alert (recorded in the side "
                         "file alerts_ack.json, never in the journal)")
    al.add_argument("--json", action="store_true")
    fl = sub.add_parser(
        "flight", help="flight-directory hygiene (disk usage of the "
                       "recorder streams)")
    fl_sub = fl.add_subparsers(dest="flight_cmd", required=True)
    fdu = fl_sub.add_parser(
        "du", help="per-stream on-disk bytes of a flight directory, "
                   "largest first — recorder growth before it becomes "
                   "an incident (the igg_flight_file_bytes gauges are "
                   "the live twin)")
    fdu.add_argument("flight_dir")
    fdu.add_argument("--json", action="store_true")
    pdb = sub.add_parser(
        "perfdb", help="perf-history database: append bench runs, gate "
                       "regressions vs the trailing window")
    pdb_sub = pdb.add_subparsers(dest="perfdb_cmd", required=True)
    pda = pdb_sub.add_parser("add", help="append a bench run to the "
                                         "history JSONL")
    pda.add_argument("rows", help="bench rows JSON (BENCH_ALL.json shape)")
    pda.add_argument("--db", required=True, help="history JSONL path")
    pda.add_argument("--note", default=None,
                     help="free-form note stored in the record's meta")
    pdc = pdb_sub.add_parser(
        "check", help="gate a bench run against the trailing history "
                      "(exit 1 on regression)")
    pdc.add_argument("rows", help="bench rows JSON (BENCH_ALL.json shape)")
    pdc.add_argument("--db", required=True, help="history JSONL path")
    pdc.add_argument("--window", type=int, default=5,
                     help="trailing history records forming the baseline")
    pdc.add_argument("--threshold", type=float, default=0.30,
                     help="relative change in the worse direction that "
                          "fails a metric")
    pdc.add_argument("--min-history", type=int, default=2,
                     help="history points a metric needs before it gates")
    pdc.add_argument("--indent", type=int, default=2)
    tu = sub.add_parser(
        "tune", help="closed-loop auto-tuner: search the cost model over "
                     "comm_every/wire_dtype/wire_stage/coalesce/overlap/"
                     "ensemble, "
                     "validate with short measured runs, persist the "
                     "winning TunedConfig")
    tu.add_argument("model",
                    help="model family to tune (diffusion3d, acoustic3d, "
                         "stokes3d) — or 'show' to inspect a persisted "
                         "config")
    tu.add_argument("path", nargs="?", default=None,
                    help="with 'show': the tuned-config JSON to print")
    tu.add_argument("--profile", default=None,
                    help="calibrated MachineProfile JSON "
                         "(tools calibrate --out); default: grid-derived "
                         "spec coefficients. A profile path also sets "
                         "the default persist location (tuned_<model>."
                         "json next to it)")
    tu.add_argument("--out", default=None,
                    help="persist the winning TunedConfig JSON here")
    tu.add_argument("--nx", type=int, default=32,
                    help="base local block edge of the tuning grid")
    tu.add_argument("--cpu", action="store_true",
                    help="tune on the 8-device virtual CPU mesh (the "
                         "bench scripts' convention)")
    tu.add_argument("--no-measure", action="store_true",
                    help="model-only search (skip the measured "
                         "validation runs)")
    tu.add_argument("--top-k", type=int, default=2,
                    help="predicted candidates to validate with "
                         "measured runs")
    tu.add_argument("--comm-every-options", default=None,
                    help="comma-separated cadence candidates (e.g. "
                         "'1,2,z:2,z:4'); default: 1, 2, and each "
                         "exchanging axis's solo cadence")
    tu.add_argument("--wire-options", default=None,
                    help="comma-separated wire-policy candidates (e.g. "
                         "'off,z:int8,z:int8,x:f32' — entries with ':' "
                         "are kept whole per policy segment; use ';' to "
                         "separate multi-axis policies)")
    tu.add_argument("--wire-stage-options", default=None,
                    help="comma-separated topology-staged wire "
                         "candidates (e.g. 'off,z:staged'): 'off' is the "
                         "flat wire, 'z:staged' routes the z exchange "
                         "ICI-gather -> striped DCN -> ICI-scatter "
                         "(needs declared DCN granules — multi-slice or "
                         "IGG_TPU_DCN_GRANULES)")
    tu.add_argument("--ensemble-options", default=None,
                    help="comma-separated ensemble sizes to sweep "
                         "(e.g. '1,4,8'; 1 = solo)")
    tu.add_argument("--overlap", action="store_true",
                    help="include overlap=True candidates")
    tu.add_argument("--indent", type=int, default=2)
    cal = sub.add_parser(
        "calibrate", help="measure this machine's profile (membw, flops, "
                          "per-axis link bw/latency) for the cost model")
    cal.add_argument("--out", default=None,
                     help="also persist the profile JSON here")
    cal.add_argument("--nx", type=int, default=32,
                     help="local block edge of the calibration grid")
    cal.add_argument("--cpu", action="store_true",
                     help="profile the 8-device virtual CPU mesh (the "
                          "bench scripts' convention) instead of the "
                          "default backend — a single-device backend has "
                          "no inter-shard link, so axes come out empty")
    cal.add_argument("--ensemble", type=int, default=None,
                     help="calibrate the per-axis link fit in the "
                          "E-member ensemble payload regime (payload "
                          "sizes scale by E behind the same ppermute "
                          "pair; recorded in the profile meta)")
    cal.add_argument("--preset", default=None, choices=("hierarchical",),
                     help="skip measurement and emit a canned profile "
                          "instead: 'hierarchical' is the ICI+DCN "
                          "link-class preset (fast/low-latency x,y; "
                          "slow/high-latency DCN z) that makes "
                          "staged-vs-flat wire pricing and the bench "
                          "modeled rows meaningful on a CPU dev box "
                          "without a pod (host-only: no grid, no "
                          "accelerator)")
    cal.add_argument("--indent", type=int, default=2)
    rs = sub.add_parser(
        "reshard", help="on-device elastic resharding: print a transfer "
                        "plan host-only, or run + contract-audit + "
                        "verify the collective re-block (exit 1 on "
                        "violation)")
    rs_sub = rs.add_subparsers(dest="reshard_cmd", required=True)
    for prs, what in ((rs_sub.add_parser(
            "plan", help="derive and print the (src -> dst) transfer "
                         "plan + its static price (host-only: no grid, "
                         "no accelerator)"), "plan"),
            (rs_sub.add_parser(
                "run", help="execute the re-block on a self-initialized "
                            "grid, audit the compiled program against "
                            "the plan contract, verify vs the host "
                            "oracle (exit 1 on any error finding or "
                            "mismatch)"), "run")):
        prs.add_argument("--src-dims", required=True,
                         help="source decomposition, e.g. 2,2,1")
        prs.add_argument("--dst-dims", required=True,
                         help="destination decomposition, e.g. 1,2,2")
        prs.add_argument("--nx", type=int, default=8,
                         help="base local block edge on the source dims")
        prs.add_argument("--fields", type=int, default=2,
                         help="number of state fields (field 1 is "
                              "x-staggered, exercising a second "
                              "signature)")
        prs.add_argument("--dtype", default="float32")
        prs.add_argument("--ensemble", type=int, default=None,
                         help="lead every field with an E-member axis "
                              "(the batched-state pass-through)")
        prs.add_argument("--periods", default="0,0,0")
        prs.add_argument("--overlaps", default="2,2,2")
        prs.add_argument("--indent", type=int, default=2)
        prs.add_argument("--json", action="store_true")
        if what == "run":
            prs.add_argument("--cpu", action="store_true",
                             help="run on the 8-device virtual CPU mesh "
                                  "(the bench scripts' convention)")
        if what == "plan":
            prs.add_argument("--nt-remaining", type=int, default=None,
                             help="steps left in the job's horizon: "
                                  "amortize the priced transfer against "
                                  "them (needs --old-step-s and "
                                  "--new-step-s; prints the same "
                                  "break_even record the autoscaler and "
                                  "service_report carry)")
            prs.add_argument("--old-step-s", type=float, default=None,
                             help="per-step seconds on the SOURCE dims "
                                  "(e.g. predict_step or a measured "
                                  "baseline)")
            prs.add_argument("--new-step-s", type=float, default=None,
                             help="per-step seconds on the DESTINATION "
                                  "dims")
    asp = sub.add_parser(
        "autoscale", help="the closed-loop autoscaler's operator "
                          "surface: reconstruct WHY the mesh resized "
                          "itself from a scheduler journal alone")
    as_sub = asp.add_subparsers(dest="autoscale_cmd", required=True)
    ax = as_sub.add_parser(
        "explain", help="every journaled autoscale_decision: the policy "
                        "echo, verdict counts, rejection histogram, and "
                        "each filed move's actuation chain "
                        "(autoscale_decision -> control -> "
                        "resize_requested -> job_resized -> job_retuned) "
                        "with its full pricing breakdown")
    ax.add_argument("flight_dir",
                    help="MeshScheduler flight directory (or its "
                         "scheduler.jsonl)")
    ax.add_argument("--job", default=None,
                    help="only this job's decisions and moves")
    ax.add_argument("--indent", type=int, default=2)
    aud = sub.add_parser(
        "audit", help="static analysis of compiled programs: collective "
                      "contract + implicit-grid lints + perfmodel "
                      "cross-check (exit 1 on error findings)")
    aud.add_argument("models", nargs="*",
                     help="model step programs to compile and audit "
                          "(diffusion3d, diffusion2d, acoustic3d, "
                          "stokes3d); omit with --hlo")
    aud.add_argument("--hlo", default=None,
                     help="audit a captured HLO/StableHLO text dump "
                          "host-only instead of compiling a model")
    aud.add_argument("--contract", default=None,
                     help="CollectiveContract JSON to check --hlo against "
                          "(default: lints only)")
    aud.add_argument("--impl", default="xla",
                     help="model step implementation (default xla; "
                          "pallas/pallas_interpret audit the fused tier "
                          "under the SAME byte-exact contract + "
                          "crosscheck — both tiers ride the canonical "
                          "wire schema)")
    aud.add_argument("--wire-dtype", default=None,
                     help="reduced-precision wire format the exchange was "
                          "built with — float casts (bfloat16/float16), "
                          "quantized (int8/int4), or a per-axis policy "
                          "like z:int8,x:f32 (audits the narrowing "
                          "reached each axis's wire)")
    aud.add_argument("--wire-stage", default=None,
                     help="topology-staged wire policy the exchange was "
                          "built with (e.g. z:staged): the staged axis's "
                          "exchange is audited as ICI leader-gather -> "
                          "one striped DCN transfer per granule pair -> "
                          "ICI scatter, against the multi-stage contract "
                          "(per-stage permute counts, routes, and "
                          "payload bytes)")
    aud.add_argument("--lowered", action="store_true",
                     help="audit the pre-backend StableHLO instead of "
                          "backend-optimized HLO (where wire downcasts "
                          "stay visible on CPU)")
    aud.add_argument("--ensemble", type=int, default=None,
                     help="audit the E-member BATCHED chunk program: the "
                          "vmapped step must keep per-axis permute "
                          "counts identical to solo with byte-exact "
                          "E-scaled payloads (collective count flat in "
                          "E; XLA tier)")
    aud.add_argument("--comm-every", default=None,
                     help="audit the deep-halo SUPER-STEP at this "
                          "cadence (int or per-axis, e.g. z:2): the "
                          "compiled cycle's per-axis permute counts and "
                          "k-wide payload bytes must match the "
                          "super-cycle contract (the self-initialized "
                          "grid gets the cadence's halo geometry; XLA "
                          "tier)")
    aud.add_argument("--no-crosscheck", action="store_true",
                     help="skip the predict_step pricing cross-check")
    aud.add_argument("--json", action="store_true",
                     help="machine-readable report instead of the summary")
    aud.add_argument("--cpu", action="store_true",
                     help="audit on the 8-device virtual CPU mesh (the "
                          "bench scripts' convention)")
    aud.add_argument("--nx", type=int, default=16,
                     help="local block edge of the self-initialized grid")
    aud.add_argument("--indent", type=int, default=2)
    args = ap.parse_args(argv)

    if args.cmd == "audit":
        return _cli_audit(args)
    if args.cmd == "reshard":
        return _cli_reshard(args)
    if args.cmd == "autoscale":
        return _cli_autoscale(args)
    if args.cmd == "jobs":
        return _cli_jobs(args)
    if args.cmd == "tune":
        return _cli_tune(args)
    if args.cmd == "watch":
        return _cli_watch(args)
    if args.cmd == "alerts":
        return _cli_alerts(args)
    if args.cmd == "flight":
        return _cli_flight(args)

    from .telemetry import prometheus_snapshot, run_report

    if args.cmd == "perfdb":
        from .telemetry import perfdb_add, perfdb_check

        if args.perfdb_cmd == "add":
            meta = {"note": args.note} if args.note else None
            rec = perfdb_add(args.db, args.rows, meta=meta)
            print(json.dumps({"db": args.db, "ts": rec["ts"],
                              "metrics": len(rec["metrics"])}))
            return 0
        rep = perfdb_check(args.db, args.rows, window=args.window,
                           threshold=args.threshold,
                           min_history=args.min_history)
        print(json.dumps(rep, indent=args.indent, default=str))
        return 0 if rep["ok"] else 1
    if args.cmd == "calibrate":
        if args.preset is not None:
            # canned profile: host-only, nothing measured
            from .telemetry import (
                hierarchical_machine_profile, save_machine_profile,
            )

            profile = hierarchical_machine_profile()
            if args.out:
                save_machine_profile(profile, args.out)
            print(json.dumps(profile.to_json(), indent=args.indent))
            return 0
        if args.cpu:
            # must precede any jax device use (the bench scripts' idiom)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
            import jax

            jax.config.update("jax_platforms", "cpu")
        from .parallel.grid import finalize_global_grid, init_global_grid
        from .parallel.topology import grid_is_initialized
        from .telemetry import calibrate_machine

        owns_grid = not grid_is_initialized()
        if owns_grid:
            import jax

            from .parallel.topology import dims_create

            dims = [int(d) for d in dims_create(len(jax.devices()),
                                                (0, 0, 0))]
            init_global_grid(args.nx, args.nx, args.nx, dimx=dims[0],
                             dimy=dims[1], dimz=dims[2], periodx=1,
                             periody=1, periodz=1, quiet=True)
        try:
            profile = calibrate_machine(args.out, ensemble=args.ensemble)
        finally:
            if owns_grid:
                finalize_global_grid()
        print(json.dumps(profile.to_json(), indent=args.indent))
        return 0

    def _agg_source():
        return args.src[0] if len(args.src) == 1 else args.src

    if args.cmd == "aggregate":
        from .telemetry import aggregate_flight

        agg = aggregate_flight(_agg_source(), run_id=args.run_id)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                for e in agg["events"]:
                    f.write(json.dumps(e, default=str) + "\n")
        summary = {k: v for k, v in agg.items() if k != "events"}
        summary["events"] = len(agg["events"])
        if args.out:
            summary["out"] = args.out
        print(json.dumps(summary, indent=args.indent, default=str))
        return 0
    if args.cmd == "trace":
        from .service.report import is_service_dir
        from .telemetry import export_chrome_trace

        src = _agg_source()
        if args.otlp:
            from .telemetry import export_otlp

            print(export_otlp(src, args.out, trace_id=args.trace_id,
                              job=args.job))
            return 0
        if isinstance(src, str) and is_service_dir(src):
            if args.trace_id is not None:
                # one trace is one request's causal slice across the
                # journal and the job recorders — filter first, then
                # the single-run exporter applies (same-host monotonic
                # stamps; the OTLP export is the span-tree view)
                import glob as _glob

                from .telemetry.recorder import read_flight_events

                evs = []
                for p in sorted(_glob.glob(
                        os.path.join(src, "*.jsonl"))):
                    try:
                        evs.extend(read_flight_events(p, offset=0)[0])
                    except InvalidArgumentError:
                        continue
                print(export_chrome_trace(evs, args.out,
                                          trace_id=args.trace_id))
                return 0
            # a MeshScheduler flight dir: jobs are tenants, not mesh
            # processes — render one Perfetto track per job instead of
            # refusing the mixed run ids
            from .service import export_service_trace

            print(export_service_trace(src, args.out))
            return 0
        path = export_chrome_trace(src, args.out, run_id=args.run_id,
                                   trace_id=args.trace_id)
        print(path)
        return 0
    if args.cmd == "stragglers":
        from .telemetry import aggregate_flight, straggler_report

        agg = aggregate_flight(_agg_source(), run_id=args.run_id)
        rep = straggler_report(agg, window=args.window, share=args.share)
        print(json.dumps(rep, indent=args.indent, default=str))
        return 0

    if args.cmd == "prom":
        sys.stdout.write(prometheus_snapshot())
        return 0
    if args.cmd == "snapshots":
        from .io import list_snapshots, open_snapshot

        for step, path in list_snapshots(args.root):
            snap = open_snapshot(path)
            nbytes = sum(
                os.path.getsize(os.path.join(path, f))
                for f in os.listdir(path)
                if f.endswith(".npz"))
            rec = {"step": step, "path": path, "fields": snap.names,
                   "global_shapes": {n: list(snap.global_shape(n))
                                     for n in snap.names},
                   "bytes": nbytes}
            if args.json:
                print(json.dumps(rec))
            else:
                shapes = ", ".join(
                    f"{n}{tuple(snap.global_shape(n))}"
                    for n in snap.names)
                print(f"step {step:>10}  {nbytes:>12} B  {shapes}  {path}")
        return 0
    if args.cmd == "probe":
        from .io import list_snapshots, open_snapshot

        if os.path.exists(os.path.join(args.path, "meta.npz")):
            series = [(None, args.path)]
        else:
            series = list_snapshots(args.path)
        for _step, path in series:
            snap = open_snapshot(path)
            v = snap.read_point(args.field, args.index)
            step = snap.step if snap.step is not None else _step
            if args.json:
                print(json.dumps({"step": step, "field": args.field,
                                  "index": list(args.index),
                                  "value": float(v)}))
            else:
                print(f"{step} {float(v)!r}")
        return 0
    rep = run_report(args.jsonl, run_id=args.run_id, trace_dir=args.trace,
                     include_metrics=not args.no_metrics)
    print(json.dumps(rep, indent=args.indent, default=str))
    return 0


def _fmt_s(v, unit="s") -> str:
    if v is None:
        return "-"
    return f"{float(v):.3g}{unit}"


def _render_watch(snap: dict) -> str:
    """One dashboard frame from a `LiveAggregate.snapshot()`. Pure
    string-building (stdlib only) so tests can assert on a frame without
    a terminal."""
    lines = []
    q = snap.get("queue") or {}
    sched = snap.get("scheduler") or {}
    hdr = f"igg watch  cursor={snap.get('cursor')}"
    tail = snap.get("tail") or {}
    if tail.get("lag_s") is not None:
        # age of the newest merged event — a growing lag on a run that
        # should be stepping means the tail (or the run) stalled
        hdr += f"  lag={_fmt_s(tail['lag_s'])}"
    if sched:
        hdr += (f"  scheduler[slices={sched.get('slices')}"
                f" draining={sched.get('draining')}]")
    if q and "pending" in q:
        hdr += (f"  queue[pending={q.get('pending')}"
                f" oldest={_fmt_s(q.get('oldest_age_s'))}]")
    gaps = snap.get("gaps") or []
    if gaps:
        hdr += f"  gaps={len(gaps)}"
    lines.append(hdr)
    jobs = snap.get("jobs") or {}
    if jobs:
        lines.append(f"{'JOB':<16} {'STATE':<9} {'STEP':>11} "
                     f"{'P50':>8} {'P90':>8} {'Z':>6} {'SLACK':>8} "
                     f"{'TRIPS':>5} {'QD':>3} {'DROP':>4}")
        for name in sorted(jobs):
            j = jobs[name]
            nt = j.get("nt")
            step = f"{j.get('step', 0)}/{nt}" if nt else str(
                j.get("step", 0))
            z = j.get("z")
            lines.append(
                f"{name[:16]:<16} {str(j.get('state', '?'))[:9]:<9} "
                f"{step:>11} {_fmt_s(j.get('step_s_p50')):>8} "
                f"{_fmt_s(j.get('step_s_p90')):>8} "
                f"{('-' if z is None else f'{z:+.1f}'):>6} "
                f"{_fmt_s(j.get('deadline_slack_s')):>8} "
                f"{j.get('guard_trips', 0):>5} "
                f"{j.get('snapshot_queue_depth', 0) or 0:>3} "
                f"{j.get('snapshot_drops', 0):>4}")
    else:
        lines.append("(no jobs yet)")
    procs = snap.get("procs") or {}
    shares = {p: r.get("slowest_share") for p, r in procs.items()
              if r.get("slowest_share") is not None}
    if shares:
        lines.append("stragglers: " + "  ".join(
            f"p{p}={shares[p]:.0%}" for p in sorted(shares)))
    alerts = snap.get("alerts") or {}
    for a in alerts.get("active") or []:
        lines.append(
            f"ALERT {a.get('severity', '?').upper():<8} "
            f"{a.get('rule')}  job={a.get('job') or '-'}  "
            f"value={a.get('value')}")
    return "\n".join(lines) + "\n"


def _cli_watch(args) -> int:
    """The ``watch`` subcommand: a live terminal dashboard. Each tick
    polls the incremental tailer (byte offsets carry over — each redraw
    reads only what the run appended since the last one) and redraws."""
    import json
    import sys
    import time

    from .telemetry.live import LiveAggregate

    agg = LiveAggregate(args.flight_dir, window=args.window)
    try:
        while True:
            agg.poll()
            snap = agg.snapshot()
            if args.json:
                print(json.dumps(snap, default=str))
            else:
                frame = _render_watch(snap)
                if not args.once:
                    sys.stdout.write("\x1b[2J\x1b[H")
                sys.stdout.write(frame)
                sys.stdout.flush()
            if args.once:
                return 0
            time.sleep(max(0.05, args.interval))
    except KeyboardInterrupt:
        return 0


def _cli_alerts(args) -> int:
    """The ``alerts`` subcommand: list the alert transitions journaled
    in a flight directory's streams, folded to current per-(rule, job)
    state, with ack bookkeeping in the SIDE file ``alerts_ack.json`` —
    flight journals are append-only and seq-validated, so acks must
    never touch them."""
    import glob as _glob
    import json
    import os
    import time

    from .telemetry.recorder import read_flight_events
    from .utils.exceptions import InvalidArgumentError

    transitions = []
    for p in sorted(_glob.glob(os.path.join(args.flight_dir, "*.jsonl"))):
        try:
            evs, _off = read_flight_events(p, offset=0)
        except InvalidArgumentError:
            continue
        transitions.extend(e for e in evs if e.get("kind") == "alert")
    transitions.sort(key=lambda e: float(e.get("t", 0.0)))

    ack_path = os.path.join(args.flight_dir, "alerts_ack.json")
    acks = {}
    if os.path.exists(ack_path):
        with open(ack_path, encoding="utf-8") as f:
            acks = json.load(f)
    if args.ack:
        rule, _, job = args.ack.partition(":")
        key = f"{rule}|{job}"
        acks[key] = {"t": time.time()}
        tmp = ack_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(acks, f, indent=2)
        os.replace(tmp, ack_path)

    # fold to current state per (rule, job): the LAST transition wins
    current: dict = {}
    for e in transitions:
        current[(e.get("rule"), e.get("job") or "")] = e
    rows = []
    for (rule, job), e in sorted(current.items()):
        key = f"{rule}|{job}"
        rows.append({"rule": rule, "job": job or None,
                     "state": e.get("state"),
                     "severity": e.get("severity"),
                     "value": e.get("value"), "t": e.get("t"),
                     "acked": key in acks,
                     "transitions": sum(
                         1 for x in transitions
                         if x.get("rule") == rule
                         and (x.get("job") or "") == job)})
    if args.json:
        print(json.dumps({"alerts": rows,
                          "transitions": len(transitions)}, default=str))
        return 0
    if not rows:
        print("no alerts journaled")
        return 0
    print(f"{'RULE':<26} {'JOB':<12} {'STATE':<9} {'SEV':<9} "
          f"{'N':>3} {'ACK':<3}")
    for r in rows:
        print(f"{str(r['rule'])[:26]:<26} "
              f"{str(r['job'] or '-')[:12]:<12} "
              f"{str(r['state'])[:9]:<9} {str(r['severity'])[:9]:<9} "
              f"{r['transitions']:>3} {'yes' if r['acked'] else 'no':<3}")
    return 0


def _cli_flight(args) -> int:
    """The ``flight du`` subcommand: per-stream on-disk sizes of a
    flight directory, largest first — the CLI twin of the
    ``igg_flight_file_bytes`` gauges the live tail stamps, so recorder
    growth on a long-running service is one command away."""
    import glob as _glob
    import json
    import os

    rows = []
    total = 0
    for p in sorted(_glob.glob(os.path.join(args.flight_dir,
                                            "*.jsonl"))):
        try:
            n = os.path.getsize(p)
        except OSError:
            continue  # rotated/removed between glob and stat
        rows.append({"file": os.path.basename(p), "bytes": int(n)})
        total += int(n)
    rows.sort(key=lambda r: (-r["bytes"], r["file"]))
    if args.json:
        print(json.dumps({"dir": args.flight_dir, "files": rows,
                          "total_bytes": total}))
        return 0
    for r in rows:
        print(f"{r['bytes']:>12}  {r['file']}")
    print(f"{total:>12}  total ({len(rows)} streams)")
    return 0


def _cli_tune(args) -> int:
    """The ``tune`` subcommand: run the closed-loop auto-tuner on a
    self-initialized grid (produce mode), or print a persisted config
    (``tune show tuned.json`` — host-only). Produce mode prints the
    winning `TunedConfig` JSON; pass ``--out`` (or a ``--profile`` path,
    whose directory becomes the default home) to persist it where
    ``jobs submit``'s ``tuned`` run knob can load it."""
    import json
    import os

    from .utils.exceptions import InvalidArgumentError

    if args.model == "show":
        from .telemetry import load_tuned_config

        if not args.path:
            raise InvalidArgumentError(
                "tools tune show: name the tuned-config JSON to print.")
        print(json.dumps(load_tuned_config(args.path).to_json(),
                         indent=args.indent))
        return 0
    if args.path:
        raise InvalidArgumentError(
            f"tools tune: unexpected argument {args.path!r} (the "
            "positional path belongs to 'tune show').")

    def _split(spec):
        # ';' separates entries so multi-axis policies like
        # 'z:int8,x:f32' stay whole; a ';'-free spec splits on ','
        parts = spec.split(";") if ";" in spec else spec.split(",")
        return tuple(p.strip() for p in parts if p.strip())

    if args.cpu:
        # must precede any jax device use (the bench scripts' idiom)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from .parallel.topology import dims_create
    from .telemetry import tune_config

    dims = [int(d) for d in dims_create(len(jax.devices()), (0, 0, 0))]
    grid = dict(nx=args.nx, ny=args.nx, nz=args.nx,
                dimx=dims[0], dimy=dims[1], dimz=dims[2],
                periodx=1, periody=1, periodz=1)
    kw = {}
    if args.comm_every_options:
        kw["comm_every_options"] = _split(args.comm_every_options)
    if args.wire_options:
        kw["wire_dtype_options"] = tuple(
            None if w.lower() in ("off", "none", "") else w
            for w in _split(args.wire_options))
    if args.wire_stage_options:
        kw["wire_stage_options"] = tuple(
            None if w.lower() in ("off", "none", "flat", "") else w
            for w in _split(args.wire_stage_options))
    if args.ensemble_options:
        kw["ensemble_options"] = tuple(
            None if int(e) <= 1 else int(e)
            for e in _split(args.ensemble_options))
    if args.overlap:
        kw["overlap_options"] = (False, True)
    cfg = tune_config(args.model, grid, args.profile,
                      measure=not args.no_measure,
                      top_k=args.top_k, path=args.out, **kw)
    print(json.dumps(cfg.to_json(), indent=args.indent))
    return 0


def _cli_reshard(args) -> int:
    """The ``reshard`` subcommand group (docs/resilience.md "On-device
    resize"). ``plan`` is host-only: derive the transfer plan for a
    synthetic state and print it with its `predict_reshard` price.
    ``run`` additionally executes it: self-initialize a grid on the
    source dims, build the state, re-block it on device
    (`reshard.reshard_state` with the contract audit on), verify the
    result bit-identical to the host oracle (`apply_plan_host`), and
    exit 1 when any error-severity finding — or a single differing
    byte — survives."""
    import json
    import os

    import numpy as np

    from .reshard import apply_plan_host, build_reshard_plan
    from .telemetry import predict_reshard
    from .utils.exceptions import InvalidArgumentError

    def _triple(spec, what):
        out = tuple(int(x) for x in str(spec).split(","))
        if len(out) != 3:
            raise InvalidArgumentError(
                f"tools reshard: {what} must be 3 comma-separated ints; "
                f"got {spec!r}.")
        return out

    src_dims = _triple(args.src_dims, "--src-dims")
    dst_dims = _triple(args.dst_dims, "--dst-dims")
    per = _triple(args.periods, "--periods")
    ol = _triple(args.overlaps, "--overlaps")
    nx = max(int(args.nx), 2 * max(ol))
    lead = () if args.ensemble is None else (int(args.ensemble),)
    topo = {"nxyz": np.array([nx] * 3), "dims": np.array(src_dims),
            "overlaps": np.array(ol), "periods": np.array(per),
            "halowidths": np.maximum(1, np.array(ol) // 2)}
    fields = {}
    for i in range(max(1, int(args.fields))):
        stag = 1 if i == 1 else 0   # field 1 x-staggered: 2nd signature
        shape = lead + (src_dims[0] * (nx + stag),
                        src_dims[1] * nx, src_dims[2] * nx)
        fields[f"f{i}"] = (shape, str(np.dtype(args.dtype)), len(lead))
    plan = build_reshard_plan(topo, dst_dims, fields)
    pred = predict_reshard(plan)
    rec = {"plan": plan.to_json(), "predicted": pred}

    if args.reshard_cmd == "plan":
        be_args = (args.nt_remaining, args.old_step_s, args.new_step_s)
        if any(a is not None for a in be_args):
            if any(a is None for a in be_args):
                raise InvalidArgumentError(
                    "tools reshard plan: --nt-remaining, --old-step-s, "
                    "and --new-step-s go together (the amortized "
                    "break-even needs all three).")
            # the one shared break-even arithmetic (telemetry.
            # ReshardPrediction) — identical to what the autoscaler
            # prices and service_report carries
            rec["break_even"] = pred.amortized_break_even_steps(
                args.nt_remaining, args.old_step_s, args.new_step_s)
        print(json.dumps(rec, indent=args.indent, default=str))
        return 0

    # -- run: execute + audit + verify -------------------------------------
    if args.cpu:
        # must precede any jax device use (the bench scripts' idiom)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from .models.common import ensemble_state
    from .parallel.grid import finalize_global_grid, init_global_grid
    from .parallel.topology import grid_is_initialized
    from .reshard import fields_of_state, reshard_state

    if plan.n_flat > len(jax.devices()):
        raise InvalidArgumentError(
            f"tools reshard run: the transfer mesh needs {plan.n_flat} "
            f"device(s), {len(jax.devices())} available.")
    if grid_is_initialized():
        raise InvalidArgumentError(
            "tools reshard run re-initializes the global grid; run it "
            "in a fresh process.")
    init_global_grid(nx, nx, nx, dimx=src_dims[0], dimy=src_dims[1],
                     dimz=src_dims[2], periodx=per[0], periody=per[1],
                     periodz=per[2], overlaps=ol, quiet=True)
    try:
        from .ops.alloc import device_put_g

        rng = np.random.default_rng(14)
        state = {}
        for name, (shape, dtype, nlead) in fields.items():
            host = rng.normal(size=shape[nlead:]).astype(dtype)
            arr = device_put_g(host)
            if nlead:
                arr = ensemble_state(arr, shape[0], perturb=0.01)
            state[name] = arr
        host_state = {k: np.asarray(v) for k, v in state.items()}
        plan = build_reshard_plan(topo, dst_dims, fields_of_state(state))
        expect = apply_plan_host(plan, host_state)
        new_state, info = reshard_state(state, dst_dims, audit=True)
        report = info.pop("audit_report")
        mismatch = [k for k in state
                    if not np.array_equal(np.asarray(new_state[k]),
                                          expect[k])]
        ok = bool(report is not None and report.ok and not mismatch)
        rec.update(
            audit=None if report is None else report.to_json(),
            audit_error=info.get("audit_error"),
            verified=not mismatch, mismatched_fields=mismatch, ok=ok)
    finally:
        if grid_is_initialized():
            finalize_global_grid()
    if args.json:
        print(json.dumps(rec, indent=args.indent, default=str))
    else:
        a = rec["audit"]
        print(f"reshard {src_dims} -> {dst_dims}: "
              f"{'OK' if ok else 'FAIL'} rounds={plan.rounds} "
              f"wire_bytes={plan.wire_bytes} "
              f"audit={'ok' if a and a['ok'] else 'FAIL'} "
              f"verify={'bit-identical' if not mismatch else mismatch}")
        if a:
            for f in a["findings"]:
                print(f"  [{f['severity']}] {f['rule']}: {f['message']}")
    return 0 if ok else 1


def _cli_autoscale(args) -> int:
    """``autoscale explain``: the closed-loop autoscaler's
    explainability contract (docs/autoscaling.md). Reconstructed from
    the scheduler journal ALONE — a service that died hours ago still
    defends every resize it made (and every one it refused): policy
    echo, verdict counts, the rejection histogram, each filed move's
    actuation chain with its signal snapshot and pricing breakdown.
    ``--job`` narrows to one tenant."""
    import json

    from .service.report import explain_autoscale

    rec = explain_autoscale(args.flight_dir)
    if args.job is not None:
        rec = {"policy": rec["policy"], "job": args.job,
               "moves": [m for m in rec["moves"]
                         if m.get("job") == args.job],
               "decisions": rec["jobs"].get(args.job, [])}
    print(json.dumps(rec, indent=args.indent, default=str))
    return 0


def _cli_jobs(args) -> int:
    """The ``jobs`` subcommand group: the multi-run scheduler's operator
    surface (`docs/service.md`).

    - ``submit QUEUE.json``: build a `service.MeshScheduler`, submit every
      described job (built-in models by name, grids per job), drain the
      queue, print the outcome. Exit 0 only when EVERY job finished
      (``done``); 1 otherwise — the CI-able batch entry point.
    - ``list DIR`` / ``status DIR NAME``: post-hoc queue inspection from
      the journal alone (a service that died hours ago still answers).
    - ``cancel DIR NAME`` / ``drain DIR``: file control requests under
      ``DIR/control/`` that a LIVE scheduler consumes at its next slice
      boundary (chunk-granular preemption — nothing is killed mid-chunk).
    """
    import json
    import os

    from .service.report import read_journal, service_report
    from .utils.exceptions import InvalidArgumentError

    if args.jobs_cmd == "submit":
        if args.cpu:
            # must precede any jax device use (the bench scripts' idiom)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
            import jax

            jax.config.update("jax_platforms", "cpu")
        from .service import JobState, MeshScheduler, jobspec_from_json

        with open(args.spec, encoding="utf-8") as f:
            queue = json.load(f)
        if not isinstance(queue, dict) or not queue.get("jobs"):
            raise InvalidArgumentError(
                f"{args.spec}: expected {{'jobs': [...]}} with at least "
                "one job.")
        policy = args.policy or queue.get("policy", "fifo")
        sched = MeshScheduler(policy=policy, flight_dir=args.flight_dir,
                              metrics_port=args.metrics_port)
        try:
            for i, rec in enumerate(queue["jobs"]):
                # one schema, one code path with POST /v1/jobs
                # (service.jobspec_from_json) — the CLI and the HTTP
                # API can never diverge
                sched.submit(jobspec_from_json(
                    rec, where=f"{args.spec}: job #{i}"))
            sched.run()
            status = sched.status()
        finally:
            sched.close()
        ok = all(j["state"] == JobState.DONE for j in status["jobs"])
        if args.json:
            print(json.dumps({"ok": ok, **status}, default=str))
        else:
            for j in status["jobs"]:
                err = f"  ({j['error']})" if j.get("error") else ""
                print(f"{j['name']}: {j['state']} step {j['step']}/"
                      f"{j['nt']} in {j['slices']} slice(s){err}")
        return 0 if ok else 1

    if args.jobs_cmd == "list":
        rep = service_report(args.flight_dir, include_jobs=False)
        if args.json:
            print(json.dumps(rep, default=str))
        else:
            for name, j in rep["jobs"].items():
                print(f"{name:<20} {j['state']:<10} "
                      f"step {j.get('step') or 0:>8}  "
                      f"slices {j['slices']:>5}  "
                      f"mesh {j['slice_s_total']:.3f}s "
                      f"({100 * j['mesh_share']:.0f}%)")
        return 0
    if args.jobs_cmd == "status":
        rep = service_report(args.flight_dir)
        job = rep["jobs"].get(args.name)
        if job is None:
            print(json.dumps({"error": f"no job named {args.name!r}",
                              "have": list(rep["jobs"])}))
            return 3
        print(json.dumps(job, indent=args.indent, default=str))
        return 0

    # control-channel commands: validated against the journal, consumed
    # by the live scheduler's _poll_control at its next slice boundary
    ctl = os.path.join(args.flight_dir, "control")
    if args.jobs_cmd == "cancel":
        jobs = service_report(args.flight_dir,
                              include_jobs=False)["jobs"]
        job = jobs.get(args.name)
        if job is None:
            print(json.dumps({"error": f"no job named {args.name!r}",
                              "have": list(jobs)}))
            return 3
        if job["state"] not in ("queued", "running"):
            print(json.dumps({"error": f"job {args.name!r} already "
                                       f"{job['state']}"}))
            return 4
        os.makedirs(ctl, exist_ok=True)
        path = os.path.join(ctl, f"cancel_{args.name}")
        with open(path, "w", encoding="utf-8"):
            pass
        print(json.dumps({"requested": "cancel", "job": args.name,
                          "control": path}))
        return 0
    if args.jobs_cmd == "resize":
        jobs = service_report(args.flight_dir,
                              include_jobs=False)["jobs"]
        job = jobs.get(args.name)
        if job is None:
            print(json.dumps({"error": f"no job named {args.name!r}",
                              "have": list(jobs)}))
            return 3
        if job["state"] not in ("queued", "running"):
            print(json.dumps({"error": f"job {args.name!r} already "
                                       f"{job['state']}"}))
            return 4
        try:
            dims = [int(x) for x in str(args.dims).split(",")]
        except ValueError:
            dims = []
        if len(dims) != 3 or any(d < 1 for d in dims):
            raise InvalidArgumentError(
                f"tools jobs resize: dims must be 3 positive "
                f"comma-separated ints; got {args.dims!r}.")
        os.makedirs(ctl, exist_ok=True)
        path = os.path.join(ctl, f"resize_{args.name}")
        # atomic: the scheduler polls this directory at slice boundaries
        # and must never read (and consume) a half-written request
        with open(path + ".tmp", "w", encoding="utf-8") as f:
            json.dump({"new_dims": dims, "via": args.via}, f)
        os.replace(path + ".tmp", path)
        print(json.dumps({"requested": "resize", "job": args.name,
                          "new_dims": dims, "via": args.via,
                          "control": path}))
        return 0
    # drain
    read_journal(args.flight_dir)  # validates the directory
    os.makedirs(ctl, exist_ok=True)
    path = os.path.join(ctl, "drain")
    with open(path, "w", encoding="utf-8"):
        pass
    print(json.dumps({"requested": "drain", "control": path}))
    return 0


def _cli_audit(args) -> int:
    """The ``audit`` subcommand: compile-and-audit model step programs, or
    host-only parse a captured dump. Exit 1 when any error-severity
    finding survives (the warning tier never gates)."""
    import json
    import os

    from .utils.exceptions import InvalidArgumentError

    if args.hlo is None and not args.models:
        raise InvalidArgumentError(
            "tools audit: name at least one model (diffusion3d, "
            "diffusion2d, acoustic3d, stokes3d) or pass --hlo FILE.")
    if args.hlo is not None and args.models:
        raise InvalidArgumentError(
            "tools audit: --hlo and model names are mutually exclusive "
            "(a dump is audited host-only, models are compiled here).")

    reports = []  # (name, AuditReport)
    if args.hlo is not None:
        from .analysis import (
            CollectiveContract, audit_program, default_lint_config,
        )

        contract = None
        if args.contract is not None:
            with open(args.contract, encoding="utf-8") as f:
                contract = CollectiveContract.from_json(f.read())
        with open(args.hlo, encoding="utf-8") as f:
            text = f.read()
        # --wire-dtype applies to a captured dump too: its absence from
        # the parsed permute payloads is the wire-downcast-missing lint
        # (the compile-path knobs --impl/--lowered/--no-crosscheck have
        # no meaning for a pre-captured text and are ignored here)
        cfg = default_lint_config(wire_dtype=args.wire_dtype) \
            if args.wire_dtype else None
        reports.append((args.hlo, audit_program(
            text, contract=contract, lint_config=cfg,
            meta={"source": args.hlo})))
    else:
        # --wire-dtype is handled by audit_model itself: it scopes
        # IGG_HALO_WIRE_DTYPE to the compile (and restores it) so the
        # program and the derived contract agree on what should cross
        # the link without leaking the mode into this process
        if args.cpu:
            # must precede any jax device use (the bench scripts' idiom)
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
            import jax

            jax.config.update("jax_platforms", "cpu")
        import jax

        from .analysis import audit_model
        from .parallel.grid import finalize_global_grid, init_global_grid
        from .parallel.topology import dims_create, grid_is_initialized

        owns_grid = not grid_is_initialized()
        if owns_grid:
            dims = [int(d) for d in dims_create(len(jax.devices()),
                                                (0, 0, 0))]
            gkw = {}
            if args.comm_every is not None:
                # the cadence's halo geometry: per axis, hw = depth*k_d
                # (depth 2 when a Stokes program is audited) and the
                # local block sized to carry it
                from .ops.wire import resolve_comm_every
                from .telemetry.perfmodel import STEP_WORKLOADS

                cad = resolve_comm_every(args.comm_every)
                depth = max((STEP_WORKLOADS[m].deep_halo_depth
                             for m in args.models
                             if m in STEP_WORKLOADS), default=1)
                hw = tuple(depth * cad.for_dim(d) for d in range(3))
                ol = tuple(2 * h for h in hw)
                gkw = {"overlaps": ol, "halowidths": hw}
                nx = [max(args.nx, 2 * o) for o in ol]
            else:
                nx = [args.nx] * 3
            init_global_grid(nx[0], nx[1], nx[2], dimx=dims[0],
                             dimy=dims[1], dimz=dims[2], periodx=1,
                             periody=1, periodz=1, quiet=True, **gkw)
        try:
            for model in args.models:
                reports.append((model, audit_model(
                    model, impl=args.impl, wire_dtype=args.wire_dtype,
                    wire_stage=args.wire_stage,
                    crosscheck=not args.no_crosscheck,
                    optimized=not args.lowered,
                    ensemble=args.ensemble,
                    comm_every=args.comm_every)))
        finally:
            if owns_grid:
                finalize_global_grid()

    ok = all(rep.ok for _, rep in reports)
    if args.json:
        print(json.dumps(
            {"ok": ok,
             "programs": [dict(rep.to_json(), name=name)
                          for name, rep in reports]},
            indent=args.indent, default=str))
    else:
        for name, rep in reports:
            cc = rep.crosscheck
            cc_txt = "" if cc is None else \
                f"  crosscheck={'ok' if cc['ok'] else 'DRIFT'}"
            print(f"{name}: {'OK' if rep.ok else 'FAIL'} "
                  f"[{rep.dialect}] errors={rep.errors} "
                  f"warnings={rep.warnings} "
                  f"collectives={rep.collectives['permutes']}p/"
                  f"{rep.collectives['all_reduces']}ar/"
                  f"{rep.collectives['all_gathers']}ag{cc_txt}")
            for f in rep.findings:
                anchor = f" @{f.computation}:{f.op}" if f.op else ""
                print(f"  [{f.severity}] {f.rule}{anchor}: {f.message}")
    return 0 if ok else 1


def coords_g(dx, dy, dz, A):
    """Broadcastable (x, y, z) global-coordinate arrays for stacked array ``A``
    — the TPU-native initial-condition idiom::

        x, y, z = coords_g(dx, dy, dz, T)            # shapes (nx,1,1),(1,ny,1),(1,1,nz)
        T = 100 * jnp.exp(-((x-lx/2)/2)**2 - ((y-ly/2)/2)**2 - ((z-lz/3)/2)**2)

    replacing the reference's per-rank comprehension IC pattern
    (`examples/diffusion3D_multigpu_CuArrays_novis.jl:35-38`).
    """
    shape = _shape_of(A) if hasattr(A, "shape") else tuple(A)
    nd = len(shape)
    outs = []
    for dim, d in zip(range(min(nd, NDIMS)), (dx, dy, dz)):
        v = np.asarray(_x_g_vec(d, shape, dim))
        sh = [1] * nd
        sh[dim] = v.shape[0]
        outs.append(v.reshape(sh))
    return tuple(outs)


if __name__ == "__main__":  # python -m implicitglobalgrid_tpu.tools ...
    import sys

    sys.exit(_cli(sys.argv[1:]))
