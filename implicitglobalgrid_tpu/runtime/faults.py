"""Deterministic fault injection — every recovery path exercised, not
believed.

The resilient driver's recovery machinery (rollback, checkpoint fallback,
elastic restart) would otherwise only run in production incidents; these
faults let tier-1 tests drive each path deterministically
(`tests/test_resilience.py`), the same philosophy as the reference wiring
its exchange through 1-process self-neighbor tests rather than trusting MPI.

Three fault species, all consumed exactly once by `run_resilient`:

- `NaNPoke` — silent-data-corruption model: one cell of one field is set
  to NaN at an exact step (the driver splits its chunk schedule so the
  poke lands at the requested step boundary). The health guard must trip
  within the following chunk and the driver roll back.
- `CheckpointCorruption` — storage-failure model: right after the N-th
  checkpoint save completes, its directory is truncated/bit-flipped/
  deleted on disk. The next restore must detect it (content checksums,
  `utils/checkpoint.py`) and fall back to the other slot.
- `ProcessLoss` — preemption/lost-chip model: at an exact step the live
  state is ABANDONED and the grid re-initialized with ``new_dims``; the
  driver elastically restores the last good checkpoint onto the new
  decomposition and recomputes the lost steps.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = ["NaNPoke", "CheckpointCorruption", "ProcessLoss",
           "poke_nan", "corrupt_checkpoint"]


@dataclass(frozen=True)
class NaNPoke:
    """Set ``state[name][index] = NaN`` when the run reaches ``step``
    (``index`` in the STACKED layout — it addresses a cell of a specific
    shard, the 'chosen shard at a chosen step' of the injection matrix)."""
    step: int
    name: str
    index: tuple = (0, 0, 0)


@dataclass(frozen=True)
class CheckpointCorruption:
    """Corrupt the checkpoint written by save number ``save_index``
    (0-based, counting the driver's initial step-0 save) immediately after
    it completes. ``kind``: ``"truncate"`` | ``"bitflip"`` | ``"delete"``;
    ``target``: ``"shard"`` (process ``process``'s file) | ``"meta"``."""
    save_index: int
    kind: str = "truncate"
    target: str = "shard"
    process: int = 0


@dataclass(frozen=True)
class ProcessLoss:
    """Abandon the live state at ``step`` and restart elastically on a
    grid decomposed as ``new_dims`` (same implicit global grid)."""
    step: int
    new_dims: tuple


def poke_nan(A, index=(0, 0, 0)):
    """Return ``A`` with the cell at stacked ``index`` set to NaN (the
    injection primitive behind `NaNPoke`; usable standalone in tests)."""
    return A.at[tuple(int(i) for i in index)].set(float("nan"))


def corrupt_checkpoint(dirpath, *, kind: str = "truncate",
                       target: str = "shard", process: int = 0) -> None:
    """Damage a sharded checkpoint directory ON DISK (the injection
    primitive behind `CheckpointCorruption`): truncate the target file to
    half its size, flip one byte in its middle, or delete it. The content
    checksums added by `save_checkpoint_sharded` guarantee a later restore
    raises instead of reassembling garbage."""
    from ..utils.exceptions import InvalidArgumentError

    if kind not in ("truncate", "bitflip", "delete"):
        raise InvalidArgumentError(
            f"corrupt_checkpoint kind must be truncate|bitflip|delete, "
            f"got {kind!r}.")
    if target not in ("shard", "meta"):
        raise InvalidArgumentError(
            f"corrupt_checkpoint target must be shard|meta, got {target!r}.")
    fname = "meta.npz" if target == "meta" else f"shards_p{process}.npz"
    path = os.path.join(dirpath, fname)
    if not os.path.exists(path):
        raise InvalidArgumentError(
            f"corrupt_checkpoint: no such checkpoint file {path}.")
    if kind == "delete":
        os.remove(path)
        return
    size = os.path.getsize(path)
    if kind == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        return
    with open(path, "r+b") as f:  # bitflip: one byte, mid-file
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
