"""Resilient simulation runtime: supervised long runs over the chunked
runners — on-device health guards, double-buffered elastic
checkpoint-restart, deterministic fault injection (no reference analog;
the reference's runtime story ends at `tic`/`toc`, SURVEY §5.4)."""

from .driver import run_resilient
from .faults import (
    CheckpointCorruption, NaNPoke, ProcessLoss, corrupt_checkpoint,
    poke_nan,
)
from .health import GuardConfig, HealthReport, make_guarded_runner
from .recovery import RecoveryPolicy, elastic_restart

__all__ = [
    "run_resilient",
    "GuardConfig", "HealthReport", "make_guarded_runner",
    "RecoveryPolicy", "elastic_restart",
    "NaNPoke", "CheckpointCorruption", "ProcessLoss",
    "poke_nan", "corrupt_checkpoint",
]
