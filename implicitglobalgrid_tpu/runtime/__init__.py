"""Resilient simulation runtime: supervised long runs over the chunked
runners — on-device health guards, double-buffered elastic
checkpoint-restart, deterministic fault injection (no reference analog;
the reference's runtime story ends at `tic`/`toc`, SURVEY §5.4). Since
ISSUE 8 the driver loop is a resumable machine (`ResilientRun`, one
`advance()` per chunk boundary) with its knob set factored into
`RunSpec` — what the multi-run scheduler (`service/`) multiplexes."""

from .driver import ResilientRun, run_resilient
from .faults import (
    CheckpointCorruption, NaNPoke, ProcessLoss, corrupt_checkpoint,
    poke_nan,
)
from .health import GuardConfig, HealthReport, make_guarded_runner
from .recovery import RecoveryPolicy, elastic_restart
from .spec import RunSpec

__all__ = [
    "run_resilient", "ResilientRun", "RunSpec",
    "GuardConfig", "HealthReport", "make_guarded_runner",
    "RecoveryPolicy", "elastic_restart",
    "NaNPoke", "CheckpointCorruption", "ProcessLoss",
    "poke_nan", "corrupt_checkpoint",
]
