"""Recovery policy and elastic restart for the resilient driver.

Rollback-to-last-good with bounded retries and escalation is the driver's
failure loop (`runtime/driver.py`); this module holds the POLICY (how many
times, how long to wait, when to shrink the chunk) and the heavyweight
recovery move: ELASTIC RESTART — re-initialize the grid with a different
``dims`` (the simulated lost-process/preemption case: fewer or differently
arranged chips) and redistribute the last good checkpoint's blocks onto the
new decomposition (`utils.checkpoint.restore_checkpoint_elastic`).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["RecoveryPolicy", "elastic_restart"]


@dataclass(frozen=True)
class RecoveryPolicy:
    """Bounded-retry rollback policy.

    ``max_retries``: consecutive guard trips (without a completed chunk in
    between) tolerated before the run raises `ResilienceError`.
    ``backoff_s``: sleep ``backoff_s * 2**(retry-1)`` before re-running a
    rolled-back chunk (0 in tests; nonzero absorbs transient hardware
    faults in production).
    ``shrink_chunk_after``: once this many consecutive trips happened, the
    driver ESCALATES by halving its chunk size (bounded by
    ``min_nt_chunk``) — smaller chunks tighten the guard's detection
    latency and shrink the recompute window, the cheap analog of disabling
    deep-halo `comm_every` modes on repeated blow-ups.
    ``on_escalate``: optional callback ``(info: dict) -> None`` invoked at
    every escalation with ``{"retries", "nt_chunk", "step"}`` — the hook
    for model-level reactions (e.g. swapping in a runner without
    `comm_every` deep halos)."""
    max_retries: int = 3
    backoff_s: float = 0.0
    shrink_chunk_after: int = 2
    min_nt_chunk: int = 1
    on_escalate: object = None


def elastic_restart(ckpt_dir, new_dims, *, quiet: bool = True):
    """Re-initialize the grid decomposed as ``new_dims`` and restore
    ``ckpt_dir`` onto it.

    Reads the saved topology from the checkpoint meta (host-only — the
    'lost' grid need not be alive), finalizes any live grid, re-inits with
    the local block size that keeps the implicit global grid identical
    (`elastic_local_size`), and redistributes the saved blocks. Returns
    ``(state, step)``. Raises `IncoherentArgumentError` when ``new_dims``
    cannot decompose the saved global grid evenly."""
    from ..parallel.grid import finalize_global_grid, init_global_grid
    from ..parallel.topology import grid_is_initialized
    from ..utils.checkpoint import (
        elastic_local_size, restore_checkpoint_elastic, saved_topology,
    )

    topo = saved_topology(ckpt_dir)
    new_dims = tuple(int(d) for d in new_dims)
    nxyz = elastic_local_size(topo, new_dims)
    if grid_is_initialized():
        finalize_global_grid()
    per = [int(p) for p in topo["periods"]]
    init_global_grid(
        nxyz[0], nxyz[1], nxyz[2],
        dimx=new_dims[0], dimy=new_dims[1], dimz=new_dims[2],
        periodx=per[0], periody=per[1], periodz=per[2],
        overlaps=tuple(int(o) for o in topo["overlaps"]),
        halowidths=tuple(int(h) for h in topo["halowidths"]),
        quiet=quiet)
    return restore_checkpoint_elastic(ckpt_dir)
