"""The resilient simulation driver: a jitted step function → a supervised
long run.

The reference stops at `tic`/`toc` (SURVEY §5.4: no checkpointing, no
monitoring); the chunked runners (`models/common.py`) and the sharded
block-coordinate checkpoints (`utils/checkpoint.py`) are the two hard
ingredients this driver composes into survival without a human in the loop:

    state, reports = igg.run_resilient(step_local, {"T": T, "Cp": Cp}, nt,
                                       nt_chunk=100, key="my_model",
                                       checkpoint_dir="/ckpt/run42")

Per chunk: ONE compiled program advances ``nt_chunk`` steps with the health
probe fused into its body (`runtime/health.py` — one tiny psum per chunk
boundary); the driver fetches the replicated stats vector (a tiny D2H that
doubles as the chunk drain), builds a `HealthReport`, and

- on a healthy chunk: commits the state, periodically saving an async-safe
  DOUBLE-BUFFERED sharded checkpoint (two slots + an atomically-renamed
  ``LATEST`` pointer file — a crash mid-write can never lose the previous
  good state);
- on a tripped guard (NaN/Inf, norm divergence): rolls back to the last
  good checkpoint under the bounded-retry `RecoveryPolicy`, escalating
  (chunk shrink, `on_escalate` hook) on repeated blow-ups;
- on a restore failure (corrupt slot): falls back to the OTHER slot —
  verified, not assumed, via the per-file content checksums;
- on a simulated process loss: re-inits the grid with different ``dims``
  and elastically redistributes the last good checkpoint onto it
  (`runtime/recovery.py`).

Every recovery path is exercised deterministically by the fault-injection
species of `runtime/faults.py` in tier-1 tests. Counters for each event
kind land in the telemetry metrics registry (the
``igg_health_events_total{kind=...}`` family, readable via
``igg.metrics_registry()`` / ``igg.prometheus_snapshot()``), and with an active
flight recorder (`igg.start_flight_recorder`) the driver streams its whole
lifecycle — chunk execute/compile splits, guard trips, rollback/restore
latencies, escalations, elastic restarts — as JSONL events that
`igg.run_report` reconstructs post-hoc. All instrumentation is host-side:
the compiled chunk program is bit-identical with telemetry on or off
(`tests/test_hlo_audit.py`) and the measured overhead sits under the 2%
gate (`bench_telemetry.py`).

Since the multi-run scheduler (ISSUE 8) the loop itself is a RESUMABLE
state machine: `ResilientRun` holds one supervised run's whole context
(runner cache key, checkpoint slots, snapshot writer, perf watch, audit
budgets) and `advance()` executes exactly ONE chunk-boundary iteration —
faults due now, one supervised chunk, commit or recovery. `run_resilient`
is the drain-it-to-completion loop over that machine; the
`service.MeshScheduler` interleaves `advance()` calls of MANY machines
through one device mesh (preemption is only ever at chunk boundaries, so a
job's trajectory is bit-identical however it is sliced).
"""

from __future__ import annotations

import json
import os
import time

from .spec import RunSpec

__all__ = ["run_resilient", "ResilientRun", "RunSpec"]


class _CheckpointSlots:
    """Double-buffered checkpoint slots under one root directory.

    Saves alternate between ``slot0``/``slot1``; after a save fully
    commits (atomic staged-directory rename inside
    `save_checkpoint_sharded`), the ``LATEST`` pointer file is replaced
    atomically (tmp + fsync + rename) to name the new last-good slot.
    Restore order is pointer target first, then the other slot — so a
    crash at ANY point (mid-save, mid-pointer-write, post-corruption)
    still finds a complete verified checkpoint."""

    SLOTS = ("slot0", "slot1")
    POINTER = "LATEST"

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def _pointer(self) -> str:
        return os.path.join(self.root, self.POINTER)

    def latest(self):
        """Path of the last committed slot, or None."""
        try:
            with open(self._pointer()) as f:
                rec = json.load(f)
            name = rec["slot"]
        except Exception:
            return None
        return os.path.join(self.root, name) if name in self.SLOTS else None

    def candidates(self) -> list:
        """Restore order: pointer target first, then the other slot."""
        latest = self.latest()
        out = [latest] if latest else []
        for s in self.SLOTS:
            p = os.path.join(self.root, s)
            if p != latest and os.path.isdir(p):
                out.append(p)
        return out

    def save(self, state: dict, step: int) -> str:
        from ..utils.checkpoint import save_checkpoint_sharded
        from ..utils.timing import barrier

        latest = self.latest()
        if latest is None or os.path.basename(latest) == self.SLOTS[1]:
            target = os.path.join(self.root, self.SLOTS[0])
        else:
            target = os.path.join(self.root, self.SLOTS[1])
        save_checkpoint_sharded(target, state, step=step)
        import jax

        if jax.process_index() == 0:
            tmp = self._pointer() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"slot": os.path.basename(target),
                           "step": int(step)}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._pointer())
        barrier()  # pointer visible everywhere before anyone proceeds
        return target

    def restore(self):
        """Restore the newest usable slot onto the LIVE grid. Returns
        ``(state, step, used_fallback)``; raises `ResilienceError` when
        every slot fails (corruption is DETECTED, via the checkpoint
        layer's content checksums, never silently restored). Goes through
        the elastic restore — which delegates to the plain block-keyed
        path when the decomposition matches — so a slot written BEFORE an
        elastic restart (old ``dims``) is still restorable after one."""
        from ..utils.checkpoint import restore_checkpoint_elastic
        from ..utils.exceptions import ResilienceError

        errors = []
        for i, path in enumerate(self.candidates()):
            try:
                state, step = restore_checkpoint_elastic(path)
                return state, int(step or 0), i > 0
            except Exception as e:  # corrupt/incomplete slot: try the other
                errors.append(f"{path}: {e}")
        raise ResilienceError(
            "No checkpoint slot could be restored:\n  "
            + ("\n  ".join(errors) if errors else "(no slot written yet)"))


class ResilientRun:
    """One supervised run as a resumable, chunk-granular state machine.

    ``ResilientRun(step_local, state, nt, spec)`` performs the whole setup
    `run_resilient` used to do inline (validation, metrics endpoint,
    snapshot writer, checkpoint slots, perf watch) — a raising constructor
    leaks none of those resources. Each `advance()` call then executes ONE
    chunk-boundary iteration: heartbeat, faults due at this boundary, one
    supervised chunk, commit-or-recover; it returns True while steps
    remain. `close()` releases the run's resources (idempotent; call it on
    every exit path — `run_resilient` does so in a ``finally``).

    The machine is what makes the mesh a multiplexable resource: the
    `service.MeshScheduler` holds many of these and interleaves their
    `advance()` calls, so preemption happens only at chunk boundaries and
    every job's trajectory is bit-identical to its solo run regardless of
    the interleaving (asserted in tests/test_service.py)."""

    def __init__(self, step_local, state: dict, nt: int,
                 spec: RunSpec | None = None):
        import numpy as np

        from ..parallel.topology import check_initialized
        from ..telemetry import record_event
        from ..telemetry.hooks import note_heartbeat
        from ..utils.exceptions import InvalidArgumentError
        from .faults import NaNPoke, ProcessLoss
        from .health import GuardConfig
        from .recovery import RecoveryPolicy

        spec = spec if spec is not None else RunSpec()
        check_initialized()
        if not isinstance(state, dict) or not state:
            raise InvalidArgumentError(
                "run_resilient expects a non-empty dict of name -> stacked "
                "array (names become checkpoint keys and HealthReport "
                "entries).")
        self.spec = spec
        self.step_local = step_local
        self.state = state
        self.names = list(state)
        self.ensemble = (None if spec.ensemble is None
                         else int(spec.ensemble))
        if self.ensemble is not None:
            if self.ensemble < 1:
                raise InvalidArgumentError(
                    f"RunSpec.ensemble must be >= 1; got {spec.ensemble}.")
            for k, v in state.items():
                if v.ndim < 2 or int(v.shape[0]) != self.ensemble:
                    raise InvalidArgumentError(
                        f"ensemble={self.ensemble} expects every field to "
                        f"lead with the member axis (shape (E, ...)); "
                        f"field {k!r} has shape {tuple(v.shape)} — build "
                        "the state with models.common.ensemble_state.")
        # member-splice recovery (ensemble only): after a PARTIAL guard
        # trip the healthy members' committed chunk output (their slices
        # only) is pinned here keyed by the tripped boundary's step, and
        # re-spliced over the replay when it reaches that step again —
        # one diverging realization rolls back alone, the rest keep
        # their trajectory. A dict (not a single slot) so a second trip
        # at a DIFFERENT boundary (chunk-shrink escalation mid-replay)
        # cannot silently drop an earlier boundary's pin.
        self._pins: dict = {}
        self.guard = spec.guard if spec.guard is not None else GuardConfig()
        self.policy = (spec.policy if spec.policy is not None
                       else RecoveryPolicy())
        self.nt = int(nt)
        self.cur_chunk = max(1, int(spec.nt_chunk))
        self.checkpoint_every = max(1, int(
            spec.checkpoint_every if spec.checkpoint_every is not None
            else self.cur_chunk))
        self.pending = list(spec.faults)
        for f in self.pending:
            if isinstance(f, (NaNPoke, ProcessLoss)) \
                    and not 0 <= f.step < self.nt:
                raise InvalidArgumentError(
                    f"Fault {f} is outside the run's step range "
                    f"[0, {self.nt}).")
            if isinstance(f, NaNPoke):
                if f.name not in state:
                    raise InvalidArgumentError(
                        f"NaNPoke names unknown field {f.name!r}.")
                shape = state[f.name].shape
                # OOB scatter updates are silently DROPPED by jax — a
                # mistyped index would inject nothing and the drill would
                # pass vacuously
                if len(f.index) != len(shape) or any(
                        not 0 <= int(i) < s
                        for i, s in zip(f.index, shape)):
                    raise InvalidArgumentError(
                        f"NaNPoke index {tuple(f.index)} is outside field "
                        f"{f.name!r} of stacked shape {tuple(shape)}.")
        # auto-tuner application (RunSpec.tuned): resolve once — a bad
        # path/record must fail construction, not chunk 40 — and scope
        # the config's trace-time knobs around every advance() so chunk
        # compiles resolve them (wire dtype / coalescing / cadence are
        # read from the environment at trace time and key the runner
        # cache). Structural knobs (overlap, deep cadence in the step
        # body, ensemble stacking) belong to the setup that built
        # step_local/state — the scheduler's admission applies those
        # (`service.job.builtin_setup(tuned=)`).
        from ..telemetry.tune import resolve_tuned

        self.tuned = resolve_tuned(spec.tuned)
        self._tuned_env = None if self.tuned is None else self.tuned.env()
        # re-tune trigger (ROADMAP tuner rung c): an elastic resize or a
        # PerfWatch drift flag invalidates the applied config — the
        # driver marks it stale (`tuned_stale` flight event) and the
        # scheduler clears it at the next slice boundary
        self.tuned_stale = False
        self.tuned_stale_reason = None
        # wall-clock deadline surface (RunSpec.deadline_s): crossing the
        # budget fires ONE deadline_missed flight event + counter at the
        # next boundary — observability, never a kill
        if spec.deadline_s is not None \
                and not float(spec.deadline_s) > 0:
            raise InvalidArgumentError(
                f"RunSpec.deadline_s is a wall-clock budget in seconds "
                f"(> 0); got {spec.deadline_s!r}.")
        self.deadline_s = (None if spec.deadline_s is None
                           else float(spec.deadline_s))
        self.deadline_missed = False
        # live slack: remaining budget minus the priced cost of the
        # remaining steps, refreshed at every boundary (`_check_deadline`)
        self.deadline_slack_s = None
        self._deadline_t0 = time.monotonic()
        if spec.audit_lints is not None and not spec.audit:
            raise InvalidArgumentError(
                "audit_lints selects rules for the compile-time audit — it "
                "needs audit=True.")
        if spec.audit_lints is not None:
            # fail fast on a typo'd rule name: inside the chunk loop it
            # would only surface as a buried `audit_failed` event (the
            # audit degrades by design), silently disabling the requested
            # audit
            from ..analysis import LINT_RULES

            unknown = sorted(set(spec.audit_lints) - set(LINT_RULES))
            if unknown:
                raise InvalidArgumentError(
                    f"audit_lints: unknown lint rule(s) {unknown}; "
                    f"available: {sorted(LINT_RULES)}.")
        self._np = np
        self._note_heartbeat = note_heartbeat
        self._record_event = record_event
        self.reducers = tuple(spec.reducers)
        # --- performance oracle: model attachment + live drift detector --
        model_step_s = model_bound = model_source = None
        if spec.perf_model is not None:
            if isinstance(spec.perf_model, dict):
                model_step_s = spec.perf_model.get("step_s")
                model_bound = spec.perf_model.get("bound")
                model_source = spec.perf_model.get("profile_source")
            else:
                model_step_s = spec.perf_model
            try:
                model_step_s = float(model_step_s)
            except (TypeError, ValueError):
                model_step_s = None
            if not model_step_s or model_step_s <= 0:
                raise InvalidArgumentError(
                    "perf_model must be a telemetry.predict_step record "
                    "(with a positive 'step_s') or modeled per-step "
                    f"seconds; got {spec.perf_model!r}.")
        self._model_step_s = model_step_s
        self._model_bound = model_bound
        self._model_source = model_source
        self.watch = None
        if int(spec.perf_window) > 0:
            from ..telemetry.perfmodel import PerfWatch

            self.watch = PerfWatch(window=int(spec.perf_window),
                                   zmax=float(spec.perf_zmax),
                                   model_step_s=model_step_s)
        # the live endpoint comes up FIRST: a port conflict must fail the
        # call before any other resource (writer thread, checkpoint dirs)
        # spins up
        self.server = None
        if spec.metrics_port is not None:
            from ..telemetry.server import start_metrics_server

            self.server = start_metrics_server(
                int(spec.metrics_port),
                healthz_max_age_s=spec.healthz_max_age_s)
        elif spec.healthz_max_age_s is not None:
            raise InvalidArgumentError(
                "healthz_max_age_s needs metrics_port (it configures the "
                "/healthz endpoint the driver starts).")
        self.writer = None
        try:
            self.slots = (_CheckpointSlots(spec.checkpoint_dir)
                          if spec.checkpoint_dir is not None else None)
            if spec.snapshot_dir is not None:
                from ..io.snapshot import SnapshotWriter

                # validate the field selection NOW, not at the first
                # cadence boundary — a typo'd name must fail before step 1,
                # not 50000 steps in
                if spec.snapshot_fields is not None:
                    unknown = [f for f in spec.snapshot_fields
                               if f not in state]
                    if unknown:
                        raise InvalidArgumentError(
                            f"snapshot_fields {unknown} are not in the "
                            f"state (have {self.names}).")
                self.writer = SnapshotWriter(
                    spec.snapshot_dir, queue_depth=spec.snapshot_queue,
                    policy=spec.snapshot_policy,
                    fields=spec.snapshot_fields)
            elif spec.snapshot_every is not None \
                    or spec.snapshot_fields is not None \
                    or spec.snapshot_policy != "block" \
                    or spec.snapshot_queue != 2:
                raise InvalidArgumentError(
                    "snapshot_every/snapshot_fields/snapshot_queue/"
                    "snapshot_policy need snapshot_dir to write into.")
            self.snapshot_every = max(1, int(
                spec.snapshot_every if spec.snapshot_every is not None
                else self.cur_chunk))
            record_event("run_begin", nt=self.nt, nt_chunk=self.cur_chunk,
                         checkpoint_every=self.checkpoint_every,
                         names=self.names,
                         checkpointing=self.slots is not None,
                         faults=len(self.pending),
                         snapshots=self.writer is not None,
                         snapshot_every=(self.snapshot_every
                                         if self.writer else None),
                         reducers=len(self.reducers))
            if model_step_s is not None:
                record_event("perf_model", step_s=model_step_s,
                             bound=model_bound, source=model_source)
            if self.tuned is not None:
                record_event("tuned", model=self.tuned.model,
                             **self.tuned.knobs(),
                             predicted_step_s=self.tuned.predicted_step_s,
                             measured_step_s=self.tuned.measured_step_s,
                             speedup=self.tuned.speedup)
        except BaseException:
            # a failed setup must not leak the endpoint or the writer
            # thread
            if self.writer is not None:
                self.writer.close()
            if self.server is not None:
                from ..telemetry.server import stop_metrics_server

                stop_metrics_server()
            raise

        self.reports = []
        self.step = 0
        self.chunk_idx = 0
        self.retries = 0
        self.saves = 0
        # each distinct chunk length n is a distinct jitted program (the
        # runner cache keys on it): audit every one the run dispatches,
        # once — a cadence-clipped first chunk must not leave the
        # steady-state program unaudited. Failures get ONE retry at a
        # later boundary (transient host error != permanently-broken
        # parser).
        self._audited_ns: set = set()
        self._audit_fail_counts: dict = {}
        self._started = False
        self._finished = False
        self._closed = False

    # -- derived views -----------------------------------------------------

    @property
    def done(self) -> bool:
        """True once the run completed all ``nt`` steps (the ``run_end``
        event has been recorded)."""
        return self._finished

    def _step_tuple(self, tup):
        out = self.step_local(dict(zip(self.names, tup)))
        return tuple(out[k] for k in self.names)

    # -- recovery helpers ---------------------------------------------------

    def _save(self, st, at_step):
        import jax

        from ..telemetry.hooks import record_health_event
        from .faults import CheckpointCorruption, corrupt_checkpoint

        path = self.slots.save(st, at_step)
        record_health_event("checkpoints_saved")
        due = [f for f in self.pending
               if isinstance(f, CheckpointCorruption)
               and f.save_index == self.saves]
        for f in due:
            self.pending.remove(f)
            self._record_event("fault_injected",
                               fault="CheckpointCorruption",
                               save_index=f.save_index, corruption=f.kind,
                               target=f.target)
            # one damage event, not one per process: applied by process 0
            # only (a second bitflip would undo the first; a second delete
            # would race-crash), made visible to all before anyone reads
            if jax.process_index() == 0:
                corrupt_checkpoint(path, kind=f.kind, target=f.target,
                                   process=f.process)
        if due and jax.process_count() > 1:
            from ..utils.timing import barrier

            barrier()
        self.saves += 1

    def _elastic_recover(self, new_dims):
        from ..telemetry.hooks import record_health_event
        from ..utils.exceptions import ResilienceError
        from .recovery import elastic_restart

        errors = []
        for i, path in enumerate(self.slots.candidates()):
            try:
                st, at = elastic_restart(path, new_dims)
            except Exception as e:
                errors.append(f"{path}: {e}")
                continue
            record_health_event("restores")
            if i > 0:
                record_health_event("restore_fallbacks")
            return st, int(at or 0)
        raise ResilienceError(
            "Elastic restart failed on every checkpoint slot:\n  "
            + "\n  ".join(errors))

    # -- elastic resize (ISSUE 14: the autoscaling primitive) ---------------

    def resize(self, new_dims, *, via: str = "auto") -> dict:
        """Re-block the run onto a ``new_dims`` decomposition of the SAME
        implicit global grid, between `advance()` calls (the scheduler's
        slice boundary). Two paths, one result:

        - ``"device"`` — the on-device fast path (`reshard.reshard_state`):
          the live state re-blocks HBM-to-HBM through a contract-audited
          collective program (sequence of ppermute slice rounds), no disk
          round-trip. Single-controller; with ``RunSpec.audit`` the
          program is statically audited against its plan-derived contract
          (an ``audit`` event with ``program="reshard"``).
        - ``"checkpoint"`` — the verified fallback and bit-identity
          oracle: save the live state to the slots, then
          `restore_checkpoint_elastic` onto the new decomposition (the
          `ProcessLoss` recovery machinery, minus the lost steps — the
          live state is the save, so nothing recomputes).

        ``"auto"`` (default) tries the device path and falls back. Both
        paths end BIT-IDENTICAL (the plan reuses the elastic restore's
        owner-map arithmetic verbatim; asserted in tests/test_reshard.py),
        so the trajectory after a resize equals the unresized run's.
        Afterwards the slots re-anchor on the new decomposition, the
        rebuilt chunk programs get fresh audit budgets, the
        ``igg_reshard_{bytes,seconds,rounds}`` metrics and a ``resize``
        flight event record the move, and an applied `TunedConfig` is
        marked stale (``tuned_stale`` event — it was tuned for the OLD
        geometry). Returns the resize record (``via``, ``seconds``,
        plan stats)."""
        from ..parallel.topology import global_grid
        from ..telemetry.hooks import observe_audit, observe_reshard, \
            record_health_event
        from ..utils.exceptions import InvalidArgumentError, ResilienceError

        if via not in ("auto", "device", "checkpoint"):
            raise InvalidArgumentError(
                f"resize: via must be auto|device|checkpoint; got {via!r}.")
        if self._finished:
            raise InvalidArgumentError(
                "resize: the run already completed all its steps.")
        new_dims = tuple(int(d) for d in new_dims)
        if len(new_dims) != 3:
            raise InvalidArgumentError(
                f"resize: new_dims must be 3 ints; got {new_dims}.")
        gg = global_grid()
        if tuple(int(d) for d in gg.dims) == new_dims:
            self._record_event("resize", via="noop",
                               new_dims=list(new_dims), step=self.step)
            return {"via": "noop", "new_dims": list(new_dims)}
        # argument-level feasibility FIRST: dims that cannot decompose
        # the implicit global grid (raises IncoherentArgumentError) or
        # that exceed the device pool fail the checkpoint path
        # identically — and the elastic fallback tears the live grid
        # down before its init would fail, so reaching it with an
        # infeasible request would leave the run DEAD, not rejected
        from ..reshard import live_topology
        from ..reshard.plan import device_pool, restore_topology
        from ..utils.checkpoint import elastic_local_size

        src_topo = live_topology(gg)
        elastic_local_size(src_topo, new_dims)
        pool = device_pool(gg)
        n_new = new_dims[0] * new_dims[1] * new_dims[2]
        if n_new > len(pool):
            raise InvalidArgumentError(
                f"resize: new_dims {new_dims} need {n_new} device(s); "
                f"{len(pool)} available.")
        t0 = time.monotonic()
        info: dict = {}
        used = device_error = None
        if via in ("auto", "device"):
            try:
                from ..reshard import reshard_state

                self.state, info = reshard_state(
                    self.state, new_dims, audit=self.spec.audit,
                    lints=self.spec.audit_lints)
                used = "device"
            except Exception as e:
                if via == "device":
                    raise
                device_error = f"{type(e).__name__}: {e}"
        if used is None:
            if self.slots is None:
                raise ResilienceError(
                    f"resize to {new_dims}: no checkpoint_dir is "
                    "configured for the elastic (checkpoint) path"
                    + (f", and the on-device path failed "
                       f"({device_error})" if device_error else "")
                    + ".")
            # anchor the LIVE state first: the checkpoint path re-blocks
            # the last save, which must be this exact boundary's state
            self._save(self.state, self.step)
            try:
                self.state, self.step = self._elastic_recover(new_dims)
            except BaseException:
                # the elastic restart finalizes + re-inits BEFORE
                # restoring: a total restore failure (every slot
                # unreadable) would otherwise leave the grid on
                # new_dims with old-dims state — put the SOURCE grid
                # back so a caller treating this as a rejected request
                # (the scheduler) keeps the tenant alive
                restore_topology(src_topo, quiet=True)
                raise
            used = "checkpoint"
        dur = time.monotonic() - t0
        report = info.pop("audit_report", None)
        if report is not None:
            observe_audit(report, program="reshard")
        if info.get("audit_error"):
            self._record_event("audit_failed", program="reshard",
                               error=info.pop("audit_error"))
        # the rebuilt decomposition's chunk programs get fresh audits —
        # and the slots re-anchor so any later rollback stays on the
        # live grid (same rule as the elastic restart)
        self._audited_ns.clear()
        self._audit_fail_counts.clear()
        if self.slots is not None:
            self._save(self.state, self.step)
        record_health_event("resizes")
        observe_reshard(
            dur, via=used, new_dims=list(new_dims), step=self.step,
            rounds=info.get("rounds"), wire_bytes=info.get("wire_bytes"),
            local_bytes=info.get("local_bytes"),
            peak_payload_bytes=info.get("peak_payload_bytes"),
            **({"device_error": device_error} if device_error else {}))
        self._mark_tuned_stale("resize")
        return {"via": used, "seconds": dur, "new_dims": list(new_dims),
                **({"device_error": device_error} if device_error else {}),
                **info}

    def _mark_tuned_stale(self, reason: str) -> None:
        """Flag the applied `TunedConfig` as invalidated (a resize changed
        the geometry it was searched for; a PerfWatch drift says its
        knobs stopped winning). No-op without a tuned config; records the
        ``tuned_stale`` flight event once."""
        if self.tuned is None or self.tuned_stale:
            return
        self.tuned_stale = True
        self.tuned_stale_reason = reason
        self._record_event("tuned_stale", reason=reason,
                           model=self.tuned.model)

    def clear_tuned(self) -> None:
        """Drop the applied `TunedConfig` (the scheduler's stale-config
        reaction at a slice boundary): subsequent chunk compiles resolve
        the DEFAULT wire/coalesce/cadence environment again. Structural
        knobs the setup baked in (overlap, a deep super-step,
        ensemble stacking) persist until re-admission — this clears the
        trace-time scope."""
        self.tuned = None
        self._tuned_env = None
        self.tuned_stale = False
        self.tuned_stale_reason = None

    def apply_tuned(self, cfg) -> None:
        """Apply a (re)tuned `TunedConfig` to the LIVE run — the
        scheduler's boundary re-tune after an autoscale resize
        (`service.autoscale`). Subsequent chunk compiles resolve the
        config's trace-time knob environment; after a resize the new
        epoch's runner caches are empty, so the very next compile picks
        it up. Structural knobs (overlap, a deep cadence baked into the
        step body, ensemble stacking) are NOT re-applied — the step
        function is already built, which is why a boundary re-tune
        searches trace-time knobs only. Clears any stale flag and
        records a ``tuned`` flight event."""
        from ..telemetry.tune import TunedConfig
        from ..utils.exceptions import InvalidArgumentError

        if not isinstance(cfg, TunedConfig):
            raise InvalidArgumentError(
                f"apply_tuned takes a telemetry.TunedConfig; got "
                f"{type(cfg).__name__}.")
        self.tuned = cfg
        self._tuned_env = cfg.env()
        self.tuned_stale = False
        self.tuned_stale_reason = None
        self._record_event("tuned", model=cfg.model, **cfg.knobs(),
                           predicted_step_s=cfg.predicted_step_s,
                           measured_step_s=cfg.measured_step_s,
                           speedup=cfg.speedup)

    def reprice(self, step_s: float, *, bound=None, source=None) -> None:
        """Replace the attached perf-model unit price (seconds per nt
        unit). The autoscaler calls this after an applied resize so the
        deadline-slack computation (`_check_deadline`) and the PerfWatch
        measured/modeled ratio track the NEW geometry instead of the
        admission-time price — without it, a grown job would keep
        reading negative slack off the old price and the policy loop
        would never converge. Records a ``perf_model`` flight event."""
        from ..utils.exceptions import InvalidArgumentError

        try:
            step_s = float(step_s)
        except (TypeError, ValueError):
            step_s = 0.0
        if not step_s > 0:
            raise InvalidArgumentError(
                f"reprice: step_s must be positive modeled seconds per "
                f"step; got {step_s!r}.")
        self._model_step_s = step_s
        self._model_bound = bound
        self._model_source = source
        if self.watch is not None:
            self.watch.model_step_s = step_s
        self._record_event("perf_model", step_s=step_s, bound=bound,
                           source=source)

    # -- the chunk-boundary iteration ---------------------------------------

    def advance(self) -> bool:
        """Execute ONE chunk-boundary iteration; return True while steps
        remain (False once the run is complete). The first call performs
        the initial step-0 checkpoint save; the call that commits step
        ``nt`` records the ``run_end`` event. Preemption between calls is
        safe — this is the scheduler's slice boundary. With a tuned
        config attached (`RunSpec.tuned`) every iteration runs under the
        config's trace-time knob scope, so any chunk compile this call
        pays resolves the tuned wire/coalesce/cadence environment."""
        if self._tuned_env is not None:
            from ..telemetry.tune import _scoped_env

            with _scoped_env(self._tuned_env):
                return self._advance()
        return self._advance()

    def _advance(self) -> bool:
        if self._finished:
            return False
        if not self._started:
            self._started = True
            if self.slots is not None:
                # rollback ALWAYS possible, even before step 1
                self._save(self.state, 0)
        if self.step < self.nt:
            self._iterate()
        if self.step >= self.nt and not self._finished:
            self._note_heartbeat(self.step)
            # a run that crossed its budget inside the FINAL chunk still
            # reports it (no further boundary would check)
            self._check_deadline()
            self._record_event("run_end", completed=self.step,
                               chunks=self.chunk_idx)
            self._finished = True
        return not self._finished

    def _check_deadline(self) -> None:
        """Boundary-granular deadline watch. Every boundary of a
        deadline-budgeted run computes the LIVE SLACK — remaining budget
        minus the priced cost of the remaining steps (the attached
        `predict_step` model when one backs the run, else the PerfWatch
        warm measured baseline, else the budget alone) — stamps the
        ``igg_deadline_slack_seconds`` gauge, and records a
        ``deadline_slack`` flight event: the signal the live plane's
        deadline-slack-burn alert subscribes to, so a bust is visible as
        a trend long before the miss. Past the budget, record ONE
        ``deadline_missed`` flight event (from the same computation:
        ``budget_s < 0``) and bump ``igg_job_deadline_missed_total`` —
        the run keeps going (a deadline is an operator contract, not a
        kill switch; the scheduler journals it and `service_report`
        surfaces it)."""
        if self.deadline_s is None:
            return
        from ..telemetry.hooks import (
            note_deadline_missed, note_deadline_slack,
        )

        elapsed_s = time.monotonic() - self._deadline_t0
        budget_s = self.deadline_s - elapsed_s
        step_s = self._model_step_s
        priced_by = "perf_model" if step_s else None
        if not step_s and self.watch is not None:
            step_s = self.watch.baseline_s()
            priced_by = "measured" if step_s else None
        remaining = max(0, self.nt - self.step)
        slack_s = budget_s - (step_s * remaining if step_s else 0.0)
        self.deadline_slack_s = slack_s
        note_deadline_slack(slack_s)
        self._record_event("deadline_slack", step=self.step,
                           slack_s=slack_s, budget_s=budget_s,
                           priced_step_s=step_s, priced_by=priced_by,
                           remaining_steps=remaining)
        if not self.deadline_missed and elapsed_s > self.deadline_s:
            self.deadline_missed = True
            note_deadline_missed()
            self._record_event("deadline_missed", step=self.step,
                               deadline_s=self.deadline_s,
                               elapsed_s=elapsed_s, slack_s=slack_s)

    def _iterate(self):
        np = self._np
        record_event = self._record_event

        from ..telemetry.hooks import (
            record_health_event, runner_cache_misses,
        )
        from ..utils.exceptions import ResilienceError
        from .faults import NaNPoke, ProcessLoss, poke_nan
        from .health import make_guarded_runner, report_from_stats

        # liveness stamp at every boundary (normal commit, retry, and
        # elastic-restart paths all come back through here): the /healthz
        # age resets as long as the driver is making progress
        self._note_heartbeat(self.step)
        self._check_deadline()
        step = self.step
        # --- faults due at this boundary (chunks split on them) ----------
        for f in [f for f in self.pending
                  if isinstance(f, NaNPoke) and f.step == step]:
            self.pending.remove(f)
            self.state = dict(self.state)
            self.state[f.name] = poke_nan(self.state[f.name], f.index)
            record_event("fault_injected", fault="NaNPoke", step=f.step,
                         name=f.name)
        loss = next((f for f in self.pending
                     if isinstance(f, ProcessLoss) and f.step == step),
                    None)
        if loss is not None:
            self.pending.remove(loss)
            record_event("fault_injected", fault="ProcessLoss",
                         step=loss.step, new_dims=list(loss.new_dims))
            if self.slots is None:
                raise ResilienceError(
                    "ProcessLoss injected with no checkpoint_dir — "
                    "nothing to restart from.")
            self.state, self.step = self._elastic_recover(loss.new_dims)
            record_health_event("elastic_restarts")
            record_event("elastic_restart", new_dims=list(loss.new_dims),
                         to_step=self.step)
            # the restart rebuilds the chunk program for the NEW
            # decomposition — audit that one too (run_report's audit
            # section treats the last audit as authoritative), with fresh
            # retry budgets
            self._audited_ns.clear()
            self._audit_fail_counts.clear()
            # re-anchor the slots on the NEW decomposition right away, so
            # a guard trip before the next cadence save rolls back onto
            # the live grid instead of re-crossing the dims change
            self._save(self.state, self.step)
            return

        # --- one supervised chunk ----------------------------------------
        nb = min(step + self.cur_chunk, self.nt)
        if self.slots is not None:  # align to the checkpoint cadence
            nb = min(nb, (step // self.checkpoint_every + 1)
                     * self.checkpoint_every)
        if self.writer is not None:  # ... and to the snapshot cadence
            nb = min(nb, (step // self.snapshot_every + 1)
                     * self.snapshot_every)
        for f in self.pending:
            if isinstance(f, (NaNPoke, ProcessLoss)) and step < f.step < nb:
                nb = f.step
        pending_pins = [s for s in self._pins if s > step]
        if pending_pins:
            # member-splice replay in flight: land exactly on the NEXT
            # pinned boundary so the healthy members' pinned chunk output
            # can be re-spliced there (an overshooting boundary would
            # strand it)
            nb = min(nb, min(pending_pins))
        n = nb - step
        state, names, spec = self.state, self.names, self.spec

        E = self.ensemble
        ndims = tuple(state[k].ndim - (1 if E else 0) for k in names)
        sizes = [int(np.prod(state[k].shape[1:] if E
                             else state[k].shape)) for k in names]
        misses0 = runner_cache_misses() if self.watch is not None else 0.0
        t_build0 = time.monotonic()
        if self.reducers:
            import jax

            from ..io.reducers import build_reducer_plan, \
                make_reduced_post_chunk
            from ..models.common import make_state_runner

            # rebuilt per boundary (cheap host work): the ownership
            # geometry follows the LIVE decomposition — an elastic restart
            # changes it — and the plan signature joins the runner key, so
            # stale compiled hooks can never serve. The plan reasons over
            # PER-MEMBER geometry (the reducer hook runs vmapped, one
            # segment set per member behind the same psum).
            plan_state = state if not E else {
                k: jax.ShapeDtypeStruct(tuple(v.shape[1:]), v.dtype)
                for k, v in state.items()}
            plan = build_reducer_plan(self.reducers, names, plan_state)
            runner = make_state_runner(
                self._step_tuple, ndims, nt_chunk=n,
                key=None if spec.key is None
                else (spec.key, "resilient-io", plan.signature),
                check_vma=spec.check_vma, unroll=spec.unroll,
                post_chunk=make_reduced_post_chunk(names, plan),
                ensemble=E)
        else:
            plan = None
            runner = make_guarded_runner(
                self._step_tuple, ndims, nt_chunk=n,
                key=None if spec.key is None else (spec.key, "resilient"),
                check_vma=spec.check_vma, unroll=spec.unroll, ensemble=E)
        t_built = time.monotonic()
        if spec.audit and n not in self._audited_ns \
                and self._audit_fail_counts.get(n, 0) < 2:
            # per distinct program, at compile time: trace+lower only —
            # the XLA executable the dispatch below builds is untouched;
            # the audit's host cost is stamped on its own event, not
            # folded into the chunk's build_s attribution
            from ..analysis import audit_chunk_program
            from ..telemetry.hooks import observe_audit

            try:
                rep_audit = audit_chunk_program(
                    runner, tuple(state[k] for k in names), names=names,
                    reducer_floats=plan.length if plan is not None else 0,
                    lints=spec.audit_lints, ensemble=E)
                observe_audit(rep_audit,
                              audit_s=time.monotonic() - t_built)
                self._audited_ns.add(n)
            except Exception as e:
                # the audit OBSERVES — a parser tripped up by a new dump
                # format must degrade to a recorded failure, never kill
                # the supervised run it watches. One retry at the next
                # boundary separates a transient host error from a
                # permanently-broken parser (whose cost must not be
                # re-paid every chunk).
                self._audit_fail_counts[n] = \
                    self._audit_fail_counts.get(n, 0) + 1
                record_event("audit_failed", error=str(e),
                             audit_s=time.monotonic() - t_built,
                             attempt=self._audit_fail_counts[n])
        t_exec0 = time.monotonic()
        out = runner(*(state[k] for k in names))
        # tiny replicated fetch = the chunk drain; with reducers the
        # vector carries [health | reducer segments] from ONE psum
        # (ensemble: an (E, 2N+R) matrix — per-member rows, one psum)
        vec = np.asarray(out[-1])
        t_done = time.monotonic()
        nh = 2 * len(names)
        if E:
            from .health import ensemble_reports_from_stats

            member_reps = ensemble_reports_from_stats(
                vec[:, :nh], names, sizes, self.guard,
                chunk=self.chunk_idx, step_begin=step, step_end=nb)
            self.reports.extend(member_reps)
            tripped = [r.member for r in member_reps if not r.ok]
            reasons = [f"{reason}@m{r.member}" for r in member_reps
                       for reason in r.reasons]
            ok = not tripped
            rep = member_reps[0]  # chunk-level anchor (chunk/step fields)
            from ..telemetry.hooks import observe_member_health

            observe_member_health(member_reps)
        else:
            rep = report_from_stats(vec[:nh], names, sizes,
                                    self.guard, chunk=self.chunk_idx,
                                    step_begin=step, step_end=nb)
            self.reports.append(rep)
            tripped, reasons, ok = None, list(rep.reasons), rep.ok
        self.chunk_idx += 1
        record_health_event("chunks")
        # exec_s covers dispatch through the stats fetch (= the chunk
        # drain); a chunk right after a runner-cache miss also pays the
        # XLA compile inside it — run_report flags those chunks as cold
        record_event("chunk", chunk=rep.chunk, step_begin=step,
                     step_end=nb, n=n, ok=ok,
                     reasons=reasons,
                     build_s=t_built - t_build0,
                     exec_s=t_done - t_exec0,
                     **({"members_tripped": tripped} if E else {}))
        if self.watch is not None:
            # live drift detection: pure host arithmetic per boundary (a
            # cold chunk — its dispatch paid the XLA compile after a
            # runner-cache miss — updates gauges only)
            verdict = self.watch.observe(
                chunk=rep.chunk, step_begin=step, step_end=nb, n=n,
                exec_s=t_done - t_exec0,
                cold=runner_cache_misses() > misses0)
            if verdict is not None:
                record_event("perf_regression", **verdict)
                self._mark_tuned_stale("perf_drift")
        if plan is not None:
            from ..telemetry.hooks import observe_reducers

            if E:
                # each scenario streams its own probes/stats: one decoded
                # segment set per member, labeled "<label>[m<member>]"
                values = {}
                for m in range(E):
                    for label, v in plan.decode(vec[m, nh:]).items():
                        values[f"{label}[m{m}]"] = v
            else:
                values = plan.decode(vec[nh:])
            observe_reducers(nb, values, ok=ok)
            if spec.on_reduce is not None:
                spec.on_reduce(nb, values)
        if spec.on_report is not None:
            for r in (member_reps if E else (rep,)):
                spec.on_report(r)

        if ok:
            self.state = dict(zip(names, out[:-1]))
            self.step = nb
            self.retries = 0
            if self.step in self._pins:
                self._splice_pin(self.step, self._pins.pop(self.step))
            # cadence saves, plus the TERMINAL state: without the latter a
            # run whose nt is off-cadence could never be resumed from its
            # own end
            if self.slots is not None \
                    and (self.step % self.checkpoint_every == 0
                         or self.step >= self.nt):
                self._save(self.state, self.step)
            if self.writer is not None \
                    and (self.step % self.snapshot_every == 0
                         or self.step >= self.nt):
                kept = self.writer.submit(self.state, self.step)
                record_event("snapshot", step=self.step,
                             displaced=not kept)
            return

        # --- guard tripped: bounded-retry rollback ------------------------
        record_health_event("guard_trips")
        self.retries += 1
        record_event("guard_trip", step_end=nb, reasons=reasons,
                     retries=self.retries,
                     **({"members": tripped} if E else {}))
        if self.slots is None:
            raise ResilienceError(
                f"Health guard tripped at step {nb} "
                f"({', '.join(reasons)}) and no checkpoint_dir is "
                "configured — cannot roll back.")
        if self.retries > self.policy.max_retries:
            raise ResilienceError(
                f"Health guard tripped {self.retries} consecutive times "
                f"at step {nb} ({', '.join(reasons)}); retry budget "
                f"({self.policy.max_retries}) exhausted.")
        if self.policy.backoff_s:
            time.sleep(self.policy.backoff_s * 2 ** (self.retries - 1))
        if self.retries >= self.policy.shrink_chunk_after \
                and self.cur_chunk > self.policy.min_nt_chunk:
            self.cur_chunk = max(self.policy.min_nt_chunk,
                                 self.cur_chunk // 2)
            record_health_event("escalations")
            record_event("escalation", retries=self.retries,
                         nt_chunk=self.cur_chunk, step=step)
            if self.policy.on_escalate is not None:
                self.policy.on_escalate({"retries": self.retries,
                                         "nt_chunk": self.cur_chunk,
                                         "step": step})
        if E and tripped:
            # PARTIAL trip: recovery keys on the member index. Pin the
            # healthy members' committed chunk output (their slices
            # only); the whole batch replays from the last-good save
            # (members are independent under vmap, so the replay IS each
            # tripped member's solo recompute), and at the pinned
            # boundary `_splice_pin` re-asserts the healthy members'
            # pinned state — surviving realizations keep their committed
            # trajectory even if the replay were to diverge; only the
            # tripped member's rolls back. An all-members trip leaves no
            # healthy set and falls through to the classic full
            # rollback (any stale pin at this boundary is dropped).
            healthy = [m for m in range(E) if m not in tripped]
            prior = self._pins.get(nb)
            if prior is not None:
                # a second trip at the SAME boundary: members healthy in
                # BOTH attempts stay pinned; newly tripped ones drop out
                healthy = [m for m in healthy if m in prior["healthy"]]
            if healthy:
                import jax.numpy as jnp

                idx = jnp.asarray(healthy)
                self._pins[nb] = {
                    "healthy": healthy,
                    "state": {k: v[idx]
                              for k, v in zip(names, out[:-1])}}
                record_health_event("member_rollbacks")
                record_event("member_rollback", members=tripped,
                             pinned=healthy, step_end=nb)
            else:
                self._pins.pop(nb, None)
        self.state, self.step, fellback = self.slots.restore()
        record_health_event("rollbacks")
        record_health_event("restores")
        if fellback:
            record_health_event("restore_fallbacks")
        record_event("rollback", to_step=self.step, fallback=fellback,
                     retries=self.retries)

    def _splice_pin(self, at_step: int, pin: dict) -> None:
        """Finish a member-splice replay: overwrite the healthy members'
        slices of the replayed state with their PINNED chunk output (the
        committed trajectory; only those members' slices were kept). The
        replay is deterministic, so this is numerically a no-op — it is
        the isolation GUARANTEE (a healthy realization can never be
        perturbed by a neighbor's rollback), and it runs before the
        commit's cadence save so checkpoints hold the spliced state."""
        import jax.numpy as jnp

        idx = jnp.asarray(pin["healthy"])
        self.state = {
            k: v.at[idx].set(pin["state"][k])
            for k, v in self.state.items()}
        self._record_event("member_splice", members=pin["healthy"],
                           step=at_step)

    def close(self) -> None:
        """Release the run's resources (metrics endpoint, snapshot-writer
        drain) — idempotent, safe on every exit path."""
        if self._closed:
            return
        self._closed = True
        if self.server is not None:
            from ..telemetry.server import stop_metrics_server

            stop_metrics_server()
        if self.writer is not None:
            # drain on EVERY exit path (normal end, retry-budget
            # ResilienceError, a user exception out of on_report): every
            # submitted snapshot is on disk before the caller proceeds
            self.writer.close()
            self._record_event("snapshot_writer_close", **self.writer.stats)


def run_resilient(step_local, state: dict, nt: int, *,
                  spec: RunSpec | None = None, **kwargs):
    """Advance ``state`` by ``nt`` steps under health supervision with
    checkpoint-rollback recovery. Returns ``(state, reports)``.

    ``step_local(state: dict) -> dict`` advances one step on LOCAL blocks
    (inside shard_map — call `local_update_halo` for exchanges, exactly as
    in `make_state_runner` steps); ``state`` maps field names to STACKED
    global arrays — the names key the checkpoints and `HealthReport`
    entries. ``key`` (hashable) enables the runner cache across chunks
    (strongly recommended: without it every chunk recompiles).

    The knobs travel either as keywords (exactly as before — the
    historical surface) or pre-packed as ``spec=RunSpec(...)`` (what the
    multi-run scheduler's `service.JobSpec` embeds); passing both raises.
    This function is a thin shim over the resumable `ResilientRun`
    machine: construct, drain `advance()` to completion, `close()`.

    ``checkpoint_dir`` enables recovery: double-buffered sharded slots +
    last-good pointer, saved every ``checkpoint_every`` steps (default:
    every chunk) — without it a tripped guard is fatal (`ResilienceError`).
    ``guard`` (`GuardConfig`) selects the on-device guards; ``policy``
    (`RecoveryPolicy`) bounds retries and escalation; ``faults`` takes the
    deterministic injection species of `runtime.faults` (each applied
    exactly once); ``on_report`` is called with every `HealthReport`.

    The chunk schedule is split at fault steps, so injections land at
    exact step boundaries; rollback recomputes from the last good save, so
    a recovered run's final state is bit-identical to an uninterrupted one
    (asserted end-to-end in `tests/test_resilience.py`).

    ``ensemble=E`` batches E scenario members through the one supervised
    run (ISSUE 12): every state array leads with the member axis (build
    with `models.common.ensemble_state`; ``step_local`` stays the
    PER-MEMBER step — the runner vmaps it), the chunk's collective count
    stays flat in E (one E x-payload ppermute pair per axis, one
    ``f32[E·(2N+R)]`` guard psum), and the guard trips PER MEMBER: a
    partial trip pins the healthy members' committed chunk output,
    replays the batch from the last-good save and re-splices the pinned
    members at the boundary (``member_rollback``/``member_splice``
    events, ``member_rollbacks`` health counter) — one diverging
    realization rolls back alone. Reducer values stream per member
    (labels suffixed ``[m<member>]``); `HealthReport.member` carries the
    member index (E reports per chunk). Elastic restart (`ProcessLoss`)
    and `ResilientRun.resize` work under ensemble too: the
    redistribution passes the leading member axis through untouched, so
    every member re-blocks exactly like a solo field (per-member
    bit-identity vs the solo elastic run, tests/test_reshard.py).

    Output pipeline (the `implicitglobalgrid_tpu/io/` subsystem —
    O(shard) per process, never a gather): ``snapshot_dir`` enables ASYNC
    sharded snapshots every ``snapshot_every`` steps (default: every
    chunk) — `io.SnapshotWriter` copies this process's shard blocks to
    host at the boundary and a background thread commits them under
    ``snapshot_dir`` (``snapshot_fields`` restricts which fields;
    ``snapshot_queue``/``snapshot_policy`` bound the queue: ``block``
    throttles, ``drop_oldest`` sheds). ``reducers`` takes `io.Probe` /
    `io.AxisSlice` / `io.Stats` specs computed INSIDE the chunk program,
    fused into the health guard's single psum (zero extra collectives);
    decoded values stream to the flight recorder + metrics gauges and to
    ``on_reduce(step, values)`` when given. Analysis side:
    `io.open_snapshot` / `read_global`.

    ``metrics_port`` (opt-in) starts the live metrics endpoint
    (`telemetry.start_metrics_server`) for the duration of the run —
    ``/metrics`` serves the Prometheus snapshot, ``/healthz`` the age of
    the driver heartbeat; ``0`` binds an ephemeral port (read it from
    ``igg.metrics_server().port``). When a server is already live in the
    process (e.g. the scheduler's long-lived endpoint), the run ATTACHES
    to it instead of failing to bind (`telemetry.server` refcounts
    starts). ``healthz_max_age_s`` makes ``/healthz`` return 503 when the
    heartbeat is older — the wedged-driver restart signal a supervisor's
    HTTP probe acts on; size it to a few chunk durations. Binds
    127.0.0.1 — see the security note in docs/observability.md. The
    heartbeat gauges themselves are stamped at every chunk boundary
    whether or not a server runs.

    Performance oracle (`telemetry.perfmodel`, host-side only): every
    chunk boundary feeds the live drift detector — a rolling per-step
    baseline (median + MAD over ``perf_window`` chunks); a chunk whose
    robust z-score exceeds ``perf_zmax`` emits a ``perf_regression``
    flight event and bumps ``igg_perf_regressions_total``, and the
    ``igg_perf_*`` gauges (per-step seconds, model ratio, z-score) track
    every boundary. Cold chunks (the dispatch after a runner-cache miss
    pays the XLA compile) are exempt from both the test and the
    baseline. ``perf_model`` attaches a prediction — a
    `telemetry.predict_step` record or modeled per-step seconds — which
    enables the measured/modeled ratio gauge and is echoed as a
    ``perf_model`` flight event for `run_report`'s ``"perf"`` section;
    ``perf_window=0`` disables the detector entirely.

    ``audit=True`` statically audits every distinct chunk program the
    run dispatches, each ONCE at compile time
    (`analysis.audit_chunk_program`): each distinct chunk length is a
    distinct jitted program (a cadence-clipped first chunk must not
    leave the steady-state program unaudited), and an elastic restart
    re-audits the rebuilt decomposition's programs. The runner is
    traced+lowered and the StableHLO checked against the guard contract
    (exactly one f32[2N + R] psum, no gathers) plus the implicit-grid
    lints (``audit_lints`` selects rules from `analysis.LINT_RULES`;
    default all). Host-side only — the XLA executable the run dispatches
    is built exactly as without the audit (HLO-asserted in
    tests/test_hlo_audit.py, gated <2% in bench_audit.py). Findings
    stream to the flight recorder (``audit`` event — `run_report`'s
    ``"audit"`` section) and the
    ``igg_audit_findings_total{rule,severity}`` metric family; an
    error-severity finding does NOT abort the run (the audit observes,
    operators gate via the report/CLI)."""
    from ..utils.exceptions import InvalidArgumentError
    from ..utils.timing import sync

    if spec is not None and kwargs:
        raise InvalidArgumentError(
            "run_resilient: pass the knobs either pre-packed via spec= or "
            f"as keywords, not both (got spec plus {sorted(kwargs)}).")
    if spec is None:
        spec = RunSpec(**kwargs)
    run = ResilientRun(step_local, state, nt, spec)
    try:
        while run.advance():
            pass
    finally:
        run.close()
    return sync(run.state), run.reports
