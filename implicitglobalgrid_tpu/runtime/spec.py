"""`RunSpec` — the supervised run's knob set as one value.

`run_resilient` grew ~20 keyword knobs across PRs 2-7 (checkpointing,
snapshots, reducers, live metrics, perf oracle, compile-time audit). The
scheduler (`service/`) needs that whole surface PER JOB — re-declaring it
on `JobSpec` would fork the API in two places that drift. So the knobs
live here, as a frozen dataclass whose defaults ARE `run_resilient`'s
defaults:

    spec = RunSpec(nt_chunk=50, checkpoint_dir="/ckpt/run42",
                   snapshot_dir="/snaps/run42", audit=True)
    state, reports = igg.run_resilient(step, state, nt, spec=spec)
    # ... or embedded in a scheduler job:
    igg.service.JobSpec(name="run42", setup=..., nt=nt, grid=..., run=spec)

`run_resilient(**kwargs)` stays a thin shim that builds the spec from its
keywords, so every existing call site keeps working unchanged. Field
semantics are documented on `run_resilient` (the single reference).
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Any

__all__ = ["RunSpec"]


@dataclass(frozen=True)
class RunSpec:
    """Every `run_resilient` keyword knob, as one immutable value (defaults
    identical to the function's). Group map:

    - chunking/caching: ``nt_chunk``, ``key``, ``check_vma``, ``unroll``
    - recovery: ``checkpoint_dir``, ``checkpoint_every``, ``guard``,
      ``policy``, ``faults``, ``on_report``
    - io pipeline: ``snapshot_dir``, ``snapshot_every``,
      ``snapshot_fields``, ``snapshot_queue``, ``snapshot_policy``,
      ``reducers``, ``on_reduce``
    - live metrics endpoint: ``metrics_port``, ``healthz_max_age_s``
    - perf oracle: ``perf_model``, ``perf_window``, ``perf_zmax``
    - static analysis: ``audit``, ``audit_lints``
    - ensemble axis: ``ensemble`` (E scenario members batched through one
      chunk program; every state array leads with the member axis — build
      with `models.common.ensemble_state` — and the guard trips per
      member)
    - deadline: ``deadline_s`` (wall-clock budget from the run's start;
      crossing it fires ONE ``deadline_missed`` flight event + the
      ``igg_job_deadline_missed_total`` counter at the next step
      boundary — observability, never a kill: the run completes. The
      scheduler fills it from ``JobSpec.deadline_s`` minus queue wait)
    - auto-tuner: ``tuned`` (a `telemetry.TunedConfig`, its JSON dict, or
      a path to one — `telemetry.tune_config` output). The driver scopes
      the config's TRACE-TIME knobs (``IGG_COMM_EVERY`` /
      ``IGG_HALO_WIRE_DTYPE`` / ``IGG_HALO_COALESCE``) around every
      chunk compile and records a ``tuned`` flight event; the scheduler
      additionally applies it at ADMISSION (setup runs under the scope,
      and a tuned ``ensemble`` fills an unset ``RunSpec.ensemble``) —
      see `service.MeshScheduler` / `service.job.builtin_setup(tuned=)`.
    """

    nt_chunk: int = 100
    key: Any = None
    checkpoint_dir: Any = None
    checkpoint_every: int | None = None
    guard: Any = None
    policy: Any = None
    faults: tuple = ()
    on_report: Any = None
    check_vma: bool | None = None
    unroll: int | None = None
    snapshot_dir: Any = None
    snapshot_every: int | None = None
    snapshot_fields: Any = None
    snapshot_queue: int = 2
    snapshot_policy: str = "block"
    reducers: tuple = ()
    on_reduce: Any = None
    metrics_port: int | None = None
    healthz_max_age_s: float | None = None
    perf_model: Any = None
    perf_window: int = 16
    perf_zmax: float = 4.0
    audit: bool = False
    audit_lints: Any = None
    ensemble: int | None = None
    tuned: Any = None
    deadline_s: float | None = None

    def to_json(self) -> dict:
        """JSON-able summary of the NON-DEFAULT, serializable knobs (for
        flight/journal records; callables and arrays are elided by name)."""
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if v == f.default:
                continue
            if callable(v):
                v = getattr(v, "__qualname__", repr(v))
            elif isinstance(v, (list, tuple)):
                v = [str(x) for x in v]
            elif not isinstance(v, (int, float, str, bool, type(None))):
                v = str(v)
            out[f.name] = v
        return out
