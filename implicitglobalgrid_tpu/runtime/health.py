"""On-device health guards for long runs — fused into the chunk body.

The reference leaves long-run survival entirely to the user (SURVEY §5.4:
`tic`/`toc` is its whole observability surface). Here every chunk of a
supervised run (`runtime/driver.py`) carries a tiny guard program INSIDE the
compiled chunk (`make_state_runner(post_chunk=...)`, `models/common.py`):
per field, a non-finite count and a squared-norm accumulator are computed on
the chunk's FINAL state and reduced with ONE small `psum` over all mesh axes
— one extra collective per chunk boundary, regardless of field count (the
same coalescing argument as the PR-1 halo exchange: compose reductions into
one collective rather than one per field — cf. HiCCL, arXiv:2408.05962).
The HLO-level guarantee is audited in `tests/test_hlo_audit.py`.

Checking the final state (not every sub-step) is sound for the blow-up modes
the guard targets: a NaN/Inf born anywhere in a stencil state propagates and
persists, so it is still visible at the chunk boundary — the driver detects
it within one chunk of its birth and rolls back to the last good checkpoint.

The replicated stats vector costs one tiny D2H fetch per chunk; fetching it
doubles as the chunk-boundary drain (it data-depends on every shard of the
final state, the `utils.timing.sync` guarantee).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

__all__ = ["GuardConfig", "HealthReport", "make_guarded_runner",
           "health_stats_local", "health_parts_local", "report_from_stats",
           "ensemble_reports_from_stats"]


@dataclass(frozen=True)
class GuardConfig:
    """What trips the guard.

    ``check_nonfinite``: any NaN/Inf cell in any field trips (default ON).
    ``rms_limit``: field-norm divergence threshold — a scalar applied to
    every field, or a dict ``name -> limit`` (fields absent from the dict
    are unchecked). The tested quantity is the RMS over the STACKED layout
    (overlap cells counted per copy — cheap and decomposition-stable
    enough for a divergence guard), accumulated in float32."""
    check_nonfinite: bool = True
    rms_limit: float | dict | None = None

    def limit_for(self, name: str):
        if isinstance(self.rms_limit, dict):
            return self.rms_limit.get(name)
        return self.rms_limit


@dataclass(frozen=True)
class HealthReport:
    """Per-chunk guard verdict (one per compiled chunk of a supervised run).

    ``nonfinite`` counts NaN/Inf cells per field (float32 accumulation:
    exact up to 2^24, saturating precision beyond — the trip condition is
    ``> 0`` either way); ``rms`` is the stacked-layout RMS per field;
    ``reasons`` names every tripped guard (``"nonfinite:T"``,
    ``"rms:T"``); ``ok`` is ``not reasons``. In an ENSEMBLE run
    (ISSUE 12) each chunk yields one report PER MEMBER — ``member`` is
    the member index (``None`` outside ensemble mode), and the guard
    trips per member: one diverging realization rolls back alone
    (`runtime/driver.py` member-splice recovery)."""
    chunk: int
    step_begin: int
    step_end: int
    nonfinite: dict
    rms: dict
    reasons: tuple = ()
    member: int | None = None

    @property
    def ok(self) -> bool:
        return not self.reasons


def health_parts_local(state) -> "jax.Array":  # noqa: F821
    """This shard's PRE-psum guard contributions: the ``(2*nfields,)``
    float32 vector ``[nonfinite_0, norm2_0, nonfinite_1, …]``. Factored
    out of `health_stats_local` so the in-situ reducer hook
    (`io/reducers.make_reduced_post_chunk`) can concatenate its own
    segments and share the guard's single psum — reducers add ZERO extra
    collectives to the chunk program."""
    import jax.numpy as jnp

    parts = []
    for x in state:
        xf = x.astype(jnp.float32)
        parts.append(jnp.sum((~jnp.isfinite(x)).astype(jnp.float32)))
        parts.append(jnp.sum(xf * xf))
    return jnp.stack(parts)


def health_stats_local(state) -> "jax.Array":  # noqa: F821
    """The in-chunk guard probe (LOCAL blocks, inside shard_map): a
    ``(2*nfields,)`` float32 vector ``[nonfinite_0, norm2_0, nonfinite_1,
    …]`` summed over every shard with ONE `psum` over all mesh axes —
    replicated on return, so the runner can emit it under a ``P()`` spec."""
    from jax import lax

    from ..parallel.topology import AXIS_NAMES

    return lax.psum(health_parts_local(state), AXIS_NAMES)


def make_guarded_runner(step_local, state_ndims, *, nt_chunk: int, key=None,
                        check_vma: bool | None = None,
                        unroll: int | None = None,
                        ensemble: int | None = None):
    """`models.common.make_state_runner` with the health probe fused into
    the chunk: the compiled program is ``state -> (*state, stats_vec)``.
    ``key`` namespaces the runner cache separately from any unguarded
    runner of the same step function. With ``ensemble=E`` the probe is
    vmapped over the member axis and the stats vector becomes
    ``f32[E, 2N]`` — still exactly ONE psum per chunk boundary
    (`f32[E·2N]` cells on the wire), with per-member verdicts
    (`ensemble_reports_from_stats`)."""
    from ..models.common import make_state_runner

    return make_state_runner(
        step_local, state_ndims, nt_chunk=nt_chunk,
        key=None if key is None else (key, "igg_health_guard"),
        check_vma=check_vma, unroll=unroll, post_chunk=health_stats_local,
        ensemble=ensemble)


def report_from_stats(vec, names, sizes, guard: GuardConfig, *,
                      chunk: int, step_begin: int, step_end: int,
                      member: int | None = None) -> HealthReport:
    """Build the host-side `HealthReport` from the fetched stats vector.
    ``sizes`` are the stacked cell counts per field (RMS denominator)."""
    nonfinite, rms, reasons = {}, {}, []
    for i, name in enumerate(names):
        bad = float(vec[2 * i])
        norm2 = float(vec[2 * i + 1])
        nonfinite[name] = int(bad)
        r = math.sqrt(norm2 / sizes[i]) if sizes[i] else 0.0
        if math.isnan(norm2) or math.isinf(norm2):
            r = float("inf")  # f32 norm2 overflow: divergence either way
        rms[name] = r
        if guard.check_nonfinite and bad > 0:
            reasons.append(f"nonfinite:{name}")
        limit = guard.limit_for(name)
        if limit is not None and not r <= float(limit):
            reasons.append(f"rms:{name}")
    return HealthReport(chunk=chunk, step_begin=step_begin,
                        step_end=step_end, nonfinite=nonfinite, rms=rms,
                        reasons=tuple(reasons), member=member)


def ensemble_reports_from_stats(mat, names, sizes, guard: GuardConfig, *,
                                chunk: int, step_begin: int, step_end: int
                                ) -> list:
    """Per-member `HealthReport`s from the ensemble chunk's ``(E, 2N)``
    stats matrix — one guard verdict PER MEMBER behind the chunk's single
    psum. ``sizes`` are the PER-MEMBER stacked cell counts."""
    return [report_from_stats(mat[m], names, sizes, guard, chunk=chunk,
                              step_begin=step_begin, step_end=step_end,
                              member=m)
            for m in range(len(mat))]
