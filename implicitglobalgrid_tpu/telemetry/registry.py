"""Process-local metrics registry — counters, gauges, fixed-bucket histograms.

The reference's observability surface is `tic`/`toc` (SURVEY §5.4); the
framework's earlier upgrades each grew ad-hoc measurement (the PR-1 bench
A/B legs, PR-2's bare `health_counters()` dict). This registry is the one
place run-level quantities accumulate: Prometheus-style named metric
families with typed kinds and label sets, process-local (one registry per
controller process — multi-host deployments scrape each process, the same
model Prometheus uses for any sharded service), and THREAD-SAFE (the
resilient driver's ``on_report`` callbacks may record from user threads).

Families are registered lazily and idempotently::

    reg = metrics_registry()
    reg.counter("igg_halo_wire_bytes_total", "Halo payload bytes on the wire.",
                ("axis", "dtype")).inc(4096, axis="gx", dtype="float32")
    reg.histogram("igg_chunk_exec_seconds", "Chunk dispatch+drain time."
                  ).observe(0.12)

Export with `telemetry.prometheus_snapshot()`; `reset_metrics()` zeros every
series for test isolation (family registrations survive, so cached family
handles stay valid). PR-2's `utils.profiling.health_counters` dict became
the ``igg_health_events_total`` family here (its deprecation shims are
retired; `telemetry.hooks.record_health_event` is the writer).
"""

from __future__ import annotations

import bisect
import re
import threading

from ..utils.exceptions import InvalidArgumentError

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "DEFAULT_BUCKETS", "metrics_registry", "reset_metrics",
           "ScopedRegistry"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-flavored default buckets (seconds): checkpoint saves and chunk
# executions both land between ~1 ms (CPU-mesh tests) and minutes (pod-scale
# restores), so the spread is wide and fixed — no dynamic re-bucketing.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class _Family:
    """One named metric family: a kind, a fixed label set, and the series
    keyed by label values. All mutation happens under the owning registry's
    lock (one lock per registry — contention is a few dict ops)."""

    kind = ""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labelnames: tuple):
        self._reg = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series: dict = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise InvalidArgumentError(
                f"Metric {self.name} takes labels {self.labelnames}; got "
                f"{tuple(sorted(labels))}.")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def samples(self) -> list:
        """``[(labels_dict, value), ...]`` snapshot (copied under lock)."""
        with self._reg._lock:
            items = list(self._series.items())
        return [(dict(zip(self.labelnames, k)), v) for k, v in items]


class Counter(_Family):
    """Monotone within a run; `inc` only accepts non-negative increments."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise InvalidArgumentError(
                f"Counter {self.name} cannot decrease (inc({n})).")
        k = self._key(labels)
        with self._reg._lock:
            self._series[k] = self._series.get(k, 0.0) + n

    def value(self, **labels) -> float:
        with self._reg._lock:
            return self._series.get(self._key(labels), 0.0)


class Gauge(_Family):
    """A value that can go anywhere (current step, live chunk size)."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._reg._lock:
            self._series[self._key(labels)] = float(v)

    def add(self, n: float, **labels) -> None:
        k = self._key(labels)
        with self._reg._lock:
            self._series[k] = self._series.get(k, 0.0) + n

    def value(self, **labels) -> float:
        with self._reg._lock:
            return self._series.get(self._key(labels), 0.0)


class Histogram(_Family):
    """Fixed-bucket histogram: per-series non-cumulative bucket counts plus
    sum/count (the exporter emits the cumulative Prometheus form). Bucket
    bounds are fixed at registration — no allocation in `observe` beyond
    the first observation of a label set."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames, buckets):
        super().__init__(registry, name, help, labelnames)
        bs = tuple(float(b) for b in buckets)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise InvalidArgumentError(
                f"Histogram {name} needs a strictly increasing, non-empty "
                f"bucket tuple; got {buckets!r}.")
        self.buckets = bs

    def observe(self, v: float, **labels) -> None:
        v = float(v)
        k = self._key(labels)
        i = bisect.bisect_left(self.buckets, v)  # first bound >= v; len=+Inf
        with self._reg._lock:
            st = self._series.get(k)
            if st is None:
                st = self._series[k] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0}
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1


class MetricsRegistry:
    """Named metric families, registered lazily and idempotently.

    Re-registering an existing name with the same kind/labels (and buckets,
    for histograms) returns the SAME family object; a conflicting
    re-registration raises `InvalidArgumentError` — two subsystems cannot
    silently write incompatible series under one name."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict = {}

    def _register(self, cls, name, help, labelnames, **extra):
        if not _NAME_RE.match(name or ""):
            raise InvalidArgumentError(f"Invalid metric name {name!r}.")
        labelnames = tuple(labelnames)
        for ln in labelnames:
            if not _LABEL_RE.match(ln or ""):
                raise InvalidArgumentError(
                    f"Invalid label name {ln!r} for metric {name}.")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                same = (fam.kind == cls.kind
                        and fam.labelnames == labelnames
                        and extra.get("buckets",
                                      getattr(fam, "buckets", None))
                        == getattr(fam, "buckets", None))
                if not same:
                    raise InvalidArgumentError(
                        f"Metric {name} is already registered as a "
                        f"{fam.kind} with labels {fam.labelnames}; cannot "
                        f"re-register as a {cls.kind} with {labelnames}.")
                return fam
            fam = cls(self, name, help, labelnames, **extra)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: tuple = ()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple = ()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames: tuple = (),
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=tuple(float(b) for b in buckets))

    def get(self, name: str):
        """The registered family, or None."""
        with self._lock:
            return self._families.get(name)

    def collect(self) -> list:
        """Snapshot of every family: ``[{name, kind, help, labelnames,
        series: [(labels_dict, value_or_hist_state), ...]}, ...]``, sorted
        by name; histogram states are deep-copied."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
            out = []
            for f in fams:
                series = []
                for k, v in f._series.items():
                    if isinstance(v, dict):  # histogram state
                        v = {"counts": list(v["counts"]),
                             "sum": v["sum"], "count": v["count"]}
                    series.append((dict(zip(f.labelnames, k)), v))
                rec = {"name": f.name, "kind": f.kind, "help": f.help,
                       "labelnames": f.labelnames, "series": series}
                if f.kind == "histogram":
                    rec["buckets"] = f.buckets
                out.append(rec)
        return out

    def scoped(self, **labels) -> "ScopedRegistry":
        """A view of this registry that namespaces every family it touches
        under fixed extra labels — the PER-JOB namespacing the multi-run
        scheduler uses (``reg.scoped(job="run42")``): a family registered
        through the view carries the scope's label names appended to its
        own, and every sample call fills them in automatically. Series
        from different scopes coexist in ONE family (one exported metric
        name, label-separated), exactly how Prometheus models tenants."""
        return ScopedRegistry(self, labels)

    def reset(self, name: str | None = None) -> None:
        """Zero every series of family ``name`` (or of ALL families).
        Registrations survive, so handles cached by callers stay valid."""
        with self._lock:
            if name is not None:
                fam = self._families.get(name)
                if fam is not None:
                    fam._series.clear()
                return
            for fam in self._families.values():
                fam._series.clear()


class _ScopedFamily:
    """A family handle that injects the scope's labels into every call.
    Mirrors the Counter/Gauge/Histogram sample surface (`inc`/`set`/`add`/
    `observe`/`value`); the underlying family is shared across scopes."""

    def __init__(self, family: _Family, labels: dict):
        self._fam = family
        self._labels = labels

    @property
    def name(self) -> str:
        return self._fam.name

    def _merge(self, labels: dict) -> dict:
        overlap = set(labels) & set(self._labels)
        if overlap:
            raise InvalidArgumentError(
                f"Metric {self._fam.name}: labels {sorted(overlap)} are "
                "fixed by the registry scope and cannot be overridden.")
        return {**labels, **self._labels}

    def inc(self, n: float = 1, **labels) -> None:
        self._fam.inc(n, **self._merge(labels))

    def set(self, v: float, **labels) -> None:
        self._fam.set(v, **self._merge(labels))

    def add(self, n: float, **labels) -> None:
        self._fam.add(n, **self._merge(labels))

    def observe(self, v: float, **labels) -> None:
        self._fam.observe(v, **self._merge(labels))

    def value(self, **labels) -> float:
        return self._fam.value(**self._merge(labels))


class ScopedRegistry:
    """A label-namespaced view of a `MetricsRegistry` (see
    `MetricsRegistry.scoped`). Registration appends the scope's label
    names to the family's own (idempotently against other scopes of the
    SAME label-name set — two jobs share one family); sample calls fill
    the scope's values in. ``remove_scope()`` drops exactly this scope's
    series from every family it touched — how the scheduler retires a
    finished job's gauges without zeroing the neighbors'."""

    def __init__(self, registry: MetricsRegistry, labels: dict):
        if not labels:
            raise InvalidArgumentError(
                "ScopedRegistry needs at least one scope label "
                "(e.g. job='run42').")
        for ln in labels:
            if not _LABEL_RE.match(ln or ""):
                raise InvalidArgumentError(
                    f"Invalid scope label name {ln!r}.")
        self.registry = registry
        self.labels = {k: str(v) for k, v in labels.items()}
        self._touched: set = set()

    def _scoped(self, fam: _Family) -> _ScopedFamily:
        self._touched.add(fam.name)
        return _ScopedFamily(fam, self.labels)

    def _labelnames(self, labelnames: tuple) -> tuple:
        clash = set(labelnames) & set(self.labels)
        if clash:
            raise InvalidArgumentError(
                f"Label name(s) {sorted(clash)} collide with the scope's.")
        return tuple(labelnames) + tuple(self.labels)

    def counter(self, name: str, help: str = "",
                labelnames: tuple = ()) -> _ScopedFamily:
        return self._scoped(self.registry.counter(
            name, help, self._labelnames(labelnames)))

    def gauge(self, name: str, help: str = "",
              labelnames: tuple = ()) -> _ScopedFamily:
        return self._scoped(self.registry.gauge(
            name, help, self._labelnames(labelnames)))

    def histogram(self, name: str, help: str = "", labelnames: tuple = (),
                  buckets: tuple = DEFAULT_BUCKETS) -> _ScopedFamily:
        return self._scoped(self.registry.histogram(
            name, help, self._labelnames(labelnames), buckets=buckets))

    def get(self, name: str):
        fam = self.registry.get(name)
        return None if fam is None else self._scoped(fam)

    def remove_scope(self) -> None:
        """Delete every series carrying THIS scope's label values from the
        families this view touched (other scopes' series survive)."""
        items = sorted(self.labels.items())
        with self.registry._lock:
            for name in self._touched:
                fam = self.registry._families.get(name)
                if fam is None:
                    continue
                pos = [fam.labelnames.index(ln) for ln, _ in items
                       if ln in fam.labelnames]
                vals = [v for ln, v in items if ln in fam.labelnames]
                for k in [k for k in fam._series
                          if [k[p] for p in pos] == vals]:
                    del fam._series[k]


_default = MetricsRegistry()


def metrics_registry() -> MetricsRegistry:
    """The process-default registry (what the framework's own
    instrumentation and `prometheus_snapshot()` use)."""
    return _default


def reset_metrics() -> None:
    """Zero every series in the default registry (test isolation /
    scrape-and-reset exporters). Family registrations survive."""
    _default.reset()
