"""Live metrics endpoint: a stdlib-only HTTP thread serving the registry.

Opt-in (`start_metrics_server(port)` or `run_resilient(metrics_port=...)`)
and deliberately tiny — `http.server.ThreadingHTTPServer` on a daemon
thread, zero dependencies, zero work on the step loop (the loop's only
related cost is the driver's per-chunk heartbeat gauge, two dict writes;
the serving happens entirely on the server's own threads when a scraper
actually connects):

- ``GET /metrics`` — `prometheus_snapshot()` of the process registry, in
  the text exposition format any Prometheus/victoria/grafana-agent
  scraper ingests directly;
- ``GET /healthz`` — JSON liveness: the age of the driver's last
  heartbeat (the ``igg_driver_heartbeat_timestamp_seconds`` gauge
  `runtime/driver.py` sets at every chunk boundary) plus the last
  committed step; returns 503 when ``healthz_max_age_s`` is set and the
  heartbeat is older (a wedged driver stops heartbeating — the signal a
  supervisor restarts on).

SECURITY: binds ``127.0.0.1`` by default. The bare /metrics + /healthz
pair is unauthenticated by design (it exposes only metrics). Extended
``routes`` surfaces (the serving tier's job API, observe plane, and
snapshot query service) can require a bearer token: pass
``auth_token=`` (the serve-tier servers default it from
``IGG_API_TOKEN``) and every routed request must carry
``Authorization: Bearer <token>`` — compared constant-time — or it is
answered 401; /metrics and /healthz stay open for scrapers and
supervisors (see docs/api.md).
"""

from __future__ import annotations

import hmac
import inspect
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.exceptions import InvalidArgumentError
from .export import prometheus_snapshot
from .hooks import (
    HEARTBEAT_STEP, HEARTBEAT_TS, JOB_HEARTBEAT_TS, SCHED_HEARTBEAT_TS,
    note_http_request,
)
from .registry import metrics_registry

__all__ = ["MetricsServer", "start_metrics_server", "stop_metrics_server",
           "metrics_server", "resolve_api_token"]


def _route_label(path: str) -> str:
    """Bounded-cardinality route label: the third path segment of a
    ``/v1/...`` route is where job/resource NAMES live (``/v1/jobs/x``,
    ``/v1/jobs/x/cancel``) — collapse it to ``{name}`` so the
    ``igg_http_requests_total`` label set stays one series per route
    pattern, not per tenant."""
    segs = path.strip("/").split("/")
    if len(segs) >= 3 and segs[0] == "v1":
        segs[2] = "{name}"
        return "/" + "/".join(segs)
    return path


def _routes_take_headers(fn) -> bool:
    """Back-compat probe: does the ``routes`` callable accept a 5th
    positional argument (the request headers)?  Older 4-arg routes keep
    working unchanged — the traceparent-aware serve tier opts in."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    n = 0
    for p in sig.parameters.values():
        if p.kind == p.VAR_POSITIONAL:
            return True
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            n += 1
    return n >= 5


def resolve_api_token(api_token) -> str | None:
    """The serve-tier servers' one token-resolution rule: ``None``
    defers to the ``IGG_API_TOKEN`` environment variable (unset or
    empty = unauthenticated), ``False`` forces an unauthenticated
    server even with the variable set, and a string is the token
    itself."""
    import os

    if api_token is False:
        return None
    if api_token is None:
        return os.environ.get("IGG_API_TOKEN") or None
    if not isinstance(api_token, str) or not api_token:
        raise InvalidArgumentError(
            "api_token must be a non-empty string, None (defer to "
            "IGG_API_TOKEN), or False (explicitly unauthenticated); "
            f"got {api_token!r}.")
    return api_token


class MetricsServer:
    """The running endpoint. ``port=0`` picks a free port (read ``.port``
    after construction — the pattern tests and parallel launchers use).
    Use as a context manager or call `close()`; the server thread is a
    daemon either way, so a crashed run never hangs on it.

    ``routes`` extends the surface beyond /metrics + /healthz (the
    serving tier's job API and snapshot query service ride on exactly
    this server): a callable ``(method, path, query, body) ->
    (code, body_bytes, ctype[, headers_dict]) | None`` — ``query`` is
    the RAW query string, ``body`` the request bytes (b"" for GET);
    return None to 404. A routes callable declaring a FIFTH positional
    parameter additionally receives the request headers (a mapping with
    ``.get``) — how the job API reads ``traceparent``; 4-arg routes are
    untouched. Every request is accounted in
    ``igg_http_requests_total{route,method,code}`` and the
    ``igg_http_request_seconds`` histogram (route label collapsed to
    its pattern, token-gate 401s included) in THIS server's registry. Route exceptions answer a JSON 500 (the server
    thread must survive any handler bug). ``auth_token`` gates the
    routed surface: every routed request (GET and POST alike) must
    carry ``Authorization: Bearer <token>`` or is answered 401 —
    /metrics and /healthz stay open.

    A route may return an ITERATOR of bytes instead of a body — the
    response then streams as HTTP/1.1 chunked transfer, one chunk per
    yielded block, flushed immediately (the ``/v1/events`` live feed).
    Exceptions raised while CREATING the iterator still 500 (raise them
    inside ``routes``, or build the generator's first state eagerly);
    once streaming began the status line is gone, so a mid-stream error
    or a hung-up consumer just ends the stream — resumable consumers
    re-request from their cursor."""

    def __init__(self, port: int = 0, *, host: str = "127.0.0.1",
                 registry=None, healthz_max_age_s: float | None = None,
                 routes=None, auth_token: str | None = None):
        reg = registry if registry is not None else metrics_registry()
        max_age = None if healthz_max_age_s is None \
            else float(healthz_max_age_s)
        if routes is not None and not callable(routes):
            raise InvalidArgumentError(
                "MetricsServer routes must be callable "
                "(method, path, query, body) -> response tuple or None.")
        # bearer auth covers the ROUTED surface only: /metrics and
        # /healthz stay open (scrapers and supervisors don't carry
        # credentials); the comparison is constant-time so the token
        # can't be recovered byte-by-byte from response timing
        token = None if auth_token is None else str(auth_token)
        if token == "":
            raise InvalidArgumentError(
                "auth_token must be a non-empty string (or None to "
                "serve the routed surface unauthenticated).")
        takes_headers = routes is not None and _routes_take_headers(routes)
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # chunked transfer (the streaming routes) needs HTTP/1.1;
            # every fixed response carries Content-Length, so keep-alive
            # stays correct for plain scrapes too
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: bytes, ctype: str,
                      headers: dict | None = None) -> None:
                self._resp_code = int(code)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _stream(self, code: int, chunks, ctype: str,
                        headers: dict | None = None) -> None:
                self._resp_code = int(code)
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Transfer-Encoding", "chunked")
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                try:
                    for chunk in chunks:
                        if not chunk:
                            continue
                        data = chunk if isinstance(chunk, bytes) \
                            else str(chunk).encode("utf-8")
                        self.wfile.write(b"%x\r\n" % len(data)
                                         + data + b"\r\n")
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except (ConnectionError, OSError):
                    # the consumer hung up mid-stream — its seq cursor
                    # resumes it; nothing to answer on a dead socket
                    self.close_connection = True
                except Exception:
                    # a generator bug after the status line went out:
                    # end the stream (the consumer sees truncation and
                    # re-requests); the server thread survives
                    self.close_connection = True

            def _route(self, method: str, body: bytes) -> None:
                path, _, query = self.path.partition("?")
                if routes is None:
                    self._send(404, b"not found\n", "text/plain")
                    return
                if token is not None:
                    auth = self.headers.get("Authorization") or ""
                    supplied = auth[7:].strip() \
                        if auth.startswith("Bearer ") else ""
                    if not hmac.compare_digest(supplied.encode("utf-8"),
                                               token.encode("utf-8")):
                        self._send(
                            401, json.dumps(
                                {"error": "missing or invalid bearer "
                                          "token"}).encode(),
                            "application/json",
                            {"WWW-Authenticate": "Bearer"})
                        return
                try:
                    resp = routes(method, path, query, body,
                                  self.headers) if takes_headers \
                        else routes(method, path, query, body)
                except Exception as e:
                    # a handler bug answers 500; the thread survives
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")
                    return
                if resp is None:
                    self._send(404, json.dumps(
                        {"error": f"no route for {method} {path}"}
                        ).encode(), "application/json")
                    return
                code, payload, ctype = resp[0], resp[1], resp[2]
                headers = resp[3] if len(resp) > 3 else None
                if isinstance(payload, (bytes, bytearray)):
                    self._send(int(code), bytes(payload), ctype, headers)
                else:
                    self._stream(int(code), payload, ctype, headers)

            def do_GET(self):
                t0 = time.monotonic()
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = prometheus_snapshot(reg).encode()
                    self._send(200, body,
                               "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    code, rec = outer._healthz()
                    self._send(code, json.dumps(rec).encode(),
                               "application/json")
                else:
                    self._route("GET", b"")
                self._account("GET", path, t0)

            def do_POST(self):
                t0 = time.monotonic()
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                except ValueError:
                    n = 0
                body = self.rfile.read(n) if n > 0 else b""
                self._route("POST", body)
                self._account("POST", self.path.partition("?")[0], t0)

            def _account(self, method: str, path: str, t0: float) -> None:
                # access telemetry for EVERY answered request (401s from
                # the token gate included); a streamed response accounts
                # its full stream lifetime. Never fails the request.
                try:
                    note_http_request(
                        _route_label(path), method,
                        getattr(self, "_resp_code", 0),
                        time.monotonic() - t0, scope=reg)
                except Exception:
                    pass

        self.registry = reg
        self.healthz_max_age_s = max_age
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"igg-metrics-server:{self.port}", daemon=True)
        self._thread.start()
        # ephemeral-port contract: port=0 binds a free port; the ACTUAL
        # port is readable from .port and from this gauge, so tests and
        # multi-tenant runs never hard-code (and collide on) a number
        from .hooks import note_metrics_server_port

        note_metrics_server_port(self.port)

    def _gauge_value(self, name):
        fam = self.registry.get(name)
        if fam is not None:
            samples = fam.samples()
            if samples:
                return samples[0][1]
        return None

    def _healthz(self):
        """(status_code, record): heartbeat age. When a scheduler owns the
        mesh its heartbeat (`igg_scheduler_heartbeat_timestamp_seconds`)
        is THE liveness — a single wedged job must not 503 the whole
        service — and per-job staleness moves to the labeled
        `igg_job_heartbeat_timestamp_seconds` gauges, echoed here as
        ``job_ages_s``. Plain supervised runs keep the driver gauge."""
        now = time.time()
        source = "driver"
        ts = self._gauge_value(SCHED_HEARTBEAT_TS)
        if ts is not None:
            source = "scheduler"
        else:
            ts = self._gauge_value(HEARTBEAT_TS)
        age = None if ts is None else now - ts
        step = self._gauge_value(HEARTBEAT_STEP)
        rec = {"ok": True, "heartbeat_age_s": age, "step": step,
               "max_age_s": self.healthz_max_age_s, "source": source}
        fam = self.registry.get(JOB_HEARTBEAT_TS)
        if fam is not None:
            jobs = {lbl.get("job", "?"): now - v
                    for lbl, v in fam.samples()}
            if jobs:
                rec["job_ages_s"] = dict(sorted(jobs.items()))
        if self.healthz_max_age_s is not None:
            rec["ok"] = age is not None and age <= self.healthz_max_age_s
        return (200 if rec["ok"] else 503), rec

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        from .hooks import note_metrics_server_port

        note_metrics_server_port(0)  # gauge reads 0 while no endpoint lives

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


_current: MetricsServer | None = None
_refs = 0
_lock = threading.Lock()


def start_metrics_server(port: int = 0, *, host: str = "127.0.0.1",
                         registry=None,
                         healthz_max_age_s: float | None = None
                         ) -> MetricsServer:
    """Start THE process metrics server, or ATTACH to the one already
    running (one endpoint per process; starts are refcounted — each
    `start_metrics_server` is balanced by one `stop_metrics_server`, and
    the socket closes only when the last holder stops). Attachment is what
    lets a scheduler-owned long-lived endpoint persist across jobs while a
    concurrent `run_resilient(metrics_port=...)` inside it still
    'starts' its server: the second start joins the first instead of
    failing to bind. An attach must be compatible: ``port`` 0 or the
    running server's own, same ``host``, same ``registry`` — a genuinely
    conflicting request still raises. The FIRST start's
    ``healthz_max_age_s`` wins (attachers observe, the owner configures).

    ``port=0`` binds an ephemeral port; the ACTUAL port is the returned
    server's ``.port`` and the ``igg_metrics_server_port`` gauge (0 again
    after the last stop). Binds ``127.0.0.1`` unless ``host`` says
    otherwise (see the module docstring's security note)."""
    global _current, _refs
    with _lock:
        if _current is not None:
            if int(port) not in (0, _current.port):
                raise InvalidArgumentError(
                    f"A metrics server is already running on "
                    f"{_current.host}:{_current.port}; a second start can "
                    f"attach (port=0 or {_current.port}) but not rebind "
                    f"to port {int(port)}.")
            if host != _current.host:
                raise InvalidArgumentError(
                    f"A metrics server is already running on host "
                    f"{_current.host}; cannot attach with host {host!r}.")
            if registry is not None and registry is not _current.registry:
                raise InvalidArgumentError(
                    "A metrics server is already running over a different "
                    "registry; stop it before serving another.")
            _refs += 1
            return _current
        _current = MetricsServer(port, host=host, registry=registry,
                                 healthz_max_age_s=healthz_max_age_s)
        _refs = 1
        return _current


def stop_metrics_server() -> None:
    """Release one hold on the process metrics server; the socket closes
    when the LAST holder releases (no-op when none is running)."""
    global _current, _refs
    with _lock:
        if _current is None:
            return
        _refs -= 1
        if _refs <= 0:
            _current.close()
            _current = None
            _refs = 0


def metrics_server() -> MetricsServer | None:
    """The running process metrics server, or None."""
    return _current
