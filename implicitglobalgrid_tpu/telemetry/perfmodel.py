"""The performance oracle: an analytical per-chunk cost model + drift watch.

PRs 3 and 5 made every run *measurable* (flight recorder, metrics,
mesh-wide aggregation); nothing could say whether a measurement was
*good*. This module is the missing judgment: a roofline over the implicit
global grid that combines

- the static halo wire plan (`ops.halo.halo_comm_plan` — bytes on wire,
  collective counts, wire dtype; already derived from shapes alone),
- a per-model step workload (stencil FLOPs + HBM traffic per cell,
  `STEP_WORKLOADS`), and
- a `MachineProfile` of MEASURED coefficients (achieved memory bandwidth,
  per-mesh-axis link bandwidth and collective latency —
  `telemetry.calibrate.calibrate_machine`; spec-based defaults exist but
  are labeled as such)

into a prediction of per-step compute time, per-axis communication time,
and exposed (un-overlapped) communication, classifying each configuration
as **latency-**, **bandwidth-**, or **compute-bound** (`predict_step`).
This is the substrate the ROADMAP's hierarchical-mesh auto-tuner needs:
picking ``comm_every`` / ``wire_dtype`` / coalescing per axis becomes a
search over this model instead of a from-scratch subsystem.

The live half is `PerfWatch`: a rolling per-chunk baseline (median + MAD
over a window, robust z-score) plus the measured/modeled ratio, driven by
`runtime/driver.py` at every chunk boundary — pure host arithmetic, zero
device work. A chunk whose per-step time drifts beyond the z threshold
emits a ``perf_regression`` flight event and the ``igg_perf_*`` gauges
feed the live ``/metrics`` endpoint; the PR-5 aggregation then
distinguishes a mesh-wide slowdown from one sick process
(`aggregate.straggler_report` ``perf_regressions``).

Everything here is host-side: the compiled chunk program is bit-identical
with the oracle on or off (tests/test_hlo_audit.py) and the per-boundary
cost is a few float ops (`bench_perf.py`, gated < 2%).
"""

from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass, field as dc_field

from ..utils.exceptions import InvalidArgumentError

__all__ = ["MachineProfile", "StepWorkload", "STEP_WORKLOADS",
           "default_machine_profile", "hierarchical_machine_profile",
           "load_machine_profile", "save_machine_profile", "predict_step",
           "predict_reshard", "ReshardPrediction", "PerfWatch", "robust_z"]

_PROFILE_VERSION = 1


@dataclass(frozen=True)
class MachineProfile:
    """Measured (or default) machine coefficients the cost model consumes.

    ``membw_GBps``/``flops_G`` are PER-DEVICE achieved rates (on the
    emulated CPU mesh the virtual devices share the host's cores — a
    calibration over the live mesh measures exactly that contention,
    which is why calibrated beats spec'ed). ``axes`` maps mesh axis names
    (``gx``/``gy``/``gz``) to ``{"GBps", "latency_s"}``: the effective
    one-direction link bandwidth and the per-ppermute-PAIR launch latency
    of an exchange along that axis (both directions' concurrency is
    absorbed into the effective bandwidth — the calibration measures the
    same forward+backward pair shape the exchange issues).
    ``source`` is ``"calibrated"`` or ``"default"`` so a prediction can
    always say whether measured coefficients backed it."""

    membw_GBps: float
    flops_G: float
    axes: dict
    source: str = "default"
    device: dict | None = None
    calibrated_at: float | None = None
    meta: dict = dc_field(default_factory=dict)

    def axis(self, name: str) -> dict:
        """Link coefficients for one mesh axis (falls back to the mean of
        the calibrated axes, then to conservative defaults, so a profile
        calibrated on a 1-D mesh still prices a 3-D one)."""
        rec = self.axes.get(name)
        if rec and rec.get("GBps"):
            return rec
        have = [r for r in self.axes.values() if r and r.get("GBps")]
        if have:
            return {"GBps": sum(r["GBps"] for r in have) / len(have),
                    "latency_s": sum(r.get("latency_s", 0.0)
                                     for r in have) / len(have)}
        return {"GBps": 1.0, "latency_s": 1e-4}

    def to_json(self) -> dict:
        return {"version": _PROFILE_VERSION,
                "membw_GBps": self.membw_GBps, "flops_G": self.flops_G,
                "axes": self.axes, "source": self.source,
                "device": self.device, "calibrated_at": self.calibrated_at,
                "meta": self.meta}


def default_machine_profile(device_type: str | None = None) -> MachineProfile:
    """Spec-flavored fallback coefficients (``source="default"``) — use
    `telemetry.calibrate.calibrate_machine` for measured ones. With no
    argument, the current grid's device type is used."""
    if device_type is None:
        from ..parallel.topology import global_grid

        device_type = global_grid().device_type
    if device_type == "tpu":
        # v5e-flavored: ~800 GB/s HBM, ~45 GB/s/direction ICI per link,
        # microsecond-scale collective launch; f32 vector flops
        axes = {a: {"GBps": 45.0, "latency_s": 5e-6}
                for a in ("gx", "gy", "gz")}
        return MachineProfile(membw_GBps=800.0, flops_G=45000.0, axes=axes,
                              source="default",
                              device={"platform": "tpu"})
    # emulated CPU mesh: the 8 virtual devices share one host's cores
    axes = {a: {"GBps": 4.0, "latency_s": 3e-5} for a in ("gx", "gy", "gz")}
    return MachineProfile(membw_GBps=6.0, flops_G=6.0, axes=axes,
                          source="default",
                          device={"platform": device_type or "cpu"})


def hierarchical_machine_profile() -> MachineProfile:
    """Canned hierarchical ICI+DCN coefficients (``source="default"``):
    ``gx``/``gy`` at ICI-class rates and ``gz`` at DCN-class rates (an
    order of magnitude less bandwidth, an order of magnitude more launch
    latency — the multi-slice pod shape the topology-staged wire exists
    for). Lets the staged-vs-flat pricing, the tuner's staged candidate
    leg, and the bench's modeled rows run on a dev box whose real links
    are all one class — the same modeled-rescue pattern as the
    comm-avoiding bench rows. Calibrate on the real pod for measured
    coefficients."""
    axes = {"gx": {"GBps": 45.0, "latency_s": 5e-6},
            "gy": {"GBps": 45.0, "latency_s": 5e-6},
            "gz": {"GBps": 2.0, "latency_s": 5e-5}}
    return MachineProfile(membw_GBps=800.0, flops_G=45000.0, axes=axes,
                          source="default",
                          device={"platform": "tpu"},
                          meta={"preset": "hierarchical",
                                "dcn_axes": ["z"]})


def save_machine_profile(profile: MachineProfile, path) -> str:
    """Persist a profile as JSON (the file `load_machine_profile` and the
    ``tools calibrate`` CLI exchange)."""
    path = os.fspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(profile.to_json(), f, indent=1)
    return path


def load_machine_profile(path) -> MachineProfile:
    path = os.fspath(path)
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        raise InvalidArgumentError(
            f"load_machine_profile: cannot read {path}: {e}") from e
    try:
        return MachineProfile(
            membw_GBps=float(rec["membw_GBps"]),
            flops_G=float(rec["flops_G"]),
            axes={str(k): dict(v) for k, v in rec.get("axes", {}).items()},
            source=str(rec.get("source", "calibrated")),
            device=rec.get("device"),
            calibrated_at=rec.get("calibrated_at"),
            meta=rec.get("meta", {}))
    except (KeyError, TypeError, ValueError) as e:
        raise InvalidArgumentError(
            f"load_machine_profile: {path} is not a machine profile "
            f"({e}).") from e


@dataclass(frozen=True)
class StepWorkload:
    """Per-cell step cost + exchange structure of one model family.

    ``flops_per_cell`` counts the stencil arithmetic (priced at the
    profile's STENCIL-calibrated FLOP rate — slice-heavy code, not peak
    FMA); ``hbm_passes`` the HBM traffic in array passes (bytes = passes
    * itemsize * cells: state reads + writes plus a slack pass for
    materialized intermediates). ``exchange_groups`` describes how the
    step actually calls the exchange: one tuple of FIELD INDICES per
    `local_update_halo` round (fields in one round coalesce into one
    ppermute pair per axis; separate rounds pay separate launches) —
    diffusion exchanges only T, the acoustic leapfrog does a V round
    then a P round. ``fused_exchange_groups`` are the rounds the Pallas
    FUSED pass issues when they differ (the acoustic kernel exchanges all
    four fields in ONE packed round where the XLA leapfrog does two);
    ``None`` means the tiers share the same rounds. Since the fused tier
    rides the canonical wire schema (`ops.wire`), these rounds price —
    and contract-audit — Pallas programs exactly like XLA ones
    (`groups_for`).

    ``deep_exchange_groups`` are the rounds of the deep-halo
    (``comm_every``) runner when they differ from the per-step scheme:
    the deep super-step exchanges its whole evolving state in ONE
    coalesced round per due axis (acoustic: one 4-field round replaces
    the V round + P round; Stokes: one 7-field round incl. dV), and
    ``deep_halo_depth`` is the scheme's per-sub-step dependency radius
    (slab width = depth * k_d — 2 for the Stokes PT iteration).
    Deliberate single-digit precision throughout: the model's job is
    picking the right regime and being within 2x, not reproducing a
    cycle simulator."""

    flops_per_cell: float
    hbm_passes: float
    exchange_groups: tuple = ((0,),)
    fused_exchange_groups: tuple | None = None
    deep_exchange_groups: tuple | None = None
    deep_halo_depth: int = 1

    def groups_for(self, impl: str = "xla", deep: bool = False) -> tuple:
        """The exchange rounds of one kernel tier: ``impl="xla"`` (or any
        non-Pallas spelling) prices the XLA step's rounds; a Pallas impl
        prices the fused pass's (same rounds unless the workload declares
        ``fused_exchange_groups``). ``deep=True`` prices the deep-halo
        runner's rounds (`deep_exchange_groups` when declared — the
        cadence tier is XLA-only, so ``deep`` wins over ``impl``)."""
        if deep and self.deep_exchange_groups is not None:
            return self.deep_exchange_groups
        if str(impl).startswith("pallas") \
                and self.fused_exchange_groups is not None:
            return self.fused_exchange_groups
        return self.exchange_groups


# One entry per model family in `models/` (validated against the measured
# bench configs in bench_perf.py / BENCH_ALL.json `model_ratio` fields).
STEP_WORKLOADS = {
    # flux (3 diffs, 3 muls) + divergence (5) + Cp array-div + update;
    # only T is exchanged (Cp is a constant coefficient field)
    "diffusion3d": StepWorkload(flops_per_cell=22.0, hbm_passes=4.0,
                                exchange_groups=((0,),)),
    "diffusion2d": StepWorkload(flops_per_cell=14.0, hbm_passes=4.0,
                                exchange_groups=((0,),)),
    # state (P, Vx, Vy, Vz): the leapfrog exchanges the 3 V fields in one
    # coalesced round, then P in its own round (overlapped when enabled);
    # the FUSED Pallas pass packs all four fields into ONE round, and so
    # does the deep-halo super-step (per due axis)
    "acoustic3d": StepWorkload(flops_per_cell=20.0, hbm_passes=8.0,
                               exchange_groups=((1, 2, 3), (0,)),
                               fused_exchange_groups=((0, 1, 2, 3),),
                               deep_exchange_groups=((0, 1, 2, 3),)),
    # state (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog): one coalesced round of
    # the 4 wave fields per PT iteration (models/stokes.py:185); the
    # deep-halo scheme exchanges the 7 evolving fields (dV included) at
    # radius-2 slabs (StokesParams.comm_every)
    "stokes3d": StepWorkload(flops_per_cell=60.0, hbm_passes=16.0,
                             exchange_groups=((1, 2, 3, 0),),
                             deep_exchange_groups=((0, 1, 2, 3, 4, 5, 6),),
                             deep_halo_depth=2),
}


def _axis_npairs(gg, dim: int) -> int:
    """Number of directed links an exchange's ppermute pair spans along
    ``dim`` (the divisor that turns the plan's all-links ``wire_bytes``
    into the one-direction per-link payload the link model prices)."""
    from ..ops.halo import _perm_pairs

    D = int(gg.dims[dim])
    periodic = bool(gg.periods[dim])
    perm_p, perm_m = _perm_pairs(D, periodic, int(gg.disp))
    return len(perm_p) + len(perm_m)


def predict_step(model, fields, *, profile: MachineProfile | None = None,
                 comm_every=1, overlap: bool = False,
                 dims=None, coalesce=None, wire_dtype=None, wire_stage=None,
                 impl: str = "xla", ensemble: int | None = None) -> dict:
    """Predict one step's cost on the CURRENT grid for stacked ``fields``.

    ``model`` is a `STEP_WORKLOADS` key or a `StepWorkload`; ``fields``
    are the stacked state arrays (or anything with shape/dtype, incl.
    ``(A, halowidths)`` tuples / `ops.fields.Field` for candidate slab
    widths) in the model's canonical state order — the workload's
    ``exchange_groups`` index into them to price each exchange round
    exactly as the step issues it (same argument forms as
    `halo_comm_plan`). ``profile`` defaults to
    `default_machine_profile()` (pass a calibrated one for measured
    coefficients). ``comm_every`` prices the deep-halo cadence — an int
    ``k`` or a PER-AXIS spec (``"z:4,x:1"`` / dict / `CommCadence`, the
    `resolve_comm_every` spelling family): each axis's exchange (whose
    k_d-wide slabs the fields' halowidths already describe) is charged
    once per ``k_d`` steps — the latency term divides by THAT axis's
    cadence, which is exactly the per-link-class amortization the
    auto-tuner (`telemetry.tune`) searches over. A deep cadence also
    switches the priced rounds to the deep runner's
    (`StepWorkload.groups_for(deep=True)` — e.g. acoustic's one 4-field
    round per due axis instead of the per-step V + P rounds).
    ``overlap`` credits communication that hides behind interior compute
    (the interior-first step shape of `hide_communication` / the
    latency-hiding scheduler). The credit is priced from the slab
    geometry of the wire schema: only the INTERIOR fraction of the
    compute can hide the wire — the boundary-shell update (the overlap
    bands each exchanging dim peels off) must complete BEFORE the
    collectives launch, so exposed comm = max(0, comm - compute *
    interior_frac) and the returned record carries ``interior_frac``.
    ``impl`` selects the kernel tier's exchange rounds
    (`StepWorkload.groups_for` — the fused Pallas pass may group rounds
    differently, e.g. acoustic's one packed 4-field round).

    ``wire_stage`` prices the topology-staged wire (`ops.halo` — the
    `resolve_wire_stage` spelling family, e.g. ``"z:staged"``): a staged
    axis's gather/scatter/intra hops are priced against the GATHER
    axis's (ICI) link coefficients while its one striped DCN transfer is
    priced against the staged axis's own (DCN) coefficients — each stage
    against the link class it actually crosses. The axis's comm record
    then carries a ``staged`` sub-record with the per-stage seconds, the
    flat-wire alternative priced on the same coefficients
    (``flat_s``/``staged_s``/``wins``) and the per-DCN-link message-fold
    ``dcn_msgs_ratio`` — the staged-vs-flat verdict the auto-tuner's
    candidate generator reads. When a latency-bound verdict lands on an
    axis the staging could (or does) fold, ``bound_detail`` names
    ``wire_stage[z]`` — the knob to turn.

    ``ensemble=E`` prices the ENSEMBLE axis (ISSUE 12): E scenario
    members batched through one chunk — compute and wire bytes scale by
    E while the collective LAUNCH count (and so the latency term) stays
    flat, which is exactly the amortization the ensemble exists for. The
    record then carries the byte-exact E-scaled totals plus the
    ``per_member_*`` fields (``per_member_step_s``, ``per_member_comm_s``,
    ``per_member_exposed_comm_s``), the solo prediction (``solo_step_s``)
    and ``ensemble_amortization`` = per-member / solo step time — the
    knob a tuner searches over E with, like any other wire knob.

    Returns a record with per-step seconds and the roofline verdict::

        {"model", "profile_source", "local_cells",
         "compute": {"flops", "hbm_bytes", "flops_s", "hbm_s", "s"},
         "comm":    {axis: {"ppermute_pairs", "per_link_bytes",
                            "latency_s", "wire_s", "s"}, ...},
         "local_copy_s", "comm_s", "exposed_comm_s",
         "step_s", "bound", "bound_detail", "terms"}

    ``bound`` is the largest cost term's class — ``"compute"`` (FLOPs),
    ``"bandwidth"`` (HBM or wire bytes; ``bound_detail`` says which), or
    ``"latency"`` (collective launches) — the knob-picking signal: a
    latency-bound config wants ``comm_every``/coalescing (and
    ``bound_detail`` names the latency-dominant AXIS's knob, e.g.
    ``comm_every[z]`` — the per-axis cadence the tuner turns), a
    bandwidth-bound one wants ``wire_dtype``, a compute-bound one is
    already at the roofline."""
    from ..ops.halo import halo_comm_plan
    from ..ops.wire import resolve_comm_every, resolve_wire_stage
    from ..parallel.topology import (
        check_initialized, global_grid, staged_wire_layout,
    )

    check_initialized()
    gg = global_grid()
    if isinstance(model, StepWorkload):
        work, model_name = model, "custom"
    else:
        work = STEP_WORKLOADS.get(str(model))
        if work is None:
            raise InvalidArgumentError(
                f"predict_step: unknown model {model!r} (have "
                f"{sorted(STEP_WORKLOADS)}; or pass a StepWorkload).")
        model_name = str(model)
    profile = profile if profile is not None else default_machine_profile()
    cad = resolve_comm_every(comm_every)
    stg = resolve_wire_stage(wire_stage)
    E = 1
    if ensemble is not None:
        E = int(ensemble)
        if E < 1:
            raise InvalidArgumentError(
                f"predict_step: ensemble must be >= 1; got {ensemble}.")

    # one wire plan per exchange ROUND the step actually performs (fields
    # in a round coalesce; separate rounds pay separate launches), merged
    # into per-axis totals
    fields = tuple(fields)
    plan = {"axes": {}, "local_copy_by_axis": {}}
    for group in work.groups_for(impl, deep=cad.deep):
        if any(i >= len(fields) for i in group):
            raise InvalidArgumentError(
                f"predict_step: model {model_name!r} expects at least "
                f"{max(group) + 1} fields in its state order "
                f"(exchange group {group}); got {len(fields)}.")
        sub = halo_comm_plan(*(fields[i] for i in group), dims=dims,
                             coalesce=coalesce, wire_dtype=wire_dtype,
                             ensemble=ensemble, wire_stage=stg)
        for axis, rec in sub["axes"].items():
            dst = plan["axes"].setdefault(
                axis, {"ppermutes": 0, "wire_bytes": 0})
            dst["ppermutes"] += rec["ppermutes"]
            dst["wire_bytes"] += rec["wire_bytes"]
            if "staged" in rec:  # merge rounds' stage tables (one layout)
                det = dst.setdefault(
                    "staged", {k: v for k, v in rec["staged"].items()
                               if k != "stages"} | {"stages": []})
                det["stages"].extend(rec["staged"]["stages"])
        for axis, b in sub["local_copy_by_axis"].items():
            plan["local_copy_by_axis"][axis] = (
                plan["local_copy_by_axis"].get(axis, 0) + b)
    # interior cells of the primary (first) field's LOCAL block
    shape0 = _shape_of(fields[0])
    local_cells = 1
    for d, s in enumerate(shape0):
        local_cells *= s // int(gg.dims[d]) if d < 3 else s

    itemsize = _itemsize_of(fields[0])
    # compute scales with the member count; the wire plan above already
    # carries the E x payloads (same launches — the latency term below is
    # the one cost the ensemble does NOT multiply)
    flops = work.flops_per_cell * local_cells * E
    hbm_bytes = work.hbm_passes * itemsize * local_cells * E
    flops_s = flops / (profile.flops_G * 1e9)
    hbm_s = hbm_bytes / (profile.membw_GBps * 1e9)
    compute_s = max(flops_s, hbm_s)

    axis_dims = {"gx": 0, "gy": 1, "gz": 2}
    comm = {}
    lat_total = wire_total = 0.0
    for axis, rec in plan["axes"].items():
        coeff = profile.axis(axis)
        pairs = rec["ppermutes"] / 2.0
        # PER-AXIS amortization: this axis's exchange fires once per its
        # OWN cadence (the k_d-wide slabs are already in the plan's
        # bytes, so per-step wire bytes stay flat while launches divide)
        k_ax = cad.for_dim(axis_dims[axis])
        if "staged" in rec:
            # hierarchical three-stage pricing: every stage against the
            # link class it actually crosses — gather/scatter/intra hops
            # on the GATHER axis's (ICI) coefficients, the one striped
            # transfer on this (DCN) axis's own. Each stage-table entry
            # is one direction; the two directions' concurrency folds
            # into a pair (ops/2), same convention as the flat pair.
            det = rec["staged"]
            ici = profile.axis(det["gather_axis"])
            lat_s = wire_s = flat_lat = flat_wire = per_link = 0.0
            stage_s: dict = {}
            flat_groups = set()
            for st in det["stages"]:
                cls = coeff if st["stage"] == "dcn" else ici
                pr = st["ops"] / 2.0
                ls = pr * float(cls.get("latency_s", 0.0)) / k_ax
                ws = pr * st["payload_bytes"] \
                    / (float(cls["GBps"]) * 1e9) / k_ax
                lat_s += ls
                wire_s += ws
                per_link += pr * st["payload_bytes"]
                stage_s[st["stage"]] = (
                    stage_s.get(st["stage"], 0.0) + ls + ws)
                if st["stage"] in ("gather", "intra") \
                        and st["group"] not in flat_groups:
                    # the flat alternative on THIS axis's link class: the
                    # fold devices of a granule share ONE physical DCN
                    # bundle per granule-pair, so the flat pair's fold
                    # messages SERIALIZE through it — M*lat + M*slab/bw
                    flat_groups.add(st["group"])
                    flat_lat += det["fold"] \
                        * float(coeff.get("latency_s", 0.0)) / k_ax
                    flat_wire += det["fold"] * st["payload_bytes"] \
                        / (float(coeff["GBps"]) * 1e9) / k_ax
            staged_s = lat_s + wire_s
            flat_s = flat_lat + flat_wire
            comm[axis] = {
                "ppermute_pairs": pairs, "per_link_bytes": per_link,
                "comm_every": k_ax,
                "latency_s": lat_s, "wire_s": wire_s,
                "s": staged_s,
                "staged": {
                    "fold": det["fold"],
                    "gather_axis": det["gather_axis"],
                    "dcn_pairs": det["dcn_pairs"],
                    "flat_dcn_pairs": det["flat_dcn_pairs"],
                    "dcn_msgs_ratio": (det["flat_dcn_pairs"]
                                       / max(1, det["dcn_pairs"])),
                    "stage_s": stage_s,
                    "staged_s": staged_s,
                    "flat_s": flat_s,
                    "wins": staged_s < flat_s,
                },
            }
            lat_total += lat_s
            wire_total += wire_s
            continue
        npairs = _axis_npairs(gg, axis_dims[axis])
        per_link = (rec["wire_bytes"] / npairs) if npairs else 0.0
        # a flat exchange on a granule-crossing axis funnels the fold
        # devices' messages through ONE physical DCN bundle per
        # granule-pair — they serialize: M*lat + M*slab/bw (the cost the
        # topology-staged wire folds back to 1 message per bundle)
        lay = staged_wire_layout(gg, axis_dims[axis])
        mult = int(lay.fold) if lay is not None else 1
        lat_s = pairs * mult * float(coeff.get("latency_s", 0.0)) / k_ax
        wire_s = per_link * mult / (float(coeff["GBps"]) * 1e9) / k_ax
        comm[axis] = {"ppermute_pairs": pairs, "per_link_bytes": per_link,
                      "comm_every": k_ax,
                      "latency_s": lat_s, "wire_s": wire_s,
                      "s": lat_s + wire_s}
        if mult > 1:
            comm[axis]["dcn_msgs_per_link"] = mult
        lat_total += lat_s
        wire_total += wire_s
    # self-neighbor local slab swaps never touch the wire: they are HBM
    # traffic (read + write) at the memory-bandwidth coefficient,
    # amortized per axis like the collectives they stand in for
    local_copy_s = sum(
        2.0 * b / (profile.membw_GBps * 1e9) / cad.for_dim(axis_dims[a])
        for a, b in plan["local_copy_by_axis"].items())
    comm_s = lat_total + wire_total + local_copy_s
    # interior-first overlap credit, priced from the slab geometry: each
    # exchanging dim peels a 2*ol-deep boundary shell off the local block
    # that must compute BEFORE the collectives launch — only the interior
    # remainder schedules under them
    interior_frac = 1.0
    if overlap:
        interior = 1
        for d in range(min(3, len(shape0))):
            n_d = shape0[d] // int(gg.dims[d])
            D = int(gg.dims[d])
            if D > 1 or bool(gg.periods[d]):
                n_d = max(0, n_d - 2 * int(gg.overlaps[d]))
            interior *= n_d
        interior_frac = interior / max(1, local_cells)
    exposed = max(0.0, comm_s - compute_s * interior_frac) if overlap \
        else comm_s
    step_s = compute_s + exposed

    # roofline verdict: the largest EXPOSED term names the regime
    scale = (exposed / comm_s) if (overlap and comm_s > 0) else 1.0
    terms = {"flops_s": flops_s, "hbm_s": hbm_s,
             "latency_s": lat_total * scale,
             "wire_s": (wire_total + local_copy_s) * scale}
    worst = max(terms, key=terms.get)
    bound = {"flops_s": "compute", "hbm_s": "bandwidth",
             "latency_s": "latency", "wire_s": "bandwidth"}[worst]
    detail = {"flops_s": "flops", "hbm_s": "hbm",
              "latency_s": "collective-launch", "wire_s": "wire"}[worst]
    if worst == "latency_s" and comm:
        # name the latency-DOMINANT axis's knob: the verdict points at
        # the per-axis cadence the auto-tuner will actually turn
        # ("comm_every[z]"), not an undifferentiated global setting
        dom = max(comm, key=lambda a: comm[a]["latency_s"])
        detail = f"comm_every[{'xyz'[axis_dims[dom]]}]"
        if "staged" in comm[dom]:
            # the staged wire's own launches dominate: name its knob
            detail = f"wire_stage[{'xyz'[axis_dims[dom]]}]"
        elif staged_wire_layout(gg, axis_dims[dom]) is not None:
            # a flat DCN-crossing axis whose granule geometry supports
            # staging: the fold IS the latency knob — name it
            detail = f"wire_stage[{'xyz'[axis_dims[dom]]}]"
    rec = {
        "model": model_name,
        "profile_source": profile.source,
        "local_cells": local_cells,
        "ensemble": E,
        "comm_every": str(cad),
        "wire_stage": None if stg is None else str(stg),
        "compute": {"flops": flops, "hbm_bytes": hbm_bytes,
                    "flops_s": flops_s, "hbm_s": hbm_s, "s": compute_s},
        "comm": comm,
        "local_copy_s": local_copy_s,
        "comm_s": comm_s,
        "interior_frac": interior_frac,
        "exposed_comm_s": exposed,
        "step_s": step_s,
        "bound": bound,
        "bound_detail": detail,
        "terms": terms,
    }
    if E > 1:
        # the priced amortization the ROADMAP auto-tuner searches over E
        # with: per-member cost vs the solo prediction of the SAME config
        # (pure host arithmetic — one recursive plan merge, no devices)
        solo = predict_step(model, fields, profile=profile,
                            comm_every=comm_every, overlap=overlap,
                            dims=dims, coalesce=coalesce,
                            wire_dtype=wire_dtype, wire_stage=stg,
                            impl=impl)
        rec["per_member_step_s"] = step_s / E
        rec["per_member_comm_s"] = comm_s / E
        rec["per_member_exposed_comm_s"] = exposed / E
        rec["solo_step_s"] = solo["step_s"]
        rec["ensemble_amortization"] = (
            (step_s / E) / solo["step_s"] if solo["step_s"] > 0 else 1.0)
    return rec


class ReshardPrediction(dict):
    """`predict_reshard`'s record — a plain dict (JSON-serializes
    unchanged, every existing ``rec["seconds"]`` consumer keeps working)
    that ALSO carries the one break-even arithmetic the autoscaler,
    ``tools reshard plan``, and `service_report` share. Keeping the
    amortization here, next to the transfer price it amortizes, means
    the three consumers cannot drift on it."""

    def amortized_break_even_steps(self, nt_remaining,
                                   old_step_s, new_step_s) -> dict:
        """Amortize this reshard's one-time cost over the steady-state
        per-step gain of the new geometry. ``nt_remaining`` is the steps
        (nt units) left in the job's horizon; ``old_step_s`` /
        ``new_step_s`` are the per-unit prices on the current and
        candidate decompositions (same source — both modeled or both
        measured — or the ratio lies).

        Returns a JSON-able record: ``gain_s_per_step`` (old - new;
        negative = the move is a slowdown), ``break_even_steps``
        (reshard seconds / gain — ``None`` when there is no gain to
        amortize against), ``within_horizon`` (the break-even lands
        inside ``nt_remaining`` — the autoscaler's grow gate), and
        ``net_gain_s`` (what the move is worth over the whole remaining
        horizon, transfer cost included; for a shrink this is the
        priced slowdown the job must be able to afford)."""
        reshard_s = float(self["seconds"])
        old_step_s = float(old_step_s)
        new_step_s = float(new_step_s)
        nt_remaining = max(0, int(nt_remaining))
        gain = old_step_s - new_step_s
        break_even = reshard_s / gain if gain > 0 else None
        return {
            "reshard_s": reshard_s,
            "old_step_s": old_step_s,
            "new_step_s": new_step_s,
            "gain_s_per_step": gain,
            "break_even_steps": break_even,
            "nt_remaining": nt_remaining,
            "within_horizon": bool(break_even is not None
                                   and break_even <= nt_remaining),
            "net_gain_s": gain * nt_remaining - reshard_s,
        }


def predict_reshard(plan, *,
                    profile: MachineProfile | None = None
                    ) -> ReshardPrediction:
    """Static price of one on-device reshard program
    (`reshard.build_reshard_plan` output) — the `halo_comm_plan`-style
    accounting of the elastic resize (ISSUE 14): per scheduled round, one
    collective launch (the latency term) plus the round's padded
    per-device payload over the link bandwidth; same-device pieces are
    HBM read+write traffic at the memory-bandwidth coefficient. Link
    coefficients come from `MachineProfile.axis("rs")` — the flat
    transfer mesh crosses arbitrary mesh links, so the mean of the
    calibrated axes is the honest single number.

    Returns a `ReshardPrediction` — a dict ``{"rounds", "wire_bytes",
    "local_bytes", "peak_payload_bytes", "latency_s", "wire_s",
    "local_s", "seconds", "profile_source"}`` whose
    `amortized_break_even_steps` method is the ONE place the break-even
    arithmetic lives (autoscaler, ``tools reshard plan``, and
    `service_report` all call it). The DISK path this replaces pays the sharded
    save + elastic restore instead — `bench_reshard.py` measures both
    and gates ``reshard_vs_disk_speedup >= 1.0``; this record is the
    model-side anchor the perfdb trajectory watches."""
    if profile is None:
        from ..parallel.topology import grid_is_initialized

        profile = (default_machine_profile() if grid_is_initialized()
                   else default_machine_profile("cpu"))
    coeff = profile.axis("rs")
    per_round = [b for sig in plan.sigs for b in sig.round_payload_bytes]
    latency_s = len(per_round) * float(coeff.get("latency_s", 0.0))
    wire_s = sum(b / (float(coeff["GBps"]) * 1e9) for b in per_round)
    local_s = 2.0 * plan.local_bytes / (profile.membw_GBps * 1e9)
    return ReshardPrediction({
        "rounds": plan.rounds,
        "wire_bytes": plan.wire_bytes,
        "local_bytes": plan.local_bytes,
        "peak_payload_bytes": plan.peak_payload_bytes,
        "latency_s": latency_s,
        "wire_s": wire_s,
        "local_s": local_s,
        "seconds": latency_s + wire_s + local_s,
        "profile_source": profile.source,
    })


def _unwrap_field(f):
    """The bare array-like of a `halo_comm_plan`-style field argument:
    `ops.fields.Field` and ``(A, halowidths)`` tuples (the per-candidate
    slab-width form the auto-tuner prices with) unwrap to their array."""
    from ..ops.fields import Field

    if isinstance(f, Field):
        return f.A
    if isinstance(f, tuple) and len(f) == 2 and hasattr(f[0], "shape") \
            and not hasattr(f[1], "shape"):
        return f[0]
    return f


def _shape_of(f) -> tuple:
    return tuple(int(s) for s in _unwrap_field(f).shape)


def _itemsize_of(f) -> int:
    import numpy as np

    try:
        return int(np.dtype(_unwrap_field(f).dtype).itemsize)
    except Exception:
        return 4


def robust_z(value: float, history, *, rel_floor: float = 0.02,
             min_samples: int = 2) -> tuple:
    """The house robust z-score: ``(z, median, mad)`` of ``value``
    against ``history`` (an iterable of floats), with

        z = (value - median) / max(1.4826 * MAD, rel_floor * median, 1e-12)

    — the one estimator shared by `PerfWatch` (in-driver drift detection)
    and `telemetry.live.LiveAggregate` (observer-side tailing), so the
    two planes can never disagree on what counts as a regression. Returns
    ``(None, None, None)`` before ``min_samples`` history entries."""
    from statistics import median

    hist = list(history)
    if len(hist) < max(2, int(min_samples)):
        return None, None, None
    med = median(hist)
    mad = median([abs(x - med) for x in hist])
    sigma = max(1.4826 * mad, rel_floor * med, 1e-12)
    return (float(value) - med) / sigma, med, mad


class PerfWatch:
    """Live drift detector over per-chunk step times (host-side only).

    The driver feeds it one observation per chunk boundary
    (``observe(...)``); it maintains a rolling baseline of per-STEP
    execution time (median + MAD over ``window`` chunks — robust to the
    occasional slow fetch) and a modeled ratio when a prediction is
    given. An observation whose robust z-score

        z = (per_step - median) / max(1.4826 * MAD, rel_floor * median)

    exceeds ``zmax`` (after ``min_samples`` warm-up chunks) returns a
    regression record the driver emits as a ``perf_regression`` flight
    event. Chunks marked ``cold`` (the dispatch paid an XLA compile after
    a runner-cache miss) update the gauges but neither test nor pollute
    the baseline. Every observation lands in the ``igg_perf_*`` gauges
    (`telemetry.hooks.observe_perf`), so the live ``/metrics`` endpoint
    always shows the current per-step time, model ratio, and z-score."""

    def __init__(self, *, window: int = 16, zmax: float = 4.0,
                 model_step_s: float | None = None, min_samples: int = 5,
                 rel_floor: float = 0.02):
        if window < 2:
            raise InvalidArgumentError(
                f"PerfWatch needs window >= 2 (got {window}).")
        self.window = int(window)
        self.zmax = float(zmax)
        # clamped to the window: a deque of maxlen=window can never hold
        # min_samples > window entries, which would silently disable the
        # z-test for small perf_window values
        self.min_samples = max(2, min(int(min_samples), self.window))
        self.rel_floor = float(rel_floor)
        self.model_step_s = (None if model_step_s is None
                             else float(model_step_s))
        self._hist: deque = deque(maxlen=self.window)
        self.regressions = 0

    def baseline_s(self) -> float | None:
        """The current warm per-step baseline (median of the rolling
        window), or None before ``min_samples`` warm chunks — the
        measured-price fallback the driver's deadline-slack computation
        uses when no `predict_step` model was attached."""
        from statistics import median

        if len(self._hist) < self.min_samples:
            return None
        return float(median(self._hist))

    def observe(self, *, chunk, step_begin, step_end, n, exec_s,
                cold: bool = False) -> dict | None:
        """One chunk boundary. Returns the regression record (or None)."""
        from .hooks import observe_perf

        per_step = float(exec_s) / max(1, int(n))
        ratio = (per_step / self.model_step_s
                 if self.model_step_s else None)
        z, med, mad = robust_z(per_step, self._hist,
                               rel_floor=self.rel_floor,
                               min_samples=self.min_samples)
        verdict = None
        if z is not None:
            if not cold and z > self.zmax:
                self.regressions += 1
                verdict = {"chunk": chunk, "step_begin": step_begin,
                           "step_end": step_end, "per_step_s": per_step,
                           "baseline_s": med, "mad_s": mad, "z": z,
                           "ratio": ratio}
        if not cold:
            self._hist.append(per_step)
        observe_perf(per_step, ratio=ratio, z=z,
                     regression=verdict is not None)
        return verdict
