"""Live observability plane: incremental flight tailing + derived
signals + a declarative SLO/alert engine.

Every other observability surface is post-hoc (`aggregate_flight` /
`straggler_report` / `run_report` re-read whole JSONLs after the run)
or point-in-time (the `/metrics` gauges). This module is the LIVE
middle: it tail-follows the per-process flight JSONLs of a run — or a
scheduler's whole flight directory, journal included — and maintains
rolling DERIVED state while the jobs are still running:

- `FlightTail` — the byte-offset-checkpointed reader loop: re-globs the
  directory each poll (new job files appear over time), resumes each
  file at its checkpointed offset (`read_flight_events(offset=)` —
  torn final lines are simply re-read next poll), and tracks per-stream
  sequence continuity WITHOUT raising: in tail mode a gap is an
  integrity observation (recorded in ``.gaps``), not a crash — the
  post-hoc aggregator stays the strict one.
- `LiveAggregate` — `FlightTail` plus the PR-5 clock-alignment math
  applied incrementally (`aggregate_events(resume=)` — per-process wall
  anchors once seen, residual offsets re-estimated over the carried
  chunk-barrier window) and the rolling signal windows: warm step-time
  quantiles + robust z (sharing `PerfWatch`'s estimator, `robust_z`),
  per-job deadline slack, chunk-boundary barrier spreads with
  persistent-straggler attribution, wire/snapshot byte rates, and
  scheduler queue pressure from the journal + `QueueBackend` counts.
  Every merged event gets a monotonically increasing ``live_seq`` —
  the resume cursor of the ``/v1/events`` stream
  (`serve.observe.ObservePlane`).
- `AlertRule` / `AlertEngine` — declarative rules (threshold, counter
  rate, burn-rate, robust z-score) over any live-derived signal (dotted
  paths into the snapshot, ``*`` wildcard fanning out per job/process)
  or any registry metric (``metric:<family>``), evaluated at chunk
  boundaries with per-(rule, key) firing/resolved state machines,
  consecutive-breach hysteresis, and dedup. Every transition is
  journaled as an ``alert`` flight event, counted as
  ``igg_alerts_total{rule,severity,state}``, and delivered to pluggable
  sinks: `log_sink`, `ControlFileSink` (files the EXISTING cancel /
  resize / drain control files — an alert can preempt a busting job at
  the next slice boundary with zero new scheduler hooks), `WebhookSink`
  (stdlib urllib POST, errors swallowed and counted).

`default_rule_pack` ships the six house rules: deadline-slack burn,
guard-trip storm, persistent straggler, perf-regression streak,
io-queue saturation, checkpoint-latency blowout (docs/observability.md
has the table). The `MeshScheduler` embeds the engine in-process
(``alerts=True``) — it evaluates over the scheduler's own state at
every slice boundary and journals through the scheduler's single-writer
journal; `LiveAggregate` is the OBSERVER-side twin for off-process
dashboards (`tools watch`) and the streaming ops endpoints.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from collections import deque
from statistics import median

from ..utils.exceptions import InvalidArgumentError
from .aggregate import _resolve_paths, aggregate_events
from .hooks import note_alert, note_flight_file_bytes
from .perfmodel import robust_z
from .recorder import read_flight_events

__all__ = ["FlightTail", "LiveAggregate", "AlertRule", "AlertEngine",
           "default_rule_pack", "log_sink", "ControlFileSink",
           "WebhookSink"]

_log = logging.getLogger("implicitglobalgrid_tpu.live")


class FlightTail:
    """Incremental reader over one or many flight JSONLs (see module
    docstring). ``source``: a directory (re-globbed for ``*.jsonl``
    EVERY poll — a scheduler admits jobs, and their files must join the
    tail mid-flight), one path, or an iterable of paths. ``run_id``
    filters to one run's records.

    `poll()` returns the newly appended raw events (each tagged with
    ``_file``), in per-file order. Integrity observations — a sequence
    gap, a seq restart (recorder reopened), a truncated/replaced file,
    interior corruption — land in ``.gaps`` instead of raising; a
    corrupt file is skipped to its end (re-following from the next
    append) so one bad stream cannot wedge the whole tail."""

    def __init__(self, source, *, run_id: str | None = None):
        self.source = source
        self.run_id = None if run_id is None else str(run_id)
        self._offsets: dict = {}       # path -> byte offset
        self._next_seq: dict = {}      # (path, run, proc) -> expected seq
        self.gaps: list = []
        self.events_read = 0

    def _paths(self) -> list:
        if isinstance(self.source, (str, os.PathLike)) \
                and os.path.isdir(os.fspath(self.source)):
            import glob

            return sorted(glob.glob(
                os.path.join(os.fspath(self.source), "*.jsonl")))
        try:
            return _resolve_paths(self.source)
        except InvalidArgumentError:
            return []  # an empty directory is a tail waiting for files

    def poll(self) -> list:
        out = []
        for p in self._paths():
            off = self._offsets.get(p, 0)
            try:
                size = os.path.getsize(p)
            except OSError:
                continue
            if size < off:
                # the file shrank: replaced or truncated under us —
                # restart from its head and say so
                self.gaps.append({"file": p, "kind": "truncated",
                                  "offset": off, "size": size,
                                  "t": time.time()})
                off = 0
                self._next_seq = {k: v for k, v in self._next_seq.items()
                                  if k[0] != p}
            try:
                evs, new_off = read_flight_events(p, offset=off)
            except InvalidArgumentError as e:
                # interior corruption: record it once and skip past —
                # the strict post-hoc reader is where this is fatal
                self.gaps.append({"file": p, "kind": "corrupt",
                                  "error": str(e), "t": time.time()})
                self._offsets[p] = size
                continue
            self._offsets[p] = new_off
            # disk hygiene rides the tail checkpoint: each stream's
            # on-disk size as a gauge, so recorder growth is visible
            # (tools flight du is the CLI twin)
            note_flight_file_bytes(os.path.basename(p), size)
            for e in evs:
                if self.run_id is not None \
                        and e.get("run") != self.run_id:
                    continue
                seq = e.get("seq")
                if seq is not None:
                    key = (p, e.get("run"), int(e.get("proc", 0)))
                    expect = self._next_seq.get(key)
                    if expect is not None and int(seq) != expect:
                        self.gaps.append({
                            "file": p, "run": e.get("run"),
                            "proc": key[2],
                            "kind": ("seq_gap" if int(seq) > expect
                                     else "seq_restart"),
                            "expected": expect, "got": int(seq),
                            "t": time.time()})
                    self._next_seq[key] = int(seq) + 1
                e = dict(e)
                e["_file"] = p
                out.append(e)
        self.events_read += len(out)
        return out


def _quantile(hist: list, q: float):
    if not hist:
        return None
    s = sorted(hist)
    return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]


class LiveAggregate:
    """Rolling mesh/service view over a tailed flight source (see the
    module docstring). ``window`` sizes the per-job rolling windows
    (step times, checkpoint latencies, byte-rate samples);
    ``straggler_window``/``min_samples`` mirror `straggler_report` /
    `PerfWatch`. ``backend`` (a `service.QueueBackend`) adds live
    pending-count/oldest-age queue pressure to every snapshot.

    Call `poll()` at your cadence (the terminal dashboard and the
    streaming endpoints do); read `snapshot()` for the derived-signal
    record and `events_since(cursor)` for the merged, clock-aligned,
    ``live_seq``-stamped event feed (bounded buffer — a consumer that
    falls more than ``buffer`` events behind detects the loss by the
    cursor jump)."""

    def __init__(self, source, *, run_id: str | None = None,
                 window: int = 16, straggler_window: int = 8,
                 min_samples: int = 5, backend=None, buffer: int = 4096):
        if int(window) < 2:
            raise InvalidArgumentError(
                f"LiveAggregate needs window >= 2 (got {window}).")
        self.tail = FlightTail(source, run_id=run_id)
        self.window = int(window)
        self.straggler_window = max(2, int(straggler_window))
        self.min_samples = max(2, min(int(min_samples), self.window))
        self.backend = backend
        self._resume: dict = {}        # run id -> aggregate resume record
        self._offsets: dict = {}       # run id -> last good proc offsets
        self._live_seq = 0
        self._buffer: deque = deque(maxlen=int(buffer))
        self._jobs: dict = {}
        self._mesh: dict = {}          # run id -> barrier-spread state
        self._alerts: dict = {}        # (rule, job) -> last transition
        self._recent_alerts: deque = deque(maxlen=64)
        self._queue: dict = {}
        self._sched = {"slices": 0, "draining": False, "last_t": None,
                       "started": False, "stopped": False}
        # the autoscaler's journaled policy verdicts (ISSUE 19) — the
        # observer-side twin of the scheduler's closed loop: policy
        # echo, verdict counts, and the recent decision records the
        # /v1/observe "autoscale" section serves
        self._autoscale = {"policy": None, "decisions": 0, "filed": 0,
                           "rejected": 0, "resizes": 0, "retunes": 0,
                           "last": None}
        self._autoscale_recent: deque = deque(maxlen=32)
        self._last_event_t = None      # newest aligned wall stamp seen
        self.align: dict = {}          # run id -> alignment metadata

    # -- tail + alignment --------------------------------------------------

    @property
    def gaps(self) -> list:
        return self.tail.gaps

    @property
    def cursor(self) -> int:
        """``live_seq`` of the last merged event (-1 before any)."""
        return self._live_seq - 1

    def poll(self) -> list:
        """Consume everything newly appended: align, merge, stamp
        ``live_seq``, fold into the derived windows. Returns the newly
        merged events (aligned copies, oldest first)."""
        raw = self.tail.poll()
        batches: dict = {}
        for e in raw:
            batches.setdefault(e.get("run"), []).append(e)
        merged = []
        for rid in sorted(batches, key=str):
            merged.extend(self._align_batch(rid, batches[rid]))
        merged.sort(key=lambda e: (e.get("t", 0.0), e.get("proc", 0),
                                   e.get("seq", 0)))
        for e in merged:
            e["live_seq"] = self._live_seq
            self._live_seq += 1
            self._consume(e)
            self._buffer.append(e)
            if e.get("t") is not None:
                t = float(e["t"])
                if self._last_event_t is None or t > self._last_event_t:
                    self._last_event_t = t
        if self.backend is not None:
            try:
                self._queue["pending"] = self.backend.pending_count()
                self._queue["oldest_age_s"] = self.backend.oldest_age_s()
            except Exception as e:  # a backend hiccup must not stop the tail
                self._queue["error"] = f"{type(e).__name__}: {e}"
        return merged

    def _align_batch(self, rid, batch: list) -> list:
        """One run's new events through the incremental aligner; a batch
        the strict aligner refuses (mid-stream attach, a gap the tail
        already recorded) degrades to shift-only alignment with the last
        known offsets instead of raising."""
        resume = self._resume.get(rid)
        if resume is not None:
            # gap tolerance: re-base each process's expected seq on what
            # actually arrived (the tail recorded the discontinuity)
            nxt = dict(resume.get("next_seq") or {})
            for e in batch:
                proc, seq = int(e.get("proc", 0)), e.get("seq")
                if seq is not None and proc in nxt \
                        and int(seq) < nxt[proc]:
                    nxt[proc] = int(seq)  # restart: allow re-validation
            for proc in {int(e.get("proc", 0)) for e in batch}:
                seqs = sorted(int(e["seq"]) for e in batch
                              if int(e.get("proc", 0)) == proc
                              and "seq" in e)
                if seqs and seqs[0] > nxt.get(proc, 0):
                    nxt[proc] = seqs[0]
            resume = dict(resume, next_seq=nxt)
        else:
            # first sight of this run: tolerate a mid-stream attach
            nxt = {}
            for proc in {int(e.get("proc", 0)) for e in batch}:
                seqs = sorted(int(e["seq"]) for e in batch
                              if int(e.get("proc", 0)) == proc
                              and "seq" in e)
                if seqs and seqs[0] > 0:
                    nxt[proc] = seqs[0]
            if nxt:
                resume = {"next_seq": nxt}
        try:
            agg = aggregate_events(batch, run_id=rid, resume=resume,
                                   _what="live_aggregate")
        except InvalidArgumentError as e:
            self.tail.gaps.append({"run": rid, "kind": "align_failed",
                                   "error": str(e), "t": time.time()})
            out = self._shift_only(rid, batch)
            # keep resuming past the bad batch
            res = self._resume.setdefault(
                rid, {"run_id": rid, "next_seq": {}, "wall_anchor": {},
                      "chunk_ends": {}})
            for e in batch:
                if "seq" in e:
                    proc = int(e.get("proc", 0))
                    res["next_seq"][proc] = max(
                        res["next_seq"].get(proc, 0), int(e["seq"]) + 1)
            return out
        self._resume[rid] = agg["resume"]
        self._offsets[rid] = {"wall_anchor":
                              dict(agg["resume"]["wall_anchor"]),
                              "offsets": dict(agg["offsets"])}
        self.align[rid] = {"anchor_proc": agg["anchor_proc"],
                           **agg["align"]}
        return agg["events"]

    def _shift_only(self, rid, batch: list) -> list:
        known = self._offsets.get(rid, {})
        wall = known.get("wall_anchor", {})
        offs = known.get("offsets", {})
        out = []
        for e in batch:
            e = dict(e)
            proc = int(e.get("proc", 0))
            shift = wall.get(proc, 0.0) - offs.get(proc, 0.0)
            if "t" in e:
                e["t_mono"] = e["t"]
                e["t"] = float(e["t"]) + shift
            out.append(e)
        return out

    # -- derived state -----------------------------------------------------

    def _job(self, name) -> dict:
        rec = self._jobs.get(name)
        if rec is None:
            rec = self._jobs[name] = {
                "state": None, "step": None, "nt": None, "chunks": 0,
                "slices": 0, "guard_trips": 0, "rollbacks": 0,
                "perf_regressions": 0, "step_s_last": None, "z": None,
                "deadline_slack_s": None, "deadline_budget_s": None,
                "deadline_missed": False, "checkpoint_s": None,
                "checkpoint_restores": 0, "snapshot_queue_depth": None,
                "snapshot_drops": 0, "snapshot_errors": 0,
                "wire_bytes_total": 0.0, "snapshot_bytes_total": 0.0,
                "wait_s_last": None,
                "_steps": deque(maxlen=self.window),
                "_ckpt": deque(maxlen=self.window),
                "_bytes": deque(maxlen=self.window),
            }
        return rec

    def _consume(self, e: dict) -> None:
        kind = e.get("kind")
        run = e.get("run")
        if run == "scheduler":
            self._consume_journal(kind, e)
            return
        job = self._job(run)
        if kind == "chunk":
            job["chunks"] += 1
            if e.get("step_end") is not None:
                job["step"] = e["step_end"]
            if not e.get("ok", True):
                job["guard_trips"] += 1
            n = int(e.get("n", 0) or 0)
            if n > 0 and e.get("exec_s") is not None and e.get("ok", True):
                per_step = float(e["exec_s"]) / n
                job["step_s_last"] = per_step
                # z against the window BEFORE this sample — PerfWatch's
                # exact discipline (a cold chunk pays an XLA compile in
                # build_s, not exec_s, so it may enter the baseline)
                z, _, _ = robust_z(per_step, job["_steps"],
                                   min_samples=self.min_samples)
                job["z"] = z
                job["_steps"].append(per_step)
            self._observe_barrier(run, e)
        elif kind == "run_begin":
            job["state"] = job["state"] or "running"
            if e.get("nt") is not None:
                job["nt"] = e["nt"]
        elif kind == "rollback":
            job["rollbacks"] += 1
        elif kind == "perf_regression":
            job["perf_regressions"] += 1
        elif kind == "deadline_slack":
            job["deadline_slack_s"] = e.get("slack_s")
            job["deadline_budget_s"] = e.get("budget_s")
        elif kind == "deadline_missed":
            job["deadline_missed"] = True
        elif kind == "checkpoint_save":
            if e.get("dur_s") is not None:
                job["checkpoint_s"] = float(e["dur_s"])
                job["_ckpt"].append(float(e["dur_s"]))
        elif kind == "checkpoint_restore":
            job["checkpoint_restores"] += 1
        elif kind == "snapshot_write":
            job["snapshot_bytes_total"] += float(e.get("nbytes", 0) or 0)
            if e.get("queue_depth") is not None:
                job["snapshot_queue_depth"] = e["queue_depth"]
            self._mark_bytes(job, e)
        elif kind == "snapshot_drop":
            job["snapshot_drops"] += 1
            if e.get("queue_depth") is not None:
                job["snapshot_queue_depth"] = e["queue_depth"]
        elif kind == "snapshot_error":
            job["snapshot_errors"] += 1
        elif kind == "halo_exchange":
            # trace-time accounting: one event per traced exchange, so
            # this is the STATIC byte volume, not a per-step counter
            job["wire_bytes_total"] += float(e.get("wire_bytes", 0) or 0)
            self._mark_bytes(job, e)
        elif kind == "run_end":
            job["state"] = "done" if job["state"] in (None, "running") \
                else job["state"]

    @staticmethod
    def _mark_bytes(job: dict, e: dict) -> None:
        job["_bytes"].append((float(e.get("t", 0.0)),
                              job["wire_bytes_total"],
                              job["snapshot_bytes_total"]))

    def _consume_journal(self, kind, e: dict) -> None:
        name = e.get("job")
        if kind == "scheduler_start":
            self._sched["started"] = True
            if e.get("autoscale") is not None:
                self._autoscale["policy"] = e["autoscale"]
        elif kind == "scheduler_stop":
            self._sched["stopped"] = True
        elif kind == "drain":
            self._sched["draining"] = True
        elif kind == "job_submitted":
            job = self._job(name)
            job["state"] = "queued"
            if e.get("nt") is not None:
                job["nt"] = e["nt"]
        elif kind == "job_admitted":
            self._job(name)["state"] = "running"
        elif kind == "slice":
            self._sched["slices"] += 1
            self._sched["last_t"] = e.get("t")
            job = self._job(name)
            job["slices"] += 1
            if e.get("step") is not None:
                job["step"] = e["step"]
            if e.get("wait_s") is not None:
                job["wait_s_last"] = e["wait_s"]
            if e.get("slack_s") is not None:
                job["deadline_slack_s"] = e["slack_s"]
        elif kind == "deadline_missed" and name is not None:
            self._job(name)["deadline_missed"] = True
        elif kind in ("job_done", "job_failed", "job_cancelled",
                      "job_rejected"):
            self._job(name)["state"] = kind[len("job_"):]
        elif kind == "alert":
            rec = {k: e.get(k) for k in
                   ("rule", "severity", "state", "job", "signal",
                    "value", "threshold", "t")}
            self._alerts[(rec["rule"], rec.get("job"))] = rec
            self._recent_alerts.append(rec)
        elif kind == "autoscale_decision":
            a = self._autoscale
            a["decisions"] += 1
            verdict = e.get("verdict")
            if verdict == "filed":
                a["filed"] += 1
            elif verdict == "rejected":
                a["rejected"] += 1
            rec = {k: e.get(k) for k in
                   ("job", "action", "verdict", "reason", "dims",
                    "new_dims", "streak", "t")}
            be = (e.get("pricing") or {}).get("break_even")
            if be:
                rec["break_even_steps"] = be.get("break_even_steps")
                rec["net_gain_s"] = be.get("net_gain_s")
            a["last"] = rec
            self._autoscale_recent.append(rec)
        elif kind == "job_resized" and name is not None:
            self._autoscale["resizes"] += 1
            job = self._job(name)
            job["resizes"] = job.get("resizes", 0) + 1
            if e.get("new_dims") is not None:
                job["dims"] = e["new_dims"]
        elif kind == "job_retuned" and name is not None:
            self._autoscale["retunes"] += 1
            self._job(name)["retunes"] = \
                self._job(name).get("retunes", 0) + 1

    # -- barrier spreads (multi-process runs) ------------------------------

    def _observe_barrier(self, rid, e: dict) -> None:
        mesh = self._mesh.setdefault(
            rid, {"procs": set(), "pending": {},
                  "spreads": deque(maxlen=self.straggler_window),
                  "last": None})
        proc = int(e.get("proc", 0))
        mesh["procs"].add(proc)
        if e.get("exec_s") is None or e.get("chunk") is None:
            return
        pend = mesh["pending"].setdefault(e["chunk"], {})
        pend[proc] = (float(e["t"]), float(e["exec_s"]))
        if len(mesh["procs"]) < 2 or len(pend) < len(mesh["procs"]):
            if len(mesh["pending"]) > 4 * self.straggler_window:
                for c in sorted(mesh["pending"])[:len(mesh["pending"])
                                                 // 2]:
                    del mesh["pending"][c]
            return
        del mesh["pending"][e["chunk"]]
        # the straggler_report arrival model, windowed: arrival =
        # corrected dispatch start + min exec_s across processes
        compute = min(x[1] for x in pend.values())
        arrivals = {p: (t - ex) + compute for p, (t, ex) in pend.items()}
        first = min(arrivals.values())
        slowest = max(arrivals, key=arrivals.get)
        mesh["spreads"].append(
            {"chunk": e["chunk"], "slowest": slowest,
             "spread_s": arrivals[slowest] - first})
        mesh["last"] = mesh["spreads"][-1]

    # -- the derived-signal snapshot ---------------------------------------

    def snapshot(self) -> dict:
        """The live-derived signal record (JSON-able): ``jobs`` (per-job
        rolling state), ``procs`` (persistent-straggler attribution,
        multi-process runs only), ``queue``, ``scheduler``, ``alerts``
        (active + recent transitions as tailed from the journal), plus
        the tail's integrity observations and alignment metadata. This
        is exactly the record `AlertRule` signals resolve against and
        ``GET /v1/observe`` serves."""
        jobs = {}
        for name, r in self._jobs.items():
            if name is None:
                continue
            hist = list(r["_steps"])
            rates = self._rates(r)
            jobs[str(name)] = {
                k: v for k, v in r.items() if not k.startswith("_")
            } | {
                "step_s_p50": _quantile(hist, 0.5),
                "step_s_p90": _quantile(hist, 0.9),
                "checkpoint_s_p50": _quantile(list(r["_ckpt"]), 0.5),
                **rates,
            }
        procs: dict = {}
        for rid, mesh in self._mesh.items():
            win = list(mesh["spreads"])
            if len(mesh["procs"]) < 2 or not win:
                continue
            counts: dict = {}
            for rec in win:
                counts[rec["slowest"]] = counts.get(rec["slowest"], 0) + 1
            for p in sorted(mesh["procs"]):
                share = counts.get(p, 0) / len(win)
                rec = procs.setdefault(
                    int(p), {"slowest_share": 0.0, "runs": []})
                rec["slowest_share"] = max(rec["slowest_share"], share)
                rec["runs"].append(str(rid))
            procs["spread_s_last"] = mesh["last"]["spread_s"] \
                if mesh["last"] else None
        active = [rec for rec in self._alerts.values()
                  if rec.get("state") == "firing"]
        return {
            "t": time.time(),
            "cursor": self.cursor,
            # tail freshness: the aligned stamps are wall clock, so the
            # age of the newest merged event distinguishes "quiet mesh"
            # (small, creeping) from "stalled tail" (growing unbounded)
            # — the local twin of /v1/events heartbeats' last_seq
            "tail": {
                "events_read": self.tail.events_read,
                "last_event_t": self._last_event_t,
                "lag_s": (max(0.0, time.time() - self._last_event_t)
                          if self._last_event_t is not None else None),
            },
            "jobs": jobs,
            "procs": procs,
            "queue": dict(self._queue),
            "scheduler": dict(self._sched),
            "alerts": {"active": active,
                       "recent": list(self._recent_alerts)},
            "autoscale": dict(self._autoscale,
                              recent=list(self._autoscale_recent)),
            "gaps": list(self.gaps),
            "align": {str(k): v for k, v in self.align.items()},
        }

    @staticmethod
    def _rates(r: dict) -> dict:
        marks = list(r["_bytes"])
        if len(marks) < 2 or marks[-1][0] <= marks[0][0]:
            return {"wire_bytes_rate": None, "snapshot_bytes_rate": None}
        dt = marks[-1][0] - marks[0][0]
        return {"wire_bytes_rate": (marks[-1][1] - marks[0][1]) / dt,
                "snapshot_bytes_rate": (marks[-1][2] - marks[0][2]) / dt}

    # -- the merged live feed ----------------------------------------------

    def events_since(self, since: int | None = None) -> tuple:
        """``(events, cursor)``: buffered merged events with
        ``live_seq > since`` (all buffered when ``since`` is None) and
        the cursor to pass next time. The buffer is bounded — when
        ``events[0]["live_seq"] > since + 1`` the consumer fell behind
        and lost the difference."""
        if since is None:
            evs = list(self._buffer)
        else:
            since = int(since)
            evs = [e for e in self._buffer if e["live_seq"] > since]
        cursor = evs[-1]["live_seq"] if evs else \
            (self.cursor if since is None else since)
        return evs, cursor


# --------------------------------------------------------------------------
# The alert engine
# --------------------------------------------------------------------------

_KINDS = ("threshold", "rate", "burn_rate", "zscore")
_OPS = {
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule (see the module docstring).

    ``signal``: a dotted path into the live snapshot with at most one
    ``*`` wildcard segment fanning the rule out per key (``jobs.*
    .guard_trips`` runs one state machine per job), or
    ``metric:<family>`` reading the process metrics registry (sum over
    the family's samples). A key whose signal is absent this evaluation
    is SKIPPED — its state machine neither breaches nor clears.

    ``kind``:

    - ``threshold`` — fire when ``value <op> threshold``.
    - ``rate`` — over a cumulative counter: fire when it grew by at
      least ``threshold`` within the last ``window`` evaluations.
    - ``burn_rate`` — over a slack-like gauge: fire when the value is
      exhausted (``<= 0``) or decreasing fast enough to exhaust within
      ``horizon_s`` at the observed burn rate.
    - ``zscore`` — fire when the value's robust z against its own
      rolling window (`telemetry.robust_z` — `PerfWatch`'s estimator)
      exceeds ``threshold``, after ``min_samples`` samples.

    ``for_count`` consecutive breaching evaluations fire (hysteresis);
    ``resolve_count`` consecutive clear evaluations resolve."""

    name: str
    signal: str
    kind: str = "threshold"
    op: str = ">"
    threshold: float = 0.0
    window: int = 8
    horizon_s: float = 60.0
    min_samples: int = 4
    for_count: int = 1
    resolve_count: int = 2
    severity: str = "warning"

    def __post_init__(self):
        if not self.name or not self.signal:
            raise InvalidArgumentError(
                "AlertRule needs a name and a signal path.")
        if self.kind not in _KINDS:
            raise InvalidArgumentError(
                f"AlertRule {self.name!r}: kind must be one of {_KINDS}; "
                f"got {self.kind!r}.")
        if self.op not in _OPS:
            raise InvalidArgumentError(
                f"AlertRule {self.name!r}: op must be one of "
                f"{sorted(_OPS)}; got {self.op!r}.")
        if int(self.window) < 1 or int(self.for_count) < 1 \
                or int(self.resolve_count) < 1:
            raise InvalidArgumentError(
                f"AlertRule {self.name!r}: window, for_count and "
                "resolve_count must be >= 1.")
        if self.signal.count("*") > 1:
            raise InvalidArgumentError(
                f"AlertRule {self.name!r}: at most one '*' wildcard "
                f"segment; got {self.signal!r}.")


def default_rule_pack() -> list:
    """The six house rules (docs/observability.md has the table)."""
    return [
        AlertRule("deadline_slack_burn", "jobs.*.deadline_slack_s",
                  kind="burn_rate", horizon_s=60.0, severity="critical"),
        AlertRule("guard_trip_storm", "jobs.*.guard_trips",
                  kind="rate", threshold=1.0, window=8,
                  severity="critical"),
        AlertRule("persistent_straggler", "procs.*.slowest_share",
                  kind="threshold", op=">", threshold=0.6, for_count=2,
                  severity="warning"),
        AlertRule("perf_regression_streak", "jobs.*.perf_regressions",
                  kind="rate", threshold=3.0, window=8,
                  severity="warning"),
        AlertRule("io_queue_saturation", "jobs.*.snapshot_drops",
                  kind="rate", threshold=1.0, window=8,
                  severity="warning"),
        AlertRule("checkpoint_latency_blowout", "jobs.*.checkpoint_s",
                  kind="zscore", threshold=4.0, min_samples=4,
                  severity="warning"),
    ]


def log_sink(transition: dict) -> None:
    """The trivial sink: one WARNING/INFO log line per transition."""
    level = logging.WARNING if transition["state"] == "firing" \
        else logging.INFO
    _log.log(level, "alert %s %s (job=%s signal=%s value=%s)",
             transition["rule"], transition["state"],
             transition.get("job"), transition.get("signal"),
             transition.get("value"))


class ControlFileSink:
    """Turn a FIRING alert into an EXISTING control-file request
    (`service.QueueBackend.control`): ``action`` ``cancel`` (default;
    needs the transition's job attribution), ``resize`` (with
    ``payload`` — the resize control JSON), or ``drain``. ``rules``
    restricts which rules may act (None = all). Each (rule, job,
    action) fires the control file at most ONCE per sink lifetime —
    re-fires after a resolve do not re-file. The scheduler consumes the
    file at its next slice boundary, exactly as if an operator had run
    ``tools jobs cancel``."""

    def __init__(self, backend, *, action: str = "cancel", rules=None,
                 payload: dict | None = None):
        if action not in ("cancel", "resize", "drain"):
            raise InvalidArgumentError(
                f"ControlFileSink action must be cancel|resize|drain; "
                f"got {action!r}.")
        if action == "resize" and not isinstance(payload, dict):
            raise InvalidArgumentError(
                "ControlFileSink(action='resize') needs a payload dict "
                "({'new_dims': [...], 'via': ...}).")
        self.backend = backend
        self.action = action
        self.rules = None if rules is None else {str(r) for r in rules}
        self.payload = payload
        self.filed: list = []
        self._seen: set = set()

    def __call__(self, transition: dict) -> None:
        if transition.get("state") != "firing":
            return
        if self.rules is not None \
                and transition.get("rule") not in self.rules:
            return
        job = transition.get("job")
        if self.action != "drain" and job is None:
            return  # an unattributed alert cannot target a job
        key = (transition.get("rule"), job, self.action)
        if key in self._seen:
            return
        self._seen.add(key)
        # the alert's own span (stamped by the engine's tracer) rides in
        # the control payload as its traceparent: the scheduler parents
        # the consumed control event on the alert that decided it
        trace = None
        if transition.get("trace_id") and transition.get("span_id"):
            trace = {"traceparent": f"00-{transition['trace_id']}-"
                                    f"{transition['span_id']}-01"}
        if self.action == "drain":
            self.backend.control("drain")
        elif self.action == "resize":
            payload = dict(self.payload)
            if trace:
                payload.update(trace)
            self.backend.control("resize", str(job), payload)
        else:
            self.backend.control("cancel", str(job), trace)
        self.filed.append({"rule": transition.get("rule"), "job": job,
                           "action": self.action})


class WebhookSink:
    """POST every transition as JSON to ``url`` (stdlib urllib only).
    Delivery errors are swallowed and counted (``.errors`` /
    ``.last_error``) — an unreachable webhook must never stall the
    scheduling loop. ``timeout_s`` bounds each attempt."""

    def __init__(self, url: str, *, timeout_s: float = 2.0):
        self.url = str(url)
        self.timeout_s = float(timeout_s)
        self.delivered = 0
        self.errors = 0
        self.last_error = None

    def __call__(self, transition: dict) -> None:
        import urllib.request

        req = urllib.request.Request(
            self.url, data=json.dumps(transition, default=str).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self.delivered += 1
        except Exception as e:
            self.errors += 1
            self.last_error = f"{type(e).__name__}: {e}"


class AlertEngine:
    """Evaluate a rule set against successive signal snapshots (see the
    module docstring). ``journal`` is a ``callable(kind, **fields)``
    receiving every transition as an ``alert`` event — the scheduler
    passes its journal's writer so alerts land in ``scheduler.jsonl``
    with single-writer seq integrity; ``registry`` backs
    ``metric:<family>`` signals (default: the process registry).

    `evaluate(snapshot)` returns the transitions it caused (empty most
    boundaries); `active()` lists currently firing (rule, key) states.
    A sink raising is caught, counted (``sink_errors``), and journaled
    once per sink — a broken sink must never take the scheduler down."""

    def __init__(self, rules=None, *, sinks=(), journal=None,
                 registry=None):
        rules = default_rule_pack() if rules is None else list(rules)
        for r in rules:
            if not isinstance(r, AlertRule):
                raise InvalidArgumentError(
                    f"AlertEngine rules must be AlertRule instances; got "
                    f"{type(r).__name__}.")
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise InvalidArgumentError(
                f"AlertEngine: duplicate rule names in {names}.")
        self.rules = rules
        self.sinks = list(sinks)
        self.journal = journal
        self.registry = registry
        # optional distributed-trace hook: callable(transition) -> trace
        # field dict, applied BEFORE journal + sinks so the alert's span
        # is known to both (the scheduler wires its per-job contexts
        # here; a ControlFileSink then files the alert's span as the
        # cancel's parent — "why was my job cancelled" is a trace walk)
        self.tracer = None
        self._state: dict = {}
        self.transitions = 0
        self.evaluations = 0
        self.sink_errors = 0
        self._sink_error_logged: set = set()

    # -- signal resolution -------------------------------------------------

    def _resolve(self, signal: str, snapshot: dict) -> dict:
        """``{key: float value}`` instances of one signal path; key is
        None for scalar signals, the wildcard match (job name, proc)
        for fanned-out ones. Missing/None values are skipped."""
        if signal.startswith("metric:"):
            reg = self.registry
            if reg is None:
                from .registry import metrics_registry

                reg = metrics_registry()
            fam = reg.get(signal[len("metric:"):])
            if fam is None:
                return {}
            total = sum(v for _, v in fam.samples())
            return {None: float(total)}
        node = snapshot
        parts = signal.split(".")
        for i, part in enumerate(parts):
            if part == "*":
                rest = ".".join(parts[i + 1:])
                out = {}
                if isinstance(node, dict):
                    for key, sub in node.items():
                        for k2, v in self._resolve(rest,
                                                   sub or {}).items():
                            out[str(key) if k2 is None
                                else f"{key}.{k2}"] = v
                return out
            if not isinstance(node, dict) or part not in node:
                return {}
            node = node[part]
        if node is None:
            return {}
        try:
            return {None: float(node)}
        except (TypeError, ValueError):
            return {}

    # -- evaluation --------------------------------------------------------

    def evaluate(self, snapshot: dict) -> list:
        """One chunk-boundary evaluation pass. Returns the transitions
        (journaled, counted, and delivered to sinks as a side effect)."""
        self.evaluations += 1
        t = snapshot.get("t") or time.time()
        out = []
        for rule in self.rules:
            for key, value in self._resolve(rule.signal,
                                            snapshot).items():
                tr = self._eval_one(rule, key, value, t)
                if tr is not None:
                    out.append(tr)
                    self._deliver(tr)
        return out

    def _eval_one(self, rule: AlertRule, key, value: float, t: float):
        st = self._state.get((rule.name, key))
        if st is None:
            st = self._state[(rule.name, key)] = {
                "state": "ok", "breach": 0, "clear": 0, "since": None,
                "value": None,
                "hist": deque(maxlen=max(int(rule.window) + 1,
                                         int(rule.min_samples) + 1)),
            }
        hist = st["hist"]
        if rule.kind == "threshold":
            breach = _OPS[rule.op](value, rule.threshold)
        elif rule.kind == "rate":
            base = hist[0][1] if hist else 0.0
            breach = (value - base) >= rule.threshold
            hist.append((t, value))
        elif rule.kind == "burn_rate":
            breach = value <= 0
            if not breach and hist:
                t0, v0 = hist[0]
                if t > t0 and value < v0:
                    burn = (v0 - value) / (t - t0)
                    breach = value / burn < rule.horizon_s
            hist.append((t, value))
        else:  # zscore
            z, _, _ = robust_z(value, (v for _, v in hist),
                               min_samples=rule.min_samples)
            breach = z is not None and z > rule.threshold
            hist.append((t, value))
        st["value"] = value
        if breach:
            st["breach"] += 1
            st["clear"] = 0
        else:
            st["clear"] += 1
            st["breach"] = 0
        if st["state"] == "ok" and breach \
                and st["breach"] >= rule.for_count:
            st["state"], st["since"] = "firing", t
            return self._transition(rule, key, value, t, "firing")
        if st["state"] == "firing" and not breach \
                and st["clear"] >= rule.resolve_count:
            st["state"] = "ok"
            return self._transition(rule, key, value, t, "resolved")
        return None

    def _transition(self, rule: AlertRule, key, value, t, state) -> dict:
        self.transitions += 1
        job = None
        if key is not None:
            job = str(key).split(".", 1)[0]
        return {"rule": rule.name, "severity": rule.severity,
                "state": state, "job": job, "key": key,
                "signal": rule.signal, "value": value,
                "threshold": rule.threshold, "t": t}

    def _deliver(self, tr: dict) -> None:
        if self.tracer is not None:
            try:
                tf = self.tracer(tr)
            except Exception:
                tf = None  # tracing must never block alert delivery
            if tf:
                tr.update(tf)
        note_alert(tr["rule"], tr["severity"], tr["state"])
        if self.journal is not None:
            self.journal("alert", **{k: v for k, v in tr.items()
                                     if k != "t"})
        for sink in self.sinks:
            try:
                sink(tr)
            except Exception as e:
                self.sink_errors += 1
                sid = id(sink)
                if sid not in self._sink_error_logged:
                    self._sink_error_logged.add(sid)
                    _log.warning("alert sink %r failed: %s", sink, e)
                    if self.journal is not None:
                        self.journal("alert_sink_error",
                                     sink=type(sink).__name__,
                                     error=f"{type(e).__name__}: {e}")

    def active(self) -> list:
        """Currently FIRING states, most recent first."""
        out = [{"rule": r, "job": None if k is None
                else str(k).split(".", 1)[0], "key": k,
                "since": st["since"], "value": st["value"]}
               for (r, k), st in self._state.items()
               if st["state"] == "firing"]
        out.sort(key=lambda rec: -(rec["since"] or 0.0))
        return out
