"""Perf-history database: append bench runs, gate regressions.

`bench_all.py` has recorded a rich per-config row set (BENCH_ALL.json)
since PR 1, but every run OVERWRITES the last — the trajectory existed
only in git archaeology and nothing failed when a metric quietly lost
30%. This module gives the benches a memory and a gate:

- `perfdb_add(db, rows)` appends one JSONL record per bench run —
  ``{"ts", "meta", "metrics": {name: value}}`` extracted from the
  BENCH_ALL-style row list (a path or the rows themselves);
- `perfdb_check(db, rows)` compares the current run against the MEDIAN
  of the trailing ``window`` history records, per metric, with the
  metric's direction inferred from its name (`metric_direction`:
  throughput-flavored names regress DOWN, overhead/latency-flavored
  names regress UP) and fails on changes beyond ``threshold``;
- ``python -m implicitglobalgrid_tpu.tools perfdb add|check`` is the CLI
  (``check`` exits 1 on regression — the bench trajectory gates itself),
  and `bench_all.py` runs both after writing BENCH_ALL.json.

The history is append-only JSONL (same durability posture as the flight
recorder: one line per run, a torn final line tolerated) so it diffs,
greps, and survives partial writes.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import time

from ..utils.exceptions import InvalidArgumentError

__all__ = ["metric_direction", "perfdb_add", "perfdb_check", "perfdb_load"]

# Name-pattern direction inference. The higher-better patterns are the
# more specific ones and are checked FIRST ("..._per_s_per_chip" also
# contains the substring "_s_" a naive seconds-pattern would catch).
_HIGHER_BETTER = ("per_s", "gbps", "gflops", "speedup", "updates",
                  "efficiency")
_LOWER_BETTER = ("overhead", "_frac", "latency", "_seconds", "pipeline_s",
                 "noise", "residual")


def metric_direction(name: str) -> str | None:
    """``"higher"`` / ``"lower"`` = which way is better, None = unknown
    (unknown metrics are reported as skipped, never gated — a typo'd
    pattern must not invert a gate silently; model-fidelity ratios have
    no better direction and stay ungated by design)."""
    n = name.lower()
    for pat in _HIGHER_BETTER:
        if pat in n:
            return "higher"
    for pat in _LOWER_BETTER:
        if pat in n:
            return "lower"
    return None


def _metrics_of(rows_or_path) -> tuple[dict, dict]:
    """(metrics, meta) from a BENCH_ALL.json path or a row list: every
    row with a string ``metric`` and a finite numeric ``value``."""
    if isinstance(rows_or_path, (str, os.PathLike)):
        path = os.fspath(rows_or_path)
        try:
            with open(path, encoding="utf-8") as f:
                rows = json.load(f)
        except (OSError, ValueError) as e:
            raise InvalidArgumentError(
                f"perfdb: cannot read bench rows from {path}: {e}") from e
    else:
        rows = list(rows_or_path)
    if not isinstance(rows, list):
        raise InvalidArgumentError(
            "perfdb: bench rows must be a list of row dicts "
            "(the BENCH_ALL.json shape).")
    metrics: dict = {}
    meta: dict = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        name, value = row.get("metric"), row.get("value")
        if not isinstance(name, str) or not isinstance(value, (int, float)) \
                or isinstance(value, bool) or not math.isfinite(value):
            continue
        metrics[name] = float(value)
        if not meta and row.get("platform"):
            meta = {k: row.get(k)
                    for k in ("platform", "device_kind", "n_devices")
                    if row.get(k) is not None}
    if not metrics:
        raise InvalidArgumentError(
            "perfdb: no usable (metric, numeric value) rows found.")
    return metrics, meta


def perfdb_load(db_path) -> list:
    """History records, oldest first (a torn final line is tolerated,
    interior corruption raises — same contract as the flight reader)."""
    path = os.fspath(db_path)
    if not os.path.exists(path):
        return []
    out = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().split("\n")
    for i, ln in enumerate(lines):
        s = ln.strip()
        if not s:
            continue
        try:
            out.append(json.loads(s))
        except ValueError:
            trailing = all(not x.strip() for x in lines[i + 1:])
            if trailing:
                break  # torn final line: crash mid-append
            raise InvalidArgumentError(
                f"perfdb: corrupt interior line {i + 1} in {path}.")
    return out


def perfdb_add(db_path, rows_or_path, *, meta: dict | None = None) -> dict:
    """Append the current bench run to the history. Returns the appended
    record ``{"ts", "meta", "metrics"}``."""
    metrics, row_meta = _metrics_of(rows_or_path)
    rec = {"ts": time.time(), "meta": {**row_meta, **(meta or {})},
           "metrics": metrics}
    path = os.fspath(db_path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return rec


def perfdb_check(db_path, rows_or_path, *, window: int = 5,
                 threshold: float = 0.30, min_history: int = 2) -> dict:
    """Gate the current run against the trailing history.

    Per metric of the current run with an inferrable direction: baseline
    = median of that metric over the last ``window`` history records
    (records missing it are skipped); a relative change beyond
    ``threshold`` in the WORSE direction is a regression. Metrics with
    fewer than ``min_history`` history points, or an unknown direction,
    are reported under ``skipped`` and never gated (a fresh metric's
    first runs build history instead of failing it).

    Returns ``{"ok", "checked", "regressions": [{metric, value, baseline,
    change, direction, n_history}], "improvements", "skipped",
    "history_runs"}`` — ``ok`` is False iff ``regressions`` is
    non-empty."""
    if not 0 < threshold:
        raise InvalidArgumentError(
            f"perfdb_check: threshold must be positive (got {threshold}).")
    history = perfdb_load(db_path)
    metrics, _ = _metrics_of(rows_or_path)
    regressions, improvements, skipped = [], [], []
    for name, value in sorted(metrics.items()):
        direction = metric_direction(name)
        if direction is None:
            skipped.append({"metric": name, "reason": "unknown-direction"})
            continue
        past = [r["metrics"][name] for r in history[-int(window):]
                if isinstance(r.get("metrics"), dict)
                and isinstance(r["metrics"].get(name), (int, float))
                and math.isfinite(r["metrics"][name])]
        if len(past) < int(min_history):
            skipped.append({"metric": name, "reason": "insufficient-history",
                            "n_history": len(past)})
            continue
        baseline = statistics.median(past)
        if baseline == 0.0:
            # relative change is undefined; gate on absolute movement away
            # from a zero baseline only in the worse direction
            change = value
        else:
            change = (value - baseline) / abs(baseline)
        worse = change < -threshold if direction == "higher" \
            else change > threshold
        rec = {"metric": name, "value": value, "baseline": baseline,
               "change": change, "direction": direction,
               "n_history": len(past)}
        if worse:
            regressions.append(rec)
        elif abs(change) > threshold:
            improvements.append(rec)
    return {
        "ok": not regressions,
        "checked": len(metrics) - len(skipped),
        "regressions": regressions,
        "improvements": improvements,
        "skipped": skipped,
        "history_runs": len(history),
        "window": int(window),
        "threshold": float(threshold),
    }
