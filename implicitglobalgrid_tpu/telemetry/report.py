"""The unified run report: flight-recorder JSONL -> one structured record.

`run_report` is the single pane of glass the ISSUE-3 tentpole asks for: it
reconstructs a supervised run's full event sequence (chunks, guard trips,
rollbacks, checkpoint saves/restores, escalations, elastic restarts) from
the flight-recorder stream ALONE, and optionally merges the live metrics
registry plus a profiler capture's `overlap_stats`/`op_breakdown` — so one
JSON object answers "what happened, what did it cost, and where did the
time go" for a run that may have died hours ago.

CLI: ``python -m implicitglobalgrid_tpu.tools report run.jsonl
[--trace DIR] [--run-id ID]``.
"""

from __future__ import annotations

import os

from ..utils.exceptions import InvalidArgumentError
from .recorder import read_flight_events
from .registry import metrics_registry

__all__ = ["run_report"]

# Event kinds that belong in the reconstructed sequence, with the fields
# worth carrying (everything else stays in the raw stream).
_SEQ_FIELDS = {
    "run_begin": ("nt", "nt_chunk", "checkpoint_every", "names"),
    "fault_injected": ("fault", "step", "name"),
    "chunk": ("chunk", "step_begin", "step_end", "ok", "reasons",
              "build_s", "exec_s", "cold"),
    "guard_trip": ("step_end", "reasons", "retries"),
    "escalation": ("retries", "nt_chunk", "step"),
    "rollback": ("to_step", "fallback"),
    "checkpoint_save": ("op", "step", "dur_s"),
    "checkpoint_restore": ("op", "step", "dur_s"),
    "elastic_restart": ("new_dims", "to_step"),
    "snapshot": ("step", "displaced"),
    "snapshot_write": ("step", "dur_s", "nbytes", "queue_depth"),
    "snapshot_drop": ("step", "queue_depth"),
    "snapshot_error": ("step", "error"),
    "snapshot_writer_close": ("submitted", "written", "staged", "dropped",
                              "errors", "bytes"),
    "reducers": ("step", "ok", "values"),
    "audit": ("program", "dialect", "ok", "errors", "warnings", "rules",
              "audit_s"),
    "audit_failed": ("error", "audit_s", "attempt"),
    "perf_model": ("step_s", "bound", "source"),
    "tuned": ("model", "comm_every", "wire_dtype", "coalesce", "overlap",
              "ensemble", "speedup"),
    "perf_regression": ("chunk", "step_begin", "step_end", "per_step_s",
                        "baseline_s", "z", "ratio"),
    "resize": ("via", "new_dims", "step", "dur_s", "rounds",
               "wire_bytes"),
    "tuned_stale": ("reason", "model"),
    "deadline_slack": ("step", "slack_s", "budget_s", "priced_step_s",
                       "priced_by", "remaining_steps"),
    "deadline_missed": ("step", "deadline_s", "elapsed_s", "slack_s"),
    "alert": ("rule", "severity", "state", "job", "signal", "value",
              "threshold"),
    "run_end": ("completed", "chunks"),
}


def _perf_section(chunks: list, perf_model: dict | None,
                  regressions: list) -> dict:
    """The report's ``"perf"`` block: the per-step time series of the OK
    warm chunks (cold chunks pay the XLA compile inside their dispatch
    and would skew every quantile), the attached model prediction with
    the measured/modeled ratio, and the drift detector's verdicts."""
    from statistics import median

    per_step = sorted(
        c["exec_s"] / max(1, c.get("n", 1)) for c in chunks
        if c.get("ok") and not c.get("cold")
        and "exec_s" in c and c.get("n"))
    med = median(per_step) if per_step else None
    out = {
        "chunks": len(per_step),
        "step_s_median": med,
        "step_s_min": per_step[0] if per_step else None,
        "step_s_max": per_step[-1] if per_step else None,
        "regressions": len(regressions),
        "worst_z": max((r.get("z", 0.0) for r in regressions),
                       default=None),
    }
    if perf_model is not None:
        out["model_step_s"] = perf_model.get("step_s")
        out["bound"] = perf_model.get("bound")
        out["model_source"] = perf_model.get("source")
        if med and perf_model.get("step_s"):
            out["model_ratio_median"] = med / float(perf_model["step_s"])
    return out


def _audit_section(audits: list, failures: list = ()) -> dict:
    """The report's ``"audit"`` block: the compile-time static-analysis
    verdicts `run_resilient(audit=True)` streamed (one ``audit`` event per
    audited program — one per run, plus one per elastic restart, whose
    rebuilt program re-audits), reconstructed from the flight JSONL alone
    like every other section. ``findings`` carries the full structured
    records of the LAST audit (re-audits supersede earlier ones);
    ``rules`` merges finding counts by rule across all of them;
    ``failed`` counts audits that crashed (``audit_failed`` events — the
    audit degrades, the run continues) with their error strings;
    ``audit_s`` totals the audits' own host cost — successful AND failed
    attempts (each event stamps its trace+lower+parse+check seconds,
    kept out of chunk ``build_s``)."""
    rules: dict = {}
    for a in audits:
        for rule, n in (a.get("rules") or {}).items():
            rules[rule] = rules.get(rule, 0) + int(n)
    last = audits[-1] if audits else None
    out = {
        "programs": len(audits),
        "ok": (all(a.get("ok", False) for a in audits)
               if audits else None),
        "errors": sum(int(a.get("errors", 0)) for a in audits),
        "warnings": sum(int(a.get("warnings", 0)) for a in audits),
        "rules": dict(sorted(rules.items())),
        "crosscheck_ok": None if last is None else last.get("crosscheck_ok"),
        "findings": [] if last is None else list(last.get("findings") or ()),
        "audit_s": (sum(float(a["audit_s"])
                        for a in (*audits, *failures)
                        if a.get("audit_s") is not None)
                    if any(a.get("audit_s") is not None
                           for a in (*audits, *failures))
                    else None),
    }
    if failures:
        out["failed"] = len(failures)
        out["failed_errors"] = [f.get("error") for f in failures]
        out["ok"] = False
    return out


def _alerts_section(alerts: list) -> dict:
    """The report's ``"alerts"`` block from the journaled ``alert``
    transitions (`telemetry.live.AlertEngine` — scheduler-side
    in-process evaluation): transition counts per rule, and the set
    still FIRING at stream end (the last transition per (rule, job)
    wins — a resolve clears it)."""
    by_rule: dict = {}
    active: dict = {}
    for a in alerts:
        rule = a.get("rule", "?")
        rec = by_rule.setdefault(
            rule, {"firing": 0, "resolved": 0,
                   "severity": a.get("severity")})
        state = a.get("state")
        if state in rec:
            rec[state] += 1
        key = (rule, a.get("job"))
        if state == "firing":
            active[key] = {"rule": rule, "job": a.get("job"),
                           "severity": a.get("severity"),
                           "signal": a.get("signal"),
                           "value": a.get("value"), "t": a.get("t")}
        elif state == "resolved":
            active.pop(key, None)
    return {"transitions": len(alerts),
            "by_rule": dict(sorted(by_rule.items())),
            "active": list(active.values())}


def _pick(ev: dict, fields: tuple) -> dict:
    out = {"kind": ev["kind"], "t": ev.get("t")}
    for f in fields:
        if f in ev:
            out[f] = ev[f]
    return out


def run_report(source, *, run_id: str | None = None,
               trace_dir: str | None = None,
               include_metrics: bool = True) -> dict:
    """Build the unified report for one run.

    ``source`` is a flight-recorder JSONL path, a DIRECTORY of per-process
    streams (the ``flight_p<i>.jsonl`` convention — aggregated and clock-
    aligned via `telemetry.aggregate.aggregate_flight` first), or an
    iterable of already-parsed event dicts. A directory holding a
    MULTI-RUN SCHEDULER journal (``scheduler.jsonl``) returns the
    SERVICE record instead — the interleaved schedule plus each
    tenant's own run report (`service.service_report`; ``run_id`` does
    not apply there — jobs are selected by name in the record). ``run_id`` selects a run when
    the file holds several (default: the LAST run that appears; for a
    directory, the single run present — several raise). ``trace_dir``
    merges a profiler capture's `overlap_stats` and `op_breakdown`;
    ``include_metrics`` attaches a snapshot of the process metrics
    registry (meaningful in-process; the report CLI runs post-hoc, where
    the registry is empty, and the JSONL carries the truth).

    When the stream spans SEVERAL processes, the per-run sections below
    reconstruct the ANCHOR process's view (the lowest index — every
    process runs the same driver loop, so counting all of them would
    multiply every aggregate by the process count) and a ``"mesh"``
    section is added: clock offsets, per-chunk barrier-arrival straggler
    attribution, persistent-straggler flags, and the wait/compute
    imbalance summary (`telemetry.aggregate.mesh_section`)."""
    agg = None
    if isinstance(source, (str, os.PathLike)) \
            and os.path.isdir(os.fspath(source)):
        from ..service.report import is_service_dir, service_report

        if is_service_dir(source):
            # a MeshScheduler flight directory (scheduler.jsonl + one
            # stream per job): the unified record is the SERVICE view —
            # the interleaved schedule plus each tenant's own run report
            # (the per-process aggregate below would refuse the mixed run
            # ids, rightly: jobs are tenants, not mesh processes)
            return service_report(source)
        from .aggregate import aggregate_flight

        agg = aggregate_flight(source, run_id=run_id)
        events = agg["events"]
    elif isinstance(source, (str, os.PathLike)):
        events = read_flight_events(source)
    else:
        events = list(source)
    if not events:
        raise InvalidArgumentError("run_report: no events to report on.")

    runs = []
    for e in events:
        r = e.get("run")
        if r is not None and r not in runs:
            runs.append(r)
    rid = str(run_id) if run_id is not None else (runs[-1] if runs else None)
    if run_id is not None and rid not in runs:
        raise InvalidArgumentError(
            f"run_report: run id {rid!r} not present (have {runs}).")
    evs = [e for e in events if e.get("run") == rid]
    evs.sort(key=lambda e: (e.get("proc", 0), e.get("seq", 0)))

    # multi-process stream: cross-process analysis first, then reconstruct
    # the anchor process's view (see docstring)
    mesh = None
    procs = sorted({int(e.get("proc", 0)) for e in evs})
    if len(procs) > 1:
        from .aggregate import aggregate_events, mesh_section

        if agg is None:
            # events arrived pre-loaded (a list, or one shared file):
            # clock-align them first — per-process monotonic stamps are
            # NOT comparable across hosts, and a straggler verdict on raw
            # clocks would be silently wrong
            agg = aggregate_events(evs, run_id=rid)
        mesh = mesh_section(agg)
        evs = [e for e in agg["events"]
               if int(e.get("proc", 0)) == procs[0]]
        evs.sort(key=lambda e: e.get("seq", 0))

    # Cold-chunk attribution: a chunk following a runner-cache miss pays
    # the XLA compile inside its first dispatch — the execute/compile
    # split the recorder captures without touching the device.
    pending = None
    sequence = []
    chunks, cache = [], {"hits": 0, "misses": 0, "uncached": 0}
    saves, restores, rollbacks = [], [], []
    trips, escalations, elastic, resizes = [], [], [], []
    perf_model, perf_regressions = None, []
    audits, audit_failures = [], []
    alerts, slack_last, deadline_miss = [], None, None
    begin = end = None
    halo = {"exchanges": 0, "ppermutes": 0, "wire_bytes": 0}
    io = {"snapshots_submitted": 0, "snapshots_written": 0,
          "snapshots_staged": 0, "snapshots_dropped": 0,
          "snapshot_errors": 0, "snapshot_bytes": 0,
          "snapshot_write_s_total": 0.0, "reducer_points": 0}
    for e in evs:
        k = e.get("kind")
        if k == "runner_cache":
            res = e.get("result", "uncached")
            slot = {"hit": "hits", "miss": "misses"}.get(res, "uncached")
            cache[slot] = cache.get(slot, 0) + 1
            pending = res
            continue
        if k == "chunk":
            e = dict(e)
            e["cold"] = pending == "miss"
            pending = None
            chunks.append(e)
        elif k == "guard_trip":
            trips.append(e)
        elif k == "rollback":
            rollbacks.append(e)
        elif k == "checkpoint_save":
            saves.append(e)
        elif k == "checkpoint_restore":
            restores.append(e)
        elif k == "escalation":
            escalations.append(e)
        elif k == "elastic_restart":
            elastic.append(e)
        elif k == "resize":
            resizes.append(e)
        elif k == "halo_exchange":
            halo["exchanges"] += 1
            halo["ppermutes"] += e.get("ppermutes", 0)
            halo["wire_bytes"] += e.get("wire_bytes", 0)
        elif k == "snapshot":
            io["snapshots_submitted"] += 1
        elif k == "snapshot_write":
            io["snapshots_written"] += 1
            io["snapshot_bytes"] += e.get("nbytes", 0)
            io["snapshot_write_s_total"] += e.get("dur_s", 0.0) or 0.0
        elif k == "snapshot_stage":
            io["snapshots_staged"] += 1
        elif k == "snapshot_drop":
            io["snapshots_dropped"] += 1
        elif k == "snapshot_error":
            io["snapshot_errors"] += 1
        elif k == "reducers":
            io["reducer_points"] += 1
        elif k == "audit":
            audits.append(e)
        elif k == "audit_failed":
            audit_failures.append(e)
        elif k == "perf_model":
            perf_model = e
        elif k == "perf_regression":
            perf_regressions.append(e)
        elif k == "alert":
            alerts.append(e)
        elif k == "deadline_slack":
            slack_last = e
        elif k == "deadline_missed":
            deadline_miss = e
        elif k == "run_begin":
            begin = e
        elif k == "run_end":
            end = e
        if k in _SEQ_FIELDS:
            sequence.append(_pick(e, _SEQ_FIELDS[k]))

    reasons: dict = {}
    for t in trips:
        for r in t.get("reasons", ()):
            reasons[r] = reasons.get(r, 0) + 1
    ok = [c for c in chunks if c.get("ok")]
    exec_s = [c["exec_s"] for c in chunks if "exec_s" in c]
    ts = [e["t"] for e in evs if "t" in e]

    report = {
        "run_id": rid,
        "n_events": len(evs),
        "wall_s": (max(ts) - min(ts)) if ts else None,
        "steps": {
            "nt": begin.get("nt") if begin else None,
            "completed": (end.get("completed") if end else
                          (max((c["step_end"] for c in ok), default=None))),
        },
        "chunks": {
            "count": len(chunks),
            "ok": len(ok),
            "tripped": len(chunks) - len(ok),
            "cold": sum(1 for c in chunks if c.get("cold")),
            "exec_s_total": sum(exec_s) if exec_s else 0.0,
            "exec_s_max": max(exec_s) if exec_s else None,
        },
        "runner_cache": cache,
        "guards": {"trips": len(trips), "reasons": reasons},
        "checkpoints": {
            "saves": len(saves),
            "save_s_total": sum(s.get("dur_s", 0.0) for s in saves),
            "restores": len(restores),
            "restore_s_total": sum(r.get("dur_s", 0.0) for r in restores),
            "rollbacks": len(rollbacks),
        },
        "escalations": len(escalations),
        "elastic_restarts": [
            {"new_dims": e.get("new_dims"), "to_step": e.get("to_step")}
            for e in elastic],
        "resizes": [
            {"via": e.get("via"), "new_dims": e.get("new_dims"),
             "step": e.get("step"), "dur_s": e.get("dur_s"),
             "rounds": e.get("rounds"), "wire_bytes": e.get("wire_bytes")}
            for e in resizes],
        "halo": halo,
        "io": io,
        "audit": _audit_section(audits, audit_failures),
        "perf": _perf_section(chunks, perf_model, perf_regressions),
        "alerts": _alerts_section(alerts),
        "deadline": {
            "missed": deadline_miss is not None,
            "missed_step": None if deadline_miss is None
            else deadline_miss.get("step"),
            "slack_s_last": None if slack_last is None
            else slack_last.get("slack_s"),
            "priced_by": None if slack_last is None
            else slack_last.get("priced_by"),
        },
        "sequence": sequence,
    }
    if mesh is not None:
        report["mesh"] = mesh
    if include_metrics:
        report["metrics"] = metrics_registry().collect()
    if trace_dir is not None:
        from ..utils.profiling import op_breakdown, overlap_stats

        report["overlap_stats"] = overlap_stats(trace_dir)
        report["op_breakdown"] = [
            {"op": k, "total_us": us, "count": c}
            for k, us, c in op_breakdown(trace_dir)]
    return report
