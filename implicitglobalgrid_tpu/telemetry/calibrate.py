"""Machine calibration: short measured runs -> a `MachineProfile`.

The cost model (`telemetry.perfmodel`) is only as good as its
coefficients, and spec sheets lie about achieved rates — on the emulated
CPU mesh the 8 "devices" share one host's cores, on a real pod the
achieved HBM stream rate sits well under the headline number. So the
profile is MEASURED, with the same machinery the standalone benches use
(`bench_membw.py`'s fused triad, `bench_halo.py`'s exchange shape),
scaled down to milliseconds of timed windows (the wall clock is
compile-dominated):

- ``membw_GBps`` — a fused elementwise triad (2 reads + 1 write) over a
  SHARDED array spanning the live mesh, so every device streams
  concurrently and the per-device rate includes real contention;
- ``flops_G`` — a chain of 3-point shifted-add stencil updates over a
  small sharded array (many FLOPs per byte: the compute roofline, not
  the memory one — and slice-heavy like the real steps, so the rate is
  what stencil code achieves, not peak FMA);
- per-axis ``{"GBps", "latency_s"}`` — a forward+backward ppermute pair
  (exactly the halo exchange's wire shape) along each multi-shard mesh
  axis, timed at a small and a large payload: the two-point fit
  ``t(S) = latency + S / bw`` separates the per-collective launch cost
  from the streaming rate per link.

All measurements use the two-window slope idiom of `bench_util.two_point`
(both windows pay identical fixed costs; the slope is the pure per-call
time), re-implemented here because the package cannot depend on the
repo-root bench scripts. `calibrate_machine` needs an initialized grid
(the mesh IS the machine being profiled) and returns/persists a
`MachineProfile` with ``source="calibrated"``.

CLI: ``python -m implicitglobalgrid_tpu.tools calibrate --out profile.json``
(``--cpu`` profiles the 8-device virtual CPU mesh).
"""

from __future__ import annotations

import time

from ..utils.exceptions import InvalidArgumentError
from .perfmodel import MachineProfile, save_machine_profile

__all__ = ["calibrate_machine"]


def _two_point(run_chunk, c1: int, c2: int, reps: int = 3) -> float:
    """Steady-state seconds/iteration via two warmed one-call windows
    (the `bench_util.two_point` idiom; wall-clock timer, caller drains).
    Min-of-``reps`` per window: calibration runs on a live (possibly
    shared) host, and the minimum is the least-contended estimate — the
    timed windows are milliseconds next to the per-shape compiles, so
    extra reps are nearly free."""
    run_chunk(c1)
    run_chunk(c2)

    def timed(c):
        t0 = time.perf_counter()
        run_chunk(c)
        return time.perf_counter() - t0

    t1 = min(timed(c1) for _ in range(reps))
    t2 = min(timed(c2) for _ in range(reps))
    if t2 <= t1:  # timer jitter: fall back to the inclusive rate
        return t2 / c2
    return (t2 - t1) / (c2 - c1)


def _sharded_ones(gg, elems_per_device: int, dtype):
    """A stacked array spanning the live mesh with ~``elems_per_device``
    elements per shard (every device streams concurrently during the
    calibration loops)."""
    import jax.numpy as jnp

    from ..ops.alloc import device_put_g

    dims = [int(d) for d in gg.dims]
    # local block (m, m, m) with m^3 ~ elems_per_device, kept modest
    m = max(8, int(round(elems_per_device ** (1.0 / 3.0))))
    shape = tuple(d * m for d in dims)
    return device_put_g(jnp.ones(shape, dtype=dtype)), m ** 3


def _measure_membw_gbps(gg, elems_per_device: int, c1: int) -> float:
    """Per-device achieved triad bandwidth (2R + 1W) over the live mesh."""
    import jax
    import jax.numpy as jnp

    a, local_elems = _sharded_ones(gg, elems_per_device, jnp.float32)
    b, _ = _sharded_ones(gg, elems_per_device, jnp.float32)

    @jax.jit
    def chunk(a, b, c):
        # carry keeps b in place (a swapped carry pays a hidden copy)
        def body(_, ab):
            a, b = ab
            return (b * 1.0001 + a * 0.5, b)
        return jax.lax.fori_loop(0, c, body, (a, b))

    s = _two_point(lambda c: jax.block_until_ready(chunk(a, b, c)),
                   c1, 3 * c1)
    return 3 * 4 * local_elems / s / 1e9


def _measure_flops_g(gg, elems_per_device: int, c1: int,
                     fma_per_iter: int = 64) -> float:
    """Per-device achieved FMA rate (many FLOPs per byte: the compute
    roofline, not a second bandwidth measurement). Measured against the
    fused stencil steps this prices, XLA's elementwise fusion brings the
    real kernels within ~10-20% of this chain (verified in the
    decomposition behind the bench_perf model-ratio rows), so no
    separate stencil-efficiency fudge factor is carried."""
    import jax
    import jax.numpy as jnp

    a, local_elems = _sharded_ones(gg, elems_per_device // 8, jnp.float32)

    @jax.jit
    def chunk(a, c):
        def body(_, x):
            for _ in range(fma_per_iter):
                x = x * 1.000001 + 1e-9
            return x
        return jax.lax.fori_loop(0, c, body, a)

    s = _two_point(lambda c: jax.block_until_ready(chunk(a, c)), c1, 3 * c1)
    return 2 * fma_per_iter * local_elems / s / 1e9


def _measure_axis_link(gg, dim: int, small_bytes: int, large_bytes: int,
                       c1: int) -> dict:
    """One mesh axis's effective link coefficients from the REAL exchange
    (`local_update_halo(x, dims=(dim,))` inside a compiled loop — the
    exact pack + ppermute pair + select + unpack the steps pay, which a
    bare ppermute ring under-prices by several x): timed at two slab
    payload sizes -> ``t_exchange(S) = latency_s + S / GBps``. The field
    is THIN along the measured axis (slab bytes scale with the
    cross-section, array size stays small) so the large payload stays
    cheap to allocate."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.fields import field_partition_spec
    from ..ops.halo import local_update_halo
    from ..utils.compat import shard_map

    hw = max(1, int(gg.halowidths[dim]))

    def exchange_time(nbytes: int) -> float:
        # the measured axis keeps the grid's own local extent (a size
        # mismatch there would read as a staggered field and shift the
        # overlap, see `ol`); the cross-section dims are free and set the
        # one-direction slab payload = mm^2 * hw * 4 bytes
        mm = max(8, int(round((nbytes / (hw * 4)) ** 0.5)))
        local = [mm] * 3
        local[dim] = int(gg.nxyz[dim])
        stacked = tuple(l * int(d) for l, d in zip(local, gg.dims))
        x = jnp.ones(stacked, jnp.float32)
        spec = field_partition_spec(3)

        def body(x, c):
            def one(_, x):
                return local_update_halo(x, dims=(dim,))
            return jax.lax.fori_loop(0, c[0], one, x)

        # check_vma off: the traced while-loop trip count has no
        # replication rule under the variance checker
        fn = jax.jit(shard_map(body, mesh=gg.mesh, in_specs=(spec, P()),
                               out_specs=spec, check_vma=False))

        def run_chunk(c):
            jax.block_until_ready(fn(x, jnp.asarray([c], jnp.int32)))

        actual = mm * mm * hw * 4
        return _two_point(run_chunk, c1, 3 * c1), actual

    t_small, s_small = exchange_time(small_bytes)
    t_large, s_large = exchange_time(large_bytes)
    if t_large > t_small and s_large > s_small:
        bw = (s_large - s_small) / (t_large - t_small)
        lat = max(0.0, t_small - s_small / bw)
    else:  # jitter collapse: charge everything to bandwidth
        bw = s_large / t_large
        lat = 0.0
    return {"GBps": bw / 1e9, "latency_s": lat}


def calibrate_machine(path=None, *, elems_per_device: int = 1 << 18,
                      link_bytes=(1 << 13, 1 << 20), c1: int = 4,
                      ensemble: int | None = None,
                      profile_meta: dict | None = None) -> MachineProfile:
    """Measure this mesh's machine profile (milliseconds of measured
    windows; wall clock is dominated by the handful of per-shape XLA
    compiles the micro-kernels pay).

    Needs an initialized grid — the live `jax.sharding.Mesh` IS the
    machine being profiled (per-device rates include any device-sharing
    contention; per-axis links are measured along the actual mesh axes).
    ``elems_per_device`` sizes the bandwidth/FLOPs arrays;
    ``link_bytes=(small, large)`` are the two payloads of the per-axis
    two-point link fit; ``c1`` is the small window's iteration count.
    Axes with a single non-periodic shard carry no wire and are profiled
    as the mean of the measured axes when the model asks.

    ``ensemble=E`` calibrates the link fit in the E-member payload
    regime (ISSUE 12): the two fitted payload sizes scale by E — the
    batched exchange ships E x the slab bytes behind the same ppermute
    pair, so an ensemble-sized fit measures the bandwidth plateau those
    payloads actually ride instead of extrapolating from solo slabs. The
    member count is recorded in the profile's ``meta``.

    With ``path``, the profile is also persisted as JSON
    (`save_machine_profile` / `load_machine_profile`). Returns the
    `MachineProfile` (``source="calibrated"``)."""
    from ..parallel.topology import check_initialized, global_grid

    check_initialized()
    gg = global_grid()
    if len(link_bytes) != 2 or link_bytes[0] >= link_bytes[1]:
        raise InvalidArgumentError(
            f"calibrate_machine: link_bytes must be (small, large) with "
            f"small < large; got {tuple(link_bytes)}.")
    if ensemble is not None:
        E = int(ensemble)
        if E < 1:
            raise InvalidArgumentError(
                f"calibrate_machine: ensemble must be >= 1; got "
                f"{ensemble}.")
        link_bytes = (int(link_bytes[0]) * E, int(link_bytes[1]) * E)
        profile_meta = dict(profile_meta or {}, ensemble=E)

    t0 = time.time()
    membw = _measure_membw_gbps(gg, elems_per_device, c1)
    flops = _measure_flops_g(gg, elems_per_device, c1)
    axes = {}
    from ..parallel.topology import AXIS_NAMES

    for dim in range(3):
        D = int(gg.dims[dim])
        if D <= 1:
            continue  # no inter-shard link along this axis
        axes[AXIS_NAMES[dim]] = _measure_axis_link(
            gg, dim, int(link_bytes[0]), int(link_bytes[1]), c1)

    device = {"platform": gg.device_type,
              "dims": [int(d) for d in gg.dims],
              "n_shards": int(gg.nprocs)}
    try:
        import jax

        d0 = jax.devices()[0]
        device["device_kind"] = d0.device_kind
    except Exception:
        pass
    profile = MachineProfile(
        membw_GBps=membw, flops_G=flops, axes=axes, source="calibrated",
        device=device, calibrated_at=t0,
        meta={**(profile_meta or {}),
              "elems_per_device": int(elems_per_device),
              "link_bytes": [int(b) for b in link_bytes],
              "calibrate_s": time.time() - t0})
    if path is not None:
        save_machine_profile(profile, path)
    return profile
