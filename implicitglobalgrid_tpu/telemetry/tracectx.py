"""W3C trace-context: the ONE causal identity threaded through the stack.

A `TraceContext` is the (trace id, span id, parent span id) triple of the
W3C Trace Context recommendation (https://www.w3.org/TR/trace-context/):
a 128-bit trace id naming the END-TO-END request and a 64-bit span id
naming the current operation within it. The wire form is the
``traceparent`` header::

    traceparent: 00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01
                 ^^ ^^^^^^^^^^^^^^^^ trace id ^^^^^^ ^^ span id ^^^^^^ ^^
               version                                              flags

`JobApiServer` parses (or mints) one per ``POST /v1/jobs``, stamps it
into the queue record, and the scheduler derives a fresh CHILD span for
the job and for every journal event under it — so a submit, its queue
claim, its admission verdict, each granted slice, the alert that fired
on it, and the resize chain it triggered all share one trace id and form
one parent-linked tree (`telemetry.otlp.export_otlp` renders it).

Everything here is stdlib-only and host-side: ids come from
`os.urandom`, no clock reads, no allocation beyond the frozen dataclass.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field, replace

from ..utils.exceptions import InvalidArgumentError

__all__ = ["TraceContext", "new_trace_id", "new_span_id"]

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id() -> str:
    """A fresh 128-bit trace id as 32 lowercase hex chars (never all-zero
    — the W3C invalid sentinel)."""
    while True:
        tid = os.urandom(16).hex()
        if tid != "0" * 32:
            return tid


def new_span_id() -> str:
    """A fresh 64-bit span id as 16 lowercase hex chars (never all-zero)."""
    while True:
        sid = os.urandom(8).hex()
        if sid != "0" * 16:
            return sid


@dataclass(frozen=True)
class TraceContext:
    """One node of a distributed trace: ``trace_id`` names the request,
    ``span_id`` this operation, ``parent_span_id`` the operation that
    caused it (None at the root).  ``flags`` is the W3C trace-flags octet
    (``01`` = sampled, the only defined bit)."""

    trace_id: str
    span_id: str = field(default_factory=new_span_id)
    parent_span_id: str | None = None
    flags: str = "01"

    def __post_init__(self):
        for name, val, n in (("trace_id", self.trace_id, 32),
                             ("span_id", self.span_id, 16)):
            if not isinstance(val, str) or len(val) != n \
                    or any(c not in "0123456789abcdef" for c in val) \
                    or val == "0" * n:
                raise InvalidArgumentError(
                    f"TraceContext: {name} must be {n} lowercase hex chars "
                    f"and not all-zero, got {val!r}.")
        if self.parent_span_id is not None \
                and (not isinstance(self.parent_span_id, str)
                     or len(self.parent_span_id) != 16
                     or any(c not in "0123456789abcdef"
                            for c in self.parent_span_id)):
            raise InvalidArgumentError(
                "TraceContext: parent_span_id must be 16 lowercase hex "
                f"chars or None, got {self.parent_span_id!r}.")

    # -- construction --------------------------------------------------

    @classmethod
    def new(cls) -> "TraceContext":
        """A fresh ROOT context: new trace id, new span id, no parent."""
        return cls(trace_id=new_trace_id())

    @classmethod
    def parse(cls, traceparent: str) -> "TraceContext":
        """Parse a ``traceparent`` header value.  The caller becomes a
        CHILD of the header's span: the parsed span id lands in
        ``span_id`` (call `child()` to derive the local span).  Raises
        `InvalidArgumentError` on malformed input, all-zero ids, or the
        reserved version ``ff``."""
        if not isinstance(traceparent, str):
            raise InvalidArgumentError(
                f"traceparent must be a string, got "
                f"{type(traceparent).__name__}.")
        m = _TRACEPARENT_RE.match(traceparent.strip().lower())
        if m is None:
            raise InvalidArgumentError(
                f"malformed traceparent {traceparent!r} (want "
                f"'<2hex>-<32hex>-<16hex>-<2hex>').")
        version, trace_id, span_id, flags = m.groups()
        if version == "ff":
            raise InvalidArgumentError(
                f"traceparent version 'ff' is invalid ({traceparent!r}).")
        if trace_id == "0" * 32 or span_id == "0" * 16:
            raise InvalidArgumentError(
                f"traceparent has all-zero id(s) ({traceparent!r}).")
        return cls(trace_id=trace_id, span_id=span_id, flags=flags)

    # -- derivation ----------------------------------------------------

    def child(self) -> "TraceContext":
        """A new span under this one: same trace, fresh span id, parent
        link to `self.span_id`."""
        return replace(self, span_id=new_span_id(),
                       parent_span_id=self.span_id)

    # -- rendering -----------------------------------------------------

    def to_traceparent(self) -> str:
        """The W3C header value for THIS span (version 00)."""
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    def fields(self) -> dict:
        """The journal/flight stamp: the keys `MeshScheduler._log` and
        `export_otlp` agree on. ``parent_span_id`` only when present."""
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id is not None:
            d["parent_span_id"] = self.parent_span_id
        return d
