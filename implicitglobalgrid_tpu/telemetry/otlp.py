"""OTLP/HTTP JSON export of traced journal + flight streams.

`export_otlp` renders every trace-stamped event in a flight directory
(the scheduler journal plus per-job flight recorders) as OTLP/HTTP JSON
``ResourceSpans`` — the wire shape any OpenTelemetry collector's
``/v1/traces`` endpoint accepts — so the causal tree the scheduler
stamped (`telemetry.tracectx`) becomes one navigable distributed trace:

- one RESOURCE per (run, process): ``service.name`` is
  ``igg-scheduler`` for the journal and ``igg-job`` for per-job flight
  streams, with ``igg.run``/``igg.proc``/``igg.pid`` attributes;
- one SPAN per traced event; journal events carry their minted span id,
  flight events (which the hot path stamps with only the trace id and
  the job-root parent, `recorder.FlightRecorder.trace`) get a
  DETERMINISTIC export-time id derived from ``(trace, run, proc, seq)``
  — the recorder pays one dict update per event, never an id mint;
- guard trips, alert transitions, and autoscale verdicts double as
  span EVENTS on their parent span (the red flags a collector UI pins
  onto the enclosing operation);
- each applied flight ``resize`` span LINKS back to the
  ``resize_requested`` journal span that asked for it, pairing the
  request/apply halves of the resize chain across streams.

`OtlpSpanExporter` is the live half: a batched, never-raising sink the
scheduler (or any journal consumer) can feed event dicts — encoded with
the same renderer and POSTed to a collector endpoint via urllib.

Everything is stdlib-only; timestamps are each stream's monotonic
stamps re-anchored to wall clock via its ``recorder_open`` record.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import urllib.request

from ..utils.exceptions import InvalidArgumentError
from .recorder import read_flight_events

__all__ = ["export_otlp", "OtlpSpanExporter"]

_SCOPE = {"name": "implicitglobalgrid_tpu"}

# Reserved stream keys that never become span attributes.
_SKIP_ATTRS = ("t", "t_mono", "t_offset", "kind", "run", "pid", "proc",
               "seq", "trace_id", "span_id", "parent_span_id", "wall",
               "version")

# Kinds that ALSO attach as OTLP span events on their parent span.
_EVENT_KINDS = ("guard_trip", "alert", "autoscale_decision",
                "deadline_missed", "rollback", "escalation",
                "fault_injected", "perf_regression")


def _synth_span_id(trace_id: str, e: dict) -> str:
    """Deterministic span id for an event that carries no minted one
    (flight-recorder hot path): stable across exports, unique per
    (trace, run, proc, seq)."""
    key = f"{trace_id}:{e.get('run')}:{e.get('proc')}:{e.get('seq')}"
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def _attr_value(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP/JSON renders int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, str):
        return {"stringValue": v}
    return {"stringValue": json.dumps(v, default=str, sort_keys=True)}


def _attrs(d: dict, skip=_SKIP_ATTRS) -> list:
    return [{"key": k, "value": _attr_value(v)}
            for k, v in d.items() if k not in skip and v is not None]


def _span_window(e: dict) -> tuple[float, float]:
    """(start, end) on the stream's monotonic clock: the stamp is the
    END; spans reach back by their recorded duration(s)."""
    end = float(e["t"])
    start = end
    if "exec_s" in e:  # chunk spans: build + exec precede the stamp
        start -= float(e.get("exec_s") or 0.0)
        start -= float(e.get("build_s") or 0.0)
    else:
        start -= float(e.get("dur_s") or 0.0)
    return start, end


def _resolve_streams(source):
    """source -> list of (label, events) per JSONL stream.  Accepts a
    directory (``*.jsonl`` globbed), one path, a list of paths, or an
    iterable of already-loaded event dicts (one stream)."""
    if isinstance(source, (str, os.PathLike)):
        src = os.fspath(source)
        if os.path.isdir(src):
            paths = sorted(
                os.path.join(src, f) for f in os.listdir(src)
                if f.endswith(".jsonl"))
            if not paths:
                raise InvalidArgumentError(
                    f"export_otlp: no *.jsonl streams under {src!r}.")
        else:
            paths = [src]
        return [(p, read_flight_events(p)) for p in paths]
    evs = list(source)
    if evs and isinstance(evs[0], (str, os.PathLike)):
        return [(os.fspath(p), read_flight_events(os.fspath(p)))
                for p in evs]
    return [("<events>", evs)]


def _stream_anchor(events: list) -> float:
    """Wall-clock anchor for a stream's monotonic stamps: its
    ``recorder_open`` record carries both clocks."""
    for e in events:
        if e.get("kind") == "recorder_open" and "wall" in e and "t" in e:
            return float(e["wall"]) - float(e["t"])
    return 0.0


def encode_spans(streams, *, trace_id=None, job=None,
                 default_anchor=None):
    """Render ``streams`` (list of (label, events)) as an OTLP/HTTP JSON
    document ``{"resourceSpans": [...]}``.  ``trace_id``/``job`` filter
    to one trace / one job's events.  Events without a ``trace_id``
    stamp are skipped — they belong to no trace."""
    by_resource: dict = {}   # (run, proc, pid) -> list of span dicts
    span_index: dict = {}    # span_id -> span dict
    meta: list = []          # (kind, job, end_ns, span) for links/events

    for _label, events in streams:
        anchor = _stream_anchor(events)
        if anchor == 0.0 and default_anchor is not None:
            anchor = default_anchor
        for e in events:
            tid = e.get("trace_id")
            if tid is None or "t" not in e or e.get("kind") is None:
                continue
            if trace_id is not None and tid != trace_id:
                continue
            run = str(e.get("run", ""))
            ejob = e.get("job") if e.get("job") is not None else \
                (run if run not in ("", "scheduler") else None)
            if job is not None and ejob != job:
                continue
            start, end = _span_window(e)
            start_ns = int((anchor + start) * 1e9)
            end_ns = int((anchor + end) * 1e9)
            sid = e.get("span_id") or _synth_span_id(tid, e)
            span = {
                "traceId": tid,
                "spanId": sid,
                "name": str(e["kind"]),
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(end_ns),
                "attributes": _attrs(e),
            }
            if e.get("parent_span_id"):
                span["parentSpanId"] = e["parent_span_id"]
            key = (run, int(e.get("proc", 0) or 0), int(e.get("pid", 0)
                                                        or 0))
            by_resource.setdefault(key, []).append(span)
            span_index[sid] = span
            meta.append((str(e["kind"]), ejob, end_ns, span))

    # span EVENTS: pin red-flag kinds onto their parent span too
    for kind, _ejob, end_ns, span in meta:
        if kind in _EVENT_KINDS and span.get("parentSpanId"):
            parent = span_index.get(span["parentSpanId"])
            if parent is not None:
                parent.setdefault("events", []).append({
                    "timeUnixNano": str(end_ns), "name": kind,
                    "attributes": span["attributes"]})

    # LINKS: each applied flight resize span -> the resize_requested
    # journal span that asked for it (paired per job, in time order)
    reqs: dict = {}
    applies: dict = {}
    for kind, ejob, end_ns, span in meta:
        if kind == "resize_requested":
            reqs.setdefault(ejob, []).append((end_ns, span))
        elif kind == "resize":
            applies.setdefault(ejob, []).append((end_ns, span))
    for ejob, apps in applies.items():
        req_spans = sorted(reqs.get(ejob, []))
        for i, (_t, span) in enumerate(sorted(apps)):
            if i < len(req_spans):
                req = req_spans[i][1]
                span.setdefault("links", []).append({
                    "traceId": req["traceId"],
                    "spanId": req["spanId"],
                    "attributes": [{"key": "igg.link",
                                    "value": {"stringValue":
                                              "resize_requested"}}]})

    resource_spans = []
    for (run, proc, pid), spans in sorted(by_resource.items()):
        service = "igg-scheduler" if run == "scheduler" else "igg-job"
        res_attrs = {"service.name": service, "igg.run": run,
                     "igg.proc": proc, "igg.pid": pid}
        resource_spans.append({
            "resource": {"attributes": _attrs(res_attrs, skip=())},
            "scopeSpans": [{"scope": dict(_SCOPE), "spans": spans}],
        })
    return {"resourceSpans": resource_spans}


def export_otlp(source, out=None, *, trace_id: str | None = None,
                job: str | None = None):
    """Render ``source`` (a flight directory, stream path(s), or event
    iterable) as OTLP/HTTP JSON ``ResourceSpans``.

    With ``out`` (a path), writes the JSON there and returns the path;
    otherwise returns the document dict.  POST it verbatim to any OTel
    collector's ``/v1/traces`` (``content-type: application/json``)."""
    doc = encode_spans(_resolve_streams(source), trace_id=trace_id,
                       job=job)
    if not doc["resourceSpans"]:
        raise InvalidArgumentError(
            "export_otlp: no trace-stamped events matched "
            f"(trace_id={trace_id!r}, job={job!r}).")
    if out is None:
        return doc
    out = os.fspath(out)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return out


class OtlpSpanExporter:
    """Batched live exporter: feed it traced event dicts (a journal
    sink), it POSTs OTLP/HTTP JSON to ``endpoint`` every ``batch``
    events.  NEVER raises into the caller's hot path — failures are
    counted (`sent`/`failed`, `last_error`) and the batch dropped.

    Live events carry in-process monotonic stamps with no
    ``recorder_open`` in sight; the exporter anchors them to wall clock
    at construction (same process, same clocks)."""

    def __init__(self, endpoint: str, *, batch: int = 64,
                 timeout_s: float = 5.0, headers: dict | None = None):
        if not isinstance(endpoint, str) or not endpoint:
            raise InvalidArgumentError(
                "OtlpSpanExporter: endpoint must be a non-empty URL.")
        if int(batch) < 1:
            raise InvalidArgumentError(
                f"OtlpSpanExporter: batch must be >= 1, got {batch}.")
        self.endpoint = endpoint
        self.batch = int(batch)
        self.timeout_s = float(timeout_s)
        self.headers = dict(headers or {})
        self.sent = 0
        self.failed = 0
        self.last_error: str | None = None
        self._buf: list = []
        self._anchor = time.time() - time.monotonic()

    def add(self, event: dict) -> None:
        """Buffer one event; flushes automatically at the batch size.
        Untraced events (no ``trace_id``) are ignored."""
        if not isinstance(event, dict) or event.get("trace_id") is None:
            return
        self._buf.append(dict(event))
        if len(self._buf) >= self.batch:
            self.flush()

    __call__ = add  # usable directly as a journal/alert sink

    def flush(self) -> None:
        if not self._buf:
            return
        batch, self._buf = self._buf, []
        doc = encode_spans([("<live>", batch)],
                           default_anchor=self._anchor)
        if not doc["resourceSpans"]:
            return
        body = json.dumps(doc).encode()
        try:
            self._post(body)
            self.sent += len(batch)
        except Exception as exc:  # noqa: BLE001 — sink must not raise
            self.failed += len(batch)
            self.last_error = f"{type(exc).__name__}: {exc}"

    def _post(self, body: bytes) -> None:
        """One OTLP/HTTP POST; override in tests to capture payloads."""
        req = urllib.request.Request(
            self.endpoint, data=body, method="POST",
            headers={"Content-Type": "application/json", **self.headers})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()

    def close(self) -> None:
        self.flush()
