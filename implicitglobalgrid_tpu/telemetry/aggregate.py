"""Cross-process flight aggregation: N per-process JSONLs -> one mesh view.

The flight recorder (`telemetry/recorder.py`) is strictly process-local:
every controller streams its own JSONL (the ``flight_p<process_index>``
convention when started with a directory). At scale the questions that
matter are CROSS-process — which process is the straggler stalling every
chunk-boundary psum, how skewed are arrivals, is the imbalance compute or
host-side — so this module merges those per-process streams post-hoc into
one mesh-wide, clock-aligned event sequence:

- `aggregate_flight(source)` loads every per-process stream (a directory
  is globbed for ``*.jsonl``), validates run-id and per-process sequence
  consistency, estimates per-process clock offsets, and returns the
  merged, time-sorted sequence plus alignment metadata.
- `straggler_report(agg)` turns the merged stream into per-chunk arrival
  spreads at the barrier, slowest-process attribution, rolling-window
  persistent-straggler flags, and a per-process wait/compute imbalance
  summary.
- `mesh_section(events)` is the compact form `run_report` embeds as its
  ``"mesh"`` section.

Clock alignment needs no new collectives: every chunk already ENDS at the
health guard's psum — a barrier all processes leave together — so each
process's ``chunk`` record timestamps the same physical instant (plus its
own tiny fetch jitter). Per process, the monotonic clock is first anchored
to wall time via its ``recorder_open`` record, then the residual offset to
the reference process (the lowest index) is the MEDIAN of the per-chunk
barrier-timestamp deltas — robust to a few slow fetches. Everything here
is pure post-hoc host arithmetic over the JSONLs.

Attribution model (documented assumption): the chunk program is identical
on every process, so the unencumbered per-chunk compute time is estimated
as the MINIMUM ``exec_s`` across processes (the last arriver never waits
at the barrier, everyone else's ``exec_s`` is inflated by exactly its
wait). A process's barrier ARRIVAL is therefore its corrected dispatch
start plus that common compute estimate — host-side delays (slow
checkpoint disk, GC pauses, a sick VM) show up as late dispatch starts
and are attributed to the process that incurred them.
"""

from __future__ import annotations

import glob
import os
import statistics

from ..utils.exceptions import InvalidArgumentError
from .recorder import read_flight_events

__all__ = ["aggregate_flight", "aggregate_events", "straggler_report",
           "mesh_section"]


def _resolve_paths(source) -> list:
    """``source`` -> list of JSONL paths: a directory is globbed for
    ``*.jsonl`` (the ``flight_p<i>.jsonl`` convention plus any legacy
    single-file streams), a single file is itself, an iterable of paths
    passes through."""
    if isinstance(source, (str, os.PathLike)):
        source = os.fspath(source)
        if os.path.isdir(source):
            paths = sorted(glob.glob(os.path.join(source, "*.jsonl")))
            if not paths:
                raise InvalidArgumentError(
                    f"aggregate_flight: no *.jsonl files under {source}.")
            return paths
        return [source]
    paths = [os.fspath(p) for p in source]
    if not paths:
        raise InvalidArgumentError("aggregate_flight: no paths given.")
    return paths


def _pick_run_id(events: list, run_id) -> str | None:
    """The one run id to aggregate: explicit, or the single id present —
    several ids without an explicit choice is an error (streams from
    different runs must never be silently mixed into one timeline)."""
    if run_id is not None:
        return str(run_id)
    ids = []
    for e in events:
        r = e.get("run")
        if r is not None and r not in ids:
            ids.append(r)
    if not ids:
        return None
    if len(ids) > 1:
        raise InvalidArgumentError(
            f"aggregate_flight: {len(ids)} run ids present ({ids}); pass "
            "run_id= to select one.")
    return ids[0]


def _chunk_ends(events: list) -> dict:
    """{chunk_index: barrier timestamp} for one process's stream."""
    return {e["chunk"]: e["t"] for e in events
            if e.get("kind") == "chunk" and "chunk" in e and "t" in e}


def aggregate_flight(source, *, run_id: str | None = None) -> dict:
    """Merge per-process flight streams into one mesh-wide sequence.

    ``source``: a directory (globbed for ``*.jsonl``), one path, or an
    iterable of paths. ``run_id`` selects a run when the streams hold
    several (required then — mixing runs raises).

    Returns ``{run_id, processes, files, anchor_proc, offsets, align,
    per_process, events}`` where ``events`` is the merged sequence sorted
    by corrected time (each event's ``t`` is rewritten onto the reference
    process's wall-anchored clock; the original monotonic stamp moves to
    ``t_mono``, the applied correction to ``t_offset``). Offsets are the
    residual per-process corrections estimated at the chunk barriers
    (``align.method[proc] == "chunk-barrier"``; a process sharing no
    chunk with the anchor falls back to its wall-clock anchor alone,
    ``"wall-anchor"``, without degrading the others' fit metadata).

    Validation: one run id across all streams; within each process the
    (possibly multi-file) sequence numbers must be duplicate-free and
    gapless FROM 0 — anything else means a foreign writer interleaved the
    stream, a file was truncated mid-run, or the stream's head (with the
    ``recorder_open`` wall anchor) is missing, and raises
    `InvalidArgumentError` (a torn FINAL line is still tolerated by the
    underlying reader)."""
    paths = _resolve_paths(source)
    raw = []
    for p in paths:
        for e in read_flight_events(p):
            e["_file"] = p
            raw.append(e)
    agg = aggregate_events(raw, run_id=run_id, _what="aggregate_flight")
    files: dict = {}
    for e in agg["events"]:
        files.setdefault(int(e.get("proc", 0)), set()).add(e.pop("_file"))
    agg["files"] = {p: sorted(fs) for p, fs in files.items()}
    for proc, meta in agg["per_process"].items():
        meta["files"] = agg["files"].get(proc, [])
    return agg


_RESUME_CHUNKS = 64  # barrier timestamps carried per process for alignment


def aggregate_events(events, *, run_id: str | None = None,
                     resume: dict | None = None,
                     _what: str = "aggregate_events") -> dict:
    """`aggregate_flight` for ALREADY-LOADED events: the same run-id
    selection, per-process seq validation, and clock alignment over an
    iterable of event dicts (however they were read or concatenated).
    Returns the same record minus the ``files`` map.

    ``resume`` makes it INCREMENTAL for tailers: pass the ``"resume"``
    record of the previous call and an events batch holding only the
    NEW records (e.g. from `read_flight_events(..., offset=)`). Seq
    validation then requires each process's batch to be gapless from
    its checkpointed next seq (not from 0), the wall anchors default to
    the checkpointed ones (a ``recorder_open`` is only expected in the
    first batch), and the barrier-offset medians are computed over the
    checkpoint's carried chunk ends PLUS the batch's — so alignment
    quality matches a full re-read without re-validating history. The
    result's ``events`` hold only the aligned batch; its ``"resume"``
    record feeds the next call. An EMPTY batch is valid with ``resume``
    (returns no events, state carried through)."""
    raw = list(events)
    prior = resume or {}
    rid = _pick_run_id(raw, run_id if run_id is not None
                       else prior.get("run_id"))
    per_proc: dict = {}
    for e in raw:
        if rid is not None and e.get("run") != rid:
            continue
        per_proc.setdefault(int(e.get("proc", 0)), []).append(e)
    if not per_proc and resume is None:
        raise InvalidArgumentError(f"{_what}: no events for run {rid!r}.")

    # --- seq consistency: duplicate-free, gapless per process (from 0,
    # or from the resume checkpoint's next expected seq) -----------------
    next_seq = {int(p): int(n)
                for p, n in (prior.get("next_seq") or {}).items()}
    per_process_meta = {}
    for proc, evs in per_proc.items():
        base = next_seq.get(proc, 0)
        seqs = sorted(e["seq"] for e in evs if "seq" in e)
        if len(set(seqs)) != len(seqs):
            raise InvalidArgumentError(
                f"{_what}: duplicate sequence numbers for process "
                f"{proc} (run {rid!r}) — two writers interleaved one "
                "stream.")
        if seqs and seqs != list(range(base, base + len(seqs))):
            at = "do not start at 0" if base == 0 else \
                f"do not resume at {base}"
            raise InvalidArgumentError(
                f"{_what}: process {proc} (run {rid!r}) has gaps in its "
                f"sequence numbers (or they {at}) — a stream "
                "file is missing, was truncated mid-run, or lost its head "
                "(the recorder_open wall anchor).")
        evs.sort(key=lambda e: e.get("seq", 0))
        if seqs:
            next_seq[proc] = seqs[-1] + 1
        per_process_meta[proc] = {
            "events": len(evs),
            "chunks": sum(1 for e in evs if e.get("kind") == "chunk"),
        }

    # --- clock alignment -------------------------------------------------
    # 1) per process: monotonic -> wall via the recorder_open anchor
    #    (carried through resume once seen)
    wall_anchor = {int(p): float(a)
                   for p, a in (prior.get("wall_anchor") or {}).items()}
    for proc, evs in per_proc.items():
        for e in evs:
            if e.get("kind") == "recorder_open" and "wall" in e:
                wall_anchor[proc] = float(e["wall"]) - float(e["t"])
                break
        wall_anchor.setdefault(proc, 0.0)
    # union of every process ever seen: a process silent THIS batch keeps
    # its alignment state (and its offset) across incremental calls
    chunk_hist = {int(p): {int(c): float(t) for c, t in ends.items()}
                  for p, ends in (prior.get("chunk_ends") or {}).items()}
    procs = sorted(set(per_proc) | set(chunk_hist) | set(wall_anchor))
    if not procs:
        raise InvalidArgumentError(f"{_what}: no events for run {rid!r}.")
    anchor = procs[0]
    # 2) residual offset to the anchor process: median delta of the
    #    chunk-barrier timestamps over the chunks both processes logged
    #    (resume carries the trailing _RESUME_CHUNKS barriers per process)
    for proc, evs in per_proc.items():
        hist = chunk_hist.setdefault(proc, {})
        hist.update(_chunk_ends(evs))
        if len(hist) > _RESUME_CHUNKS:
            for c in sorted(hist)[:len(hist) - _RESUME_CHUNKS]:
                del hist[c]
    ref_ends = chunk_hist.get(anchor, {})
    offsets = {anchor: 0.0}
    residuals = {anchor: 0.0}
    chunks_used = {anchor: len(ref_ends)}
    # per-process alignment method: one crashed-early stream falling back
    # to its wall anchor must not misreport the healthy streams' quality
    methods = {anchor: "anchor"}
    for proc in procs[1:]:
        ends = chunk_hist.get(proc, {})
        common = sorted(set(ends) & set(ref_ends))
        deltas = [(ends[c] + wall_anchor[proc])
                  - (ref_ends[c] + wall_anchor[anchor]) for c in common]
        chunks_used[proc] = len(common)
        methods[proc] = "chunk-barrier"
        if len(deltas) >= 2:
            off = statistics.median(deltas)
            residuals[proc] = statistics.median(
                abs(d - off) for d in deltas)
        elif deltas:
            off = deltas[0]
            residuals[proc] = 0.0
        else:  # nothing shared: the wall anchor is all we have
            off, residuals[proc] = 0.0, None
            methods[proc] = "wall-anchor"
        offsets[proc] = off

    merged = []
    for proc, evs in per_proc.items():
        shift = wall_anchor[proc] - offsets[proc]
        for e in evs:
            e = dict(e)
            if "t" in e:
                e["t_mono"] = e["t"]
                e["t"] = float(e["t"]) + shift
            e["t_offset"] = offsets[proc]
            merged.append(e)
    merged.sort(key=lambda e: (e.get("t", 0.0), e.get("proc", 0),
                               e.get("seq", 0)))
    return {
        "run_id": rid,
        "processes": procs,
        "anchor_proc": anchor,
        "offsets": offsets,
        "align": {"method": methods,
                  "chunks_used": chunks_used,
                  "residual_s": residuals},
        "per_process": per_process_meta,
        "events": merged,
        "resume": {"run_id": rid,
                   "next_seq": dict(next_seq),
                   "wall_anchor": dict(wall_anchor),
                   "chunk_ends": {p: dict(h)
                                  for p, h in chunk_hist.items()}},
    }


def _events_of(agg_or_events) -> list:
    if isinstance(agg_or_events, dict):
        return agg_or_events["events"]
    return list(agg_or_events)


def straggler_report(agg_or_events, *, window: int = 8,
                     share: float = 0.5) -> dict:
    """Straggler & imbalance analysis over an aggregated event stream.

    ``agg_or_events``: the `aggregate_flight` result (or any clock-aligned
    event list). ``window``/``share``: a process is flagged a PERSISTENT
    straggler when it is the slowest arriver in more than ``share`` of the
    chunks of any ``window``-chunk rolling window (adjacent flagged
    windows merge into one span).

    Returns::

        {"processes": [...],
         "chunks": [{chunk, step_end, spread_s, slowest, compute_s,
                     arrival_s: {proc: lateness vs first}}, ...],
         "slowest_counts": {proc: n},
         "persistent": [{proc, first_chunk, last_chunk, chunks, share}],
         "imbalance": {proc: {exec_s_total, compute_s_total, wait_s_total,
                              wait_frac, build_s_total}},
         "perf_regressions": {events, per_process, chunks: [{chunk,
                              procs, scope, max_z}], mesh_wide, localized}
                              | None,
         "summary": {chunks, spread_s_mean, spread_s_max, worst_proc}}

    ``perf_regressions`` classifies the drift detector's
    ``perf_regression`` events (`telemetry.perfmodel.PerfWatch` via the
    driver) across the mesh: a chunk flagged by at least half the
    processes — and never fewer than two, so one sick process can't
    read as the whole mesh — is a MESH-WIDE slowdown (thermal
    throttling, a shared-filesystem stall, an interconnect event); one
    flagged by fewer is LOCALIZED and attributed to the flagging
    process(es) — the same
    verdict the arrival-spread analysis gives, but from each process's
    own baseline, so it also catches a slowdown that hits everyone
    equally (which barrier spreads are blind to). None when no stream
    carries perf events.

    Arrival model: see the module docstring — arrival = corrected dispatch
    start + min-across-processes ``exec_s`` (the unencumbered compute
    estimate); the per-chunk barrier wait of a process is its ``exec_s``
    excess over that minimum. Only chunks logged by EVERY process enter
    the analysis (a chunk one process never ran — mid-rollback divergence
    — has no mesh-wide barrier to measure)."""
    events = _events_of(agg_or_events)
    by_chunk: dict = {}
    procs = set()
    for e in events:
        if e.get("kind") != "chunk" or "exec_s" not in e:
            continue
        proc = int(e.get("proc", 0))
        procs.add(proc)
        # retried chunk indices (rollback) keep the LAST occurrence
        by_chunk.setdefault(e.get("chunk"), {})[proc] = e
    procs = sorted(procs)
    if len(procs) < 2:
        raise InvalidArgumentError(
            "straggler_report needs chunk events from at least two "
            f"processes (have {procs}); aggregate per-process streams "
            "first (aggregate_flight).")

    chunks = []
    slowest_counts = {p: 0 for p in procs}
    totals = {p: {"exec_s_total": 0.0, "wait_s_total": 0.0,
                  "build_s_total": 0.0} for p in procs}
    for c in sorted(k for k, v in by_chunk.items() if len(v) == len(procs)):
        recs = by_chunk[c]
        compute = min(float(r["exec_s"]) for r in recs.values())
        arrivals = {p: (float(r["t"]) - float(r["exec_s"])) + compute
                    for p, r in recs.items()}
        first = min(arrivals.values())
        slowest = max(arrivals, key=arrivals.get)
        spread = arrivals[slowest] - first
        slowest_counts[slowest] += 1
        for p, r in recs.items():
            totals[p]["exec_s_total"] += float(r["exec_s"])
            totals[p]["wait_s_total"] += float(r["exec_s"]) - compute
            totals[p]["build_s_total"] += float(r.get("build_s", 0.0))
        chunks.append({
            "chunk": c,
            "step_end": recs[slowest].get("step_end"),
            "spread_s": spread,
            "slowest": slowest,
            "compute_s": compute,
            "arrival_s": {p: arrivals[p] - first for p in procs},
        })

    # rolling-window persistent-straggler flags (merged into spans); a
    # run shorter than the window is judged over the chunks it has
    win_n = min(int(window), len(chunks))
    persistent = []
    for i in range(len(chunks) - win_n + 1 if win_n else 0):
        win = chunks[i:i + win_n]
        counts: dict = {}
        for ch in win:
            counts[ch["slowest"]] = counts.get(ch["slowest"], 0) + 1
        for p, n in counts.items():
            if n / len(win) <= share:
                continue
            prev = persistent[-1] if persistent else None
            if prev and prev["proc"] == p \
                    and win[0]["chunk"] <= prev["last_chunk"] + 1:
                prev["last_chunk"] = win[-1]["chunk"]
            else:
                persistent.append({"proc": p,
                                   "first_chunk": win[0]["chunk"],
                                   "last_chunk": win[-1]["chunk"]})
    # chunks/share describe the MERGED span, not one contributing window
    for span in persistent:
        within = [c for c in chunks
                  if span["first_chunk"] <= c["chunk"]
                  <= span["last_chunk"]]
        n = sum(1 for c in within if c["slowest"] == span["proc"])
        span["chunks"] = n
        span["share"] = n / len(within)

    imbalance = {}
    for p, t in totals.items():
        ex = t["exec_s_total"]
        imbalance[p] = {
            **t,
            "compute_s_total": ex - t["wait_s_total"],
            "wait_frac": (t["wait_s_total"] / ex) if ex else 0.0,
        }
    spreads = [c["spread_s"] for c in chunks]
    return {
        "processes": procs,
        "chunks": chunks,
        "slowest_counts": slowest_counts,
        "persistent": persistent,
        "imbalance": imbalance,
        "perf_regressions": _perf_regressions(events, procs),
        "summary": {
            "chunks": len(chunks),
            "spread_s_mean": (sum(spreads) / len(spreads)) if spreads
            else None,
            "spread_s_max": max(spreads) if spreads else None,
            "worst_proc": (max(slowest_counts, key=slowest_counts.get)
                           if chunks else None),
        },
    }


def _perf_regressions(events, procs) -> dict | None:
    """Mesh-wide classification of the drift detector's flags (see
    `straggler_report`). ``procs`` is the straggler analysis's process
    list — the mesh-wide threshold counts against EVERY process with
    chunk events, not just the flagging ones."""
    flags = [e for e in events if e.get("kind") == "perf_regression"]
    if not flags:
        return None
    by_chunk: dict = {}
    per_proc: dict = {}
    for e in flags:
        p = int(e.get("proc", 0))
        per_proc[p] = per_proc.get(p, 0) + 1
        rec = by_chunk.setdefault(e.get("chunk"), {"procs": set(),
                                                   "max_z": 0.0})
        rec["procs"].add(p)
        rec["max_z"] = max(rec["max_z"], float(e.get("z", 0.0) or 0.0))
    need = max(2, (len(procs) + 1) // 2)  # at least half the mesh
    chunks = []
    mesh_wide = 0
    for c in sorted(by_chunk, key=lambda x: (x is None, x)):
        rec = by_chunk[c]
        scope = "mesh-wide" if len(rec["procs"]) >= need else "process"
        mesh_wide += scope == "mesh-wide"
        chunks.append({"chunk": c, "procs": sorted(rec["procs"]),
                       "scope": scope, "max_z": rec["max_z"]})
    return {
        "events": len(flags),
        "per_process": per_proc,
        "chunks": chunks,
        "mesh_wide": mesh_wide,
        "localized": len(chunks) - mesh_wide,
    }


def mesh_section(agg_or_events, *, window: int = 8,
                 share: float = 0.5) -> dict | None:
    """The compact cross-process record `run_report` embeds as ``"mesh"``:
    alignment metadata (when given an `aggregate_flight` result) plus the
    straggler report minus its per-chunk bulk (the full per-chunk rows
    stay available via `straggler_report`). None when the stream holds
    fewer than two processes' chunk events."""
    events = _events_of(agg_or_events)
    procs = {int(e.get("proc", 0)) for e in events
             if e.get("kind") == "chunk"}
    if len(procs) < 2:
        return None
    rep = straggler_report(events, window=window, share=share)
    out = {
        "processes": rep["processes"],
        "slowest_counts": rep["slowest_counts"],
        "persistent_stragglers": rep["persistent"],
        "imbalance": rep["imbalance"],
        "perf_regressions": rep["perf_regressions"],
        "summary": rep["summary"],
    }
    if isinstance(agg_or_events, dict):
        out["offsets"] = agg_or_events.get("offsets")
        out["align"] = agg_or_events.get("align")
    return out
