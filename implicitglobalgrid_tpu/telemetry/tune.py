"""The closed-loop auto-tuner: search the oracle, validate, persist, apply.

PRs 1-12 built the knobs (collective coalescing, per-axis wire precision,
deep-halo ``comm_every`` cadences, interior-first ``overlap``, the
ensemble axis) and PR 6 built the pricing (`predict_step` over a measured
`MachineProfile`). What remained was the loop that turns them: this
module's `tune_config` SEARCHES the model over per-axis ``comm_every`` x
per-axis ``wire_dtype`` x per-axis ``wire_stage`` (the PR 16
topology-staged wire) x ``coalesce`` x ``overlap`` x ensemble ``E``,
VALIDATES the top candidates with short measured calibration runs
(min-of-reps two-point windows — the same estimator
`calibrate_machine` uses), and persists the winning `TunedConfig` JSON
next to the machine profile, where the per-job application layer
(`runtime.RunSpec(tuned=...)`, `service.MeshScheduler` admission, the
``tools tune`` / ``tools jobs`` CLI) loads and applies it.

The search is honest about geometry: a deep cadence candidate is priced
(and measured) on the grid it actually needs — ``depth * k_d``-wide halos
and the correspondingly LARGER local blocks over the SAME implicit global
grid — so the Stokes-style failure mode (uniform deep halos winning on
latency but losing on slab-width compute, COMM_AVOID.json's 0.51x row)
prices as the loss it is, while a z-only cadence on a hierarchical
ICI+DCN profile prices as the win the per-axis knob exists for.

`tune_config` owns its grids (the measured candidates need different halo
geometries): it swaps any live grid aside (`topology.swap_global_grid`,
retained so the caller's compiled caches survive) and restores it on
exit.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from dataclasses import dataclass, field as dc_field, replace

from ..utils.exceptions import InvalidArgumentError

__all__ = ["TunedConfig", "tune_config", "save_tuned_config",
           "load_tuned_config", "resolve_tuned", "tuned_config_path"]

_TUNED_VERSION = 1

# per-model measured-run support: canonical state staggering (offsets
# added to the local block shape per field, in state order) — the shapes
# `predict_step` prices candidates with
_MODEL_STAGGER = {
    "diffusion3d": ((0, 0, 0),) * 2,
    "acoustic3d": ((0, 0, 0), (1, 0, 0), (0, 1, 0), (0, 0, 1)),
    "stokes3d": ((0, 0, 0), (1, 0, 0), (0, 1, 0), (0, 0, 1),
                 (1, 0, 0), (0, 1, 0), (0, 0, 1), (0, 0, 0)),
}
_DIM_NAMES = ("x", "y", "z")


@dataclass(frozen=True)
class TunedConfig:
    """One model family's winning knob set on one machine/mesh geometry.

    The knobs are exactly the surface the runtime applies per job:
    ``comm_every`` (canonical per-axis cadence string), ``wire_dtype``
    (canonical per-axis wire policy, or ``None`` = exact),
    ``wire_stage`` (canonical per-axis topology-staged wire policy, or
    ``None`` = flat), ``coalesce``, ``overlap``, and ``ensemble``
    (``None`` = solo). ``predicted_step_s``
    is the oracle's per-(member-)step price; ``measured_step_s`` /
    ``baseline_step_s`` are the calibration-run numbers when the tuner
    measured (``speedup`` = baseline / measured — >= 1.0 by
    construction, the default config is always in the measured set).
    ``grid`` records the geometry the config was tuned FOR (dims,
    periods, base local size, and the cadence's overlaps/halowidths);
    ``meta`` the search accounting (candidates priced/measured/skipped,
    search wall time)."""

    model: str
    comm_every: str = "1"
    wire_dtype: str | None = None
    wire_stage: str | None = None
    coalesce: bool = True
    overlap: bool = False
    ensemble: int | None = None
    predicted_step_s: float | None = None
    measured_step_s: float | None = None
    baseline_step_s: float | None = None
    speedup: float | None = None
    profile_source: str | None = None
    grid: dict = dc_field(default_factory=dict)
    meta: dict = dc_field(default_factory=dict)

    def knobs(self) -> dict:
        """The applied-surface subset, as one dict."""
        return {"comm_every": self.comm_every,
                "wire_dtype": self.wire_dtype,
                "wire_stage": self.wire_stage,
                "coalesce": self.coalesce, "overlap": self.overlap,
                "ensemble": self.ensemble}

    def env(self) -> dict:
        """The environment-variable form of the trace-time knobs — what
        the driver/scheduler scope around a tuned job's compiles
        (``IGG_COMM_EVERY`` / ``IGG_HALO_WIRE_DTYPE`` /
        ``IGG_HALO_COALESCE``, plus ``IGG_HALO_WIRE_STAGE`` when the
        tuner selected staging; ``overlap`` and ``ensemble`` are
        structural and applied at setup time instead)."""
        env = {"IGG_COMM_EVERY": str(self.comm_every),
               "IGG_HALO_WIRE_DTYPE": (self.wire_dtype or "off"),
               "IGG_HALO_COALESCE": "1" if self.coalesce else "0"}
        if self.wire_stage is not None:
            env["IGG_HALO_WIRE_STAGE"] = str(self.wire_stage)
        return env

    def to_json(self) -> dict:
        return {"version": _TUNED_VERSION, "model": self.model,
                "comm_every": self.comm_every,
                "wire_dtype": self.wire_dtype,
                "wire_stage": self.wire_stage,
                "coalesce": self.coalesce, "overlap": self.overlap,
                "ensemble": self.ensemble,
                "predicted_step_s": self.predicted_step_s,
                "measured_step_s": self.measured_step_s,
                "baseline_step_s": self.baseline_step_s,
                "speedup": self.speedup,
                "profile_source": self.profile_source,
                "grid": self.grid, "meta": self.meta}

    @classmethod
    def from_json(cls, rec) -> "TunedConfig":
        if isinstance(rec, (str, bytes)):
            rec = json.loads(rec)
        try:
            return cls(
                model=str(rec["model"]),
                comm_every=str(rec.get("comm_every", "1")),
                wire_dtype=rec.get("wire_dtype"),
                wire_stage=rec.get("wire_stage"),
                coalesce=bool(rec.get("coalesce", True)),
                overlap=bool(rec.get("overlap", False)),
                ensemble=(None if rec.get("ensemble") is None
                          else int(rec["ensemble"])),
                predicted_step_s=rec.get("predicted_step_s"),
                measured_step_s=rec.get("measured_step_s"),
                baseline_step_s=rec.get("baseline_step_s"),
                speedup=rec.get("speedup"),
                profile_source=rec.get("profile_source"),
                grid=dict(rec.get("grid", {})),
                meta=dict(rec.get("meta", {})))
        except (KeyError, TypeError, ValueError) as e:
            raise InvalidArgumentError(
                f"TunedConfig.from_json: malformed record ({e}).") from e


def save_tuned_config(cfg: TunedConfig, path) -> str:
    """Persist a tuned config as JSON (the file `load_tuned_config`, the
    ``tools tune`` CLI, and `RunSpec(tuned=...)` exchange)."""
    path = os.fspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(cfg.to_json(), f, indent=1)
    return path


def load_tuned_config(path) -> TunedConfig:
    path = os.fspath(path)
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        raise InvalidArgumentError(
            f"load_tuned_config: cannot read {path}: {e}") from e
    return TunedConfig.from_json(rec)


def tuned_config_path(profile_path, model: str) -> str:
    """The canonical on-disk home of a model's tuned config: NEXT TO the
    machine profile it was searched against
    (``<profile dir>/tuned_<model>.json``)."""
    base = os.path.dirname(os.fspath(profile_path))
    return os.path.join(base, f"tuned_{model}.json")


def resolve_tuned(tuned) -> TunedConfig | None:
    """Normalize every accepted `RunSpec.tuned` form: ``None`` passes
    through, a `TunedConfig` is returned as-is, a dict parses as its
    JSON record, and a string/path loads the persisted file."""
    if tuned is None or isinstance(tuned, TunedConfig):
        return tuned
    if isinstance(tuned, dict):
        return TunedConfig.from_json(tuned)
    if isinstance(tuned, (str, os.PathLike)):
        return load_tuned_config(tuned)
    raise InvalidArgumentError(
        f"tuned must be a TunedConfig, its JSON dict, or a path; got "
        f"{type(tuned).__name__}.")


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

def _grid_geometry(grid: dict) -> tuple:
    """(base local n, base overlaps, dims-ish kwargs) from the user's
    `init_global_grid` keyword dict."""
    g = dict(grid)
    try:
        n = (int(g.pop("nx")), int(g.pop("ny")), int(g.pop("nz")))
    except KeyError as e:
        raise InvalidArgumentError(
            f"tune_config: grid needs nx/ny/nz ({e} missing).") from e
    ol = g.pop("overlaps", (2, 2, 2))
    ol = tuple(int(o) for o in (ol if hasattr(ol, "__len__")
                                else (ol,) * 3))
    g.pop("halowidths", None)  # derived per candidate
    return n, ol, g


def _candidate_grid(n_base, ol_base, rest: dict, cad, depth: int) -> dict:
    """`init_global_grid` kwargs for one cadence candidate, holding the
    IMPLICIT GLOBAL GRID fixed: per dim, ``n - ol`` is invariant, so a
    deeper overlap grows the local block by exactly the extra overlap —
    the honest compute cost of the wider slabs."""
    if cad.deep:
        hw = tuple(depth * cad.for_dim(d) for d in range(3))
        ol = tuple(2 * h for h in hw)
    else:
        hw = None  # grid default (min(1, ol//2)-ish) — the base geometry
        ol = ol_base
    n = tuple(nb - ob + o for nb, ob, o in zip(n_base, ol_base, ol))
    kw = dict(rest, nx=n[0], ny=n[1], nz=n[2], overlaps=ol, quiet=True)
    if hw is not None:
        kw["halowidths"] = hw
    return kw


def _grid_ok(kw: dict) -> bool:
    """Host-side feasibility of a candidate grid (mirrors the
    `init_global_grid` coherence checks plus `validate_deep_halo`'s
    freshness bound, so an infeasible cadence is a SKIPPED candidate,
    not a crash mid-search)."""
    n = (kw["nx"], kw["ny"], kw["nz"])
    ol = kw["overlaps"]
    hw = kw.get("halowidths", (0, 0, 0))
    periods = (kw.get("periodx", 0), kw.get("periody", 0),
               kw.get("periodz", 0))
    for d in range(3):
        if n[d] < 2:
            return False
        if periods[d] and n[d] < 2 * ol[d] - 1:
            return False
        if n[d] < ol[d] + hw[d]:  # deep send slabs must stay fresh
            return False
    return True


def _model_fields(model: str, gg, hw, dtype):
    """Stacked `jax.ShapeDtypeStruct` state (with per-field halowidths)
    for pricing — nothing is allocated."""
    import jax
    import numpy as np

    stagger = _MODEL_STAGGER[model]
    dims = tuple(int(d) for d in gg.dims)
    n = tuple(int(v) for v in gg.nxyz)
    out = []
    for offs in stagger:
        # staggered fields are local n+1 per shard, stacked dims*(n+1)
        # (how init_* builds them — zeros_g of the staggered local shape)
        shape = tuple(dims[d] * (n[d] + offs[d]) for d in range(3))
        sds = jax.ShapeDtypeStruct(shape, np.dtype(dtype))
        out.append((sds, tuple(hw)) if hw is not None else sds)
    return tuple(out)


def _scoped_env(env: dict):
    """Context manager setting/restoring environment variables (the
    trace-time knob scope — also used by the driver's tuned apply)."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        saved = {k: os.environ.get(k) for k in env}
        try:
            for k, v in env.items():
                os.environ[k] = str(v)
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
    return scope()


def _build_runner(model: str, cand: dict, dtype):
    """(state tuple, runner_factory(nt_chunk), physical steps per
    chunk-unit) under the CURRENT grid for one measured candidate."""
    from .. import models as M
    from ..models.common import ensemble_state, resolve_comm_every

    cad = resolve_comm_every(cand["comm_every"])
    E = cand.get("ensemble")
    if model == "diffusion3d":
        T, Cp, p = M.init_diffusion3d(dtype=dtype,
                                      comm_every=cand["comm_every"],
                                      overlap=cand["overlap"])
        state = (T, Cp)
        if cad.deep:
            factory = (lambda c: M.make_run_deep(p, c, ensemble=E))
        else:
            factory = (lambda c: M.make_run(p, c, impl="xla", ensemble=E))
    elif model == "acoustic3d":
        state, p = M.init_acoustic3d(dtype=dtype,
                                     comm_every=cand["comm_every"],
                                     overlap=cand["overlap"])
        if cad.deep:
            factory = (lambda c: M.make_acoustic_run_deep(p, c, ensemble=E))
        else:
            factory = (lambda c: M.make_acoustic_run(p, c, impl="xla",
                                                     ensemble=E))
    elif model == "stokes3d":
        state, p = M.init_stokes3d(dtype=dtype,
                                   comm_every=cand["comm_every"],
                                   overlap=cand["overlap"])
        if cad.deep:
            factory = (lambda c: M.make_stokes_run_deep(p, c, ensemble=E))
        else:
            factory = (lambda c: M.make_stokes_run(p, c, impl="xla",
                                                   ensemble=E))
    else:
        raise InvalidArgumentError(
            f"tune_config: unsupported model {model!r} (have "
            f"{sorted(_MODEL_STAGGER)}).")
    if E:
        state = ensemble_state(state, int(E))
    per_unit = cad.cycle if cad.deep else 1
    return tuple(state), factory, per_unit


def _measure_candidate(model: str, cand: dict, grid_kw: dict, dtype,
                       c1: int, reps: int) -> float:
    """Measured per-(member-)step seconds of one candidate on its own
    grid: min-of-``reps`` two-point windows (`calibrate._two_point` — the
    same estimator `calibrate_machine` uses, contention-robust on shared
    hosts) over whole compiled chunks."""
    from ..parallel.grid import finalize_global_grid, init_global_grid
    from ..utils.timing import sync
    from .calibrate import _two_point

    init_global_grid(**grid_kw)
    try:
        with _scoped_env({
                "IGG_HALO_WIRE_DTYPE": cand["wire_dtype"] or "off",
                "IGG_HALO_WIRE_STAGE": cand.get("wire_stage") or "off",
                "IGG_HALO_COALESCE": "1" if cand["coalesce"] else "0"}):
            state, factory, per_unit = _build_runner(model, cand, dtype)

            def chunk(c):
                sync(factory(c)(*state))

            sec_per_unit = _two_point(chunk, c1, 3 * c1, reps=reps)
        E = cand.get("ensemble") or 1
        return sec_per_unit / per_unit / E
    finally:
        finalize_global_grid()


def _default_comm_every_options(dims, periods) -> tuple:
    """The default cadence candidates: exchange-every-step, the uniform
    deep cadence, and each EXCHANGING axis's solo cadence (the per-axis
    win the tuner exists to find)."""
    opts = ["1", "2"]
    for d in range(3):
        if int(dims[d]) > 1 or int(periods[d]):
            opts.append(f"{_DIM_NAMES[d]}:2")
    return tuple(opts)


def tune_config(model: str, grid: dict, profile=None, *,
                dtype="float32",
                comm_every_options=None, wire_dtype_options=(None,),
                wire_stage_options=(None,),
                coalesce_options=(True,), overlap_options=(False,),
                ensemble_options=(None,),
                top_k: int = 2, measure: bool = True,
                measure_steps: int = 4, reps: int = 3,
                path=None) -> TunedConfig:
    """Search -> validate -> persist one model family's knob set.

    ``grid`` is the BASE geometry as `init_global_grid` keywords (nx/ny/
    nz + dims/periods; ``overlaps`` defaults to the grid default) — the
    implicit GLOBAL grid it describes is held fixed across candidates,
    so a deep cadence pays its honest slab-width compute. ``profile`` is
    a `MachineProfile` or a path to one (`calibrate_machine` output);
    a path also sets the default persist location
    (`tuned_config_path`). The candidate space is the cross product of
    the ``*_options`` (defaults: cadences from
    `_default_comm_every_options`, exact wire, coalescing on, overlap
    off, solo) minus infeasible combos (deep cadence x overlap — the
    runners ignore overlap under a cadence; grids the geometry cannot
    carry). Every candidate is priced with `predict_step` on its OWN
    grid geometry; with ``measure=True`` the ``top_k`` predicted (plus
    the all-defaults baseline) are validated with short measured
    calibration runs and the MEASURED winner is returned —
    ``speedup = baseline_step_s / measured_step_s`` is >= 1.0 by
    construction since the baseline is always in the measured set.

    `tune_config` owns grid lifecycle: any live grid is swapped aside
    (epoch retained — its compiled caches survive) and restored on
    exit; candidate grids are initialized and finalized internally.
    Returns the winning `TunedConfig` (persisted when ``path`` or a
    profile path was given).

    ``wire_stage_options`` adds the topology-staged wire (PR 16) to the
    search: a ``"z:staged"`` candidate reroutes the z exchange as ICI
    leader-gather -> one striped DCN transfer per granule pair -> ICI
    scatter. It is priced per stage against each stage's own link class,
    so it only ranks ahead of flat where the profile is genuinely
    hierarchical — and with ``measure=True`` it must ALSO win the
    measured validation leg before `tune_config` selects it (model and
    measurement have to agree)."""
    from ..models.common import resolve_comm_every
    from ..parallel import topology as top
    from ..parallel.grid import finalize_global_grid, init_global_grid
    from .perfmodel import (
        STEP_WORKLOADS, default_machine_profile, load_machine_profile,
    )

    if model not in _MODEL_STAGGER:
        raise InvalidArgumentError(
            f"tune_config: unsupported model {model!r} (have "
            f"{sorted(_MODEL_STAGGER)}).")
    work = STEP_WORKLOADS[model]
    profile_path = None
    if isinstance(profile, (str, os.PathLike)):
        profile_path = os.fspath(profile)
        profile = load_machine_profile(profile_path)
    t0 = time.time()
    n_base, ol_base, rest = _grid_geometry(grid)
    dims = [int(rest.get(k, 0)) for k in ("dimx", "dimy", "dimz")]
    periods = [int(rest.get(k, 0))
               for k in ("periodx", "periody", "periodz")]
    if comm_every_options is None:
        comm_every_options = _default_comm_every_options(dims, periods)

    # candidate space (canonical cadence/stage strings de-dup spellings)
    from ..ops.wire import resolve_wire_stage

    cands = []
    seen = set()
    for ce, wd, ws, co, ov, E in itertools.product(
            comm_every_options, wire_dtype_options, wire_stage_options,
            coalesce_options, overlap_options, ensemble_options):
        cad = resolve_comm_every(ce)
        if cad.deep and ov:
            continue  # the deep runners ignore overlap — not a real combo
        # canonicalize the stage spelling without the env fallback
        # (resolve_wire_stage(None) reads IGG_HALO_WIRE_STAGE — a tune
        # candidate's None means FLAT, not "whatever the env says")
        stg = None if ws is None else resolve_wire_stage(ws)
        stg = None if stg is None else str(stg)
        key = (str(cad), wd, stg, bool(co), bool(ov),
               None if E is None else int(E))
        if key in seen:
            continue
        seen.add(key)
        cands.append({"comm_every": str(cad), "wire_dtype": wd,
                      "wire_stage": stg,
                      "coalesce": bool(co), "overlap": bool(ov),
                      "ensemble": None if E is None else int(E)})
    default_cand = {"comm_every": "1", "wire_dtype": None,
                    "wire_stage": None,
                    "coalesce": True, "overlap": False, "ensemble": None}
    if not any(c == default_cand for c in cands):
        cands.insert(0, dict(default_cand))

    prev = top.swap_global_grid(None)
    if prev is not None:
        top.retain_epoch(prev.epoch)
    priced, skipped = [], []
    try:
        # ---- phase 1: price every candidate on its own geometry -------
        by_geom: dict = {}
        for c in cands:
            cad = resolve_comm_every(c["comm_every"])
            kw = _candidate_grid(n_base, ol_base, rest, cad,
                                 work.deep_halo_depth)
            if not _grid_ok(kw):
                skipped.append({**c, "reason": "infeasible grid"})
                continue
            by_geom.setdefault(
                (kw["nx"], kw["ny"], kw["nz"], tuple(kw["overlaps"]),
                 tuple(kw.get("halowidths", ()))), (kw, []))[1].append(c)
        from .perfmodel import predict_step

        prof = profile
        for kw, group in by_geom.values():
            init_global_grid(**kw)
            try:
                gg = top.global_grid()
                if prof is None:  # grid-derived default coefficients
                    prof = default_machine_profile()
                hw = tuple(int(h) for h in gg.halowidths)
                fields = _model_fields(model, gg, hw, dtype)
                for c in group:
                    pred = predict_step(
                        model, fields, profile=prof,
                        comm_every=c["comm_every"],
                        overlap=c["overlap"], coalesce=c["coalesce"],
                        wire_dtype=c["wire_dtype"],
                        wire_stage=c["wire_stage"],
                        ensemble=c["ensemble"])
                    E = c["ensemble"] or 1
                    priced.append((pred["step_s"] / E, c, pred, dict(kw)))
            finally:
                finalize_global_grid()
        if not priced:
            raise InvalidArgumentError(
                "tune_config: every candidate was infeasible on this "
                f"grid geometry ({grid!r}) — nothing to tune.")
        if measure and not any(t[1] == default_cand for t in priced):
            # the >= 1.0 speedup guarantee hinges on the measured set
            # containing the all-defaults baseline — a base geometry
            # that cannot even run the default config is a caller
            # error, not a StopIteration deep in phase 2
            raise InvalidArgumentError(
                "tune_config: the base grid geometry cannot run the "
                f"default (cadence-1) configuration ({grid!r} — see "
                "meta would-be 'skipped'); fix the base nx/ny/nz/"
                "overlaps or pass measure=False for a model-only "
                "search.")
        priced.sort(key=lambda t: t[0])

        # ---- phase 2: measured validation of the top candidates -------
        measured = []
        if measure:
            chosen = [t for t in priced[:max(1, int(top_k))]]
            if not any(t[1] == default_cand for t in chosen):
                base_t = next(t for t in priced if t[1] == default_cand)
                chosen.append(base_t)
            for pred_s, c, pred, kw in chosen:
                s = _measure_candidate(model, c, kw, dtype,
                                       c1=max(1, int(measure_steps)),
                                       reps=max(1, int(reps)))
                measured.append((s, pred_s, c, pred, kw))
            measured.sort(key=lambda t: t[0])
            win_s, win_pred_s, win_c, win_pred, win_kw = measured[0]
            base_s = next(t[0] for t in measured if t[2] == default_cand)
        else:
            win_pred_s, win_c, win_pred, win_kw = priced[0]
            win_s = base_s = None
    finally:
        if prev is not None:
            top.swap_global_grid(prev)
            top.release_epoch(prev.epoch)

    cfg = TunedConfig(
        model=model,
        comm_every=win_c["comm_every"],
        wire_dtype=win_c["wire_dtype"],
        wire_stage=win_c["wire_stage"],
        coalesce=win_c["coalesce"],
        overlap=win_c["overlap"],
        ensemble=win_c["ensemble"],
        predicted_step_s=float(win_pred["step_s"])
        / (win_c["ensemble"] or 1),
        measured_step_s=win_s,
        baseline_step_s=base_s,
        speedup=(None if win_s is None
                 else (base_s / win_s if win_s > 0 else 1.0)),
        profile_source=win_pred["profile_source"],
        grid={"base": dict(grid), "winner": {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in win_kw.items()}},
        meta={"candidates": len(cands), "priced": len(priced),
              "measured": len(measured) if measure else 0,
              "skipped": skipped,
              "ranking": [
                  {"score_s": s, **c} for s, c, _, _ in priced[:8]],
              "search_s": time.time() - t0,
              "tuned_at": t0})
    if path is None and profile_path is not None:
        path = tuned_config_path(profile_path, model)
    if path is not None:
        save_tuned_config(cfg, path)
        cfg = replace(cfg, meta=dict(cfg.meta, path=os.fspath(path)))
    return cfg
