"""Chrome/Perfetto trace-event export of an aggregated flight stream.

`export_chrome_trace` renders the mesh-wide event sequence
(`telemetry.aggregate.aggregate_flight`) as Trace Event Format JSON —
the format both ``chrome://tracing`` and https://ui.perfetto.dev open
directly — so a multi-process run becomes one navigable timeline:

- one TRACK per process (trace ``pid`` = jax process index), with the
  driver loop on thread 0 (``chunk`` spans nesting their ``build`` /
  ``exec`` phases, checkpoint save/restore spans) and the background io
  writer on thread 1 (``snapshot_write`` spans);
- guard trips, rollbacks, escalations, elastic restarts, and fault
  injections as INSTANT events (the red flags an operator scans for);
- COUNTER tracks per process for ``igg_io_queue_depth`` (the writer's
  live backpressure), cumulative halo wire bytes, and the perf oracle's
  per-step execution time (``igg_perf_step_seconds`` — drift is visible
  as a rising counter next to its ``perf_regression`` instant marker).

Timestamps are the aggregated stream's corrected wall clock (barrier-
aligned across processes, `docs/observability.md` "Mesh-wide view"),
rebased to the earliest event and expressed in microseconds as the
format requires — so the per-process chunk spans line up at the chunk-
boundary psum exactly as they did on the machine floor.
"""

from __future__ import annotations

import json
import os

from ..utils.exceptions import InvalidArgumentError
from .aggregate import aggregate_events, aggregate_flight
from .recorder import read_flight_events

__all__ = ["export_chrome_trace"]

# Instant-event kinds (the operator's red flags), with the scope chrome
# renders them at: process-wide bars.
_INSTANTS = ("guard_trip", "rollback", "escalation", "elastic_restart",
             "fault_injected", "snapshot_drop", "snapshot_error",
             "perf_regression", "tuned_stale", "deadline_missed")

_TID_DRIVER = 0
_TID_IO = 1


def _normalize(source, run_id):
    """source -> (events, meta): an `aggregate_flight` result, a
    directory/path-list (aggregated here), a single JSONL file, or an
    already-merged event iterable. Pre-loaded events and single files
    that turn out to span SEVERAL processes are clock-aligned too
    (`aggregate_events`) — per-process monotonic stamps are not
    comparable raw, and a Perfetto timeline drawn on them would be
    silently uncorrelatable across tracks."""
    if isinstance(source, dict):
        if "events" not in source:
            raise InvalidArgumentError(
                "export_chrome_trace: dict source must be an "
                "aggregate_flight result (no 'events' key).")
        return source["events"], source
    if isinstance(source, (str, os.PathLike)):
        src = os.fspath(source)
        if os.path.isdir(src):
            agg = aggregate_flight(src, run_id=run_id)
            return agg["events"], agg
        evs = read_flight_events(src, run_id=run_id)
    else:
        evs = list(source)
        if evs and isinstance(evs[0], (str, os.PathLike)):
            agg = aggregate_flight(evs, run_id=run_id)
            return agg["events"], agg
    if len({int(e.get("proc", 0)) for e in evs}) > 1:
        agg = aggregate_events(evs, run_id=run_id)
        return agg["events"], agg
    return evs, None


def _args(e: dict, skip=("t", "t_mono", "t_offset", "kind", "run", "pid",
                         "proc", "seq")) -> dict:
    return {k: v for k, v in e.items() if k not in skip}


def _span_start(e: dict) -> float | None:
    """Earliest timeline point an event reaches back to (its stamp is its
    END; spans carry their duration before it). None for unstamped
    events."""
    if "t" not in e:
        return None
    t = float(e["t"])
    for f in ("dur_s", "exec_s"):
        t -= float(e.get(f, 0.0) or 0.0)
    t -= float(e.get("build_s", 0.0) or 0.0) if "exec_s" in e else 0.0
    return t


def _track_meta(trace: list, pid: int, name: str) -> None:
    """Track metadata: one Perfetto process row per pid, with the driver
    and io-writer threads named."""
    trace.append({"ph": "M", "pid": pid, "name": "process_name",
                  "args": {"name": name}})
    trace.append({"ph": "M", "pid": pid, "tid": _TID_DRIVER,
                  "name": "thread_name", "args": {"name": "driver"}})
    trace.append({"ph": "M", "pid": pid, "tid": _TID_IO,
                  "name": "thread_name", "args": {"name": "io-writer"}})


def export_chrome_trace(source, out=None, *, run_id: str | None = None,
                        trace_id: str | None = None):
    """Render ``source`` as Chrome trace-event JSON.

    ``source``: an `aggregate_flight` result, a directory of per-process
    ``*.jsonl`` streams (aggregated here), a list of stream paths, one
    JSONL path, or an iterable of (already merged) event dicts.

    ``trace_id`` filters to the events stamped with ONE distributed
    trace (`telemetry.tracectx` — the causal slice of a single request
    on a Perfetto timeline; OTLP export is the span-tree view).

    With ``out`` (a path), writes the JSON there and returns the path;
    otherwise returns the trace dict (``{"traceEvents": [...], ...}``).
    Open the file at https://ui.perfetto.dev or ``chrome://tracing``."""
    events, agg = _normalize(source, run_id)
    if trace_id is not None:
        events = [e for e in events if e.get("trace_id") == trace_id]
        if not events:
            raise InvalidArgumentError(
                f"export_chrome_trace: no events carry trace_id "
                f"{trace_id!r}.")
    if not events:
        raise InvalidArgumentError("export_chrome_trace: no events.")
    # rebase to the earliest point on the timeline — span STARTS included
    starts = [s for s in map(_span_start, events) if s is not None]
    t0 = min(starts)

    def us(t: float) -> float:
        return (float(t) - t0) * 1e6

    trace: list = []
    procs = sorted({int(e.get("proc", 0)) for e in events})
    for p in procs:
        _track_meta(trace, p, f"igg process {p}")

    wire_cum = {p: 0 for p in procs}
    for e in events:
        if "t" not in e or e.get("kind") is None:
            continue
        _emit_event(trace, e, int(e.get("proc", 0)), us, wire_cum)

    doc = {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "implicitglobalgrid_tpu flight recorder",
            "processes": procs,
        },
    }
    if trace_id is not None:
        doc["otherData"]["trace_id"] = trace_id
    if agg is not None:
        doc["otherData"]["run_id"] = agg.get("run_id")
        doc["otherData"]["offsets"] = {
            str(k): v for k, v in (agg.get("offsets") or {}).items()}
        doc["otherData"]["align"] = agg.get("align")
    if out is None:
        return doc
    out = os.fspath(out)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return out


def _emit_event(trace: list, e: dict, p: int, us, wire_cum: dict) -> None:
    """Render ONE flight event onto track ``p`` (trace pid). Shared by the
    per-process export above and the per-JOB export of the multi-run
    scheduler (`service.export_service_trace` — there ``p`` is the job's
    track, not a jax process index)."""
    kind = e.get("kind")
    t = float(e["t"])
    if kind is not None:
        if kind == "chunk":
            build = float(e.get("build_s", 0.0) or 0.0)
            ex = float(e.get("exec_s", 0.0) or 0.0)
            start = t - ex - build
            args = _args(e)
            trace.append({"ph": "X", "pid": p, "tid": _TID_DRIVER,
                          "cat": "chunk",
                          "name": f"chunk {e.get('chunk')}",
                          "ts": us(start), "dur": (build + ex) * 1e6,
                          "args": args})
            if build > 0:
                trace.append({"ph": "X", "pid": p, "tid": _TID_DRIVER,
                              "cat": "chunk", "name": "build",
                              "ts": us(start), "dur": build * 1e6})
            if ex > 0:
                trace.append({"ph": "X", "pid": p, "tid": _TID_DRIVER,
                              "cat": "chunk", "name": "exec",
                              "ts": us(t - ex), "dur": ex * 1e6})
            # perf-oracle counter track: per-step execution time per
            # boundary — the drift an operator eyeballs next to the
            # perf_regression instant markers
            if e.get("n"):
                trace.append({"ph": "C", "pid": p,
                              "name": "igg_perf_step_seconds",
                              "ts": us(t),
                              "args": {"s": ex / max(1, int(e["n"]))}})
        elif kind == "resize":
            # the resize span (ISSUE 14): how long the mesh was re-
            # blocking instead of stepping — the downtime an operator
            # weighs against the disk path's
            dur = float(e.get("dur_s", 0.0) or 0.0)
            trace.append({"ph": "X", "pid": p, "tid": _TID_DRIVER,
                          "cat": "resize",
                          "name": f"resize {e.get('new_dims')} "
                                  f"[{e.get('via')}]",
                          "ts": us(t - dur), "dur": dur * 1e6,
                          "args": _args(e)})
        elif kind in ("checkpoint_save", "checkpoint_restore"):
            dur = float(e.get("dur_s", 0.0) or 0.0)
            trace.append({"ph": "X", "pid": p, "tid": _TID_DRIVER,
                          "cat": "checkpoint",
                          "name": e.get("op", kind),
                          "ts": us(t - dur), "dur": dur * 1e6,
                          "args": _args(e)})
        elif kind == "snapshot_write":
            dur = float(e.get("dur_s", 0.0) or 0.0)
            trace.append({"ph": "X", "pid": p, "tid": _TID_IO,
                          "cat": "io",
                          "name": f"snapshot step {e.get('step')}",
                          "ts": us(t - dur), "dur": dur * 1e6,
                          "args": _args(e)})
            if e.get("queue_depth") is not None:
                trace.append({"ph": "C", "pid": p,
                              "name": "igg_io_queue_depth", "ts": us(t),
                              "args": {"depth": e["queue_depth"]}})
        elif kind == "alert":
            # an alert transition (live plane): a named red flag so the
            # rule and new state read straight off the timeline
            trace.append({"ph": "i", "pid": p, "tid": _TID_DRIVER,
                          "cat": "alert",
                          "name": f"alert {e.get('rule')} "
                                  f"{e.get('state')}",
                          "ts": us(t), "s": "p", "args": _args(e)})
        elif kind == "deadline_slack":
            # the slack trajectory as a counter track — the burn an
            # operator eyeballs next to the deadline_missed instant
            if e.get("slack_s") is not None:
                trace.append({"ph": "C", "pid": p,
                              "name": "igg_deadline_slack_seconds",
                              "ts": us(t),
                              "args": {"s": float(e["slack_s"])}})
        elif kind in _INSTANTS:
            trace.append({"ph": "i", "pid": p, "tid": _TID_DRIVER,
                          "cat": "event", "name": kind, "ts": us(t),
                          "s": "p", "args": _args(e)})
            if kind == "snapshot_drop" \
                    and e.get("queue_depth") is not None:
                trace.append({"ph": "C", "pid": p,
                              "name": "igg_io_queue_depth", "ts": us(t),
                              "args": {"depth": e["queue_depth"]}})
        elif kind == "halo_exchange":
            wire_cum[p] += int(e.get("wire_bytes", 0) or 0)
            trace.append({"ph": "C", "pid": p,
                          "name": "igg_halo_wire_bytes_total",
                          "ts": us(t), "args": {"bytes": wire_cum[p]}})
        elif kind in ("run_begin", "run_end", "snapshot", "reducers",
                      "snapshot_writer_close"):
            trace.append({"ph": "i", "pid": p, "tid": _TID_DRIVER,
                          "cat": "run", "name": kind, "ts": us(t),
                          "s": "t", "args": _args(e)})
