"""Span/event flight recorder — an append-only JSONL stream per run.

Every supervised run (`runtime/driver.py`) can stream its lifecycle —
chunk execute/compile splits, checkpoint save/restore/rollback latencies,
guard trips, escalations, elastic restarts — into one newline-delimited
JSON file that survives the process (the black-box the reference's
`tic`/`toc` story has no analog of). Records carry a MONOTONIC timestamp
``t`` (ordering-safe across NTP steps; the ``recorder_open`` record anchors
it to wall time), the writer's ``pid`` and jax ``proc``ess index, the run
id, and a per-recorder sequence number, so a post-hoc reader can
reconstruct the exact event sequence from the file alone
(`telemetry.run_report`).

All instrumentation goes through the module-level current recorder::

    igg.start_flight_recorder("/logs/run42.jsonl")
    state, reports = igg.run_resilient(...)   # driver streams its events
    path = igg.stop_flight_recorder()
    report = igg.run_report(path)

`record_event` is a no-op when no recorder is active — the framework's hot
paths stay instrumented at the cost of one None-check (the <2% overhead
gate of `bench_telemetry.py` measures the recorder ON). Writes are
line-buffered and lock-protected (driver callbacks may record from user
threads); every line is flushed so a crash loses at most the line being
written, which `read_flight_events` tolerates.
"""

from __future__ import annotations

import contextlib
import json
import os
import secrets
import sys
import threading
import time

from ..utils.exceptions import InvalidArgumentError

__all__ = ["FlightRecorder", "start_flight_recorder",
           "stop_flight_recorder", "flight_recorder", "record_event",
           "record_span", "read_flight_events", "use_flight_recorder",
           "bind_thread_recorder"]

_FORMAT_VERSION = 1


def _process_index() -> int:
    """jax process index without forcing a backend init: 0 unless jax is
    already imported and initialized enough to answer."""
    j = sys.modules.get("jax")
    if j is None:
        return 0
    try:
        return int(j.process_index())
    except Exception:
        return 0


def _jsonable(o):
    """Fallback encoder for numpy scalars/arrays and everything else.
    Numeric scalars go through float FIRST (``int(np.float32(0.33))``
    would silently truncate), demoted back to int when integral."""
    try:
        f = float(o)
    except (TypeError, ValueError):
        pass
    else:
        return int(f) if f.is_integer() and abs(f) < 2.0 ** 53 else f
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


class FlightRecorder:
    """Append-only JSONL event stream for one run.

    ``path`` may be a file path (created/appended) or an existing
    directory, in which case the PER-PROCESS convention applies: a
    ``flight_p<process_index>.jsonl`` file is created/appended inside it,
    so N controllers recording into one shared directory never interleave
    writers into one file — exactly the layout
    `telemetry.aggregate.aggregate_flight(dir)` globs (``*.jsonl``) to
    rebuild the mesh-wide view. In multi-controller runs open the
    recorder AFTER ``jax.distributed.initialize`` (before it, every
    controller reads process index 0 and would share one filename).
    ``run_id`` defaults to a fresh random
    token; it tags every record, so several runs can share one file and
    still be separated by `read_flight_events(path, run_id=...)`."""

    def __init__(self, path, *, run_id: str | None = None):
        self.run_id = str(run_id) if run_id is not None else \
            secrets.token_hex(8)
        path = os.fspath(path)
        if os.path.isdir(path):
            path = os.path.join(path, f"flight_p{_process_index()}.jsonl")
        self.path = path
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._proc = _process_index()
        self._seq = 0
        # optional distributed-trace context (`telemetry.tracectx`): when
        # set, every record is stamped with the trace id and the owning
        # span — one dict update per event, ids synthesized at export
        # (`telemetry.otlp`). None (the default) changes NOTHING: records
        # are byte-identical to an untraced recorder's.
        self.trace = None
        self._f = open(path, "a", encoding="utf-8")
        self.event("recorder_open", wall=time.time(),
                   version=_FORMAT_VERSION)

    def event(self, kind: str, **fields) -> None:
        """Append one record. Reserved keys (``t``, ``kind``, ``run``,
        ``pid``, ``proc``, ``seq``) always win over ``fields``."""
        rec = dict(fields)
        tr = self.trace
        if tr is not None:
            rec.setdefault("trace_id", tr.trace_id)
            rec.setdefault("parent_span_id", tr.span_id)
        rec["t"] = time.monotonic()
        rec["kind"] = str(kind)
        rec["run"] = self.run_id
        rec["pid"] = self._pid
        rec["proc"] = self._proc
        with self._lock:
            if self._f is None:
                return  # closed: late events (daemon threads) are dropped
            rec["seq"] = self._seq
            self._seq += 1
            self._f.write(json.dumps(rec, default=_jsonable) + "\n")
            self._f.flush()

    @contextlib.contextmanager
    def span(self, kind: str, **fields):
        """Time the enclosed block and append one record with ``dur_s``."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.event(kind, dur_s=time.monotonic() - t0, **fields)

    def close(self) -> None:
        self.event("recorder_close")
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_current: FlightRecorder | None = None
_tls = threading.local()


def bind_thread_recorder(rec: FlightRecorder | None) -> None:
    """Pin THIS thread's events to ``rec``, overriding the process-wide
    current recorder (None unpins). For long-lived background threads
    that belong to one run — e.g. a job's async snapshot writer under the
    multi-run scheduler, whose commits land while ANOTHER job's recorder
    holds the global slot (or none does, between slices): the thread
    captures its run's recorder once and its events stay correctly
    attributed. Events bound to a recorder that has since closed are
    dropped (the recorder's own closed-check), same as any late event."""
    _tls.recorder = rec


def start_flight_recorder(path, *, run_id: str | None = None
                          ) -> FlightRecorder:
    """Open a `FlightRecorder` and make it THE current recorder — all
    framework instrumentation (`record_event`) streams into it until
    `stop_flight_recorder`. An already-active recorder is closed first."""
    global _current
    # open the NEW recorder first: a failed open (bad path) must leave the
    # active recorder recording, not point _current at a closed one
    new = FlightRecorder(path, run_id=run_id)
    if _current is not None:
        _current.close()
    _current = new
    return new


def stop_flight_recorder() -> str | None:
    """Close the current recorder; returns its file path (None if no
    recorder was active)."""
    global _current
    if _current is None:
        return None
    path = _current.path
    _current.close()
    _current = None
    return path


def flight_recorder() -> FlightRecorder | None:
    """The current recorder, or None."""
    return _current


@contextlib.contextmanager
def use_flight_recorder(rec: FlightRecorder | None):
    """Temporarily make ``rec`` the current recorder WITHOUT closing the
    previous one, restoring it on exit — the multi-run scheduler's
    per-slice routing primitive (each job's driver events stream into that
    job's own JSONL; the outer recorder, if any, resumes afterwards).
    ``rec=None`` silences instrumentation for the block."""
    global _current
    prev = _current
    _current = rec
    try:
        yield rec
    finally:
        _current = prev


def record_event(kind: str, **fields) -> None:
    """Append to this thread's bound recorder (`bind_thread_recorder`) or
    the process-wide current one; no-op (one None-check) when neither is
    active — safe on hot paths."""
    r = getattr(_tls, "recorder", None) or _current
    if r is not None:
        r.event(kind, **fields)


@contextlib.contextmanager
def record_span(kind: str, **fields):
    """Span against the current recorder; when none is active the block
    runs untimed (no clock reads)."""
    r = getattr(_tls, "recorder", None) or _current
    if r is None:
        yield
        return
    with r.span(kind, **fields):
        yield


def read_flight_events(path, *, run_id: str | None = None,
                       offset: int | None = None):
    """Parse a flight-recorder JSONL file back into a list of dicts, in
    file order.

    A malformed FINAL line is tolerated (a crash mid-write is exactly the
    scenario flight recorders exist for); a malformed interior line raises
    `InvalidArgumentError` (the file was edited or interleaved by a foreign
    writer). ``run_id`` filters to one run's records.

    ``offset`` switches to RESUMABLE mode for tailers (`telemetry.live`):
    reading starts at that byte offset and the return value becomes
    ``(events, new_offset)``, where ``new_offset`` is the position after
    the last COMPLETE well-formed line consumed. A torn final line — no
    trailing newline yet, or not yet parseable — is left unconsumed, so
    the next poll re-reads it once the writer's flush completes; it only
    becomes the fatal interior-corruption case when a later complete line
    follows it. Pass ``offset=0`` for the first read and the returned
    ``new_offset`` thereafter; the whole-file form (``offset=None``)
    behaves exactly as before."""
    path = os.fspath(path)
    if not os.path.exists(path):
        raise InvalidArgumentError(f"Flight-recorder file not found: {path}")
    if offset is None:
        out = []
        bad_at = None
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                if not line.strip():
                    continue
                if bad_at is not None:
                    raise InvalidArgumentError(
                        f"Flight-recorder file {path} has a malformed "
                        f"interior line {bad_at + 1} — corrupt or foreign "
                        "content.")
                try:
                    out.append(json.loads(line))
                except ValueError:
                    bad_at = i  # fatal only if any well-formed line follows
        if run_id is not None:
            out = [e for e in out if e.get("run") == str(run_id)]
        return out

    # resumable tail read: byte-offset bookkeeping in BINARY mode (text
    # offsets are not seekable positions under utf-8)
    pos = int(offset)
    if pos < 0:
        raise InvalidArgumentError(
            f"read_flight_events offset must be >= 0; got {offset}.")
    out = []
    bad = None  # (byte_pos_of_line, reason) of a malformed COMPLETE line
    with open(path, "rb") as f:
        f.seek(pos)
        while True:
            line = f.readline()
            if not line:
                break
            if not line.endswith(b"\n"):
                break  # torn tail mid-write: re-read next poll
            if bad is not None:
                if not line.strip():
                    pos += len(line)  # blank after the bad line: benign
                    continue
                raise InvalidArgumentError(
                    f"Flight-recorder file {path} has a malformed interior "
                    f"line at byte {bad} — corrupt or foreign content.")
            if not line.strip():
                pos += len(line)
                continue
            try:
                out.append(json.loads(line.decode("utf-8")))
            except ValueError:
                bad = pos  # fatal only if any well-formed line follows
                continue
            pos += len(line)
    if run_id is not None:
        out = [e for e in out if e.get("run") == str(run_id)]
    return out, pos
