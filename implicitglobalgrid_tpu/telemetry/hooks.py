"""Framework-side instrumentation hooks: the one place the hot paths call.

Each hook bumps the process metrics registry (always on — a few dict ops
under a lock) and appends a flight-recorder event when a recorder is
active (a no-op None-check otherwise). Keeping the metric names and label
sets here, instead of scattered over `ops/halo.py` / `models/common.py` /
`utils/checkpoint.py`, means the exported surface is greppable in one
module and a rename can never desynchronize producers.
"""

from __future__ import annotations

import time

from .recorder import record_event
from .registry import metrics_registry

__all__ = ["note_runner_cache", "account_halo_exchange",
           "record_health_event",
           "observe_checkpoint", "observe_snapshot", "note_io_queue",
           "observe_reducers", "note_heartbeat", "observe_perf",
           "note_metrics_server_port", "observe_audit",
           "note_scheduler_heartbeat", "note_queue_depth", "job_gauges",
           "observe_job_slice", "clear_scheduler_heartbeat",
           "note_job_transition", "observe_member_health",
           "observe_reshard", "note_deadline_slack", "note_queue_backlog",
           "note_alert", "note_autoscale_decision",
           "note_job_target_devices", "note_http_request",
           "note_flight_file_bytes"]

# Metric family names (the exported contract; see docs/observability.md).
RUNNER_CACHE = "igg_runner_cache_total"
HEALTH_EVENTS = "igg_health_events_total"
HALO_EXCHANGES = "igg_halo_exchanges_total"
HALO_PPERMUTES = "igg_halo_ppermutes_total"
HALO_WIRE_BYTES = "igg_halo_wire_bytes_total"
HALO_LOCAL_BYTES = "igg_halo_local_copy_bytes_total"
CKPT_SECONDS = "igg_checkpoint_seconds"
SNAP_TOTAL = "igg_snapshots_total"
SNAP_BYTES = "igg_snapshot_bytes_total"
SNAP_SECONDS = "igg_snapshot_seconds"
IO_QUEUE_DEPTH = "igg_io_queue_depth"
REDUCER_VALUE = "igg_reducer_value"
HEARTBEAT_TS = "igg_driver_heartbeat_timestamp_seconds"
HEARTBEAT_STEP = "igg_driver_step"
PERF_STEP_S = "igg_perf_step_seconds"
PERF_RATIO = "igg_perf_model_ratio"
PERF_Z = "igg_perf_zscore"
PERF_REGRESSIONS = "igg_perf_regressions_total"
METRICS_SERVER_PORT = "igg_metrics_server_port"
AUDIT_FINDINGS = "igg_audit_findings_total"
# multi-run scheduler (service/): the per-tenant ops surface
SCHED_HEARTBEAT_TS = "igg_scheduler_heartbeat_timestamp_seconds"
SCHED_SLICES = "igg_scheduler_slices_total"
QUEUE_DEPTH = "igg_jobs_queued"
JOBS_RUNNING = "igg_jobs_running"
JOBS_TOTAL = "igg_jobs_total"
JOB_HEARTBEAT_TS = "igg_job_heartbeat_timestamp_seconds"
JOB_STEP = "igg_job_step"
JOB_PERF_STEP_S = "igg_job_perf_step_seconds"
JOB_PERF_RATIO = "igg_job_perf_model_ratio"
JOB_AUDIT_FINDINGS = "igg_job_audit_findings_total"
JOB_SLICE_SECONDS = "igg_job_slice_seconds"
JOB_WAIT_SECONDS = "igg_job_wait_seconds"
DEADLINE_MISSED = "igg_job_deadline_missed_total"
# ensemble axis (ISSUE 12): per-member guard verdicts as labeled series
# (the igg_job_* twins are the scheduler's per-tenant scoped mirrors —
# distinct family names because a ScopedRegistry view adds the job label
# to the family's labelnames, and one family cannot carry both shapes)
# on-device elastic resharding (ISSUE 14): resize downtime + wire volume
RESHARD_BYTES = "igg_reshard_bytes_total"
RESHARD_SECONDS = "igg_reshard_seconds"
RESHARD_ROUNDS = "igg_reshard_rounds"
MEMBER_RMS = "igg_member_rms"
MEMBER_NONFINITE = "igg_member_nonfinite_cells"
MEMBER_TRIPS = "igg_member_guard_trips_total"
JOB_MEMBER_RMS = "igg_job_member_rms"
JOB_MEMBER_NONFINITE = "igg_job_member_nonfinite_cells"
JOB_MEMBER_TRIPS = "igg_job_member_guard_trips_total"
# live observability plane (ISSUE 18): deadline slack, queue pressure,
# alert transitions (scoped igg_job_* twin per the label-shape rule above)
DEADLINE_SLACK = "igg_deadline_slack_seconds"
JOB_DEADLINE_SLACK = "igg_job_deadline_slack_seconds"
QUEUE_PENDING = "igg_queue_pending"
QUEUE_OLDEST = "igg_queue_oldest_age_seconds"
ALERTS_TOTAL = "igg_alerts_total"
# closed-loop autoscaler (ISSUE 19): policy verdicts + the per-job
# target-geometry gauge (scoped per the label-shape rule above)
AUTOSCALE_DECISIONS = "igg_autoscale_decisions_total"
AUTOSCALE_RESIZES = "igg_autoscale_resizes_total"
AUTOSCALE_REJECTED = "igg_autoscale_rejected_total"
JOB_TARGET_DEVICES = "igg_job_target_devices"
# serving-tier self-measurement (ISSUE 20): HTTP access telemetry on
# every routed surface + flight-file growth from the tail checkpoints
HTTP_REQUESTS = "igg_http_requests_total"
HTTP_REQUEST_SECONDS = "igg_http_request_seconds"
FLIGHT_FILE_BYTES = "igg_flight_file_bytes"


def runner_cache_misses() -> float:
    """Current ``miss`` count of the runner-cache family (0 before any
    runner was built) — the driver diffs it around a runner build to tag
    COLD chunks for the perf drift detector."""
    fam = metrics_registry().get(RUNNER_CACHE)
    return fam.value(result="miss") if fam is not None else 0.0


def note_runner_cache(result: str, build_s: float | None = None) -> None:
    """Record a `make_state_runner` cache outcome: ``hit`` (compiled chunk
    reused), ``miss`` (new program built — the following dispatch pays the
    XLA compile), or ``uncached`` (no key given)."""
    metrics_registry().counter(
        RUNNER_CACHE,
        "Chunk-runner cache outcomes (miss = the next dispatch compiles).",
        ("result",)).inc(1, result=result)
    if build_s is None:
        record_event("runner_cache", result=result)
    else:
        record_event("runner_cache", result=result, build_s=build_s)


def record_health_event(kind: str, n: int = 1) -> None:
    """Bump the resilient-runtime ``igg_health_events_total{kind=...}``
    counter by ``n`` (`runtime.run_resilient`: kinds include ``chunks``,
    ``guard_trips``, ``rollbacks``, ``checkpoints_saved``, ``restores``,
    ``restore_fallbacks``, ``elastic_restarts``, ``escalations``,
    ``resizes``). Read
    the family via ``igg.metrics_registry()`` or
    ``igg.prometheus_snapshot()`` — the PR-2 `health_counters` dict API
    was retired after two majors of deprecation."""
    metrics_registry().counter(
        HEALTH_EVENTS,
        "Resilient-runtime events by kind (chunks, guard_trips, rollbacks, "
        "checkpoints_saved, restores, restore_fallbacks, elastic_restarts, "
        "escalations, resizes).", ("kind",)).inc(int(n), kind=str(kind))


def account_halo_exchange(plan: dict) -> None:
    """Record one `update_halo` call from its static wire plan
    (`ops.halo.halo_comm_plan`): bytes-on-wire and collective counts per
    mesh axis, derived at trace time from shapes/overlaps/wire dtype —
    zero device syncs (the TPU analog of the reference's printed GB/s
    estimate, computed instead of measured)."""
    reg = metrics_registry()
    reg.counter(HALO_EXCHANGES, "update_halo calls accounted.").inc(1)
    pperm = reg.counter(
        HALO_PPERMUTES,
        "collective-permute ops issued by halo exchanges, per mesh axis.",
        ("axis",))
    wire = reg.counter(
        HALO_WIRE_BYTES,
        "Halo payload bytes crossing the interconnect (all links summed), "
        "per mesh axis and on-wire dtype.", ("axis", "dtype"))
    for axis, rec in plan["axes"].items():
        if rec["ppermutes"]:
            pperm.inc(rec["ppermutes"], axis=axis)
        for dt, b in rec["by_dtype"].items():
            wire.inc(b, axis=axis, dtype=dt)
    if plan["local_copy_bytes"]:
        reg.counter(
            HALO_LOCAL_BYTES,
            "Halo bytes moved by self-neighbor local copies (no wire)."
        ).inc(plan["local_copy_bytes"])
    record_event("halo_exchange", fields=plan["fields"],
                 ppermutes=plan["ppermutes"],
                 wire_bytes=plan["wire_bytes"],
                 local_copy_bytes=plan["local_copy_bytes"])


def observe_checkpoint(op: str, dur_s: float, *, path: str,
                       step=None, **fields) -> None:
    """Record a checkpoint save/restore latency (``op``: ``save`` |
    ``save_sharded`` | ``restore`` | ``restore_sharded`` |
    ``restore_elastic``)."""
    metrics_registry().histogram(
        CKPT_SECONDS, "Checkpoint save/restore wall time by operation.",
        ("op",)).observe(dur_s, op=op)
    kind = "checkpoint_save" if op.startswith("save") else \
        "checkpoint_restore"
    record_event(kind, op=op, dur_s=dur_s, path=str(path), step=step,
                 **fields)


def observe_snapshot(result: str, dur_s: float | None = None, *,
                     path: str, step=None, nbytes: int = 0,
                     queue_depth=None, **fields) -> None:
    """Record one async-snapshot outcome (``result``: ``written`` |
    ``dropped`` | ``error``) from `io.snapshot.SnapshotWriter`. Bytes are
    THIS process's committed shard payload (the O(shard) volume that
    actually moved); the flight event kind is ``snapshot_write`` /
    ``snapshot_drop`` / ``snapshot_error``."""
    reg = metrics_registry()
    reg.counter(SNAP_TOTAL, "Async snapshot outcomes.",
                ("result",)).inc(1, result=result)
    if result == "written":
        if nbytes:
            reg.counter(
                SNAP_BYTES,
                "Snapshot payload bytes written (this process's shard "
                "blocks).").inc(nbytes)
        if dur_s is not None:
            reg.histogram(
                SNAP_SECONDS,
                "Background snapshot serialize+fsync+commit wall time."
            ).observe(dur_s)
        record_event("snapshot_write", step=step, path=str(path),
                     dur_s=dur_s, nbytes=nbytes,
                     queue_depth=queue_depth, **fields)
    elif result == "dropped":
        record_event("snapshot_drop", step=step, path=str(path),
                     queue_depth=queue_depth, **fields)
    else:
        record_event("snapshot_error", step=step, path=str(path),
                     **fields)


def note_io_queue(depth: int) -> None:
    """Track the snapshot writer's live queue depth (gauge: the
    backpressure signal an operator watches before picking ``block`` vs
    ``drop_oldest``)."""
    metrics_registry().gauge(
        IO_QUEUE_DEPTH,
        "Snapshots queued for the background writer right now.").set(depth)


def note_heartbeat(step) -> None:
    """Stamp the driver's liveness: wall time of the last completed chunk
    boundary plus the last committed step. Two gauge writes (dict ops
    under the registry lock) — the whole step-loop cost of the live
    `/healthz` endpoint (`telemetry.server`), whether or not a server is
    actually running."""
    reg = metrics_registry()
    reg.gauge(HEARTBEAT_TS,
              "Wall-clock time of the resilient driver's last chunk "
              "boundary (unix seconds).").set(time.time())
    reg.gauge(HEARTBEAT_STEP,
              "Last step the resilient driver committed.").set(step)


def observe_perf(per_step_s: float, *, ratio=None, z=None,
                 regression: bool = False) -> None:
    """Record one chunk boundary's perf-oracle observation
    (`telemetry.perfmodel.PerfWatch`): the measured per-step time, the
    measured/modeled ratio (when a model prediction backs the run), the
    rolling robust z-score vs the chunk baseline, and the regression
    counter. Gauge writes only — the whole per-boundary cost of the live
    drift detector (gated in bench_perf.py)."""
    reg = metrics_registry()
    reg.gauge(PERF_STEP_S,
              "Measured per-step execution time of the last chunk "
              "(exec_s / steps).").set(per_step_s)
    if ratio is not None:
        reg.gauge(PERF_RATIO,
                  "Measured / modeled per-step time (perfmodel."
                  "predict_step backing the run).").set(ratio)
    if z is not None:
        reg.gauge(PERF_Z,
                  "Rolling robust z-score of the last chunk's per-step "
                  "time vs the median+MAD baseline.").set(z)
    if regression:
        reg.counter(PERF_REGRESSIONS,
                    "Chunks flagged by the perf drift detector "
                    "(perf_regression flight events).").inc(1)


def note_metrics_server_port(port: int) -> None:
    """Expose the ACTUAL bound port of the live metrics endpoint (the
    ephemeral-port contract: start with port=0, read the gauge — or the
    returned server's ``.port`` — instead of hard-coding)."""
    metrics_registry().gauge(
        METRICS_SERVER_PORT,
        "TCP port the live /metrics+/healthz endpoint is bound to "
        "(0 = no server started yet this process).").set(int(port))


def observe_audit(report, *, program: str = "chunk",
                  audit_s: float | None = None) -> None:
    """Record one static-analysis audit of a compiled program
    (`analysis.AuditReport`, from `run_resilient(audit=True)` or any
    caller of `analysis.audit_program`): every finding bumps the
    ``igg_audit_findings_total{rule,severity}`` family and the full
    report streams to the flight recorder as an ``audit`` event —
    `run_report`'s ``"audit"`` section is reconstructed from that event
    alone. ``audit_s`` (host seconds the audit itself took — trace +
    lower + parse + check) rides on the event when the caller timed
    it, keeping chunk ``build_s`` attribution honest."""
    reg = metrics_registry()
    fam = reg.counter(
        AUDIT_FINDINGS,
        "Static-analysis findings from compiled-program audits "
        "(analysis.audit_program), by rule and severity.",
        ("rule", "severity"))
    for f in report.findings:
        fam.inc(1, rule=f.rule, severity=f.severity)
    rules = report.by_rule()
    extra = {} if audit_s is None else {"audit_s": audit_s}
    record_event("audit", program=program, dialect=report.dialect,
                 ok=report.ok, errors=report.errors,
                 warnings=report.warnings, rules=rules,
                 findings=[f.to_json() for f in report.findings],
                 collectives=report.collectives,
                 crosscheck_ok=(None if report.crosscheck is None
                                else bool(report.crosscheck.get("ok"))),
                 **extra)


def note_scheduler_heartbeat(granted: bool = False) -> None:
    """Stamp the multi-run scheduler's liveness (one gauge write per
    scheduling decision — idle polls included, they prove the loop is
    alive). When this gauge is live, `/healthz` judges THE SCHEDULER by
    it — a single wedged job must not 503 the whole service (per-job
    staleness is the labeled `igg_job_heartbeat_*` family). The slice
    counter moves only when a slice was actually ``granted``, so it
    reconciles exactly against the journal's slice events."""
    reg = metrics_registry()
    reg.gauge(SCHED_HEARTBEAT_TS,
              "Wall-clock time of the scheduler's last scheduling "
              "decision (unix seconds).").set(time.time())
    if granted:
        reg.counter(SCHED_SLICES,
                    "Chunk-granular slices the scheduler has granted."
                    ).inc(1)


def clear_scheduler_heartbeat() -> None:
    """Retire the scheduler heartbeat series (scheduler close): /healthz
    falls back to judging the plain driver heartbeat again."""
    metrics_registry().reset(SCHED_HEARTBEAT_TS)


def note_queue_depth(queued: int, running: int) -> None:
    """Track the scheduler's admission queue (gauges: jobs waiting for
    their first slice, jobs currently multiplexed)."""
    reg = metrics_registry()
    reg.gauge(QUEUE_DEPTH,
              "Jobs queued behind the scheduler (admitted, not yet "
              "granted their first slice).").set(queued)
    reg.gauge(JOBS_RUNNING,
              "Jobs currently being multiplexed through the mesh."
              ).set(running)


def note_job_transition(state: str) -> None:
    """Count one job lifecycle transition (``done``/``failed``/
    ``cancelled``/``submitted``)."""
    metrics_registry().counter(
        JOBS_TOTAL, "Job lifecycle transitions by terminal state.",
        ("state",)).inc(1, state=state)


def note_deadline_missed() -> None:
    """Count one run crossing its ``deadline_s`` budget (the driver
    fires it at most once per run, with the ``deadline_missed`` flight
    event — the alertable twin of the journal record)."""
    metrics_registry().counter(
        DEADLINE_MISSED,
        "Runs that crossed their deadline_s budget while running."
        ).inc(1)


def note_deadline_slack(slack_s: float) -> None:
    """Stamp the driver's live deadline slack (remaining budget minus the
    priced cost of the remaining steps) — the signal the deadline-slack
    burn alert and next arc's preemption policy subscribe to. One gauge
    write per chunk boundary, only on deadline-budgeted runs."""
    metrics_registry().gauge(
        DEADLINE_SLACK,
        "Remaining deadline budget minus predicted remaining work "
        "(seconds; negative = provable bust).").set(slack_s)


def note_queue_backlog(pending: int, oldest_age_s: float | None) -> None:
    """Track the submission-queue BACKLOG (jobs filed on the queue
    backend, not yet claimed by any scheduler — upstream of
    `note_queue_depth`'s admitted-jobs gauges): pending count and the age
    of the oldest unclaimed record, the queue-pressure pair the ROADMAP
    autoscaler watches."""
    reg = metrics_registry()
    reg.gauge(QUEUE_PENDING,
              "Unclaimed job records on the submission queue backend."
              ).set(int(pending))
    if oldest_age_s is not None:
        reg.gauge(QUEUE_OLDEST,
                  "Age of the oldest unclaimed queue record (seconds)."
                  ).set(float(oldest_age_s))


def note_alert(rule: str, severity: str, state: str) -> None:
    """Count one alert state-machine transition
    (``igg_alerts_total{rule,severity,state}``; ``state``: ``firing`` |
    ``resolved``). The journal's ``alert`` event is the detailed twin."""
    metrics_registry().counter(
        ALERTS_TOTAL,
        "Alert-engine state transitions by rule, severity, and new state.",
        ("rule", "severity", "state")).inc(
        1, rule=str(rule), severity=str(severity), state=str(state))


def note_autoscale_decision(action: str, verdict: str,
                            reason: str | None = None) -> None:
    """Count one autoscaler policy verdict
    (``igg_autoscale_decisions_total{action,verdict}``; ``action``:
    ``grow`` | ``shrink``, ``verdict``: ``filed`` | ``rejected``). A
    filed move also bumps ``igg_autoscale_resizes_total``; a rejection
    bumps ``igg_autoscale_rejected_total{reason}`` (``hysteresis`` /
    ``cooldown`` / ``priced_out`` / ...). The journal's
    ``autoscale_decision`` event is the detailed twin carrying the full
    signal snapshot and pricing breakdown."""
    reg = metrics_registry()
    reg.counter(
        AUTOSCALE_DECISIONS,
        "Autoscaler policy verdicts by candidate action and outcome.",
        ("action", "verdict")).inc(
        1, action=str(action), verdict=str(verdict))
    if verdict == "filed":
        reg.counter(
            AUTOSCALE_RESIZES,
            "Resizes the autoscaler filed through the control path."
            ).inc(1)
    elif verdict == "rejected":
        reg.counter(
            AUTOSCALE_REJECTED,
            "Autoscale candidates rejected before actuation, by reason.",
            ("reason",)).inc(1, reason=str(reason or "unknown"))


def note_job_target_devices(scope, devices: int) -> None:
    """Stamp the device count the autoscaler currently targets for one
    job (its `ScopedRegistry` view — the gauge an operator compares
    against the mesh's pool size to see the policy's live allocation)."""
    scope.gauge(
        JOB_TARGET_DEVICES,
        "Devices this job's decomposition currently targets (product of "
        "its dims; moved by autoscale resizes).").set(int(devices))


def job_gauges(registry, job: str):
    """The per-job labeled families, as a `ScopedRegistry` view bound to
    one tenant — what `/metrics` serves across job lifetimes (step,
    heartbeat, perf, slice/wait latencies; a finished job's final values
    stay scrapeable while the service lives) and what the scheduler
    retires via ``remove_scope()`` when IT closes."""
    return (registry or metrics_registry()).scoped(job=str(job))


def observe_job_slice(scope, *, step, slice_s: float, wait_s: float,
                      perf_step_s=None, perf_ratio=None,
                      audit_findings: float = 0.0,
                      slack_s=None) -> None:
    """Record one granted slice for one job into its scoped gauge view
    (`job_gauges`): committed step + heartbeat, slice/wait latency
    histograms, and the perf-oracle mirrors (the process-wide
    ``igg_perf_*`` gauges flap between tenants under multiplexing — the
    per-job labeled copies are the ones an operator alerts on).
    ``slack_s`` mirrors the driver's live deadline slack into the
    per-job label (same label-shape rule as the perf pair: the
    process-wide ``igg_deadline_slack_seconds`` flaps between
    tenants)."""
    scope.gauge(JOB_STEP, "Last step this job committed.").set(step)
    scope.gauge(JOB_HEARTBEAT_TS,
                "Wall-clock time of this job's last granted slice "
                "(unix seconds).").set(time.time())
    scope.histogram(JOB_SLICE_SECONDS,
                    "Wall time of this job's granted slices (one "
                    "chunk-boundary iteration each).").observe(slice_s)
    scope.histogram(JOB_WAIT_SECONDS,
                    "Time this job waited between slices (queue + other "
                    "tenants' slices).").observe(wait_s)
    if perf_step_s is not None:
        scope.gauge(JOB_PERF_STEP_S,
                    "Measured per-step execution time of this job's last "
                    "chunk.").set(perf_step_s)
    if perf_ratio is not None:
        scope.gauge(JOB_PERF_RATIO,
                    "Measured / modeled per-step time for this job."
                    ).set(perf_ratio)
    if audit_findings:
        scope.counter(JOB_AUDIT_FINDINGS,
                      "Static-analysis findings attributed to this job's "
                      "compile-time audits.").inc(audit_findings)
    if slack_s is not None:
        scope.gauge(JOB_DEADLINE_SLACK,
                    "This job's remaining deadline budget minus predicted "
                    "remaining work (seconds; negative = provable bust)."
                    ).set(slack_s)


def observe_member_health(reports, scope=None) -> None:
    """Per-member ensemble health as labeled series: stacked-layout RMS
    and non-finite cell counts per (member, field) gauge, and a
    per-member guard-trip counter. ``reports`` are the chunk's per-member
    `HealthReport`s (`runtime.health.ensemble_reports_from_stats`);
    ``scope`` routes into a job's `ScopedRegistry` view (the scheduler
    mirrors the last chunk's members there, so batched jobs expose
    per-member series under their own job label)."""
    reg = scope if scope is not None else metrics_registry()
    scoped = scope is not None
    rms = reg.gauge(JOB_MEMBER_RMS if scoped else MEMBER_RMS,
                    "Stacked-layout RMS per ensemble member and field.",
                    ("member", "field"))
    nonf = reg.gauge(JOB_MEMBER_NONFINITE if scoped else MEMBER_NONFINITE,
                     "Non-finite cell count per ensemble member and "
                     "field.", ("member", "field"))
    trips = reg.counter(JOB_MEMBER_TRIPS if scoped else MEMBER_TRIPS,
                        "Guard trips attributed to one ensemble member.",
                        ("member",))
    for rep in reports:
        m = str(rep.member)
        for field, v in rep.rms.items():
            rms.set(v, member=m, field=field)
        for field, v in rep.nonfinite.items():
            nonf.set(float(v), member=m, field=field)
        if not rep.ok:
            trips.inc(1, member=m)


def observe_reshard(dur_s: float, *, via: str, new_dims, step=None,
                    rounds=None, wire_bytes=None, local_bytes=None,
                    **fields) -> None:
    """Record one elastic resize (`runtime.ResilientRun.resize`): wall
    time by path (``via``: ``device`` | ``checkpoint``), the collective
    program's wire/local byte volume and scheduled round count (device
    path only — the checkpoint path's volume is its restore's), and the
    ``resize`` flight event the run report / Perfetto trace render as a
    span."""
    reg = metrics_registry()
    reg.histogram(
        RESHARD_SECONDS,
        "Elastic resize wall time (state re-blocked onto new dims), "
        "by path.", ("via",)).observe(dur_s, via=str(via))
    bytes_fam = reg.counter(
        RESHARD_BYTES,
        "Bytes moved by on-device reshard programs, wire (padded "
        "all-links ppermute payloads) vs local (same-device copies).",
        ("kind",))
    if wire_bytes:
        bytes_fam.inc(int(wire_bytes), kind="wire")
    if local_bytes:
        bytes_fam.inc(int(local_bytes), kind="local")
    if rounds is not None:
        reg.gauge(
            RESHARD_ROUNDS,
            "Scheduled ppermute slice rounds of the last on-device "
            "reshard program.").set(int(rounds))
    record_event("resize", via=str(via), new_dims=list(new_dims),
                 dur_s=dur_s, step=step, rounds=rounds,
                 wire_bytes=wire_bytes, local_bytes=local_bytes, **fields)


def observe_reducers(step, values: dict, *, ok: bool = True) -> None:
    """Record one chunk boundary's in-situ reducer results: scalar values
    land in the ``igg_reducer_value`` gauge family (labeled by reducer
    name; per-stat sub-labeled ``name:stat``), every value streams to the
    flight recorder (``reducers`` event — slices included, they are
    axis-sized)."""
    g = metrics_registry().gauge(
        REDUCER_VALUE,
        "Latest in-situ reducer results (probes, stats).", ("name",))
    for name, v in values.items():
        if isinstance(v, dict):
            for stat, sv in v.items():
                g.set(sv, name=f"{name}:{stat}")
        elif not hasattr(v, "__len__"):
            g.set(float(v), name=name)
    record_event("reducers", step=step, ok=ok, values=values)


def note_http_request(route: str, method: str, code: int,
                      dur_s: float, scope=None) -> None:
    """Account one routed HTTP request on the serving tier
    (`telemetry.server.MetricsServer` dispatch — token-gate 401s
    included).  ``route`` is the NORMALIZED route pattern (job names
    collapsed to ``{name}``), keeping label cardinality bounded;
    ``scope`` routes into the registry the answering server serves."""
    reg = scope if scope is not None else metrics_registry()
    reg.counter(
        HTTP_REQUESTS,
        "Routed HTTP requests by route pattern, method, and status code.",
        ("route", "method", "code")).inc(
            1, route=str(route), method=str(method), code=str(int(code)))
    reg.histogram(
        HTTP_REQUEST_SECONDS,
        "Routed HTTP request handling wall time.", ("route",)
    ).observe(float(dur_s), route=str(route))


def note_flight_file_bytes(file: str, nbytes: int) -> None:
    """Stamp one flight/journal stream's on-disk size (gauge, labeled by
    basename) — fed from the live tail's byte-offset checkpoints
    (`telemetry.live.FlightTail`), so recorder growth is visible before
    it becomes a disk incident (``tools flight du`` is the CLI twin)."""
    metrics_registry().gauge(
        FLIGHT_FILE_BYTES,
        "Bytes consumed so far by each flight/journal JSONL stream.",
        ("file",)).set(int(nbytes), file=str(file))
