"""Framework-side instrumentation hooks: the one place the hot paths call.

Each hook bumps the process metrics registry (always on — a few dict ops
under a lock) and appends a flight-recorder event when a recorder is
active (a no-op None-check otherwise). Keeping the metric names and label
sets here, instead of scattered over `ops/halo.py` / `models/common.py` /
`utils/checkpoint.py`, means the exported surface is greppable in one
module and a rename can never desynchronize producers.
"""

from __future__ import annotations

from .recorder import record_event
from .registry import metrics_registry

__all__ = ["note_runner_cache", "account_halo_exchange",
           "observe_checkpoint"]

# Metric family names (the exported contract; see docs/observability.md).
RUNNER_CACHE = "igg_runner_cache_total"
HALO_EXCHANGES = "igg_halo_exchanges_total"
HALO_PPERMUTES = "igg_halo_ppermutes_total"
HALO_WIRE_BYTES = "igg_halo_wire_bytes_total"
HALO_LOCAL_BYTES = "igg_halo_local_copy_bytes_total"
CKPT_SECONDS = "igg_checkpoint_seconds"


def note_runner_cache(result: str, build_s: float | None = None) -> None:
    """Record a `make_state_runner` cache outcome: ``hit`` (compiled chunk
    reused), ``miss`` (new program built — the following dispatch pays the
    XLA compile), or ``uncached`` (no key given)."""
    metrics_registry().counter(
        RUNNER_CACHE,
        "Chunk-runner cache outcomes (miss = the next dispatch compiles).",
        ("result",)).inc(1, result=result)
    if build_s is None:
        record_event("runner_cache", result=result)
    else:
        record_event("runner_cache", result=result, build_s=build_s)


def account_halo_exchange(plan: dict) -> None:
    """Record one `update_halo` call from its static wire plan
    (`ops.halo.halo_comm_plan`): bytes-on-wire and collective counts per
    mesh axis, derived at trace time from shapes/overlaps/wire dtype —
    zero device syncs (the TPU analog of the reference's printed GB/s
    estimate, computed instead of measured)."""
    reg = metrics_registry()
    reg.counter(HALO_EXCHANGES, "update_halo calls accounted.").inc(1)
    pperm = reg.counter(
        HALO_PPERMUTES,
        "collective-permute ops issued by halo exchanges, per mesh axis.",
        ("axis",))
    wire = reg.counter(
        HALO_WIRE_BYTES,
        "Halo payload bytes crossing the interconnect (all links summed), "
        "per mesh axis and on-wire dtype.", ("axis", "dtype"))
    for axis, rec in plan["axes"].items():
        if rec["ppermutes"]:
            pperm.inc(rec["ppermutes"], axis=axis)
        for dt, b in rec["by_dtype"].items():
            wire.inc(b, axis=axis, dtype=dt)
    if plan["local_copy_bytes"]:
        reg.counter(
            HALO_LOCAL_BYTES,
            "Halo bytes moved by self-neighbor local copies (no wire)."
        ).inc(plan["local_copy_bytes"])
    record_event("halo_exchange", fields=plan["fields"],
                 ppermutes=plan["ppermutes"],
                 wire_bytes=plan["wire_bytes"],
                 local_copy_bytes=plan["local_copy_bytes"])


def observe_checkpoint(op: str, dur_s: float, *, path: str,
                       step=None, **fields) -> None:
    """Record a checkpoint save/restore latency (``op``: ``save`` |
    ``save_sharded`` | ``restore`` | ``restore_sharded`` |
    ``restore_elastic``)."""
    metrics_registry().histogram(
        CKPT_SECONDS, "Checkpoint save/restore wall time by operation.",
        ("op",)).observe(dur_s, op=op)
    kind = "checkpoint_save" if op.startswith("save") else \
        "checkpoint_restore"
    record_event(kind, op=op, dur_s=dur_s, path=str(path), step=step,
                 **fields)
