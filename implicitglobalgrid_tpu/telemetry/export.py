"""Prometheus text-format exporter for the metrics registry.

One function, `prometheus_snapshot`, renders the registry in the
Prometheus exposition format (text/plain version 0.0.4): ``# HELP`` /
``# TYPE`` comment pairs per family, samples with escaped label values,
histograms in the cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``
form. Operators scrape it however they already scrape sidecar files
(node-exporter textfile collector, a 5-line HTTP handler, or the report
CLI); the framework deliberately ships the FORMAT, not a server.
"""

from __future__ import annotations

import math

from .registry import metrics_registry

__all__ = ["prometheus_snapshot"]


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    if isinstance(v, float) and math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    if float(v) == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels(d: dict, extra: dict | None = None) -> str:
    items = list(d.items()) + (list(extra.items()) if extra else [])
    if not items:
        return ""
    body = ",".join(f'{k}="{_esc_label(str(v))}"' for k, v in items)
    return "{" + body + "}"


def prometheus_snapshot(registry=None) -> str:
    """Render ``registry`` (default: the process registry) as Prometheus
    exposition text. Families sort by name and series by label values, so
    successive snapshots diff cleanly."""
    reg = registry if registry is not None else metrics_registry()
    lines = []
    for fam in reg.collect():
        name, kind = fam["name"], fam["kind"]
        lines.append(f"# HELP {name} {_esc_help(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        series = sorted(fam["series"], key=lambda s: sorted(s[0].items()))
        if kind in ("counter", "gauge"):
            for labels, v in series:
                lines.append(f"{name}{_labels(labels)} {_fmt(v)}")
            continue
        # histogram: cumulative buckets + _sum/_count per series
        bounds = fam["buckets"]
        for labels, st in series:
            cum = 0
            for b, c in zip(bounds, st["counts"]):
                cum += c
                lines.append(
                    f"{name}_bucket{_labels(labels, {'le': _fmt(b)})} "
                    f"{_fmt(cum)}")
            lines.append(
                f"{name}_bucket{_labels(labels, {'le': '+Inf'})} "
                f"{_fmt(st['count'])}")
            lines.append(f"{name}_sum{_labels(labels)} {_fmt(st['sum'])}")
            lines.append(f"{name}_count{_labels(labels)} "
                         f"{_fmt(st['count'])}")
    return "\n".join(lines) + ("\n" if lines else "")
