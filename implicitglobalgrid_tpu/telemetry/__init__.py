"""Run telemetry: metrics registry, flight recorder, exporters, run report.

The observability subsystem (ISSUE 3 tentpole) — every run becomes
structured, exportable data instead of a `tic`/`toc` printout (the
reference's whole surface, SURVEY §5.4):

- `registry` — process-local, thread-safe metric families (counters,
  gauges, fixed-bucket histograms) with labels; absorbs PR-2's
  `health_counters` (kept as a shim in `utils.profiling`).
- `recorder` — the span/event flight recorder: one append-only JSONL
  stream per run (monotonic timestamps, pid/process index, run id),
  streamed by `runtime/driver.py`, the runner caches, and the
  checkpoint layer.
- `hooks` — the metric-name contract the framework's hot paths call
  (runner-cache outcomes, static halo comm accounting, checkpoint
  latencies).
- `export` — Prometheus text-format snapshots.
- `report` — `run_report`: the unified record merging the flight log
  with `overlap_stats`/`op_breakdown`; also the `python -m
  implicitglobalgrid_tpu.tools report` CLI's engine.

All instrumentation is HOST-side: compiled chunk programs are unchanged
(`tests/test_hlo_audit.py` proves identical collective and fetch counts)
and the measured overhead sits under the 2% gate (`bench_telemetry.py`).
"""

from .export import prometheus_snapshot
from .hooks import account_halo_exchange, note_runner_cache, \
    observe_checkpoint
from .recorder import (
    FlightRecorder, flight_recorder, read_flight_events, record_event,
    record_span, start_flight_recorder, stop_flight_recorder,
)
from .registry import (
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry,
    metrics_registry, reset_metrics,
)
from .report import run_report

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "DEFAULT_BUCKETS",
    "metrics_registry", "reset_metrics",
    "FlightRecorder", "start_flight_recorder", "stop_flight_recorder",
    "flight_recorder", "record_event", "record_span", "read_flight_events",
    "prometheus_snapshot", "run_report",
    "note_runner_cache", "account_halo_exchange", "observe_checkpoint",
]
