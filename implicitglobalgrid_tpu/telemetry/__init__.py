"""Run telemetry: metrics registry, flight recorder, exporters, run report.

The observability subsystem (ISSUE 3 tentpole) — every run becomes
structured, exportable data instead of a `tic`/`toc` printout (the
reference's whole surface, SURVEY §5.4):

- `registry` — process-local, thread-safe metric families (counters,
  gauges, fixed-bucket histograms) with labels; absorbed PR-2's
  `health_counters` (the ``igg_health_events_total`` family — the
  deprecation shims in `utils.profiling` are retired).
- `recorder` — the span/event flight recorder: one append-only JSONL
  stream per run (monotonic timestamps, pid/process index, run id),
  streamed by `runtime/driver.py`, the runner caches, and the
  checkpoint layer.
- `hooks` — the metric-name contract the framework's hot paths call
  (runner-cache outcomes, static halo comm accounting, checkpoint
  latencies).
- `export` — Prometheus text-format snapshots.
- `report` — `run_report`: the unified record merging the flight log
  with `overlap_stats`/`op_breakdown`; also the `python -m
  implicitglobalgrid_tpu.tools report` CLI's engine.
- `aggregate` — the MESH-wide view (ISSUE 5 tentpole):
  `aggregate_flight` merges N per-process flight streams into one
  clock-aligned sequence (offsets estimated post-hoc at the chunk-
  boundary psum barriers — no new collectives) and `straggler_report`
  attributes per-chunk barrier arrivals, flags persistent stragglers,
  and summarizes wait/compute imbalance (`run_report`'s ``"mesh"``
  section).
- `trace_export` — `export_chrome_trace`: the merged stream as
  Chrome/Perfetto trace-event JSON (one track per process, chunk/
  checkpoint/snapshot spans, instant guard events, counter tracks).
- `tracectx` / `otlp` — END-TO-END distributed tracing (ISSUE 20
  tentpole): `TraceContext` is the W3C ``traceparent``-compatible
  causal identity the serve tier mints per request and the scheduler
  threads through every journal event and flight span of a job;
  `export_otlp` renders the merged streams as OTLP/HTTP JSON
  ``ResourceSpans`` for any OpenTelemetry collector and
  `OtlpSpanExporter` is the batched live sink.
- `server` — `start_metrics_server`: opt-in stdlib HTTP thread serving
  ``/metrics`` (Prometheus exposition) and ``/healthz`` (driver
  heartbeat age); started by `run_resilient(metrics_port=...)`; routes
  may stream chunked responses (the live event feed).
- `live` — the LIVE observability plane (ISSUE 18 tentpole):
  `FlightTail` (byte-offset-checkpointed incremental tailing of flight
  JSONLs, torn-line and gap tolerant), `LiveAggregate` (rolling derived
  signals while jobs still run: step quantiles + robust z, deadline
  slack, barrier-spread straggler attribution, byte rates, queue
  pressure), and the declarative `AlertRule`/`AlertEngine` with
  `default_rule_pack` and pluggable sinks (`log_sink`,
  `ControlFileSink`, `WebhookSink`); served over HTTP by
  `serve.ObservePlane` (``/v1/observe`` + ``/v1/events``) and embedded
  in-process by `service.MeshScheduler(alerts=True)`.
- `perfmodel` — the performance ORACLE (ISSUE 6 tentpole): `predict_step`
  combines the static halo wire plan, per-model stencil workloads, and a
  `MachineProfile` of measured coefficients into per-step compute/comm/
  exposed-comm predictions with a latency/bandwidth/compute-bound
  verdict; `PerfWatch` is the live drift detector the driver feeds
  (rolling median+MAD baseline, ``perf_regression`` events,
  ``igg_perf_*`` gauges).
- `calibrate` — `calibrate_machine`: short measured runs (sharded triad,
  FMA chain, per-axis ppermute-pair two-point fits) that produce the
  machine-profile JSON the model consumes.
- `perfdb` — the perf-history database and gate: `perfdb_add` appends
  each bench run to a JSONL history, `perfdb_check` fails metrics that
  regress beyond a threshold vs the trailing window (the ``tools perfdb``
  CLI and `bench_all.py`'s self-gate).
- `tune` — the CLOSED-LOOP auto-tuner (ISSUE 13 tentpole): `tune_config`
  searches `predict_step` over per-axis ``comm_every`` x per-axis
  ``wire_dtype`` x coalesce x overlap x ensemble E (every candidate on
  its own grid geometry), validates the top candidates with short
  measured calibration runs, and persists the winning `TunedConfig`
  next to the machine profile; applied per job via
  `runtime.RunSpec(tuned=)` / the scheduler's admission / ``tools
  tune``.

All instrumentation is HOST-side: compiled chunk programs are unchanged
(`tests/test_hlo_audit.py` proves identical collective and fetch counts)
and the measured overhead sits under the 2% gate (`bench_telemetry.py`).
"""

from .aggregate import (
    aggregate_events, aggregate_flight, mesh_section, straggler_report,
)
from .calibrate import calibrate_machine
from .export import prometheus_snapshot
from .hooks import account_halo_exchange, note_heartbeat, \
    note_runner_cache, observe_checkpoint
from .live import (
    AlertEngine, AlertRule, ControlFileSink, FlightTail, LiveAggregate,
    WebhookSink, default_rule_pack, log_sink,
)
from .perfdb import metric_direction, perfdb_add, perfdb_check, perfdb_load
from .perfmodel import (
    MachineProfile, PerfWatch, ReshardPrediction, STEP_WORKLOADS,
    StepWorkload, default_machine_profile, hierarchical_machine_profile,
    load_machine_profile, predict_reshard,
    predict_step, robust_z, save_machine_profile,
)
from .recorder import (
    FlightRecorder, flight_recorder, read_flight_events, record_event,
    record_span, start_flight_recorder, stop_flight_recorder,
)
from .registry import (
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry,
    ScopedRegistry, metrics_registry, reset_metrics,
)
from .report import run_report
from .tune import (
    TunedConfig, load_tuned_config, resolve_tuned, save_tuned_config,
    tune_config, tuned_config_path,
)
from .server import (
    MetricsServer, metrics_server, start_metrics_server,
    stop_metrics_server,
)
from .otlp import OtlpSpanExporter, export_otlp
from .trace_export import export_chrome_trace
from .tracectx import TraceContext

__all__ = [
    "MetricsRegistry", "ScopedRegistry", "Counter", "Gauge", "Histogram",
    "DEFAULT_BUCKETS", "metrics_registry", "reset_metrics",
    "FlightRecorder", "start_flight_recorder", "stop_flight_recorder",
    "flight_recorder", "record_event", "record_span", "read_flight_events",
    "prometheus_snapshot", "run_report",
    "aggregate_flight", "aggregate_events", "straggler_report",
    "mesh_section", "export_chrome_trace",
    "TraceContext", "export_otlp", "OtlpSpanExporter",
    "MetricsServer", "start_metrics_server", "stop_metrics_server",
    "metrics_server",
    "note_runner_cache", "account_halo_exchange", "observe_checkpoint",
    "note_heartbeat",
    "FlightTail", "LiveAggregate", "AlertRule", "AlertEngine",
    "default_rule_pack", "log_sink", "ControlFileSink", "WebhookSink",
    "MachineProfile", "StepWorkload", "STEP_WORKLOADS", "PerfWatch",
    "robust_z",
    "default_machine_profile", "hierarchical_machine_profile",
    "load_machine_profile",
    "save_machine_profile", "predict_step", "predict_reshard",
    "ReshardPrediction", "calibrate_machine",
    "metric_direction", "perfdb_add", "perfdb_check", "perfdb_load",
    "TunedConfig", "tune_config", "save_tuned_config",
    "load_tuned_config", "resolve_tuned", "tuned_config_path",
]
