"""In-situ reducers: probes, axis slices, global stats — one tiny psum.

The analysis questions a long run actually asks per output interval —
"what is the value at the sensor point", "give me the centerline", "is
the max still bounded" — need O(1)..O(axis) numbers, yet the gather path
answers them by materializing O(global). These reducers compute them
INSIDE the supervised chunk program (`make_state_runner(post_chunk=...)`,
the same fusion point as the health guard) over the IMPLICIT grid:
every shard masks the cells it OWNS (`io/layout.py` — the
`gather_interior` ownership arithmetic, overlap cells counted once,
periodic ghosts excluded), contributes to a small f32 vector, and ONE
`psum` over all mesh axes — shared with the health guard's stats, so an
enabled reducer set adds ZERO extra collectives to the chunk program
(`tests/test_hlo_audit.py`) — replicates the results to every process.
The driver decodes the vector tail on the host and streams it to the
flight recorder + metrics gauges. No gather, ever.

Global min/max ride the same single psum via a slot trick: each shard
writes its local masked min/max into ITS slot of a ``nprocs``-long
segment (every other shard contributes zero there), and the host reduces
over slots — sum-reduction hardware, min/max semantics, exactly.

Reducer species (field names refer to the supervised state dict):

- `Probe(field, index)` — one global cell's value per chunk boundary
  (a point time-series; shard-local indexing, owner computed at trace
  time).
- `AxisSlice(field, axis, index)` — the 1-D line along ``axis`` through
  global anchor ``index`` (``index[axis]`` is ignored).
- `Stats(field, which=("min","max","mean","rms"))` — exact global scalar
  stats over the implicit grid (float32 accumulation, like the health
  guard).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..parallel.topology import AXIS_NAMES, global_grid
from ..utils.exceptions import InvalidArgumentError
from .layout import field_geometry, global_shape_of, owner_maps

__all__ = ["Probe", "AxisSlice", "Stats", "ReducerPlan",
           "build_reducer_plan", "make_reduced_post_chunk"]

_STATS_KINDS = ("min", "max", "mean", "rms")


@dataclass(frozen=True)
class Probe:
    """Value of one IMPLICIT-global cell of ``field`` (staggering
    included: indices address `gather_interior(field)`'s coordinates)."""
    field: str
    index: tuple
    name: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "index",
                           tuple(int(i) for i in self.index))

    @property
    def label(self) -> str:
        return self.name or f"probe:{self.field}@" + \
            ",".join(str(i) for i in self.index)


@dataclass(frozen=True)
class AxisSlice:
    """The 1-D line of ``field`` along ``axis`` through the global anchor
    ``index`` (whose ``axis`` entry is ignored)."""
    field: str
    axis: int
    index: tuple
    name: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "index",
                           tuple(int(i) for i in self.index))

    @property
    def label(self) -> str:
        anchor = ",".join("_" if d == self.axis else str(i)
                          for d, i in enumerate(self.index))
        return self.name or f"slice:{self.field}[{self.axis}]@{anchor}"


@dataclass(frozen=True)
class Stats:
    """Global scalar statistics of ``field`` over the implicit grid."""
    field: str
    which: tuple = dc_field(default=_STATS_KINDS)
    name: str | None = None

    def __post_init__(self):
        object.__setattr__(self, "which", tuple(self.which))
        bad = [w for w in self.which if w not in _STATS_KINDS]
        if bad or not self.which:
            raise InvalidArgumentError(
                f"Stats.which entries must be among {_STATS_KINDS}; "
                f"got {tuple(self.which)}.")

    @property
    def label(self) -> str:
        return self.name or f"stats:{self.field}"


class ReducerPlan:
    """The compiled-side layout of a reducer set: per-reducer segment
    offsets into the chunk stats vector, the traced contribution builder,
    and the host-side decoder. Built per grid epoch (`build_reducer_plan`)
    because ownership geometry depends on the live decomposition —
    `run_resilient` rebuilds it after an elastic restart."""

    def __init__(self, entries, signature, nprocs: int):
        self._entries = entries          # [(reducer, offset, length, geoms)]
        self.signature = signature       # hashable: joins the runner key
        self.nprocs = int(nprocs)        # min/max slot count at build time
        self.length = sum(e[2] for e in entries)
        self.labels = [e[0].label for e in entries]
        dup = {l for l in self.labels if self.labels.count(l) > 1}
        if dup:
            raise InvalidArgumentError(
                f"Duplicate reducer label(s) {sorted(dup)}: give the "
                "colliding reducers distinct name=...")

    # -- traced side -------------------------------------------------------

    def local_parts(self, state_names, state):
        """The PRE-psum contribution vector of this shard (inside
        shard_map; ``state`` is the tuple of LOCAL blocks in
        ``state_names`` order). float32, length `self.length`."""
        import jax.numpy as jnp

        by_name = dict(zip(state_names, state))
        parts = []
        for red, _off, _ln, geoms in self._entries:
            x = by_name[red.field].astype(jnp.float32)
            if isinstance(red, Probe):
                parts.append(_probe_part(x, red, geoms))
            elif isinstance(red, AxisSlice):
                parts.append(_slice_part(x, red, geoms))
            else:
                parts.append(_stats_part(x, geoms))
        return jnp.concatenate(parts)

    # -- host side ---------------------------------------------------------

    def decode(self, tail) -> dict:
        """label -> value(s), from the psum'ed vector's reducer tail."""
        tail = np.asarray(tail)
        if tail.shape != (self.length,):
            raise InvalidArgumentError(
                f"Reducer tail has shape {tail.shape}; the plan expects "
                f"({self.length},).")
        out = {}
        P = self.nprocs
        for red, off, ln, geoms in self._entries:
            seg = tail[off:off + ln]
            if isinstance(red, Probe):
                out[red.label] = float(seg[0])
            elif isinstance(red, AxisSlice):
                out[red.label] = np.array(seg)
            else:
                count = float(np.prod(global_shape_of(geoms)))
                vals = {"min": float(np.min(seg[2:2 + P])),
                        "max": float(np.max(seg[2 + P:2 + 2 * P])),
                        "mean": float(seg[0]) / count,
                        "rms": math.sqrt(max(float(seg[1]), 0.0) / count)}
                out[red.label] = {w: vals[w] for w in red.which}
        return out


# ---------------------------------------------------------------------------
# Traced contribution builders (inside shard_map, pre-psum)
# ---------------------------------------------------------------------------

def _axis_idx(d):
    from jax import lax

    return lax.axis_index(AXIS_NAMES[d])


def _replica_guard(rank: int):
    """Fields of rank < 3 are replicated over the unused mesh axes: only
    the axis-0 copy contributes, or the psum would multiply sums and
    probes by the replica count."""
    import jax.numpy as jnp

    g = jnp.float32(1.0)
    for d in range(rank, 3):
        g = g * (_axis_idx(d) == 0).astype(jnp.float32)
    return g


def _is_owner(geoms, index, dims_sel):
    """1.0 iff THIS shard owns the anchor cells of ``index`` along every
    dim in ``dims_sel`` (owners are static host ints; the comparison
    against `lax.axis_index` is the traced part)."""
    import jax.numpy as jnp

    m = jnp.float32(1.0)
    locals_ = {}
    for d in dims_sel:
        c, i = owner_maps(geoms[d], np.asarray([index[d]]))
        m = m * (_axis_idx(d) == int(c[0])).astype(jnp.float32) \
            if d < 3 else m
        locals_[d] = int(i[0])
    return m, locals_


def _own_mask_1d(geom, d):
    """Traced ownership mask over the ``n`` local cells of dim ``d``."""
    import jax.numpy as jnp

    i = jnp.arange(geom.n)
    if geom.per:
        return (i >= 1) & (i <= geom.s)
    last = _axis_idx(d) == geom.dd - 1 if d < 3 else True
    return i < jnp.where(last, geom.n, geom.s)


def _probe_part(x, red: Probe, geoms):
    import jax.numpy as jnp

    rank = x.ndim
    mine, locals_ = _is_owner(geoms, red.index, range(rank))
    val = x[tuple(locals_[d] for d in range(rank))]
    return jnp.reshape(val * mine * _replica_guard(rank), (1,))


def _slice_part(x, red: AxisSlice, geoms):
    import jax.numpy as jnp

    rank = x.ndim
    a = red.axis
    geom = geoms[a]
    mine, locals_ = _is_owner(geoms, red.index,
                              [d for d in range(rank) if d != a])
    idx = tuple(slice(None) if d == a else locals_[d] for d in range(rank))
    line = x[idx]                       # (n_a,) local cells along the axis
    own = _own_mask_1d(geom, a).astype(jnp.float32)
    c = _axis_idx(a) if a < 3 else 0
    i = jnp.arange(geom.n)
    if geom.per:
        g = (c * geom.s + i - 1) % geom.size
    else:
        g = c * geom.s + i
    contrib = line * own * mine * _replica_guard(rank)
    return jnp.zeros((geom.size,), jnp.float32).at[g].add(contrib)


def _stats_part(x, geoms):
    import jax.numpy as jnp

    rank = x.ndim
    mask = None
    for d in range(rank):
        md = _own_mask_1d(geoms[d], d)
        md = md.reshape([-1 if dd == d else 1 for dd in range(rank)])
        mask = md if mask is None else mask & md
    gg = global_grid()
    guard = _replica_guard(rank)
    ssum = jnp.sum(jnp.where(mask, x, 0.0)) * guard
    ssq = jnp.sum(jnp.where(mask, x * x, 0.0)) * guard
    mn = jnp.min(jnp.where(mask, x, jnp.inf))
    mx = jnp.max(jnp.where(mask, x, -jnp.inf))
    # slot trick: shard r's min/max land in slot r alone, the host takes
    # min/max over slots — order statistics through a sum-collective
    dims = [int(d) for d in gg.dims]
    r = (_axis_idx(0) * dims[1] + _axis_idx(1)) * dims[2] + _axis_idx(2)
    P = dims[0] * dims[1] * dims[2]
    slots_mn = jnp.zeros((P,), jnp.float32).at[r].set(mn)
    slots_mx = jnp.zeros((P,), jnp.float32).at[r].set(mx)
    return jnp.concatenate([jnp.stack([ssum, ssq]), slots_mn, slots_mx])


# ---------------------------------------------------------------------------
# Plan building and the fused post-chunk hook
# ---------------------------------------------------------------------------

def build_reducer_plan(reducers, names, state) -> ReducerPlan:
    """Validate ``reducers`` against the supervised ``state`` (dict of
    name -> stacked array) on the LIVE grid and lay out their segments.
    Host-side and cheap; the plan's `signature` must join the runner
    cache key (geometry changes with the decomposition)."""
    gg = global_grid()
    entries = []
    off = 0
    P = int(np.prod(np.asarray(gg.dims)))
    for red in reducers:
        if not isinstance(red, (Probe, AxisSlice, Stats)):
            raise InvalidArgumentError(
                f"Unknown reducer type {type(red).__name__}; use Probe, "
                "AxisSlice or Stats.")
        if red.field not in names:
            raise InvalidArgumentError(
                f"Reducer {red.label!r} names unknown field "
                f"{red.field!r} (state has {list(names)}).")
        shape = tuple(int(s) for s in state[red.field].shape)
        loc = [shape[d] // int(gg.dims[d]) if d < 3 else shape[d]
               for d in range(len(shape))]
        geoms = field_geometry(gg.dims, gg.nxyz, gg.overlaps, gg.periods,
                               loc)
        gshape = global_shape_of(geoms)
        if isinstance(red, (Probe, AxisSlice)):
            if len(red.index) != len(gshape):
                raise InvalidArgumentError(
                    f"Reducer {red.label!r} index {red.index} has "
                    f"{len(red.index)} entries; field {red.field!r} is "
                    f"{len(gshape)}-D (global shape {gshape}).")
            for d, i in enumerate(red.index):
                free = isinstance(red, AxisSlice) and d == red.axis
                if not free and not 0 <= i < gshape[d]:
                    raise InvalidArgumentError(
                        f"Reducer {red.label!r} index {red.index} is "
                        f"outside the implicit global shape {gshape}.")
        if isinstance(red, AxisSlice):
            if not 0 <= red.axis < len(gshape):
                raise InvalidArgumentError(
                    f"AxisSlice axis {red.axis} is outside field "
                    f"{red.field!r}'s rank {len(gshape)}.")
            ln = geoms[red.axis].size
        elif isinstance(red, Probe):
            ln = 1
        else:
            ln = 2 + 2 * P
        entries.append((red, off, ln, geoms))
        off += ln
    # the signature must pin the GEOMETRY too, not just the specs: the
    # hook closure bakes owner coords/strides in as static ints, and the
    # runner cache would otherwise serve a stale closure for a same-named
    # field whose staggering (local shape) changed within one grid epoch
    sig = tuple(
        (type(r).__name__, r.field,
         getattr(r, "axis", None), getattr(r, "index", None),
         getattr(r, "which", None), r.label, tuple(g))
        for r, _o, _l, g in entries)
    return ReducerPlan(entries, sig, P)


def make_reduced_post_chunk(names, plan: ReducerPlan):
    """The fused guard+reducer hook for `make_state_runner(post_chunk=)`:
    health parts (`runtime/health.health_parts_local`) and reducer parts
    concatenate into ONE vector reduced by ONE psum over all mesh axes —
    the compiled chunk still carries exactly one tiny all-reduce
    (`tests/test_hlo_audit.py`). The driver slices the fetched vector:
    ``[:2*nfields]`` health, ``[2*nfields:]`` reducers.

    ENSEMBLE runs (ISSUE 12) vmap this hook over the member axis
    (`make_state_runner(ensemble=E)`): the reducer segments gain a
    per-member dimension — the fetched matrix is ``(E, 2N+R)``, each
    scenario streaming its own probes/slices/stats behind the SAME single
    psum — and the driver decodes each member's tail with this plan
    (labels suffixed ``[m<member>]``). The plan itself is built over the
    PER-MEMBER (physical) shapes; nothing here changes."""
    from jax import lax

    from ..runtime.health import health_parts_local

    names = tuple(names)

    def post_chunk(state):
        import jax.numpy as jnp

        vec = jnp.concatenate([health_parts_local(state),
                               plan.local_parts(names, state)])
        return lax.psum(vec, AXIS_NAMES)

    return post_chunk
