"""Sharded snapshot & in-situ analysis pipeline — get data off the grid
without ever building the grid.

The framework's premise is that the global grid is never materialized, yet
its classic output path (`ops/gather.py`) funnels the WHOLE global array
through one host — and in multi-host runs stalls the step loop while every
process materializes it. This subsystem (ISSUE 4 tentpole) replaces that
funnel with three O(shard)-per-process pillars:

- `snapshot` — **async sharded snapshots**: `SnapshotWriter` copies each
  process's shard blocks device->host (the only step-loop-blocking cost)
  and hands them to a bounded background writer queue with a backpressure
  policy (``block`` | ``drop_oldest``); blocks land on disk in the PR-2
  checkpoint container format (`utils/blockio.py`: block-coordinate keys,
  sha256 sidecars, staged-directory atomic commit), so the jitted step
  loop never waits on disk and an interrupted writer never leaves a
  committed-but-corrupt snapshot.
- `reducers` — **in-situ reduction**: point probes, axis slices, and
  global min/max/mean/RMS over the IMPLICIT grid (overlap cells counted
  once), fused into the supervised chunk program and reduced together
  with the health guard in ONE tiny `psum` per chunk boundary — results
  stream to the flight recorder, no gather ever.
- `reader` — **lazy assembly**: `open_snapshot(dir)` + `read_global(
  name, box=...)` assemble any sub-box of the implicit global grid on
  the host in O(box) memory with `gather_interior`-identical semantics
  (overlap stripped, periodic ghost shift and wrap handled) — the
  analysis-side replacement for gather-to-root. Host-only: works on a
  machine with no accelerator runtime, and reads PR-2 sharded
  checkpoints too (same container format).

Wired into `run_resilient(snapshot_dir=..., snapshot_every=...,
reducers=[...])` (`runtime/driver.py`), the telemetry metric families
(`igg_snapshot_bytes_total`, `igg_io_queue_depth`,
`igg_snapshot_seconds` — `telemetry/hooks.py`), `igg.run_report`, and the
``python -m implicitglobalgrid_tpu.tools snapshots|probe`` CLI.
"""

from .reader import Snapshot, list_snapshots, open_snapshot
from .reducers import AxisSlice, Probe, Stats, build_reducer_plan
from .snapshot import SnapshotWriter, write_snapshot

__all__ = [
    "SnapshotWriter", "write_snapshot",
    "Snapshot", "open_snapshot", "list_snapshots",
    "Probe", "AxisSlice", "Stats", "build_reducer_plan",
]
