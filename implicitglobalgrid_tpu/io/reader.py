"""Lazy snapshot reader: assemble any sub-box, never the grid.

`open_snapshot(dir)` parses a block container's ``meta.npz`` (topology,
names, stacked shapes, dtypes); `Snapshot.read_global(name, box=...)`
assembles the requested sub-box of the IMPLICIT global grid on the host —
overlap duplication stripped, periodic ghost shift and wrap applied —
with `gather_interior`-identical semantics (bit-for-bit: the same
ownership arithmetic, `io/layout.py`). Memory stays O(box + one shard
block): the block scanner (`utils/blockio.py`) loads only the blocks the
box touches, each file opened at most once, every byte checksum-verified
before use.

This is the analysis-side replacement for gather-to-root: where
`igg.gather_interior` funnels O(global) through one process DURING the
run, a post-hoc reader pulls exactly the probe point / slice plane /
sub-volume it needs from a committed snapshot — on any host with numpy,
no accelerator runtime, no initialized grid. Because snapshots share the
PR-2 checkpoint container (`utils/blockio.py`), `open_snapshot` on a
`save_checkpoint_sharded` directory works too.

CLI: ``python -m implicitglobalgrid_tpu.tools snapshots <root>`` and
``... probe <root|snapshot> <field> i j k`` (`tools.py`).
"""

from __future__ import annotations

import itertools
import os

import numpy as np

from ..utils.blockio import block_scanner, load_prefixed_meta, shard_key
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError
from .layout import (
    field_geometry, global_shape_of, normalize_box, owner_maps,
)
from .snapshot import STEP_PREFIX

__all__ = ["Snapshot", "open_snapshot", "list_snapshots"]


class Snapshot:
    """One committed block container, opened lazily (meta only; shard
    files are read on demand, box-sized)."""

    def __init__(self, dirpath):
        self.path = os.fspath(dirpath)
        base = os.path.basename(os.path.normpath(self.path))
        if ".tmp-" in base or ".old-" in base:
            # a staging (or moved-aside) directory is NOT a snapshot: a
            # query-service replica polling a live root must get the
            # typed refusal, never a torn read of a half-written set
            raise IncoherentArgumentError(
                f"{self.path} is an uncommitted staging directory "
                "(.tmp-/.old- — an in-flight or interrupted writer); "
                "only committed snapshot directories can be opened. "
                "Use list_snapshots(root) — it never lists these.")
        if not os.path.isdir(self.path):
            raise InvalidArgumentError(
                f"Snapshot directory not found: {self.path}")
        meta = load_prefixed_meta(self.path)
        self._meta = meta
        tok = meta.get("save_token")
        self.token = None if tok is None else str(tok)
        self.names = [str(n) for n in meta.get("names", ())]
        self.step = int(meta["step"]) if "step" in meta else None
        self._checksums = "checksums" in meta
        n_files = int(meta.get("nprocs_files", 0)) or 1
        self.files = [os.path.join(self.path, f"shards_p{i}.npz")
                      for i in range(n_files)]
        missing = [f for f in self.files if not os.path.exists(f)]
        if missing:
            raise IncoherentArgumentError(
                f"Snapshot {self.path} is incomplete: missing shard "
                f"file(s) {missing} — it was partially copied or "
                "tampered with after commit (an interrupted writer "
                "leaves an uncommitted .tmp- staging dir instead; a "
                "committed dir must be whole).")
        self._verified: set = set()

    # -- meta --------------------------------------------------------------

    def topology(self) -> dict:
        """The saved grid topology (``nxyz, dims, overlaps, periods,
        halowidths, step``) — same record as `igg.saved_topology`."""
        out = {k: np.asarray(self._meta[k], dtype=np.int64)
               for k in ("nxyz", "dims", "overlaps", "periods",
                         "halowidths")}
        out["step"] = self.step
        return out

    def dtype(self, name: str) -> np.dtype:
        self._check_name(name)
        return np.dtype(str(self._meta[f"dtype__{name}"]))

    def stacked_shape(self, name: str) -> tuple:
        self._check_name(name)
        return tuple(int(s) for s in self._meta[f"shape__{name}"])

    def _check_name(self, name: str) -> None:
        if name not in self.names:
            raise InvalidArgumentError(
                f"Snapshot {self.path} has no field {name!r} "
                f"(have {self.names}).")

    def _geoms(self, name: str) -> tuple:
        m = self._meta
        shape = self.stacked_shape(name)
        dims = np.asarray(m["dims"], dtype=np.int64)
        loc = [shape[d] // int(dims[d]) if d < 3 else shape[d]
               for d in range(len(shape))]
        for d in range(min(len(shape), 3)):
            if shape[d] % int(dims[d]):
                raise IncoherentArgumentError(
                    f"Stacked size {shape[d]} of `{name}` along dimension "
                    f"{d} is not divisible by dims[{d}]={int(dims[d])}.")
        return field_geometry(dims, m["nxyz"], m["overlaps"], m["periods"],
                              loc)

    def global_shape(self, name: str) -> tuple:
        """Implicit-global shape of ``name`` — what `gather_interior`
        would return for the same (possibly staggered) field."""
        return global_shape_of(self._geoms(name))

    # -- data --------------------------------------------------------------

    def read_global(self, name: str, box=None) -> np.ndarray:
        """Assemble the ``box`` (per-dim ``(lo, hi)`` half-open global
        ranges; ``None`` = whole axis/grid) of field ``name`` —
        bit-identical to ``gather_interior(A)[box]`` on the snapshotted
        state, in O(box) host memory."""
        geoms = self._geoms(name)
        gshape = global_shape_of(geoms)
        box = normalize_box(box, gshape)
        dtype = self.dtype(name)
        loc = tuple(g.n for g in geoms)

        # Per-axis owner maps of the requested cells, then the block set
        # they touch (the keys the lazy scanner is allowed to cache).
        per_axis = []
        for d, (lo, hi) in enumerate(box):
            c_of, i_of = owner_maps(geoms[d], np.arange(lo, hi))
            per_axis.append((c_of, i_of))
        wanted = {
            shard_key(name, tuple(int(co[d]) * loc[d]
                                  for d in range(len(loc))))
            for co in itertools.product(
                *[np.unique(pa[0]) for pa in per_axis])}
        find_block = block_scanner(self.files, wanted, self._checksums,
                                   self._verified, pop=False)

        out = np.empty(tuple(hi - lo for lo, hi in box), dtype=dtype)
        for co in itertools.product(*[np.unique(pa[0]) for pa in per_axis]):
            sel_out, sel_src = [], []
            for d in range(len(loc)):
                c_of, i_of = per_axis[d]
                jj = np.nonzero(c_of == co[d])[0]
                sel_out.append(jj)
                sel_src.append(i_of[jj])
            key = shard_key(name, tuple(int(co[d]) * loc[d]
                                        for d in range(len(loc))))
            block = np.asarray(self._fetch_block(name, key, find_block))
            out[np.ix_(*sel_out)] = block[np.ix_(*sel_src)]
        return out

    def _fetch_block(self, name: str, key: str, find_block):
        """Block-fetch hook: the base reader just scans the shard files
        (`block_scanner` — sha256-verified on first open). The serving
        tier's `serve.CachedSnapshot` overrides this with a bounded LRU
        keyed by (save token, field, block coordinate), so hot blocks
        decode once ACROSS requests instead of once per read."""
        return find_block(key)

    def read_point(self, name: str, index) -> float:
        """One global cell (the CLI probe's engine): O(1 block) read."""
        index = tuple(int(i) for i in index)
        gshape = self.global_shape(name)
        if len(index) != len(gshape):
            raise InvalidArgumentError(
                f"Point index {index} has {len(index)} entries; field "
                f"{name!r} is {len(gshape)}-D (global shape {gshape}).")
        box = tuple((i, i + 1) for i in index)
        return self.read_global(name, box)[(0,) * len(index)]

    def __repr__(self) -> str:  # operator-friendly
        return (f"Snapshot({self.path!r}, step={self.step}, "
                f"fields={self.names})")


def open_snapshot(dirpath) -> Snapshot:
    """Open one committed snapshot (or `save_checkpoint_sharded`)
    directory for lazy box reads."""
    return Snapshot(dirpath)


def list_snapshots(root) -> list:
    """The COMMITTED snapshots under ``root``, as ``(step, path)`` sorted
    by step. Staged ``.tmp-``/``.old-`` directories (an interrupted
    writer's leftovers) and directories without a ``meta.npz`` commit
    record are never listed — an uncommitted snapshot does not exist."""
    root = os.fspath(root)
    if not os.path.isdir(root):
        raise InvalidArgumentError(f"Snapshot root not found: {root}")
    out = []
    for entry in sorted(os.listdir(root)):
        if not entry.startswith(STEP_PREFIX) or ".tmp-" in entry \
                or ".old-" in entry:
            continue
        path = os.path.join(root, entry)
        if not os.path.isdir(path) \
                or not os.path.exists(os.path.join(path, "meta.npz")):
            continue
        try:
            step = int(entry[len(STEP_PREFIX):])
        except ValueError:
            continue
        out.append((step, path))
    out.sort()
    return out
