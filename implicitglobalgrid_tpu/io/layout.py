"""Implicit-grid ownership geometry — the ONE place the gather/reader/
reducer cell mapping lives.

`ops/gather.gather_interior` defines the framework's canonical stacked ->
implicit-global mapping (from the reference's coordinate formula,
`tools.jl:100`): along a sharded dim with local size ``n``, stride
``s = n - ol``, shard ``c``'s local cell ``i`` is global cell

- non-periodic: ``c*s + i`` — shards overlap by ``ol`` and LATER shards
  win ties (harmless: overlapping cells are equal after `update_halo`),
  so the OWNER of global cell ``p`` is ``min(p // s, dims-1)``;
- periodic: ``(c*s + i - 1) mod N`` with ``N = dims*s`` — everything
  shifts by one ghost cell and wraps, the owner of ``p`` is ``p // s``
  and its local index ``p - c*s + 1``.

The snapshot reader (`io/reader.py`) inverts this mapping on the host
from numpy meta alone, and the in-situ reducers (`io/reducers.py`) apply
it inside the compiled chunk via `lax.axis_index` masks; both must agree
with `gather_interior` BIT-FOR-BIT (asserted in `tests/test_io.py`), so
the arithmetic lives here once.

Everything here is plain host numpy over topology vectors (no jax, no
live grid) — the reader works from a snapshot's meta record on machines
with no accelerator runtime.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..utils.exceptions import InvalidArgumentError

__all__ = ["AxisGeometry", "axis_geometry", "field_geometry",
           "global_shape_of", "owner_maps", "normalize_box"]


class AxisGeometry(NamedTuple):
    """Per-dimension ownership record of one field's stacked layout.

    ``dd`` shards of local size ``n`` overlapping by ``ol`` (the FIELD's
    overlap: grid overlap plus staggering extra), stride ``s = n - ol``,
    covering ``size`` implicit-global cells, ``per``iodic or not."""
    dd: int
    n: int
    ol: int
    s: int
    per: bool
    size: int


def axis_geometry(dims, nxyz, overlaps, periods, n: int, d: int
                  ) -> AxisGeometry:
    """Geometry of dimension ``d`` for a field whose LOCAL size along it
    is ``n`` (staggered fields differ from ``nxyz[d]``; the difference
    joins the overlap, reference `ol(dim, A)` / `shared.jl:107`).

    Matches `gather_interior`'s shape rule exactly, including its
    single-shard non-periodic special case (``size == n``: the lone block
    is the global axis, overlap and all)."""
    if d >= 3 or (int(dims[d]) == 1 and not periods[d]):
        return AxisGeometry(1, n, 0, n, False, n)
    dd = int(dims[d])
    ol = int(overlaps[d]) + (n - int(nxyz[d]))
    s = n - ol
    per = bool(periods[d])
    size = dd * s if per else dd * s + ol
    return AxisGeometry(dd, n, ol, s, per, size)


def field_geometry(dims, nxyz, overlaps, periods, loc) -> tuple:
    """`axis_geometry` for every dimension of a field of LOCAL shape
    ``loc`` (any rank; dims beyond the third are trivially unsharded)."""
    return tuple(
        axis_geometry(dims, nxyz, overlaps, periods, int(loc[d]), d)
        for d in range(len(loc)))


def global_shape_of(geoms) -> tuple:
    """The field's implicit-global shape — `gather_interior`'s output
    shape for the same field."""
    return tuple(g.size for g in geoms)


def owner_maps(geom: AxisGeometry, g: np.ndarray):
    """For global cells ``g`` along one axis: the owning shard ``c_of[k]``
    and its block-local index ``i_of[k]`` (the `gather_interior`
    tie-breaking: later shards win the overlap)."""
    g = np.asarray(g, dtype=np.int64)
    if geom.per:
        c = g // geom.s
        i = g - c * geom.s + 1
    else:
        c = np.minimum(g // geom.s, geom.dd - 1)
        i = g - c * geom.s
    return c, i


def normalize_box(box, shape) -> tuple:
    """Validate a per-dimension ``(lo, hi)`` half-open box against the
    implicit-global ``shape``; ``None`` (whole box) and ``None`` entries
    (whole axis) are filled in. Returns a tuple of ``(lo, hi)`` pairs."""
    nd = len(shape)
    box = list(box) if box is not None else []
    if len(box) > nd:
        raise InvalidArgumentError(
            f"Box {tuple(box)} has more entries than the array has "
            f"dimensions ({nd}).")
    box = box + [None] * (nd - len(box))
    out = []
    for d in range(nd):
        if box[d] is None:
            out.append((0, int(shape[d])))
            continue
        lo, hi = (int(box[d][0]), int(box[d][1]))
        if not (0 <= lo < hi <= int(shape[d])):
            raise InvalidArgumentError(
                f"Box along dimension {d} must satisfy 0 <= lo < hi <= "
                f"{int(shape[d])}; got ({lo}, {hi}).")
        out.append((lo, hi))
    return tuple(out)
