"""Async sharded snapshots: the step loop never waits on disk.

`SnapshotWriter.submit` does the ONLY work that blocks the caller — a
device->host copy of this process's addressable shard blocks (O(shard),
the same per-process volume `save_checkpoint_sharded` writes) — then
hands the host buffers to a bounded background writer queue. Serialization,
fsync, checksums, and the directory-atomic commit all happen on the writer
thread, overlapped with the next compiled chunk. Two backpressure
policies when the queue is full:

- ``block`` (default): `submit` waits for a slot — bounded memory, the
  run throttles to disk speed (the checkpoint-grade choice);
- ``drop_oldest``: the oldest queued snapshot is discarded and counted
  (``igg_snapshots_total{result="dropped"}`` + a ``snapshot_drop`` flight
  event) — bounded memory AND bounded stall, for visualization outputs
  where freshness beats completeness. SINGLE-PROCESS only: each
  process's queue fills at its own disk speed, so independent drops
  would desynchronize the per-step shard sets across processes; the
  constructor rejects it when ``jax.process_count() > 1``.

On-disk layout: ``<root>/step_<NNNNNNNNNN>/`` in the PR-2 checkpoint
container format (`utils/blockio.py` — ``shards_p<i>.npz`` keyed by block
coordinates, ``meta.npz``, sha256 sidecars). Commit protocol per
snapshot: every process stages into the SAME ``.tmp-step…`` directory
(the staging name is derived from the step, so no cross-process broadcast
is needed — background threads must not enter jax collectives); a
process's sidecar appears only after its data file is fsync'ed, so
process 0's writer thread polls for the full sidecar set, writes
``meta.npz`` (the commit record), and renames the staging directory into
place. A crash at ANY point leaves either a committed, checksum-complete
snapshot or a stale ``.tmp-`` directory that `io.reader.list_snapshots`
never lists — never a committed-but-corrupt one. A RE-attempt of the
same step reuses the deterministic staging dir; each process unlinks its
own stale sidecar before rewriting, so a prior aborted attempt's
completion markers cannot satisfy the current commit poll mid-write.

`write_snapshot` is the synchronous single-snapshot core (what the writer
thread runs); it is also the honest baseline the async overhead is
benchmarked against (`bench_io.py`).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..parallel.topology import check_initialized, global_grid
from ..utils.blockio import (
    META_PREFIX, commit_staged_dir, grid_meta, shard_key, starts_of,
    validate_block_keys, write_npz_synced,
)
from ..utils.exceptions import InvalidArgumentError

__all__ = ["SnapshotWriter", "write_snapshot", "snapshot_dirname"]

_POLICIES = ("block", "drop_oldest")
STEP_PREFIX = "step_"


def snapshot_dirname(step: int) -> str:
    """Directory name of the snapshot at ``step`` (zero-padded so lexical
    order IS step order — `list_snapshots` relies on it)."""
    return f"{STEP_PREFIX}{int(step):010d}"


def _capture_shards(state: dict, fields=None) -> dict:
    """The device->host part of a snapshot: copy this process's
    addressable shard blocks (replica 0 only) plus everything the writer
    thread needs to serialize WITHOUT touching jax or the live grid
    (which may be re-initialized under it by an elastic restart)."""
    import jax

    from ..ops.alloc import device_put_g

    check_initialized()
    gg = global_grid()
    if not isinstance(state, dict) or not state:
        raise InvalidArgumentError(
            "snapshot expects a non-empty dict of name -> stacked array.")
    if fields is not None:
        missing = [f for f in fields if f not in state]
        if missing:
            raise InvalidArgumentError(
                f"snapshot fields {missing} are not in the state "
                f"(have {list(state)}).")
    names = list(state) if fields is None else list(fields)
    validate_block_keys(dict.fromkeys(names), "snapshot")
    blocks, shapes, dtypes = {}, {}, {}
    nbytes = 0
    for k in names:
        v = state[k]
        if not hasattr(v, "addressable_shards"):  # host array: shard first
            v = device_put_g(v)
        shapes[k] = tuple(int(s) for s in v.shape)
        dtypes[k] = str(v.dtype)
        for s in v.addressable_shards:
            if getattr(s, "replica_id", 0) != 0:
                continue
            block = np.asarray(s.data)
            blocks[shard_key(k, starts_of(s.index))] = block
            nbytes += block.nbytes
    return {
        "names": names, "shapes": shapes, "dtypes": dtypes,
        "blocks": blocks, "nbytes": nbytes,
        "grid_meta": grid_meta(gg),
        "pidx": int(jax.process_index()),
        "nprocs_files": int(jax.process_count()),
    }


def _write_captured(root: str, step: int, cap: dict, *,
                    commit_timeout: float = 120.0) -> tuple:
    """Serialize one captured snapshot into ``<root>/step_<n>`` with the
    staged-directory atomic commit. Pure host code — safe on a background
    thread. Returns ``(path, committed)``: process 0 commits (path is the
    final directory); other processes only stage their shard file — their
    snapshot exists only once process 0's commit lands."""
    final = os.path.join(root, snapshot_dirname(step))
    token = snapshot_dirname(step)  # deterministic: no cross-process bcast
    stage = f"{final}.tmp-{token}"
    os.makedirs(stage, exist_ok=True)

    payload = {f"{META_PREFIX}save_token": np.str_(token)}
    payload.update(cap["blocks"])
    shard_file = os.path.join(stage, f"shards_p{cap['pidx']}.npz")
    # A re-attempt of the same step (rollback replay, or a retry after an
    # aborted commit) reuses the deterministic stage dir: drop the OWN
    # stale sidecar before touching the data file, so process 0's poll
    # can never read a prior attempt's completion marker while this one
    # is mid-write.
    try:
        os.unlink(shard_file + ".sha256")
    except FileNotFoundError:
        pass
    write_npz_synced(shard_file, payload)
    if cap["pidx"] != 0:
        return stage, False

    # Process 0 commits: wait for every process's sidecar (a sidecar is
    # written only after its data file is fsync'ed — presence == complete),
    # then write meta.npz (the commit record) and rename the set into
    # place. Polling replaces the checkpoint path's barrier: a writer
    # thread must never enter a jax collective.
    deadline = time.monotonic() + commit_timeout
    sidecars = [os.path.join(stage, f"shards_p{i}.npz.sha256")
                for i in range(cap["nprocs_files"])]
    while not all(os.path.exists(p) for p in sidecars):
        if time.monotonic() > deadline:
            raise InvalidArgumentError(
                f"Snapshot commit timed out after {commit_timeout}s: "
                f"missing {[p for p in sidecars if not os.path.exists(p)]} "
                f"in {stage} — a peer process stalled or died; the staged "
                "directory is left for inspection (it is never listed as "
                "a snapshot).")
        time.sleep(0.01)

    meta = dict(cap["grid_meta"])
    meta[f"{META_PREFIX}names"] = np.asarray(cap["names"])
    meta[f"{META_PREFIX}save_token"] = np.str_(token)
    meta[f"{META_PREFIX}nprocs_files"] = np.int64(cap["nprocs_files"])
    meta[f"{META_PREFIX}checksums"] = np.str_("sha256")
    meta[f"{META_PREFIX}step"] = np.int64(step)
    meta[f"{META_PREFIX}kind"] = np.str_("snapshot")
    for k in cap["names"]:
        meta[f"{META_PREFIX}shape__{k}"] = np.asarray(cap["shapes"][k],
                                                      dtype=np.int64)
        meta[f"{META_PREFIX}dtype__{k}"] = np.str_(cap["dtypes"][k])
    write_npz_synced(os.path.join(stage, "meta.npz"), meta)
    # re-snapshot of the same step (rollback replay): the old committed
    # dir is replaced whole (`blockio.commit_staged_dir`, shared with the
    # checkpoint save)
    commit_staged_dir(stage, final, token)
    return final, True


def write_snapshot(root, state: dict, *, step: int, fields=None,
                   commit_timeout: float = 120.0) -> str:
    """Synchronously write one snapshot of ``state`` under ``root``
    (directory ``<root>/step_<n>``). The synchronous core of
    `SnapshotWriter` — same container, same commit protocol, no queue.
    Collective only in the filesystem sense: in multi-host runs every
    process must call it for the commit to complete. Returns the
    snapshot path."""
    os.makedirs(str(root), exist_ok=True)
    cap = _capture_shards(state, fields)
    _write_captured(str(root), int(step), cap,
                    commit_timeout=commit_timeout)
    # the FINAL path on every process — non-root processes only staged,
    # but the committed directory name is deterministic
    return os.path.join(str(root), snapshot_dirname(int(step)))


class SnapshotWriter:
    """Bounded-queue async snapshot writer (module docstring has the
    full protocol). One writer owns one ``root`` directory; `submit`
    is called from the driver loop, everything else happens on a daemon
    writer thread. Thread-safe; `close` (or context-manager exit) drains
    the queue."""

    def __init__(self, root, *, queue_depth: int = 2,
                 policy: str = "block", fields=None,
                 commit_timeout: float = 120.0):
        import jax

        if policy not in _POLICIES:
            raise InvalidArgumentError(
                f"SnapshotWriter policy must be one of {_POLICIES}; "
                f"got {policy!r}.")
        if policy == "drop_oldest" and jax.process_count() > 1:
            # each process's queue fills at its own disk speed, so drop
            # decisions would desynchronize the per-step shard sets and
            # stall every commit against its timeout — only the lockstep
            # `block` policy is sound across processes
            raise InvalidArgumentError(
                "SnapshotWriter policy='drop_oldest' is single-process "
                "only: multi-host runs must use policy='block' so every "
                "process stages the same snapshot sequence.")
        if int(queue_depth) < 1:
            raise InvalidArgumentError(
                f"SnapshotWriter queue_depth must be >= 1; got "
                f"{queue_depth}.")
        self.root = str(root)
        self.policy = policy
        self.queue_depth = int(queue_depth)
        self.fields = None if fields is None else tuple(fields)
        self.commit_timeout = float(commit_timeout)
        os.makedirs(self.root, exist_ok=True)
        self._cv = threading.Condition()
        self._queue: list = []     # [(step, captured)] oldest first
        self._busy = False         # writer thread mid-write
        self._closed = False
        self._stats = {"submitted": 0, "written": 0, "staged": 0,
                       "dropped": 0, "errors": 0, "bytes": 0}
        # the writer thread's events belong to THE RUN THAT OWNS THIS
        # WRITER: capture its recorder now and pin the thread to it —
        # commits land asynchronously, when the process-wide current
        # recorder may already be another tenant's (multi-run scheduler)
        # or none at all (between slices)
        from ..telemetry.recorder import flight_recorder

        self._recorder = flight_recorder()
        self._thread = threading.Thread(
            target=self._run, name="igg-snapshot-writer", daemon=True)
        self._thread.start()

    # -- producer side ----------------------------------------------------

    def submit(self, state: dict, step: int) -> bool:
        """Snapshot ``state`` at ``step``: device->host copy now, disk on
        the writer thread. Returns False iff the job displaced the oldest
        queued snapshot (``drop_oldest`` under a full queue)."""
        from ..telemetry.hooks import note_io_queue, observe_snapshot

        cap = _capture_shards(state, self.fields)
        dropped = None
        with self._cv:
            if self._closed:
                raise InvalidArgumentError(
                    "SnapshotWriter is closed; create a new one.")
            while (self.policy == "block"
                   and len(self._queue) >= self.queue_depth
                   and not self._closed):
                self._cv.wait()
            if self._closed:
                raise InvalidArgumentError(
                    "SnapshotWriter was closed while waiting for a queue "
                    "slot; the snapshot was not submitted.")
            if len(self._queue) >= self.queue_depth:  # drop_oldest
                dropped = self._queue.pop(0)
                self._stats["dropped"] += 1
            self._queue.append((int(step), cap))
            self._stats["submitted"] += 1
            depth = len(self._queue)
            self._cv.notify_all()
        note_io_queue(depth)
        if dropped is not None:
            observe_snapshot("dropped", step=dropped[0],
                             path=os.path.join(
                                 self.root, snapshot_dirname(dropped[0])),
                             queue_depth=depth)
        return dropped is None

    def flush(self, timeout: float | None = None) -> bool:
        """Wait until every submitted snapshot is on disk (or dropped).
        Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._queue or self._busy:
                rem = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                if rem == 0.0:
                    return False
                self._cv.wait(timeout=rem)
        return True

    def close(self, timeout: float | None = None) -> bool:
        """Drain and stop the writer thread (idempotent). Returns the
        `flush` verdict."""
        ok = self.flush(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=5.0)
        return ok

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def stats(self) -> dict:
        """Counters snapshot: submitted / written (COMMITTED — process 0
        only in multi-host runs) / staged (non-root shard files handed to
        process 0's commit) / dropped / errors / bytes (committed payload
        bytes, this process's blocks)."""
        with self._cv:
            return dict(self._stats)

    # -- writer thread -----------------------------------------------------

    def _run(self) -> None:
        from ..telemetry.hooks import note_io_queue, observe_snapshot
        from ..telemetry.recorder import bind_thread_recorder, record_event

        if self._recorder is not None:
            bind_thread_recorder(self._recorder)
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue:  # closed and drained
                    return
                step, cap = self._queue.pop(0)
                self._busy = True
                depth = len(self._queue)
                self._cv.notify_all()
            note_io_queue(depth)
            t0 = time.monotonic()
            try:
                path, committed = _write_captured(
                    self.root, step, cap,
                    commit_timeout=self.commit_timeout)
            except Exception as e:  # never kill the run from the writer
                with self._cv:
                    self._stats["errors"] += 1
                    self._busy = False
                    self._cv.notify_all()
                observe_snapshot(
                    "error", step=step,
                    path=os.path.join(self.root, snapshot_dirname(step)),
                    error=f"{e.__class__.__name__}: {e}")
                continue
            dur = time.monotonic() - t0
            # only a COMMITTED snapshot counts as written: a non-root
            # process merely staged its shard file — claiming "written"
            # here would over-count whenever process 0's commit later
            # fails, telling operators a missing snapshot exists
            slot = "written" if committed else "staged"
            with self._cv:
                self._stats[slot] += 1
                if committed:
                    self._stats["bytes"] += cap["nbytes"]
                self._busy = False
                self._cv.notify_all()
            if committed:
                observe_snapshot("written", dur_s=dur, step=step,
                                 path=path, nbytes=cap["nbytes"],
                                 queue_depth=depth)
            else:
                record_event("snapshot_stage", step=step, dur_s=dur,
                             nbytes=cap["nbytes"])
