"""Streaming ops endpoints — the live observability plane's network
surface (serving tier + ISSUE 18).

`ObservePlane` wraps one `telemetry.LiveAggregate` (the incremental
flight tailer + derived-signal windows) behind two `MetricsServer`
routes, mounted by `JobApiServer` (``observe=True``, the default) or
served standalone by `ObserveServer`:

- ``GET /v1/observe`` — one JSON snapshot of the live-derived signals:
  per-job rolling step-time quantiles + robust z, deadline slack, guard
  trips, snapshot/wire rates; persistent-straggler attribution;
  queue pressure; active + recent alerts (tailed from the scheduler
  journal, merged with this plane's own observer-side engine when one
  is configured). The record carries the current ``cursor`` — the
  ``live_seq`` high-water mark to resume the event stream from.
- ``GET /v1/events?since=<seq>`` — the merged, clock-aligned live event
  feed as chunked NDJSON: every line one flight event (``live_seq``
  stamped), heartbeat lines (``{"kind": "heartbeat", "cursor": n,
  "server_ts": unix_s, "last_seq": m}`` — ``last_seq`` ahead of the
  client's cursor means a stalled tail, not a quiet mesh)
  while idle so consumers distinguish quiet from dead, bounded by
  ``timeout_s`` per request. RESUMABLE: each response ends with a final
  heartbeat carrying the cursor; pass it back as ``since=`` and only
  newer events stream. Query knobs: ``since`` (exclusive ``live_seq``
  cursor; omit for the whole buffer), ``timeout_s`` (stream duration,
  default 10, capped), ``heartbeat_s`` (idle keep-alive cadence,
  default 2), ``max_events`` (end early after N events — the polling
  CLI uses 0 = unlimited).

The plane POLLS ITS TAILER ON DEMAND — each request drains whatever the
jobs appended since the last one; an idle plane costs nothing. An
optional OBSERVER-SIDE `AlertEngine` (``rules=``/``sinks=``) evaluates
over the tailed snapshot after every poll that merged new events —
off-process alerting with the same rule grammar as the scheduler's
in-process engine, including `ControlFileSink` (an observer can file a
cancel the scheduler consumes at its next slice boundary). Its
transitions are NOT journaled (the scheduler's journal has exactly one
writer); they surface in ``/v1/observe`` tagged ``source:
"observer"``.

SECURITY: inherits `MetricsServer`'s loopback-by-default bind, and the
``/v1`` surface can require a bearer token: pass ``api_token=``
(defaults from ``IGG_API_TOKEN``) and every request must carry
``Authorization: Bearer <token>`` (constant-time compare; 401
otherwise) — ``/metrics`` + ``/healthz`` stay open (docs/api.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from collections import deque

from ..service.backend import QueueBackend
from ..telemetry.live import AlertEngine, LiveAggregate
from ..telemetry.server import MetricsServer, resolve_api_token
from ..utils.exceptions import InvalidArgumentError

__all__ = ["ObservePlane", "ObserveServer"]

_MAX_STREAM_S = 600.0   # one /v1/events request never outlives this
_POLL_SLEEP_S = 0.05    # tail cadence while a stream is idle


class ObservePlane:
    """The routes + the tailer (see module docstring). ``source`` is a
    flight directory (or one JSONL, or a list); ``backend`` adds queue
    pressure to snapshots; ``rules``/``sinks`` configure the optional
    observer-side engine (``rules=True`` = the default pack). Thread
    safe: the `MetricsServer` handles requests concurrently, the plane
    serializes tailer access."""

    def __init__(self, source, *, backend: QueueBackend | None = None,
                 rules=None, sinks=(), window: int = 16):
        if backend is not None and not isinstance(backend, QueueBackend):
            raise InvalidArgumentError(
                f"backend must be a service.QueueBackend; got "
                f"{type(backend).__name__}.")
        self.live = LiveAggregate(source, window=window, backend=backend)
        self.engine = None
        if rules or sinks:
            self.engine = AlertEngine(
                None if rules in (True, "default") else list(rules or ()),
                sinks=sinks, journal=None)
        self._transitions: deque = deque(maxlen=64)
        self._lock = threading.Lock()

    # -- polling -----------------------------------------------------------

    def poll(self) -> list:
        """Drain the tail once (thread safe); evaluates the observer
        engine when new events merged. Returns the new events."""
        with self._lock:
            return self._poll_locked()

    def _poll_locked(self) -> list:
        evs = self.live.poll()
        if evs and self.engine is not None:
            for tr in self.engine.evaluate(self.live.snapshot()):
                self._transitions.append(dict(tr, source="observer"))
        return evs

    def snapshot(self) -> dict:
        """Poll, then return the derived-signal record (the
        ``/v1/observe`` body)."""
        with self._lock:
            self._poll_locked()
            snap = self.live.snapshot()
            if self.engine is not None:
                snap["alerts"]["active"] = list(
                    snap["alerts"]["active"]) + [
                    dict(a, source="observer")
                    for a in self.engine.active()]
                snap["alerts"]["recent"] = list(
                    snap["alerts"]["recent"]) + list(self._transitions)
            return snap

    # -- routing -----------------------------------------------------------

    def routes(self, method: str, path: str, query: str, body: bytes):
        """The `MetricsServer` ``routes=`` callable (chainable: returns
        None for paths it does not own)."""
        if method != "GET":
            return None
        if path == "/v1/observe":
            return 200, json.dumps(self.snapshot(),
                                   default=str).encode(), \
                "application/json"
        if path == "/v1/events":
            try:
                params = self._stream_params(query)
            except (ValueError, TypeError) as e:
                return 400, json.dumps(
                    {"error": f"bad /v1/events query: {e}"}).encode(), \
                    "application/json"
            return 200, self._event_stream(**params), \
                "application/x-ndjson"
        return None

    @staticmethod
    def _stream_params(query: str) -> dict:
        q = urllib.parse.parse_qs(query or "")

        def one(key, cast, default):
            return cast(q[key][0]) if key in q else default

        timeout_s = min(max(0.0, one("timeout_s", float, 10.0)),
                        _MAX_STREAM_S)
        return {"since": one("since", int, None),
                "timeout_s": timeout_s,
                "heartbeat_s": max(0.1, one("heartbeat_s", float, 2.0)),
                "max_events": max(0, one("max_events", int, 0))}

    def _event_stream(self, *, since, timeout_s, heartbeat_s,
                      max_events):
        """The chunked-NDJSON generator behind ``GET /v1/events``."""
        deadline = time.monotonic() + timeout_s
        cursor = since
        last_emit = time.monotonic()
        sent = 0
        while True:
            with self._lock:
                self._poll_locked()
                evs, cursor = self.live.events_since(cursor)
            for e in evs:
                yield json.dumps(e, default=str).encode() + b"\n"
                sent += 1
                last_emit = time.monotonic()
                if max_events and sent >= max_events:
                    # resume from the last event actually SENT, not the
                    # batch high-water mark — the cut-off tail must
                    # stream again on the next request
                    yield self._hb(e.get("live_seq", cursor), done=True)
                    return
            now = time.monotonic()
            if now >= deadline:
                # the final heartbeat carries the resume cursor
                yield self._hb(cursor, done=True)
                return
            if not evs and now - last_emit >= heartbeat_s:
                yield self._hb(cursor)
                last_emit = now
            time.sleep(min(_POLL_SLEEP_S, max(0.0, deadline - now)))

    def _hb(self, cursor, done: bool = False) -> bytes:
        # server_ts + last_seq let a stream client tell "quiet mesh"
        # (last_seq == its cursor, server_ts advancing) from "stalled
        # tail" (last_seq ahead of what it received) — tools watch
        # surfaces the same lag
        rec = {"kind": "heartbeat", "cursor": cursor,
               "server_ts": time.time(), "last_seq": self.live.cursor}
        if done:
            rec["done"] = True
        return json.dumps(rec).encode() + b"\n"


class ObserveServer:
    """Standalone streaming ops endpoint over one flight directory —
    `ObservePlane` on its own `MetricsServer` (``/metrics`` +
    ``/healthz`` come free), for deployments that want the live plane
    without the job API. ``port=0`` binds an ephemeral port — read
    ``.port``. ``api_token`` requires ``Authorization: Bearer <token>``
    on the ``/v1`` routes (module docstring; defaults from
    ``IGG_API_TOKEN``; ``False`` = explicitly unauthenticated). Context
    manager; `close()` stops the server only (the flight files and any
    live scheduler are untouched)."""

    def __init__(self, flight_dir, port: int = 0, *,
                 host: str = "127.0.0.1",
                 backend: QueueBackend | None = None, rules=None,
                 sinks=(), window: int = 16, registry=None,
                 api_token=None):
        self.flight_dir = os.fspath(flight_dir)
        self.plane = ObservePlane(self.flight_dir, backend=backend,
                                  rules=rules, sinks=sinks,
                                  window=window)
        self._server = MetricsServer(
            port, host=host, registry=registry,
            routes=self.plane.routes,
            auth_token=resolve_api_token(api_token))
        self.host = self._server.host
        self.port = self._server.port

    def close(self) -> None:
        self._server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
