"""Serving tier: the framework's networked front doors.

Two stateless HTTP services turn a scheduler flight directory and a
snapshot root into network surfaces, so tenants and dashboards need
neither a filesystem mount nor an accelerator runtime:

- `JobApiServer` (`serve.api`) — the WRITE side: versioned JSON job
  API over one flight directory. Submissions become queue-backend
  records a live `service.MeshScheduler` claims; cancel/resize/drain
  become the exact control files ``tools jobs`` writes; status is
  re-derived from the journal (`service_report`'s source).
- `SnapshotQueryServer` (`serve.query`) — the READ side: O(box)
  sub-box reads of any committed snapshot, streamed as ``.npy`` bytes,
  answered through a bounded `BlockCache` LRU (`serve.cache`) of
  checksum-verified decoded blocks. Replicas never touch the mesh.
- `ObservePlane` / `ObserveServer` (`serve.observe`) — the LIVE side:
  ``GET /v1/observe`` (derived-signal snapshot: rolling step quantiles,
  deadline slack, stragglers, queue pressure, active alerts) and
  ``GET /v1/events?since=<seq>`` (the merged clock-aligned flight feed
  as resumable chunked NDJSON), tail-following the flight directory
  incrementally (`telemetry.LiveAggregate`). Mounted on `JobApiServer`
  by default; `ObserveServer` serves it standalone.

All ride on `telemetry.MetricsServer` (``routes=``), so every
endpoint also serves ``/metrics`` + ``/healthz`` and binds loopback by
default. See docs/serving.md for the API reference and deployment
notes.
"""

from .api import JobApiServer
from .cache import BlockCache, CachedSnapshot
from .observe import ObservePlane, ObserveServer
from .query import SnapshotQueryServer

__all__ = [
    "JobApiServer", "SnapshotQueryServer", "BlockCache", "CachedSnapshot",
    "ObservePlane", "ObserveServer",
]
