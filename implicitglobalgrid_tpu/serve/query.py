"""`SnapshotQueryServer` — the read-side front door (serving tier leg b).

A stateless HTTP service over one COMMITTED snapshot root: clients read
any sub-box of the implicit global grid in O(box) — the paper's
analysis-side contract — without a filesystem mount, an accelerator
runtime, or any contact with the mesh. N replicas pointed at one root
scale reads horizontally; the writer's atomic staged-rename commit plus
the reader's typed refusal of staging dirs mean a replica can poll a
LIVE root and never serve a torn read.

Routes (all GET; rides on `telemetry.MetricsServer`, so ``/metrics`` +
``/healthz`` come free):

- ``/v1/snapshots`` — committed snapshots (step, path, fields, global
  shapes) + block-cache stats.
- ``/v1/snapshots/<step>/<field>?box=i0:i1,j0:j1,k0:k1`` — the sub-box,
  streamed as ``.npy`` bytes (``np.load(BytesIO(body))`` on the client)
  with the geometry echoed in ``X-IGG-*`` headers. No ``box`` = the
  whole field; a missing axis spec (``i0:i1,,``) = that whole axis.
- ``/v1/snapshots/<step>/<field>?point=i,j,k`` — one cell, as JSON.

Answers are assembled by the PR-4 lazy reader (`io.Snapshot`,
bit-identical to ``gather_interior``) through a bounded LRU
`BlockCache` (`serve.cache`): hot blocks are checksum-verified and
decoded once ACROSS clients. Errors map to transport codes: bad
request shapes 400, unknown step/field 404, a half-committed or
corrupt container 503 (retry after the writer commits).

Status codes aside, the server never touches the mesh — deploy it on
any host that can read the snapshot root (see docs/serving.md for
deployment + cache sizing). The ``/v1`` routes can require a bearer
token: pass ``api_token=`` (defaults from ``IGG_API_TOKEN``) and every
request must carry ``Authorization: Bearer <token>`` (constant-time
compare; 401 otherwise) — ``/metrics`` + ``/healthz`` stay open.
"""

from __future__ import annotations

import io
import json
import os

import numpy as np

from ..io.reader import list_snapshots
from ..telemetry.server import MetricsServer, resolve_api_token
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError
from .cache import BlockCache, CachedSnapshot

__all__ = ["SnapshotQueryServer"]


def _parse_box(text: str, gshape: tuple):
    """``i0:i1,j0:j1,...`` -> per-dim (lo, hi) tuple (None entries for
    empty axis specs = whole axis). Validation beyond shape arity is
    `io.layout.normalize_box`'s job."""
    parts = text.split(",")
    if len(parts) != len(gshape):
        raise InvalidArgumentError(
            f"box={text!r} has {len(parts)} axis range(s); the field is "
            f"{len(gshape)}-D (global shape {tuple(gshape)}).")
    box = []
    for part in parts:
        part = part.strip()
        if not part:
            box.append(None)
            continue
        lo, sep, hi = part.partition(":")
        if not sep:
            raise InvalidArgumentError(
                f"box axis spec {part!r} is not 'lo:hi' (half-open "
                "global range).")
        try:
            box.append((int(lo), int(hi)))
        except ValueError as e:
            raise InvalidArgumentError(
                f"box axis spec {part!r} is not integer 'lo:hi'.") from e
    return tuple(box)


class SnapshotQueryServer:
    """Serve O(box) reads of the committed snapshots under ``root``
    (see module docstring). ``port=0`` binds an ephemeral port — read
    ``.port``. ``cache_bytes`` bounds the shared block LRU (sizing: a
    few times the hot fields' per-block bytes; stats on
    ``/v1/snapshots``). ``api_token`` requires ``Authorization: Bearer
    <token>`` on the ``/v1`` routes (defaults from ``IGG_API_TOKEN``;
    ``False`` = explicitly unauthenticated). Context manager; `close()`
    stops the server."""

    def __init__(self, root, port: int = 0, *, host: str = "127.0.0.1",
                 cache_bytes: int = 256 << 20, registry=None,
                 api_token=None):
        self.root = os.fspath(root)
        if not os.path.isdir(self.root):
            raise InvalidArgumentError(
                f"Snapshot root not found: {self.root}")
        self.cache = BlockCache(cache_bytes)
        self._server = MetricsServer(
            port, host=host, registry=registry, routes=self._route,
            auth_token=resolve_api_token(api_token))
        self.host = self._server.host
        self.port = self._server.port

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._server.close()
        self.cache.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- routing -----------------------------------------------------------

    @staticmethod
    def _json(code: int, rec: dict):
        return code, json.dumps(rec, default=str).encode(), \
            "application/json"

    def _route(self, method: str, path: str, query: str, body: bytes):
        if method != "GET":
            return self._json(405, {"error": f"{method} not allowed "
                                             "(read-side service)"})
        if path in ("/v1/snapshots", "/v1/snapshots/"):
            return self._list()
        prefix = "/v1/snapshots/"
        if not path.startswith(prefix):
            return None
        rest = path[len(prefix):].split("/")
        if len(rest) != 2 or not rest[0] or not rest[1]:
            return self._json(
                404, {"error": "expected /v1/snapshots/<step>/<field>"})
        try:
            return self._read(rest[0], rest[1], query)
        except InvalidArgumentError as e:
            return self._json(400, {"error": str(e)})
        except IncoherentArgumentError as e:
            # half-committed / corrupt container: the writer's problem,
            # not the client's — retryable after the next commit
            return self._json(503, {"error": str(e)})

    def _list(self):
        snaps = []
        for step, path in list_snapshots(self.root):
            rec = {"step": step, "path": path}
            try:
                snap = CachedSnapshot(path, self.cache)
                rec["fields"] = snap.names
                rec["global_shapes"] = {
                    n: list(snap.global_shape(n)) for n in snap.names}
            except (InvalidArgumentError, IncoherentArgumentError) as e:
                # a torn/corrupt dir degrades ITS entry, not the listing
                rec["error"] = str(e)
            snaps.append(rec)
        return self._json(200, {"root": self.root, "snapshots": snaps,
                                "cache": self.cache.stats()})

    def _read(self, step_s: str, field: str, query: str):
        from urllib.parse import parse_qs

        try:
            step = int(step_s)
        except ValueError:
            return self._json(404, {"error": f"step {step_s!r} is not "
                                             "an integer"})
        path = dict(list_snapshots(self.root)).get(step)
        if path is None:
            return self._json(
                404, {"error": f"no committed snapshot for step {step} "
                               f"under {self.root}"})
        snap = CachedSnapshot(path, self.cache)
        if field not in snap.names:
            return self._json(
                404, {"error": f"snapshot step {step} has no field "
                               f"{field!r} (have {snap.names})"})
        q = parse_qs(query, keep_blank_values=True)
        if "point" in q and "box" in q:
            raise InvalidArgumentError(
                "pass either ?box= or ?point=, not both.")
        hits0 = self.cache.hits
        if "point" in q:
            try:
                index = tuple(int(x) for x in q["point"][0].split(","))
            except ValueError as e:
                raise InvalidArgumentError(
                    f"point={q['point'][0]!r} is not a comma-separated "
                    "integer index.") from e
            value = snap.read_point(field, index)
            return self._json(200, {"step": step, "field": field,
                                    "index": list(index),
                                    "value": float(value),
                                    "dtype": str(snap.dtype(field)),
                                    "cache_hit": self.cache.hits > hits0})
        box = None
        if "box" in q:
            box = _parse_box(q["box"][0], snap.global_shape(field))
        arr = snap.read_global(field, box)
        buf = io.BytesIO()
        np.save(buf, arr)
        payload = buf.getvalue()
        headers = {
            "X-IGG-Step": step,
            "X-IGG-Field": field,
            "X-IGG-Shape": ",".join(str(s) for s in arr.shape),
            "X-IGG-Dtype": str(arr.dtype),
            "X-IGG-Box": ";".join(
                "all" if b is None else f"{b[0]}:{b[1]}"
                for b in (box if box is not None
                          else (None,) * arr.ndim)),
            # block-level attribution for THIS request: a warm re-read
            # of the same box answers entirely from the LRU
            "X-IGG-Cache-Hits": self.cache.hits - hits0,
        }
        return 200, payload, "application/octet-stream", headers
