"""Bounded LRU block cache for the read-side query service.

The snapshot container keys every shard block by BLOCK COORDINATES
(`utils.blockio.shard_key`), so a block is immutable once its directory
commits — the perfect cache unit. `BlockCache` holds decoded blocks
under a byte budget (thread-safe LRU: the query server answers
concurrent clients from `ThreadingHTTPServer` threads);
`CachedSnapshot` plugs it into the reader's `Snapshot._fetch_block`
hook, so a hot block is checksum-verified and decoded ONCE across
requests instead of once per read. Cache entries key on (snapshot
path, save token, block key): a re-committed snapshot at the same path
carries a new token and can never be answered from the old set's
blocks.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from ..io.reader import Snapshot
from ..utils.exceptions import InvalidArgumentError

__all__ = ["BlockCache", "CachedSnapshot"]


class BlockCache:
    """Thread-safe bounded-bytes LRU over decoded snapshot blocks."""

    def __init__(self, max_bytes: int = 256 << 20):
        if int(max_bytes) <= 0:
            raise InvalidArgumentError(
                f"BlockCache.max_bytes must be positive; got {max_bytes}.")
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._blocks: OrderedDict = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        """The cached block (freshened to most-recent) or None."""
        with self._lock:
            block = self._blocks.get(key)
            if block is None:
                self.misses += 1
                return None
            self._blocks.move_to_end(key)
            self.hits += 1
            return block

    def put(self, key, block) -> None:
        """Insert one decoded block, evicting least-recently-used
        entries past the byte budget. A block larger than the whole
        budget is served but never cached."""
        nbytes = int(block.nbytes)
        with self._lock:
            if nbytes > self.max_bytes:
                return
            old = self._blocks.pop(key, None)
            if old is not None:
                self.bytes -= int(old.nbytes)
            self._blocks[key] = block
            self.bytes += nbytes
            while self.bytes > self.max_bytes:
                _, dropped = self._blocks.popitem(last=False)
                self.bytes -= int(dropped.nbytes)
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._blocks.clear()
            self.bytes = 0

    def stats(self) -> dict:
        """JSON-able counters (the query service's /v1/snapshots echo —
        cache sizing feedback for the operator)."""
        with self._lock:
            return {"entries": len(self._blocks), "bytes": self.bytes,
                    "max_bytes": self.max_bytes, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}


class CachedSnapshot(Snapshot):
    """A `Snapshot` whose block fetches go through a shared
    `BlockCache`. Fills are sha256-verified exactly like the base
    reader's (the cache sits BEHIND `block_scanner`'s verify-on-first-
    open); reads stay bit-identical to the uncached path."""

    def __init__(self, dirpath, cache: BlockCache):
        if not isinstance(cache, BlockCache):
            raise InvalidArgumentError(
                f"CachedSnapshot needs a BlockCache; got "
                f"{type(cache).__name__}.")
        super().__init__(dirpath)
        self._cache = cache

    def _fetch_block(self, name: str, key: str, find_block):
        ck = (self.path, self.token, key)
        block = self._cache.get(ck)
        if block is None:
            block = np.asarray(find_block(key))
            self._cache.put(ck, block)
        return block
