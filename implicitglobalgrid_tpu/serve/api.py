"""`JobApiServer` — the networked job front door (serving tier leg a).

A versioned JSON HTTP surface over one scheduler flight directory. It
owns NO scheduler state: submissions become queue-backend records
(`service.DirectoryBackend` — the atomic-rename claim protocol a live
`MeshScheduler` polls), control verbs become the exact control files
``tools jobs cancel|resize|drain`` writes, and status is reconstructed
from the journal — the same source `service_report` reads. The API
writes exactly what the CLI writes, so a live scheduler needs zero new
hooks and the two can never diverge.

Routes (rides on `telemetry.MetricsServer`; ``/metrics`` + ``/healthz``
come free):

- ``POST /v1/jobs`` — submit: the ``tools jobs submit`` queue-JSON
  (``{"jobs": [{name, model, nt, grid?, dtype?, priority?, deadline_s?,
  perturb?, run?}]}`` — ``run`` takes every `RunSpec` knob incl.
  ``tuned``), or one bare job record. Every record is validated
  through `service.jobspec_from_json` BEFORE any is enqueued (400 on
  the first bad one; 409 on a name the service already knows), then
  all are enqueued: 202.
- ``GET /v1/jobs`` / ``GET /v1/jobs/<name>`` — journal-derived state
  and progress, merged with not-yet-claimed queue records (state
  ``"pending"``).
- ``POST /v1/jobs/<name>/cancel`` — a still-pending record is atomically
  discarded before any scheduler claims it; otherwise the control file
  (404 unknown name, 409 already terminal).
- ``POST /v1/jobs/<name>/resize`` — body ``{"new_dims": [dx,dy,dz],
  "via"?: "auto"|"device"|"checkpoint"}`` -> the resize control file.
- ``POST /v1/drain`` — the global drain request.
- ``GET /v1/observe`` / ``GET /v1/events?since=<seq>`` — the live
  observability plane (`serve.observe.ObservePlane`, mounted over the
  same flight directory unless ``observe=False``): the derived-signal
  snapshot and the resumable chunked-NDJSON event stream.

TRACING: every submit / cancel / resize accepts a W3C ``traceparent``
request header (or mints a fresh trace), echoes it on the response, and
stamps it into the queue record / control payload — the claiming
scheduler threads it through every journal event and flight span of the
job (`telemetry.tracectx`; export with ``tools trace --otlp``).

SECURITY: inherits `MetricsServer`'s loopback-by-default bind, and the
whole ``/v1`` surface — mutating AND read routes — can require a bearer
token: pass ``api_token=`` (defaults from the ``IGG_API_TOKEN``
environment variable) and every request must carry ``Authorization:
Bearer <token>`` (constant-time compare; 401 otherwise). ``/metrics``
and ``/healthz`` stay open for scrapers and supervisors (docs/api.md).
"""

from __future__ import annotations

import json
import os

from ..service.backend import DirectoryBackend, QueueBackend
from ..service.job import jobspec_from_json
from ..service.report import is_service_dir, service_report
from ..telemetry.server import MetricsServer, resolve_api_token
from ..telemetry.tracectx import TraceContext
from ..utils.exceptions import InvalidArgumentError

__all__ = ["JobApiServer"]

_TERMINAL_STATES = ("done", "failed", "cancelled", "rejected")


class JobApiServer:
    """Serve the job API over one scheduler ``flight_dir`` (see module
    docstring). ``backend`` defaults to the `DirectoryBackend` over
    that directory — pass the shared backend instance when schedulers
    use a custom one. ``port=0`` binds an ephemeral port — read
    ``.port``. ``api_token`` requires ``Authorization: Bearer <token>``
    on every ``/v1`` route (module docstring; defaults from
    ``IGG_API_TOKEN``; pass ``api_token=False`` to force an
    unauthenticated server even with the variable set). Context
    manager; `close()` stops the server (the queue and any live
    scheduler are untouched — the API is stateless)."""

    def __init__(self, flight_dir, port: int = 0, *,
                 host: str = "127.0.0.1", backend: QueueBackend | None = None,
                 registry=None, observe: bool = True,
                 observe_window: int = 16, api_token=None):
        self.flight_dir = os.fspath(flight_dir)
        os.makedirs(self.flight_dir, exist_ok=True)
        if backend is not None and not isinstance(backend, QueueBackend):
            raise InvalidArgumentError(
                f"backend must be a service.QueueBackend; got "
                f"{type(backend).__name__}.")
        self.backend = (backend if backend is not None
                        else DirectoryBackend(self.flight_dir))
        # the live plane rides the same server: /v1/observe (derived
        # signals + alerts) and /v1/events (streaming feed) over the
        # same flight directory the job routes reconstruct state from
        self.observe = None
        if observe:
            from .observe import ObservePlane

            self.observe = ObservePlane(self.flight_dir,
                                        backend=self.backend,
                                        window=observe_window)
        self._server = MetricsServer(port, host=host, registry=registry,
                                     routes=self._route,
                                     auth_token=resolve_api_token(api_token))
        self.host = self._server.host
        self.port = self._server.port

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._server.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- journal view ------------------------------------------------------

    def _journal_jobs(self) -> dict:
        if not is_service_dir(self.flight_dir):
            return {}
        return service_report(self.flight_dir, include_jobs=False)["jobs"]

    def _jobs_view(self) -> dict:
        jobs = self._journal_jobs()
        for name in self.backend.pending():
            if name not in jobs:
                # enqueued, no scheduler has claimed it yet
                jobs[name] = {"name": name, "state": "pending"}
        return jobs

    # -- routing -----------------------------------------------------------

    @staticmethod
    def _json(code: int, rec: dict, headers: dict | None = None):
        resp = (code, json.dumps(rec, default=str).encode(),
                "application/json")
        return resp + (headers,) if headers else resp

    @staticmethod
    def _trace_ctx(headers) -> TraceContext:
        """The request's trace context: a CHILD of the caller's
        ``traceparent`` span, or a fresh root when the header is absent.
        A malformed header RESTARTS the trace (the W3C-recommended
        degradation) rather than failing the request."""
        tp = headers.get("traceparent") if headers is not None else None
        if tp:
            try:
                return TraceContext.parse(str(tp)).child()
            except InvalidArgumentError:
                pass
        return TraceContext.new()

    def _route(self, method: str, path: str, query: str, body: bytes,
               headers=None):
        if self.observe is not None:
            resp = self.observe.routes(method, path, query, body)
            if resp is not None:
                return resp
        if path == "/v1/drain" and method == "POST":
            self.backend.control("drain")
            return self._json(202, {"requested": "drain"})
        if path in ("/v1/jobs", "/v1/jobs/"):
            if method == "POST":
                return self._submit(body, self._trace_ctx(headers))
            return self._json(200, {"jobs": self._jobs_view()})
        prefix = "/v1/jobs/"
        if not path.startswith(prefix):
            return None
        rest = path[len(prefix):].split("/")
        if method == "GET" and len(rest) == 1 and rest[0]:
            job = self._jobs_view().get(rest[0])
            if job is None:
                return self._json(
                    404, {"error": f"no job named {rest[0]!r}",
                          "have": sorted(self._jobs_view())})
            return self._json(200, job)
        if method == "POST" and len(rest) == 2 and rest[0] \
                and rest[1] in ("cancel", "resize"):
            try:
                return self._control(rest[0], rest[1], body,
                                     self._trace_ctx(headers))
            except InvalidArgumentError as e:
                return self._json(400, {"error": str(e)})
        return None

    def _submit(self, body: bytes, ctx: TraceContext):
        try:
            doc = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return self._json(400, {"error": f"request body is not "
                                             f"JSON: {e}"})
        if isinstance(doc, dict) and "jobs" in doc:
            records = doc["jobs"]
        elif isinstance(doc, dict):
            records = [doc]  # one bare job record
        else:
            records = None
        if not isinstance(records, list) or not records:
            return self._json(
                400, {"error": "expected {'jobs': [...]} (the tools "
                               "jobs submit queue-JSON) or one job "
                               "record object."})
        # validate EVERYTHING before enqueueing ANYTHING — a bad record
        # in a batch must not half-submit it
        known = set(self._jobs_view())
        names = []
        for i, rec in enumerate(records):
            try:
                spec = jobspec_from_json(rec,
                                         where=f"POST /v1/jobs job #{i}")
            except InvalidArgumentError as e:
                return self._json(400, {"error": str(e)})
            if spec.name in known or spec.name in names:
                return self._json(
                    409, {"error": f"a job named {spec.name!r} already "
                                   "exists on this service (names key "
                                   "journals and queue records)."})
            names.append(spec.name)
        # the submit span's traceparent rides INSIDE each queue record:
        # `DirectoryBackend` round-trips it verbatim and the claiming
        # scheduler derives the job's root span from it — the causal
        # thread from this HTTP request to every collective under it
        tp = ctx.to_traceparent()
        for rec in records:
            rec = dict(rec)
            rec["traceparent"] = tp
            self.backend.submit(rec)
        return self._json(202, {"submitted": names, "traceparent": tp},
                          {"traceparent": tp})

    def _control(self, name: str, verb: str, body: bytes,
                 ctx: TraceContext):
        tp = ctx.to_traceparent()
        if verb == "cancel" and self.backend.discard(name):
            # atomically beat every scheduler to the pending record —
            # the job never existed as far as any journal is concerned
            return self._json(202, {"requested": "cancel", "job": name,
                                    "discarded": True},
                              {"traceparent": tp})
        job = self._jobs_view().get(name)
        if job is None:
            return self._json(404, {"error": f"no job named {name!r}",
                                    "have": sorted(self._jobs_view())})
        if job["state"] in _TERMINAL_STATES:
            return self._json(409, {"error": f"job {name!r} already "
                                             f"{job['state']}"})
        if verb == "cancel":
            self.backend.control("cancel", name, {"traceparent": tp})
            return self._json(202, {"requested": "cancel", "job": name},
                              {"traceparent": tp})
        # resize
        try:
            req = json.loads(body.decode("utf-8")) if body else {}
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return self._json(400, {"error": f"resize body is not "
                                             f"JSON: {e}"})
        if not isinstance(req, dict):
            return self._json(400, {"error": "resize body must be "
                                             "{'new_dims': [dx,dy,dz], "
                                             "'via'?: ...}"})
        dims = req.get("new_dims")
        try:
            dims = [int(x) for x in (dims or ())]
        except (TypeError, ValueError):
            dims = []
        via = req.get("via", "auto")
        if len(dims) != 3 or any(d < 1 for d in dims):
            return self._json(400, {"error": "new_dims must be 3 "
                                             f"positive ints; got "
                                             f"{req.get('new_dims')!r}"})
        if via not in ("auto", "device", "checkpoint"):
            return self._json(400, {"error": f"via must be auto|device|"
                                             f"checkpoint; got {via!r}"})
        self.backend.control("resize", name,
                             {"new_dims": dims, "via": via,
                              "traceparent": tp})
        return self._json(202, {"requested": "resize", "job": name,
                                "new_dims": dims, "via": via},
                          {"traceparent": tp})
