"""Hardware validation of the Pallas kernel tier.

Round-1 gap (VERDICT): every Pallas kernel was only ever validated in
interpret mode on CPU, which cannot catch Mosaic lowering/tiling failures.
This script runs EACH kernel non-interpreted on the real device and asserts
equality with the XLA (or numpy) reference, emitting one JSON row per kernel:

    {"metric": "pallas_check_<kernel>", "value": 1.0|0.0, "unit": "pass", ...}

plus a summary row. Run on TPU: `python bench_pallas_check.py`.
`--cpu` smoke-tests the harness itself in interpret mode (the CPU backend
has no non-interpret pallas); only the TPU run proves Mosaic lowering.
"""

from __future__ import annotations

import sys
import traceback

import bench_util


def _checks(interpret: bool):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        init_diffusion3d, run_diffusion,
    )
    from implicitglobalgrid_tpu.ops import pallas_halo as ph
    from implicitglobalgrid_tpu.ops import pallas_stencil as ps

    rng = np.random.default_rng(7)
    shape = (64, 64, 256)
    nx, ny, nz = shape
    A = jnp.asarray(rng.standard_normal(shape).astype(np.float32))

    def run(name, fn):
        try:
            ok, note = fn()
            yield_row(name, bool(ok), note)
            return bool(ok)
        except Exception:
            yield_row(name, False, traceback.format_exc()[-600:])
            return False

    def yield_row(name, ok, note):
        bench_util.emit({
            "metric": f"pallas_check_{name}",
            "value": 1.0 if ok else 0.0,
            "unit": "pass",
            **({"note": note} if note else {}),
        })

    results = []

    # --- in-place halo writes, dims 0 and 1 -------------------------------
    def check_write_dim0():
        sl = jnp.asarray(rng.standard_normal((1, ny, nz)).astype(np.float32))
        sr = jnp.asarray(rng.standard_normal((1, ny, nz)).astype(np.float32))
        out = jax.jit(lambda a, l, r: ph.halo_write_inplace(
            a, l, r, dim=0, hw=1, interpret=interpret))(A, sl, sr)
        exp = np.asarray(A).copy()
        exp[0:1] = np.asarray(sl)
        exp[nx - 1:nx] = np.asarray(sr)
        return np.array_equal(np.asarray(out), exp), None

    def check_write_dim1():
        sl = jnp.asarray(rng.standard_normal((nx, 1, nz)).astype(np.float32))
        sr = jnp.asarray(rng.standard_normal((nx, 1, nz)).astype(np.float32))
        out = jax.jit(lambda a, l, r: ph.halo_write_inplace(
            a, l, r, dim=1, hw=1, interpret=interpret))(A, sl, sr)
        exp = np.asarray(A).copy()
        exp[:, 0:1] = np.asarray(sl)
        exp[:, ny - 1:ny] = np.asarray(sr)
        return np.array_equal(np.asarray(out), exp), None

    # --- single-pass self-neighbor exchange -------------------------------
    def check_self_exchange():
        out = jax.jit(lambda a: ph.halo_self_exchange_pallas(
            a, modes=(True, True, True), ols=(2, 2, 2),
            interpret=interpret))(A)
        exp = np.asarray(A).copy()
        exp[:, :, 0] = exp[:, :, nz - 2]      # z first
        exp[:, :, nz - 1] = exp[:, :, 1]
        exp[0] = exp[nx - 2]                  # then x (with z edits applied)
        exp[nx - 1] = exp[1]
        exp[:, 0] = exp[:, ny - 2]            # then y
        exp[:, ny - 1] = exp[:, 1]
        return np.array_equal(np.asarray(out), exp), None

    # --- combined one-pass delivery ---------------------------------------
    def check_combined_write():
        rxs = jnp.asarray(rng.standard_normal((2, ny, nz)).astype(np.float32))
        rys = jnp.asarray(rng.standard_normal((nx, 2, nz)).astype(np.float32))
        rzs = jnp.asarray(rng.standard_normal((nx, ny, 2)).astype(np.float32))
        out = jax.jit(lambda a, rx, ry, rz: ph.halo_write_combined_pallas(
            a, {0: (rx[:1], rx[1:]), 1: (ry[:, :1], ry[:, 1:]),
                2: (rz[:, :, :1], rz[:, :, 1:])},
            modes=(True, True, True), hws=(1, 1, 1),
            interpret=interpret))(A, rxs, rys, rzs)
        exp = np.asarray(A).copy()
        exp[:, :, 0] = np.asarray(rzs)[:, :, 0]   # z, then x planes, then y
        exp[:, :, nz - 1] = np.asarray(rzs)[:, :, 1]
        exp[0] = np.asarray(rxs)[0]
        exp[nx - 1] = np.asarray(rxs)[1]
        exp[:, 0] = np.asarray(rys)[:, 0]
        exp[:, ny - 1] = np.asarray(rys)[:, 1]
        return np.array_equal(np.asarray(out), exp), None

    results.append(run("halo_write_dim0", check_write_dim0))
    results.append(run("halo_write_dim1", check_write_dim1))
    results.append(run("self_exchange", check_self_exchange))
    results.append(run("combined_write", check_combined_write))

    # --- model kernels on a real grid (self-neighbor periodic) ------------
    igg.init_global_grid(64, 64, 256, periodx=1, periody=1, periodz=1,
                         quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def check_step_plain():
        a = np.asarray(igg.gather(run_diffusion(T, Cp, p, 2, nt_chunk=2,
                                                impl="xla")))
        b = np.asarray(igg.gather(run_diffusion(T, Cp, p, 2, nt_chunk=2,
                                impl="pallas_interpret" if interpret
                                else "pallas")))
        ok = np.allclose(a, b, rtol=2e-6, atol=2e-5)
        return ok, f"max_abs_diff={float(np.max(np.abs(a - b))):.3e}"

    def check_step_exchange_fused():
        # force the fused step+exchange kernel (bypassing the all-self
        # sigma path) — validates _plane_step_recv_kernel lowering
        gg = igg.global_grid()
        from implicitglobalgrid_tpu.ops.fields import local_shape_of

        loc = local_shape_of(tuple(int(s) for s in T.shape))
        modes = ps.step_exchange_modes(
            gg, jax.ShapeDtypeStruct(loc, T.dtype))
        if modes is None:
            return False, "modes gate unexpectedly None"
        from implicitglobalgrid_tpu.ops.fields import field_partition_spec

        spec = field_partition_spec(3)

        def local(Tb, Cpb):
            return ps.diffusion3d_step_exchange_pallas(
                Tb, Cpb, gg, modes, lam=p.lam, dt=p.dt, dx=p.dx, dy=p.dy,
                dz=p.dz, interpret=interpret)

        from implicitglobalgrid_tpu.utils.compat import shard_map

        fused = jax.jit(shard_map(local, mesh=gg.mesh,
                                  in_specs=(spec, spec), out_specs=spec,
                                  check_vma=False))
        a = np.asarray(igg.gather(run_diffusion(T, Cp, p, 1, nt_chunk=1,
                                                impl="xla")))
        b = np.asarray(igg.gather(fused(T, Cp)))
        ok = np.allclose(a, b, rtol=2e-6, atol=2e-5)
        return ok, f"max_abs_diff={float(np.max(np.abs(a - b))):.3e}"

    results.append(run("fused_step_self", check_step_plain))
    results.append(run("fused_step_exchange", check_step_exchange_fused))
    igg.finalize_global_grid()

    # --- window-handoff variant: >= 3 windows (128/P=32 -> 4), exercising
    # the VMEM overlap handoff of `_window_pipeline_handoff` on hardware
    def check_step_handoff():
        igg.init_global_grid(128, 64, 256, periodx=1, periody=1,
                             periodz=1, quiet=True)
        try:
            sds = jax.ShapeDtypeStruct((128, 64, 256), np.float32)
            if not ps.mp_handoff(sds, interpret=interpret):
                return False, "handoff gate unexpectedly off"
            Th, Cph, ph = init_diffusion3d(dtype=np.float32)
            a = np.asarray(igg.gather(run_diffusion(
                Th, Cph, ph, 2, nt_chunk=2, impl="xla")))
            b = np.asarray(igg.gather(run_diffusion(
                Th, Cph, ph, 2, nt_chunk=2,
                impl="pallas_interpret" if interpret else "pallas")))
            ok = np.allclose(a, b, rtol=2e-6, atol=2e-5)
            return ok, f"max_abs_diff={float(np.max(np.abs(a - b))):.3e}"
        finally:
            igg.finalize_global_grid()

    results.append(run("fused_step_self_handoff", check_step_handoff))

    # --- fused acoustic and Stokes passes (staggered multi-field tiers) ---
    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, init_stokes3d, run_acoustic, run_stokes,
    )

    pal = "pallas_interpret" if interpret else "pallas"

    def check_acoustic_fused():
        igg.init_global_grid(32, 64, 256, periodx=1, periody=1, periodz=1,
                             quiet=True)
        try:
            state, pa = init_acoustic3d(dtype=np.float32)
            a = run_acoustic(state, pa, 2, nt_chunk=2, impl="xla")
            b = run_acoustic(state, pa, 2, nt_chunk=2, impl=pal)
            md = max(float(np.max(np.abs(np.asarray(igg.gather(x))
                                         - np.asarray(igg.gather(y)))))
                     for x, y in zip(a, b))
            return md < 1e-5, f"max_abs_diff={md:.3e}"
        finally:
            igg.finalize_global_grid()

    def check_stokes_fused():
        igg.init_global_grid(32, 64, 256, quiet=True)
        try:
            state, pstk = init_stokes3d(dtype=np.float32)
            a = run_stokes(state, pstk, 2, nt_chunk=2, impl="xla")
            b = run_stokes(state, pstk, 2, nt_chunk=2, impl=pal)
            md = 0.0
            for x, y in zip(a, b):
                gx = np.asarray(igg.gather(x))
                gy = np.asarray(igg.gather(y))
                scale = max(1.0, float(np.abs(gx).max()))
                md = max(md, float(np.max(np.abs(gx - gy))) / scale)
            return md < 1e-4, f"max_rel_diff={md:.3e}"
        finally:
            igg.finalize_global_grid()

    results.append(run("acoustic_fused", check_acoustic_fused))
    results.append(run("stokes_fused", check_stokes_fused))

    n_pass = sum(results)
    bench_util.emit({
        "metric": "pallas_checks_passed",
        "value": float(n_pass),
        "unit": f"of {len(results)}",
        "vs_baseline": n_pass / len(results),
    })


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=1"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    _checks(interpret=cpu)  # CPU backend has no non-interpret pallas


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("pallas_checks_passed", "of N")
