"""Shared benchmark-harness hardening.

Round-1 lesson: the driver's TPU capture failed because `jax.devices()` threw
on a transient backend-init error and `bench.py` died with a stack trace
instead of a JSON line.  Round-3 lesson (BENCH_r03.json, rc=124): two more
failure modes — a leaked ``IGG_BENCH_CHILD`` in the invoking environment sent
the script straight down the unsupervised child path, and an unavailable TPU
backend burned the whole driver timeout in backend-init retries.  Every bench
entry point now runs through :func:`run_with_retries`:

- :func:`is_child` only accepts a marker stamped with the supervising
  parent's own pid, so an inherited/leaked env var can never bypass
  supervision;
- before the first attempt the backend is probed in a throwaway subprocess
  with a hard timeout; if the probe fails, the run falls back to ``--cpu``
  immediately and the emitted rows carry a ``fallback`` note;
- the measurement runs in a fresh *child process* per attempt, so a cached
  backend-init failure in jax's ``xla_bridge`` can never poison a retry;
- a total wall-clock budget (``IGG_BENCH_BUDGET`` seconds, default
  ``_DEFAULT_BUDGET`` = 3000) bounds probe + attempts + fallback so a
  JSON line always lands inside any driver timeout larger than that;
- on unrecoverable failure the parent still prints one JSON line
  ``{"metric": ..., "value": null, "error": ...}`` and exits 0, so the driver
  always records a parseable row.

Every row emitted through :func:`emit` carries ``platform`` /
``device_kind`` / ``n_devices`` fields (round-1 weakness: CPU-mesh numbers
were indistinguishable from TPU numbers in the committed artifacts).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD_ENV = "IGG_BENCH_CHILD"
_PROBE_TIMEOUT = 150  # seconds for the throwaway backend probe
_CPU_RESERVE = 500    # budget kept back for the --cpu fallback attempt

# Default budget: probe (<=150s) + a full-evidence TPU attempt (measured
# ~900s healthy: dominated by ~10 tunnel compiles + the pallas_check
# subprocess, see bench.py) with ~2.3x headroom + the CPU-fallback reserve.
# Killing a healthy TPU run is the worst outcome (a killed TPU-attached
# process wedges the chip claim) — size generously; if the DRIVER's own
# timeout is smaller, the driver kills us either way and the budget only
# changes who does it.  ``IGG_BENCH_BUDGET=0`` (or negative) disables the
# kill entirely: no deadline, no attempt timeout — the mode
# `capture_tpu_evidence.sh` runs in, where a timeout-killed TPU-attached
# child is strictly worse than a slow capture.
_DEFAULT_BUDGET = 3000.0


def _budget() -> float:
    """Wall-clock budget in seconds; ``inf`` when disabled via
    ``IGG_BENCH_BUDGET=0`` (never timeout-kill a TPU-attached child)."""
    try:
        b = float(os.environ.get("IGG_BENCH_BUDGET", str(_DEFAULT_BUDGET)))
    except ValueError:
        return _DEFAULT_BUDGET
    return float("inf") if b <= 0 else b


def device_fields() -> dict:
    """platform/device_kind/n_devices of the active jax backend."""
    import jax

    d = jax.devices()
    return {
        "platform": d[0].platform,
        "device_kind": d[0].device_kind,
        "n_devices": len(d),
    }


def emit(row: dict) -> dict:
    """Tag *row* with device fields and print it as one JSON line."""
    try:
        row = {**row, **device_fields()}
    except Exception as e:  # still emit the measurement if tagging fails
        row = {**row, "platform": None, "device_note": repr(e)}
    print(json.dumps(row))
    return row


def child_env() -> dict:
    """Environment for spawning a measurement child of THIS process: the
    marker carries our pid plus a random token, so neither a leaked ``1``
    (round-3 driver environment) nor a stale marker from another run can
    route a fresh invocation down the unsupervised child path."""
    import secrets

    return {**os.environ,
            _CHILD_ENV: f"{os.getpid()}:{secrets.token_hex(8)}"}


def probe_backend(timeout: float = _PROBE_TIMEOUT, platform: str | None = None):
    """Check a jax backend in a throwaway subprocess.

    ``platform=None`` probes the DEFAULT backend — on this image that is
    the axon/TPU tunnel whenever it registers, which is exactly what the
    bench needs to know about.  (Note ``JAX_PLATFORMS`` env is NOT a
    reliable override here: the axon register re-forces
    ``jax_platforms="axon,cpu"`` at import; only an in-process
    ``jax.config.update`` after import wins, which is what ``platform=``
    does and what ``bench.py --cpu`` does.)

    Returns ``None`` when the backend came up, else a one-line failure
    description.  A hard timeout bounds the hang-in-backend-init failure
    mode (the probe holds no TPU program when killed, unlike a measurement
    child, so killing it is safe)."""
    force = (f"jax.config.update('jax_platforms', {platform!r}); "
             if platform else "")
    code = (f"import jax; {force}d = jax.devices()[0]; "
            "print('IGG_PROBE_OK', d.platform, d.device_kind)")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return f"backend probe timed out after {timeout:.0f}s"
    except Exception as e:  # pragma: no cover - spawn failure
        return f"backend probe failed to spawn: {e!r}"
    if proc.returncode == 0 and "IGG_PROBE_OK" in proc.stdout:
        return None
    return ("backend probe rc=%d: %s"
            % (proc.returncode, (proc.stderr or proc.stdout or "")[-600:]))


def _forward_rows(stdout: str, fallback_note) -> None:
    """Print the child's JSON rows, tagging each with the fallback note."""
    for ln in stdout.splitlines():
        s = ln.strip()
        if not s.startswith("{"):
            continue
        if fallback_note is not None:
            try:
                row = json.loads(s)
                row["fallback"] = fallback_note
                s = json.dumps(row)
            except Exception:
                pass
        print(s)


def run_with_retries(metric: str, unit: str, argv: list[str] | None = None,
                     probe_platform: str | None = None) -> None:
    """Re-exec the calling script as a supervised child process.

    The calling script's ``__main__`` must branch on :func:`is_child` — the
    child runs the real measurement; the parent (this function) supervises:
    backend probe → (TPU attempts) → automatic ``--cpu`` fallback, all under
    one wall-clock budget.  Never raises; always prints >=1 JSON line;
    always exits 0 (unless ``IGG_BENCH_STRICT=1``).

    ``probe_platform`` forces the pre-flight probe onto a named backend
    (tests); ``None`` probes the default (accelerator) backend.
    """
    argv = list(argv) if argv is not None else list(sys.argv)
    deadline = time.monotonic() + _budget()
    cpu_mode = "--cpu" in argv
    fallback_note = None
    last_tail = ""

    if not cpu_mode:
        # Round-4 lesson: a single failed probe forfeited the round's TPU
        # artifact even though the tunnel was up earlier (and later) in the
        # session.  Probes hold no chip claim and are safe to kill, so
        # re-probe a few times across the window before settling for --cpu.
        tries = 3
        try:
            tries = max(1, int(os.environ.get("IGG_BENCH_PROBE_RETRIES", "3")))
        except ValueError:
            pass
        probe_err = None
        for p in range(tries):
            probe_window = deadline - time.monotonic() - _CPU_RESERVE
            if probe_window == float("inf"):
                probe_window = _PROBE_TIMEOUT
            probe_err = probe_backend(
                min(_PROBE_TIMEOUT, max(10.0, probe_window)),
                platform=probe_platform)
            if probe_err is None:
                break
            sys.stderr.write(f"[bench_util] probe {p + 1}/{tries}: "
                             f"{probe_err}\n")
            # Stop early when another full probe + fallback no longer fits.
            if (p + 1 < tries
                    and deadline - time.monotonic() - _CPU_RESERVE > 90):
                time.sleep(30)
            else:
                break
        if probe_err is not None:
            sys.stderr.write("[bench_util] falling back to --cpu\n")
            fallback_note = "tpu_unavailable: " + probe_err[-300:]
            argv.append("--cpu")
            cpu_mode = True

    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        # On the accelerator path, keep enough budget back to still run one
        # CPU-fallback attempt afterwards.  With the budget disabled, an
        # ACCELERATOR child is never timeout-killed (a killed TPU-attached
        # process wedges the chip claim) — but a CPU child is safe to kill
        # and still gets a finite cap, so a deadlocked fallback cannot hang
        # an unsupervised capture forever.
        if remaining == float("inf"):
            attempt_timeout = _DEFAULT_BUDGET if cpu_mode else None
        else:
            attempt_timeout = remaining - (0 if cpu_mode else _CPU_RESERVE)
        if attempt_timeout is not None and attempt_timeout < 30:
            if not cpu_mode:
                # no room for an accelerator attempt, but the reserve can
                # still buy the CPU fallback — use it instead of giving up
                fallback_note = "tpu_skipped: budget too small for an " \
                                "accelerator attempt"
                argv.append("--cpu")
                cpu_mode = True
                continue
            last_tail = last_tail or "wall-clock budget exhausted"
            break
        try:
            proc = subprocess.run(
                [sys.executable, *argv],
                env=child_env(),
                capture_output=True,
                text=True,
                timeout=attempt_timeout,
            )
            if proc.returncode == 0 and any(
                ln.strip().startswith("{") for ln in proc.stdout.splitlines()
            ):
                # Forward stdout only on success: a failed attempt may have
                # printed partial rows which would duplicate/contradict the
                # retry's rows in the driver's line-parsed capture.
                _forward_rows(proc.stdout, fallback_note)
                sys.stdout.flush()
                sys.exit(0)
            last_tail = (proc.stderr or proc.stdout or "")[-2000:]
        except subprocess.TimeoutExpired:
            last_tail = (f"attempt timed out after {attempt_timeout:.0f}s; "
                         "the measurement child was KILLED mid-run (if it "
                         "was TPU-attached the chip claim may be wedged — "
                         "set IGG_BENCH_BUDGET=0 for unsupervised captures)")
        except Exception as e:  # subprocess spawn failure etc.
            last_tail = repr(e)
        sys.stderr.write(f"[bench_util] attempt {attempt} "
                         f"({'cpu' if cpu_mode else 'accel'}) failed\n")
        sys.stderr.write(last_tail + "\n")
        if not cpu_mode:
            # One accelerator attempt only — a post-probe failure is almost
            # never transient; spend the remaining budget on the fallback.
            fallback_note = ("tpu_attempt_failed: " + last_tail[-300:])
            argv.append("--cpu")
            cpu_mode = True
        elif attempt >= 3:
            break
        time.sleep(5)
    print(json.dumps({
        "metric": metric,
        "value": None,
        "unit": unit,
        "error": last_tail[-1000:],
        "fallback": fallback_note,
    }))
    # Exit-0-with-null-row is the contract the driver needs (a parseable row
    # no matter what); CI needs red builds instead — IGG_BENCH_STRICT=1.
    sys.exit(1 if os.environ.get("IGG_BENCH_STRICT") == "1" else 0)


def is_child() -> bool:
    """True only when the marker has the ``<ppid>:<token>`` shape stamped
    by :func:`child_env` and the pid half names OUR direct parent — a
    leaked ``IGG_BENCH_CHILD=1`` from the invoking environment (the
    round-3 failure: it sent `bench.py` straight down the unsupervised
    child path, even matching ppid 1 in a container) cannot bypass
    supervision."""
    val = os.environ.get(_CHILD_ENV, "")
    pid, sep, token = val.partition(":")
    return bool(sep) and len(token) >= 8 and pid == str(os.getppid())


def measure_triad_gbps(n: int, c1: int = 4) -> float:
    """Fused-XLA triad bandwidth (2 reads + 1 write over ``n`` f32
    elements): the practical HBM ceiling used for roofline percentages.
    Shared by `bench.py` (in-run calibration) and `bench_membw.py` — the
    loop carry keeps ``b`` in place, because a swapped carry pins
    while-loop buffers and pays a hidden full-array copy per step (see
    docs/performance.md trace notes). Grid-independent (wall-clock timer;
    the chunk drains its own outputs)."""
    import time

    import jax
    import jax.numpy as jnp

    a = jnp.arange(n, dtype=jnp.float32)
    b = jnp.ones((n,), jnp.float32)

    @jax.jit
    def triad_chunk(a, b, c):
        def body(_, ab):
            a, b = ab
            return (b * 1.0001 + a * 0.5, b)
        return jax.lax.fori_loop(0, c, body, (a, b))

    def chunk(c):
        jax.block_until_ready(triad_chunk(a, b, c))

    def timer(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    s = two_point(chunk, c1, 3 * c1, timer=timer)
    return 3 * 4 * n / s / 1e9


def two_point(run_chunk, c1: int, c2: int, reps: int = 2,
              timer=None) -> float:
    """Steady-state seconds/step via two warmed one-call chunk windows.

    ``run_chunk(c)`` must execute ONE chunk call of ``c`` steps and drain
    its outputs (`igg.sync`). Both windows pay identical fixed costs (one
    dispatch + one drain round trip — substantial on tunneled PJRT
    transports, absent on a normal TPU host), so the slope
    ``(t(c2)-t(c1))/(c2-c1)`` is the pure per-step device time — the same
    amortized steady-state quantity the reference's 100k-step wall-clock
    anchor reports (`reference README.md:163-167`). Each window is
    measured ``reps`` times, keeping the minimum.

    ``timer(fn) -> seconds`` defaults to the barrier-synchronized
    ``igg.tic()``/``igg.toc()`` pair; tests inject a fake clock.

    After each call, ``two_point.last`` records ``{"method", "t1", "t2"}``;
    ``method`` is ``"two-point"`` for a true slope or
    ``"inclusive-fallback"`` when timer jitter produced ``t2 <= t1`` and
    the bigger window's inclusive rate was returned instead (that rate
    re-includes the fixed per-call cost — emitted rows should carry the
    distinction)."""
    if timer is None:
        import implicitglobalgrid_tpu as igg

        def timer(fn):
            igg.tic()
            fn()
            return igg.toc()

    run_chunk(c1)
    run_chunk(c2)  # warm both programs + both drain signatures

    t1 = min(timer(lambda: run_chunk(c1)) for _ in range(reps))
    t2 = min(timer(lambda: run_chunk(c2)) for _ in range(reps))
    if t2 <= t1:  # timer jitter on tiny windows: never emit a <=0 slope;
        two_point.last = {"method": "inclusive-fallback", "t1": t1, "t2": t2}
        return t2 / c2  # fall back to the bigger window's inclusive rate
    two_point.last = {"method": "two-point", "t1": t1, "t2": t2}
    return (t2 - t1) / (c2 - c1)


two_point.last = None
