"""Shared benchmark-harness hardening.

Round-1 lesson: the driver's TPU capture failed because `jax.devices()` threw
on a transient backend-init error and `bench.py` died with a stack trace
instead of a JSON line.  Every bench entry point now runs through
:func:`run_with_retries`:

- the measurement runs in a fresh *child process* per attempt, so a cached
  backend-init failure in jax's ``xla_bridge`` can never poison a retry;
- attempts back off (5s, 15s, 30s, 60s);
- on unrecoverable failure the parent still prints one JSON line
  ``{"metric": ..., "value": null, "error": ...}`` and exits 0, so the driver
  always records a parseable row.

Every row emitted through :func:`emit` carries ``platform`` /
``device_kind`` / ``n_devices`` fields (round-1 weakness: CPU-mesh numbers
were indistinguishable from TPU numbers in the committed artifacts).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD_ENV = "IGG_BENCH_CHILD"
_BACKOFFS = (5, 15, 30, 60)
_ATTEMPT_TIMEOUT = 2400  # seconds per child attempt (the full-evidence bench runs 7 configs + the kernel checks)


def device_fields() -> dict:
    """platform/device_kind/n_devices of the active jax backend."""
    import jax

    d = jax.devices()
    return {
        "platform": d[0].platform,
        "device_kind": d[0].device_kind,
        "n_devices": len(d),
    }


def emit(row: dict) -> dict:
    """Tag *row* with device fields and print it as one JSON line."""
    try:
        row = {**row, **device_fields()}
    except Exception as e:  # still emit the measurement if tagging fails
        row = {**row, "platform": None, "device_note": repr(e)}
    print(json.dumps(row))
    return row


def run_with_retries(metric: str, unit: str, argv: list[str] | None = None) -> None:
    """Re-exec the calling script as a child process with retries.

    The calling script's ``__main__`` must branch on :func:`is_child` — the
    child runs the real measurement; the parent (this function) supervises.
    Never raises; always prints >=1 JSON line; always exits 0.
    """
    argv = argv if argv is not None else sys.argv
    last_tail = ""
    for attempt, backoff in enumerate(_BACKOFFS + (None,)):
        try:
            proc = subprocess.run(
                [sys.executable, *argv],
                env={**os.environ, _CHILD_ENV: "1"},
                capture_output=True,
                text=True,
                timeout=_ATTEMPT_TIMEOUT,
            )
            if proc.returncode == 0 and any(
                ln.strip().startswith("{") for ln in proc.stdout.splitlines()
            ):
                # Forward stdout only on success: a failed attempt may have
                # printed partial rows which would duplicate/contradict the
                # retry's rows in the driver's line-parsed capture.
                sys.stdout.write(proc.stdout)
                sys.stdout.flush()
                sys.exit(0)
            last_tail = (proc.stderr or proc.stdout or "")[-2000:]
        except subprocess.TimeoutExpired as e:
            last_tail = f"attempt timed out after {_ATTEMPT_TIMEOUT}s: {e}"
        except Exception as e:  # subprocess spawn failure etc.
            last_tail = repr(e)
        sys.stderr.write(
            f"[bench_util] attempt {attempt + 1} failed"
            + (f"; retrying in {backoff}s\n" if backoff else "; giving up\n")
        )
        sys.stderr.write(last_tail + "\n")
        if backoff is None:
            break
        time.sleep(backoff)
    print(json.dumps({
        "metric": metric,
        "value": None,
        "unit": unit,
        "error": last_tail[-1000:],
    }))
    # Exit-0-with-null-row is the contract the driver needs (a parseable row
    # no matter what); CI needs red builds instead — IGG_BENCH_STRICT=1.
    sys.exit(1 if os.environ.get("IGG_BENCH_STRICT") == "1" else 0)


def is_child() -> bool:
    return os.environ.get(_CHILD_ENV) == "1"


def two_point(run_chunk, c1: int, c2: int, reps: int = 2) -> float:
    """Steady-state seconds/step via two warmed one-call chunk windows.

    ``run_chunk(c)`` must execute ONE chunk call of ``c`` steps and drain
    its outputs (`igg.sync`). Both windows pay identical fixed costs (one
    dispatch + one drain round trip — substantial on tunneled PJRT
    transports, absent on a normal TPU host), so the slope
    ``(t(c2)-t(c1))/(c2-c1)`` is the pure per-step device time — the same
    amortized steady-state quantity the reference's 100k-step wall-clock
    anchor reports (`reference README.md:163-167`). Each window is
    measured ``reps`` times, keeping the minimum."""
    import implicitglobalgrid_tpu as igg

    run_chunk(c1)
    run_chunk(c2)  # warm both programs + both drain signatures

    def timed(c):
        igg.tic()
        run_chunk(c)
        return igg.toc()

    t1 = min(timed(c1) for _ in range(reps))
    t2 = min(timed(c2) for _ in range(reps))
    if t2 <= t1:  # timer jitter on tiny windows: never emit a <=0 slope;
        return t2 / c2  # fall back to the bigger window's inclusive rate
    return (t2 - t1) / (c2 - c1)
