"""Benchmark suite: one JSON line per BASELINE.json config.

Runs every workload family of `/root/repo/BASELINE.json` on the available
devices (one real TPU chip, or the 8-device virtual CPU mesh with --cpu):

- diffusion3D 256^3/chip, f32 and f64 (configs 1, 3; f64 is the reference's
  anchor dtype — on v5e it runs through the f32 pipeline emulation)
- 2-D diffusion, f32 (config 2)
- 3-D acoustic wave with hide_communication overlap (config 4)
- 3-D pseudo-transient Stokes (config 5)

`bench.py` stays the single-headline-metric entry point (the driver runs
it); this suite is for the full per-config record. Weak-scaling efficiency
needs >1 chip — see bench_weak.py (virtual-mesh harness).

Usage: python bench_all.py [--cpu]
"""

from __future__ import annotations

import json
import sys

import bench_util


def _rate(cells, steps, t):
    return cells * steps / t


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    if cpu:  # f64 anchor config needs x64; TPU has no native f64 pipeline
        jax.config.update("jax_enable_x64", True)
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, init_diffusion2d, init_diffusion3d,
        run_acoustic, run_diffusion, run_stokes, init_stokes3d,
    )

    nd = len(jax.devices())
    dims3 = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    n_chips = int(np.prod(dims3))
    results = []

    def record(name, value, unit, baseline=None):
        row = {"metric": name, "value": value, "unit": unit}
        if baseline:
            row["vs_baseline"] = value / baseline
        results.append(bench_util.emit(row))

    def timed(run_fn, state, nt, chunk):
        """Two-point steady-state: returns equivalent seconds for ``nt``
        steps, i.e. nt * the per-step slope (`bench_util.two_point`)."""
        del chunk

        def one(c):
            run_fn(state, c, c)  # run_* drain internally (run_chunked)

        c1 = max(1, nt // 10)
        return nt * bench_util.two_point(one, c1, 3 * c1)

    # --- diffusion3D f32 / f64 (BASELINE configs 1, 3) ---------------------
    nx, nt = (48, 50) if cpu else (256, 1000)
    dtypes = [(np.float32, "f32")]
    if cpu:
        dtypes.append((np.float64, "f64"))
    else:
        row = {
            "metric": "diffusion3D_f64_cell_updates_per_s_per_chip",
            "value": None, "unit": "cell-updates/s/chip",
            "note": "no native f64 on this TPU generation; f64 semantics "
                    "verified on the x64 CPU mesh (tests, bench_all --cpu)",
        }
        results.append(bench_util.emit(row))
    for dtype, tag in dtypes:
        igg.init_global_grid(nx, nx, nx, dimx=dims3[0], dimy=dims3[1],
                             dimz=dims3[2], periodx=1, periody=1, periodz=1,
                             quiet=True)
        T, Cp, p = init_diffusion3d(dtype=dtype)
        t = timed(lambda s, n, c: run_diffusion(s[0], s[1], p, n, nt_chunk=c),
                  (T, Cp), nt, max(1, nt // 10))
        cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
        record(f"diffusion3D_{tag}_cell_updates_per_s_per_chip",
               _rate(cells, nt, t) / n_chips, "cell-updates/s/chip",
               baseline=0.95e9)  # reference: 0.95e9/GPU f64 (BASELINE.md)
        igg.finalize_global_grid()

    # --- diffusion2D f32 (BASELINE config 2: 2-D on a 2x2 mesh) ------------
    dims2 = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 1)))
    nx2, nt2 = (64, 50) if cpu else (4096, 1000)
    igg.init_global_grid(nx2, nx2, 1, dimx=dims2[0], dimy=dims2[1], dimz=1,
                         periodx=1, periody=1, quiet=True)
    T, Cp, p = init_diffusion2d(dtype=np.float32)
    t = timed(lambda s, n, c: run_diffusion(s[0], s[1], p, n, nt_chunk=c),
              (T, Cp), nt2, max(1, nt2 // 10))
    record("diffusion2D_f32_cell_updates_per_s_per_chip",
           _rate(float(igg.nx_g()) * float(igg.ny_g()), nt2, t) / n_chips,
           "cell-updates/s/chip")
    igg.finalize_global_grid()

    # --- acoustic 3-D with hide_communication (BASELINE config 4) ----------
    nxa, nta = (32, 30) if cpu else (192, 600)
    igg.init_global_grid(nxa, nxa, nxa, dimx=dims3[0], dimy=dims3[1],
                         dimz=dims3[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    state, p = init_acoustic3d(dtype=np.float32, overlap=True)
    t = timed(lambda s, n, c: run_acoustic(s, p, n, nt_chunk=c),
              state, nta, max(1, nta // 10))
    cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
    record("acoustic3D_overlap_f32_cell_updates_per_s_per_chip",
           _rate(cells, nta, t) / n_chips, "cell-updates/s/chip")
    igg.finalize_global_grid()

    # --- halo coalescing A/B (2/4/8/16 fields) + pack attribution ----------
    # one packed ppermute pair per axis vs 2·N per-field permutes, plus the
    # pack/unpack-vs-permute attribution rows (`update_halo_pack_frac_*`)
    # the perfdb gate watches for pack-bound regressions. Config owned by
    # `bench_halo.run_coalescing_ab` (shared with the standalone bench).
    import bench_halo

    coalesce_rows = bench_halo.run_coalescing_ab(dims3, cpu)
    for row in coalesce_rows:
        results.append(bench_util.emit(row))
    # ISSUE 11 absolute gate: the 8-field coalesced exchange must beat the
    # per-field baseline (>= 1x) — the canonical-wire-schema fix for the
    # 0.75x regression. A direct gate like lint_ok: rc 1 under
    # IGG_BENCH_STRICT=1, independent of the trailing-median perfdb check
    # (which would tolerate a slow drift back below 1x).
    speed8 = next(r["value"] for r in coalesce_rows
                  if r["metric"] == "update_halo_coalesced_speedup_8fields")
    coalesce8_ok = speed8 >= 1.0
    results.append(bench_util.emit({
        "metric": "coalesce_8field_restored_ok",
        "value": 1.0 if coalesce8_ok else 0.0,
        "unit": "bool (1 = 8-field coalesced exchange >= per-field)",
        "speedup_8fields": speed8,
    }))

    # --- topology-staged wire (ISSUE 16) -----------------------------------
    # z exchange re-routed ICI leader-gather -> ONE striped DCN transfer
    # per granule pair -> ICI scatter, on a two-granule mesh: the static
    # per-DCN-link message-count fold (`staged_dcn_msgs_ratio`, gated
    # absolute >= devices-per-granule/2 under IGG_BENCH_STRICT), the
    # measured staging-overhead A/B, and the modeled speedup on the
    # hierarchical ICI+DCN profile. Config owned by
    # `bench_halo.run_staged_ab` (shared with the standalone bench).
    staged_rows = bench_halo.run_staged_ab(dims3, cpu)
    for row in staged_rows:
        results.append(bench_util.emit(row))
    staged_ok = all(
        r["value"] >= 1.0 for r in staged_rows
        if r["metric"] == "staged_msgs_gate_ok" and r["value"] is not None)

    # --- ensemble axis: per-member step vs solo at E=4/8/16 (ISSUE 12) -----
    # one vmapped chunk advances E scenario members behind the SAME
    # collectives; per-member speedup rows ride the perfdb gate and two
    # absolute gates travel with them: compiled permute+psum count at E=8
    # equals E=1 (`ensemble_permutes_flat_ok`) and per-member step within
    # 10% of solo (`ensemble_amortization_ok`). Config owned by
    # `bench_ensemble.run_ensemble_ab` (shared with the standalone bench).
    import bench_ensemble

    ensemble_rows = bench_ensemble.run_ensemble_ab(dims3, cpu)
    for row in ensemble_rows:
        results.append(bench_util.emit(row))
    ensemble_ok = all(
        r["value"] >= 1.0 for r in ensemble_rows
        if r["metric"] in ("ensemble_permutes_flat_ok",
                           "ensemble_amortization_ok"))

    # --- quantized halo wire A/B (ISSUE 10) --------------------------------
    # static f32/int8 wire-byte ratio at 4 coalesced fields (payload +
    # per-slab scales), the quantize/dequantize overhead gate on the live
    # mesh, and the modeled exposed-comm delta of the per-axis z:int8
    # policy on an ICI+DCN profile. Config owned by
    # `bench_quant.run_quant_ab` (shared with the standalone bench).
    import bench_quant

    for row in bench_quant.run_quant_ab(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- resilience guard overhead (guarded vs plain chunk) ----------------
    # the supervised driver's per-chunk health probe + fetch as a fraction
    # of step time; target < 2% (ISSUE 2). Config owned by
    # `bench_resilience.run_guard_overhead` (shared with the standalone).
    import bench_resilience

    for row in bench_resilience.run_guard_overhead(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- telemetry overhead (flight recorder + metrics on vs off) ----------
    # the observability layer's host-side cost per supervised run as a
    # fraction of run time; target < 2% (ISSUE 3). Config owned by
    # `bench_telemetry.run_telemetry_overhead` (shared with the standalone).
    import bench_telemetry

    tel_rows = bench_telemetry.run_telemetry_overhead(dims3, cpu)
    for row in tel_rows:
        results.append(bench_util.emit(row))

    # --- live observability plane (ISSUE 18) --------------------------------
    # the in-process alert cadence (tail drain + default rule pack per
    # chunk boundary — what MeshScheduler(alerts=True) adds per slice) as
    # a fraction of the telemetry leg's off-run time, gated < 2%; the
    # /v1/observe round trip and /v1/events append-to-line lag ride the
    # perfdb trajectory. Config owned by `bench_telemetry.live_plane_rows`.
    tel_ref = next(r for r in tel_rows
                   if r["metric"] == "telemetry_overhead_frac")
    live_rows = bench_telemetry.live_plane_rows(
        tel_ref["off_run_s_median"],
        n_boundaries=tel_ref["nt"] // tel_ref["nt_chunk"])
    for row in live_rows:
        results.append(bench_util.emit(row))
    live_ok = next(r["value"] for r in live_rows
                   if r["metric"] == "live_tail_overhead_frac") < 0.02

    # --- distributed tracing (ISSUE 20) -------------------------------------
    # the recorder's per-event trace stamp (two dict inserts) as a
    # fraction of the telemetry leg's off-run time, gated < 2%; the
    # 10k-event OTLP export rides the perfdb trajectory. Config owned by
    # `bench_telemetry.tracing_rows`.
    tracing = bench_telemetry.tracing_rows(tel_ref["off_run_s_median"],
                                           tel_ref["events_per_run"])
    for row in tracing:
        results.append(bench_util.emit(row))
    tracing_ok = next(r["value"] for r in tracing
                      if r["metric"] == "trace_ctx_overhead_frac") < 0.02

    # --- mesh observability: trace pipeline + server-off step-loop cost ----
    # aggregation+straggler+Perfetto-export wall time on a 10k-event
    # two-process stream (host-only, target < 5 s) and the deterministic
    # accounting that the step loop pays ~nothing when the metrics server
    # is off (ISSUE 5). Config owned by `bench_trace.run_trace_overhead`.
    import bench_trace

    for row in bench_trace.run_trace_overhead(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- io: async snapshot overhead + vs-gather speedup -------------------
    # the snapshot pipeline's step-loop cost (submit = D2H + enqueue) as a
    # fraction of run time, target < 2%, plus the speedup over the legacy
    # gather-per-snapshot output path (ISSUE 4). Config owned by
    # `bench_io.run_io_overhead` (shared with the standalone bench).
    import bench_io

    for row in bench_io.run_io_overhead(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- performance oracle: drift-detector overhead + model fidelity ------
    # the live PerfWatch's per-boundary cost (deterministic accounting,
    # target < 2%) and the calibrated model's measured/modeled per-step
    # ratio for the diffusion3D/acoustic3D configs with the roofline
    # bound verdict and its repeat-calibration stability (ISSUE 6).
    # Config owned by `bench_perf.run_perf_overhead`/`run_model_ratio`.
    import bench_perf

    for row in bench_perf.run_perf_overhead(dims3, cpu):
        results.append(bench_util.emit(row))
    for row in bench_perf.run_model_ratio(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- closed-loop auto-tuner (ISSUE 13) ---------------------------------
    # search predict_step over per-axis comm_every x wire x coalesce,
    # validate the top candidates with measured runs: the tuned config
    # must never lose to the default (absolute gate >= 1.0 — the
    # baseline is in the measured set) and the search wall time rides
    # the perfdb trajectory. Config owned by `bench_tune.run_tune_rows`.
    import bench_tune

    tune_rows = bench_tune.run_tune_rows(dims3, cpu)
    for row in tune_rows:
        results.append(bench_util.emit(row))
    tuned_speedup = next(r["value"] for r in tune_rows
                         if r["metric"] == "tuned_vs_default_speedup")
    tuned_ok = tuned_speedup is not None and tuned_speedup >= 1.0

    # --- on-device elastic resharding (ISSUE 14) ---------------------------
    # resize downtime of the HBM-to-HBM collective re-block vs the
    # checkpoint (disk) path it replaces: the on-device path must never
    # lose (absolute gate `reshard_vs_disk_speedup >= 1.0` under
    # IGG_BENCH_STRICT; downtimes + one-time compile ride the perfdb
    # trajectory). Config owned by `bench_reshard.run_reshard_ab`.
    import bench_reshard

    reshard_rows = bench_reshard.run_reshard_ab(dims3, cpu)
    for row in reshard_rows:
        results.append(bench_util.emit(row))
    reshard_speedup = next(r["value"] for r in reshard_rows
                           if r["metric"] == "reshard_vs_disk_speedup")
    reshard_ok = reshard_speedup is None or reshard_speedup >= 1.0

    # --- multi-run scheduler: steady-state multiplexing overhead -----------
    # warm per-slice time of a two-job round_robin scheduler (every slice
    # a context switch) vs a bare warm ResilientRun loop; target < 2%,
    # warm switch cost recorded (ISSUE 8). Config owned by
    # `bench_service.run_service_overhead` (shared with the standalone).
    import bench_service

    for row in bench_service.run_service_overhead(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- serving tier: job API round trip + read-side query cache ----------
    # the HTTP front doors (ISSUE 17): submit+status round trip against a
    # live JobApiServer, cold sub-box snapshot read over HTTP, and the
    # block-LRU cold/warm speedup — the warm read answers from decoded
    # blocks, so `query_cache_speedup >= 1.0` is an absolute gate (rc 1
    # under IGG_BENCH_STRICT=1); the latencies ride the perfdb trajectory.
    # Config owned by `bench_service.run_serving_tier`.
    serve_rows = bench_service.run_serving_tier(dims3, cpu)
    for row in serve_rows:
        results.append(bench_util.emit(row))
    query_speedup = next(r["value"] for r in serve_rows
                         if r["metric"] == "query_cache_speedup")
    serve_ok = query_speedup is None or query_speedup >= 1.0

    # --- closed-loop autoscaler (ISSUE 19) ---------------------------------
    # per-boundary policy cost (< 2% of the slice it rides) and the
    # reactivity gate: the drill's starved tenant must be GROWN and the
    # idle one SHRUNK through the journaled control path with no
    # operator input (`autoscale_reacts_ok`, rc 1 under
    # IGG_BENCH_STRICT=1). Config owned by
    # `bench_autoscale.run_autoscale_rows` (shared with the standalone).
    import bench_autoscale

    autoscale_rows = bench_autoscale.run_autoscale_rows(dims3, cpu)
    for row in autoscale_rows:
        results.append(bench_util.emit(row))
    autoscale_ok = all(
        (r["frac_of_slice"] < 0.02 if r["metric"] == "autoscale_decision_s"
         else r["value"] >= 1.0)
        for r in autoscale_rows)

    # --- static analysis: compile-time audit overhead ----------------------
    # run_resilient(audit=True)'s one-time trace+lower+parse+check cost as
    # a fraction of run time; target < 2% (ISSUE 7). Config owned by
    # `bench_audit.run_audit_overhead` (shared with the standalone bench).
    import bench_audit

    for row in bench_audit.run_audit_overhead(dims3, cpu):
        results.append(bench_util.emit(row))

    # --- pseudo-transient Stokes 3-D (BASELINE config 5) -------------------
    nxs, nts = (24, 20) if cpu else (128, 300)
    igg.init_global_grid(nxs, nxs, nxs, dimx=dims3[0], dimy=dims3[1],
                         dimz=dims3[2], quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    t = timed(lambda s, n, c: run_stokes(s, p, n, nt_chunk=c),
              state, nts, max(1, nts // 10))
    cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
    record("stokes3D_pt_f32_cell_updates_per_s_per_chip",
           _rate(cells, nts, t) / n_chips, "cell-updates/s/chip")
    igg.finalize_global_grid()

    # --- repo lint gate: `ruff check .` travels with the perf gates --------
    # (ISSUE 7) the [tool.ruff] config in pyproject.toml is the contract;
    # value 1 = clean tree, 0 = findings (a direct gate: rc 1 under
    # IGG_BENCH_STRICT=1, same contract as the perfdb gate below).
    # Containers without ruff record the row as skipped instead of
    # vacuously passing.
    import os
    import subprocess

    lint = subprocess.run(
        [sys.executable, "-m", "ruff", "check", "."],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    ruff_missing = lint.returncode != 0 and "No module named" in lint.stderr
    results.append(bench_util.emit({
        "metric": "lint_ok",
        "value": None if ruff_missing else (1.0 if lint.returncode == 0
                                            else 0.0),
        "unit": "bool (1 = `python -m ruff check .` clean)",
        **({"note": "ruff unavailable in this environment; row skipped"}
           if ruff_missing else
           {} if lint.returncode == 0 else
           {"findings": lint.stdout.strip().splitlines()[-20:]}),
    }))

    # --- perf-history gate: the bench trajectory checks itself -------------
    # current run vs the trailing PERF_HISTORY.jsonl window (checked
    # BEFORE appending, so a run never gates against itself); the verdict
    # rides BENCH_ALL.json as its own row. Exit-0-with-recorded-failure is
    # the bench contract; IGG_BENCH_STRICT=1 turns a regression into rc=1.
    from implicitglobalgrid_tpu.telemetry import perfdb_add, perfdb_check

    hist = "PERF_HISTORY.jsonl"
    gate = perfdb_check(hist, results)
    perfdb_add(hist, results)
    results.append(bench_util.emit({
        "metric": "perfdb_gate_ok",
        "value": 1.0 if gate["ok"] else 0.0,
        "unit": "bool (1 = no metric regressed vs the trailing window)",
        "history_runs": gate["history_runs"],
        "checked": gate["checked"],
        "regressions": [r["metric"] for r in gate["regressions"]],
        "improvements": [r["metric"] for r in gate["improvements"]],
    }))

    with open("BENCH_ALL.json", "w") as f:
        json.dump(results, f, indent=1)
    lint_failed = not ruff_missing and lint.returncode != 0
    if (not gate["ok"] or lint_failed or not coalesce8_ok
            or not ensemble_ok or not tuned_ok or not reshard_ok
            or not staged_ok or not serve_ok or not live_ok
            or not autoscale_ok or not tracing_ok) \
            and os.environ.get("IGG_BENCH_STRICT") == "1":
        sys.exit(1)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("bench_all", "suite")
