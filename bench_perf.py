"""Benchmark: the performance oracle — overhead gate + model fidelity.

Two legs (both land in BENCH_ALL.json via bench_all.py):

- ``perf_overhead_frac`` (gated < 2%): what the live drift detector
  costs the step loop. The per-boundary work is one
  `PerfWatch.observe` call — a handful of float ops on a rolling window
  plus 2-4 gauge writes — so, like the telemetry leg, the gated figure
  is DETERMINISTIC accounting: the microbenchmarked per-observe cost
  times the boundaries a supervised run crosses, over the run's wall
  time (expect per-boundary arithmetic only, orders of magnitude under
  the gate).

- ``perf_model_ratio_*`` (recorded, acceptance: within 2x on the CPU
  mesh): measured vs modeled per-step time for the diffusion3D and
  acoustic3D bench configs — the model calibrated on THIS mesh
  (`telemetry.calibrate_machine`), the measurement the same two-point
  steady-state slope `bench_all.py` uses. Three INDEPENDENT calibrations
  back each row: the modeled time is their median prediction, the
  roofline verdict (``bound``) is the majority vote, and
  ``bound_stable`` says a majority existed — a single contention burst
  during one calibration cannot flip the recorded classification.

Usage: python bench_perf.py          (real chip)
       python bench_perf.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import os
import sys

import bench_util


def perf_overhead_rows(nx: int, nt_chunk: int, n_chunks: int = 3):
    """Drift-detector overhead on the CURRENT grid (caller owns
    init/finalize): deterministic per-boundary accounting vs run time."""
    import statistics
    import time

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    state = {"T": T, "Cp": Cp}
    nt = nt_chunk * n_chunks
    key = ("bench_perf", nx, nt_chunk)

    def run():
        igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key)

    run()  # warm: compile once
    times = []
    for _ in range(5):
        igg.tic()
        run()
        times.append(igg.toc())
    t_run = statistics.median(times)

    # the per-boundary cost: one observe() on a warm window, gauges incl.
    watch = igg.PerfWatch(window=16, zmax=4.0, model_step_s=1e-3)
    n_probe = 5000
    t0 = time.monotonic()
    for i in range(n_probe):
        watch.observe(chunk=i, step_begin=0, step_end=nt_chunk,
                      n=nt_chunk, exec_s=0.01)
    per_observe_s = (time.monotonic() - t0) / n_probe
    frac = per_observe_s * n_chunks / t_run
    return [{
        "metric": "perf_overhead_frac",
        "value": frac,
        "unit": "fraction of run time, deterministic per-boundary "
                "accounting (target < 0.02)",
        "target": 0.02,
        "nt": nt, "nt_chunk": nt_chunk,
        "per_observe_s": per_observe_s,
        "run_s_median": t_run,
        "note": "one PerfWatch.observe (rolling median+MAD + igg_perf_* "
                "gauge writes) per chunk boundary — the drift detector's "
                "whole step-loop footprint",
    }]


def model_ratio_rows(dims, cpu: bool):
    """Measured/modeled per-step ratio rows for the diffusion3D and
    acoustic3D bench configs, on self-initialized grids over ``dims``.
    Calibrates THREE times so the rows witness classification stability
    (majority-vote verdict, median model time)."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, init_diffusion3d, run_acoustic, run_diffusion,
    )

    rows = []
    profiles = []

    def measured_step_s(run_fn, nt):
        # min-of-3 over longer windows: the SAME least-contended estimate
        # the calibration's min-of-reps produces, so the ratio compares
        # like with like on a shared box
        c1 = max(2, nt // 5)
        return bench_util.two_point(lambda c: run_fn(c, c), c1, 3 * c1,
                                    reps=3)

    # --- diffusion3D f32 (the flagship config) -------------------------
    nx, nt = (48, 50) if cpu else (256, 1000)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        for _ in range(3):  # independent calibrations (majority vote)
            profiles.append(igg.calibrate_machine())
        T, Cp, p = init_diffusion3d(dtype=np.float32)
        t_step = measured_step_s(
            lambda n, c: run_diffusion(T, Cp, p, n, nt_chunk=c), nt)
        preds = [igg.predict_step("diffusion3d", (T, Cp), profile=pr)
                 for pr in profiles]
        rows.append(_ratio_row("diffusion3D_f32", t_step, preds))
    finally:
        igg.finalize_global_grid()

    # --- acoustic3D with overlap ---------------------------------------
    nxa, nta = (32, 30) if cpu else (192, 600)
    igg.init_global_grid(nxa, nxa, nxa, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        state, p = init_acoustic3d(dtype=np.float32, overlap=True)
        t_step = measured_step_s(
            lambda n, c: run_acoustic(state, p, n, nt_chunk=c), nta)
        preds = [igg.predict_step("acoustic3d", state, profile=pr,
                                  overlap=True)
                 for pr in profiles]
        rows.append(_ratio_row("acoustic3D_overlap_f32", t_step, preds))
    finally:
        igg.finalize_global_grid()
    return rows


def _ratio_row(tag: str, measured_s: float, preds: list) -> dict:
    """One BENCH_ALL row from N independent calibrations' predictions:
    median model time (robust to one contended calibration), majority
    bound verdict, ``bound_stable`` = a majority existed."""
    import statistics
    from collections import Counter

    model_s = statistics.median(p["step_s"] for p in preds)
    ratio = measured_s / model_s if model_s else None
    bounds = [p["bound"] for p in preds]
    (bound, votes), = Counter(bounds).most_common(1)
    lead = next(p for p in preds if p["bound"] == bound)
    return {
        "metric": f"perf_model_ratio_{tag}",
        "value": ratio,
        "unit": "measured / modeled per-step time (acceptance: within "
                "2x, i.e. 0.5 <= ratio <= 2)",
        "measured_step_s": measured_s,
        "model_step_s": model_s,
        "bound": bound,
        "bound_detail": lead["bound_detail"],
        "bound_votes": bounds,
        "bound_stable": votes > len(bounds) // 2,
        "profile_source": lead["profile_source"],
        "within_2x": (ratio is not None and 0.5 <= ratio <= 2.0),
    }


def run_perf_overhead(dims, cpu: bool):
    """The canonical overhead leg: init its own grid over ``dims``,
    measure, finalize, return the rows (shared with `bench_all.py`)."""
    import implicitglobalgrid_tpu as igg

    nx, nt_chunk = (32, 60) if cpu else (256, 200)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return perf_overhead_rows(nx, nt_chunk)
    finally:
        igg.finalize_global_grid()


def run_model_ratio(dims, cpu: bool):
    """The canonical model-fidelity leg (shared with `bench_all.py`)."""
    return model_ratio_rows(dims, cpu)


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_perf_overhead(dims, cpu):
        bench_util.emit(row)
    for row in run_model_ratio(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("perf_overhead_frac", "fraction")
