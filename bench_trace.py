"""Benchmark: the mesh-observability pipeline's cost envelope (ISSUE 5).

Two gated figures:

- ``trace_pipeline_s_10k_events``: wall time to aggregate two synthetic
  per-process flight streams totalling ~10k events (clock alignment +
  run-id/seq validation), run the straggler analyzer, and export the
  Chrome/Perfetto trace JSON. All pure post-hoc host work — the gate
  (< 5 s) keeps the operator loop ("the run just died, what happened")
  interactive even for long flights.
- ``metrics_server_off_overhead_frac``: the step-loop cost the mesh layer
  adds to a supervised run when the live endpoint is NOT enabled — the
  per-chunk-boundary heartbeat gauge stamps are the ONLY addition
  (serving runs on its own thread and only when opted in via
  ``metrics_port``). Deterministic accounting like bench_telemetry.py:
  the microbenchmarked per-heartbeat cost times the boundaries a real
  run crosses, over the run's median wall time — target < 2% (measures
  orders of magnitude under; "zero" at the gate's resolution). The row
  also asserts no server thread exists when ``metrics_port`` is unset.

Usage: python bench_trace.py          (real chip)
       python bench_trace.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import time

import bench_util


def _write_synth_stream(path, proc, n_chunks, *, events_between=3,
                        run_id="bench"):
    """One synthetic per-process flight JSONL: a barrier-consistent chunk
    schedule plus interleaved halo/snapshot events, dense enough that two
    processes total ~10k events at the default sizing."""
    t = 1000.0 + 0.001 * proc
    seq = 0
    with open(path, "w") as f:
        def ev(kind, **kw):
            nonlocal seq
            f.write(json.dumps({"t": t, "kind": kind, "run": run_id,
                                "pid": 10 + proc, "proc": proc,
                                "seq": seq, **kw}) + "\n")
            seq += 1

        ev("recorder_open", wall=2000.0 + 0.01 * proc, version=1)
        ev("run_begin", nt=n_chunks * 10, nt_chunk=10, names=["T"])
        for c in range(n_chunks):
            start = t + (0.002 if proc else 0.0)
            t += 0.01
            for i in range(events_between):
                ev("halo_exchange", fields=1, ppermutes=6,
                   wire_bytes=4096, local_copy_bytes=0)
            ev("snapshot_write", step=(c + 1) * 10, dur_s=0.001,
               nbytes=1 << 16, queue_depth=1, path="x")
            ev("chunk", chunk=c, step_begin=c * 10, step_end=(c + 1) * 10,
               n=10, ok=True, reasons=[], build_s=0.001,
               exec_s=t - start)
        ev("run_end", completed=n_chunks * 10, chunks=n_chunks)
        ev("recorder_close")
    return seq


def trace_pipeline_rows(n_events_target: int = 10_000):
    """Aggregate + analyze + export wall time on a synthetic two-process
    stream of ~``n_events_target`` events (host-only; no grid)."""
    import implicitglobalgrid_tpu as igg

    tmp = tempfile.mkdtemp(prefix="igg_bench_trace_")
    # each chunk contributes (events_between + 2) records per process,
    # plus a handful of run-level records
    per_chunk = 3 + 2
    n_chunks = max(1, n_events_target // (2 * per_chunk))
    total = 0
    for proc in range(2):
        total += _write_synth_stream(
            os.path.join(tmp, f"flight_p{proc}.jsonl"), proc, n_chunks)

    t0 = time.monotonic()
    agg = igg.aggregate_flight(tmp)
    t_agg = time.monotonic() - t0
    t0 = time.monotonic()
    rep = igg.straggler_report(agg)
    t_strag = time.monotonic() - t0
    out = os.path.join(tmp, "trace.json")
    t0 = time.monotonic()
    igg.export_chrome_trace(agg, out)
    t_export = time.monotonic() - t0
    assert rep["summary"]["chunks"] == n_chunks
    assert os.path.getsize(out) > 0

    return [{
        "metric": "trace_pipeline_s_10k_events",
        "value": t_agg + t_strag + t_export,
        "unit": "seconds to aggregate+analyze+export (target < 5)",
        "target": 5.0,
        "events": total,
        "aggregate_s": t_agg,
        "stragglers_s": t_strag,
        "export_s": t_export,
        "trace_bytes": os.path.getsize(out),
    }]


def heartbeat_overhead_rows(nx: int, nt_chunk: int, n_chunks: int = 3,
                            reps: int = 5):
    """Deterministic accounting of the server-off step-loop addition (the
    per-boundary heartbeat stamps) on the CURRENT grid — the
    bench_telemetry.py estimator, scoped to the mesh layer."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.telemetry import metrics_server
    from implicitglobalgrid_tpu.telemetry.hooks import note_heartbeat

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    state = {"T": T, "Cp": Cp}
    nt = nt_chunk * n_chunks
    key = ("bench_trace", nx, nt_chunk)

    def run():
        igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key)

    run()  # warm compile
    assert metrics_server() is None  # metrics_port unset -> no server
    times = []
    for _ in range(reps):
        igg.tic()
        run()
        times.append(igg.toc())
    assert metrics_server() is None

    n_probe = 20_000
    t0 = time.monotonic()
    for i in range(n_probe):
        note_heartbeat(i)
    per_call_s = (time.monotonic() - t0) / n_probe
    # boundaries per run: one per loop iteration + the final run_end stamp
    boundaries = n_chunks + 1
    t_med = statistics.median(times)
    return [{
        "metric": "metrics_server_off_overhead_frac",
        "value": per_call_s * boundaries / t_med,
        "unit": "fraction of run time, deterministic per-heartbeat "
                "accounting (target < 0.02)",
        "target": 0.02,
        "nt": nt,
        "nt_chunk": nt_chunk,
        "per_heartbeat_s": per_call_s,
        "boundaries_per_run": boundaries,
        "run_s_median": t_med,
        "note": "metrics_port unset: no server thread exists (asserted); "
                "the per-boundary heartbeat gauge stamps are the only "
                "step-loop addition of the mesh-observability layer",
    }]


def run_trace_overhead(dims, cpu: bool):
    """The canonical leg: host-side pipeline timing plus the server-off
    step-loop accounting on a grid over ``dims``. Shared by this script's
    __main__ and `bench_all.py` so the config stays in ONE place."""
    import implicitglobalgrid_tpu as igg

    rows = trace_pipeline_rows()
    nx, nt_chunk = (32, 60) if cpu else (256, 200)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        rows += heartbeat_overhead_rows(nx, nt_chunk)
    finally:
        igg.finalize_global_grid()
    return rows


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_trace_overhead(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("trace_pipeline_s_10k_events",
                                    "seconds")
