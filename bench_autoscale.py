"""Benchmark: closed-loop autoscaler decision cost and reactivity.

The autoscaler (`service.Autoscaler`) runs at every slice boundary; its
steady-state cost must be invisible next to the chunk work the slice
carried, and the loop must actually MOVE the mesh when the signals say
so. Two rows, shared with `bench_all.py`:

- ``autoscale_decision_s``: MEDIAN per-boundary policy cost (signal
  read, streak/cooldown arithmetic) from the engine's own
  `perf_counter` accounting (``decision_s_recent``). Gated as a
  fraction of the median journal ``slice`` duration: target < 2%
  (ISSUE 19 acceptance — same bar as the scheduler's own bookkeeping in
  bench_service.py). The rare boundary where a matured streak PRICES
  candidates (host-side grid swaps + `predict_step`/`predict_reshard`)
  rides along as ``priced_max_s`` — that cost is paid once per move and
  is already amortized into the break-even verdict that justifies it,
  so it is reported, not gated.
- ``autoscale_reacts_ok``: absolute gate — in the same run, the starved
  high-priority tenant must have been GROWN and the idle one SHRUNK
  with no operator input, every applied move carrying the full journal
  chain (``autoscale_decision`` -> ... -> ``job_resized``). 1.0 = the
  loop closed; rc 1 under IGG_BENCH_STRICT=1 otherwise.

The drill is the test suite's (tests/test_autoscale.py): ``hot`` is a
compute-dominated single-device job with a deadline and ``grow_slack_s``
set above any live slack, ``idle`` spreads a small grid over four
devices it does not need.

Usage: python bench_autoscale.py          (real chip)
       python bench_autoscale.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import sys

import bench_util


def run_autoscale_rows(dims, cpu: bool):
    """The canonical leg, shared with `bench_all.py` so the config lives
    in ONE place. ``dims`` is unused (the drill owns its per-job
    geometries — the point IS that they move) but kept for the shared
    leg signature."""
    import os
    import statistics
    import tempfile

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.runtime import RunSpec
    from implicitglobalgrid_tpu.service import (
        AutoscalePolicy, JobSpec, MeshScheduler, ScaleBounds,
        builtin_setup, explain_autoscale,
    )
    from implicitglobalgrid_tpu.telemetry import read_flight_events

    nx_hot = 66 if cpu else 130
    grid_hot = dict(nx=nx_hot, ny=nx_hot, nz=nx_hot, dimx=1, dimy=1,
                    dimz=1, overlaps=(2, 2, 2))
    grid_idle = dict(nx=18, ny=18, nz=18, dimx=2, dimy=2, dimz=1,
                     overlaps=(2, 2, 2))
    pol = AutoscalePolicy(grow_slack_s=1e9, shrink_queue_pending=1,
                          hysteresis_slices=2, cooldown_slices=2,
                          bounds={"hot": ScaleBounds(1, 4),
                                  "idle": ScaleBounds(1, 8)})

    d = tempfile.mkdtemp(prefix="bench_autoscale_")
    with MeshScheduler(policy="fair", flight_dir=d,
                       autoscale=pol) as sched:
        sched.submit(JobSpec(
            name="hot", setup=builtin_setup("diffusion3d"),
            model="diffusion3d", nt=60, grid=grid_hot,
            run=RunSpec(nt_chunk=5, key=("bench_as", "hot")),
            priority=2, deadline_s=600.0))
        sched.submit(JobSpec(
            name="idle", setup=builtin_setup("diffusion3d"),
            model="diffusion3d", nt=60, grid=grid_idle,
            run=RunSpec(nt_chunk=5, key=("bench_as", "idle"))))
        sched.run()
        states = sched.status()["states"]
        a = sched.autoscaler
        samples = list(a.decision_s_recent)
        decision_s = statistics.median(samples)
        evaluations, filed = a.evaluations, a.moves_filed
        hot_dims = tuple(int(x) for x in sched.job("hot").gg.dims)
        idle_dims = tuple(int(x) for x in sched.job("idle").gg.dims)
    if states != {"done": 2}:
        raise RuntimeError(
            f"bench_autoscale: jobs did not finish: {states}")

    # warm slice durations anchor the gate (first slice per job is the
    # cold compile — excluded, as in bench_service.py)
    slices: dict = {}
    for e in read_flight_events(os.path.join(d, "scheduler.jsonl")):
        if e.get("kind") == "slice":
            slices.setdefault(e["job"], []).append(float(e["dur_s"]))
    warm = [s for durs in slices.values() for s in durs[1:]]
    slice_s = statistics.median(warm)

    rec = explain_autoscale(d)
    applied = [m for m in rec["moves"] if m["applied"]]
    grew = any(m["job"] == "hot" and m["action"] == "grow"
               for m in applied)
    shrank = any(m["job"] == "idle" and m["action"] == "shrink"
                 for m in applied)
    chains_ok = all(m["chain"][0] == "autoscale_decision"
                    and "job_resized" in m["chain"] for m in applied)
    reacts = grew and shrank and chains_ok \
        and hot_dims == (4, 1, 1) and idle_dims == (1, 1, 1)

    return [{
        "metric": "autoscale_decision_s",
        "value": decision_s,
        "unit": "s per boundary evaluation, median (engine accounting)",
        "frac_of_slice": decision_s / slice_s,
        "target_frac": 0.02,
        "slice_s_median": slice_s,
        # the pricing boundaries (one per move, amortized by the
        # break-even verdict) are visible, not gated
        "priced_max_s": max(samples),
        "mean_s": statistics.mean(samples),
        "evaluations": evaluations,
        "moves_filed": filed,
    }, {
        "metric": "autoscale_reacts_ok",
        "value": 1.0 if reacts else 0.0,
        "unit": "1 = starved tenant grown AND idle tenant shrunk, "
                "chains journaled (target >= 1)",
        "target": 1.0,
        "hot_dims": list(hot_dims),
        "idle_dims": list(idle_dims),
        "applied_moves": len(applied),
        "decisions": rec["decisions"],
    }]


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_autoscale_rows(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("autoscale_decision_s", "seconds")
