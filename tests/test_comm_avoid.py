"""Communication-avoiding deep-halo stepping (`comm_every=k`): the interior
trajectory must be BIT-IDENTICAL to the exchange-every-step scheme — the
skipped halo-band cells are exactly the cells the k-wide exchange
overwrites, so the masked sub-steps (`diffusion._fresh_mask`) change the
collective cadence, never the numbers."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)


def _stacked_from_global_index(n, k, dims, periods, fn):
    """Host-built stacked array whose every cell is ``fn(gx, gy, gz)`` of
    its INTEGER global-grid index — the same float lands at the same
    physical position no matter which overlap width maps it, so two
    decompositions of one implicit grid start bit-identical.
    (Coordinate-based ICs cannot guarantee this: different
    ``ix + coord*(n-ol)`` float groupings of one global position round
    ~1 ulp apart, especially through the periodic wrap.)

    Per dim: ``g = ix + b*(n-ol)``; periodic dims shift by ONE ghost cell
    (the gather/x_g convention, reference `tools.jl:102-104` — independent
    of halowidth) and WRAP ``g`` mod the global size, so halo cells carry
    exactly the values of the interior cells they mirror."""
    ol = 2 * k
    n = tuple(n) if isinstance(n, (tuple, list)) else (n,) * 3
    S = np.zeros(tuple(d * m for d, m in zip(dims, n)))

    def gidx(b, d):
        g = np.arange(n[d]) + b * (n[d] - ol)
        if periods[d]:
            g = (g - 1) % (dims[d] * (n[d] - ol))
        return g

    for bx in range(dims[0]):
        for by in range(dims[1]):
            for bz in range(dims[2]):
                S[bx * n[0]:(bx + 1) * n[0], by * n[1]:(by + 1) * n[1],
                  bz * n[2]:(bz + 1) * n[2]] = fn(
                      gidx(bx, 0)[:, None, None],
                      gidx(by, 1)[None, :, None],
                      gidx(bz, 2)[None, None, :])
    return S


def _run(local_n, k, nt, periods, dims=(2, 2, 2)):
    """Run nt steps with exchange cadence k (halowidth k, overlap 2k)."""
    ln = (tuple(local_n) if isinstance(local_n, (tuple, list))
          else (local_n,) * 3)
    igg.init_global_grid(ln[0], ln[1], ln[2],
                         dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2],
                         overlaps=(2 * k,) * 3, halowidths=(k,) * 3,
                         quiet=True)
    try:
        _, _, p = init_diffusion3d(dtype=np.float64, comm_every=k)
        T = igg.device_put_g(_stacked_from_global_index(
            ln, k, dims, periods,
            lambda x, y, z: 100 * np.exp(-((x / 7.0 - 1) ** 2)
                                         - ((y / 5.0 - 1) ** 2)
                                         - ((z / 6.0 - 1) ** 2))))
        Cp = igg.device_put_g(_stacked_from_global_index(
            ln, k, dims, periods,
            lambda x, y, z: 1.0 + np.exp(-((x / 9.0 - 1) ** 2)
                                         - ((y / 8.0 - 1) ** 2)
                                         - ((z / 7.0 - 1) ** 2))))
        out = run_diffusion(T, Cp, p, nt, nt_chunk=max(k, 4 * k))
        return np.asarray(igg.gather_interior(out))
    finally:
        igg.finalize_global_grid()


# local sizes giving the SAME implicit global grid for k=1 (ol=2) and
# k=2 (ol=4): non-periodic  dims*(n-ol)+ol,  periodic  dims*(n-ol)
@pytest.mark.parametrize("periods,n1,n2", [
    ((0, 0, 0), 8, 9),            # global 14³ both
    ((1, 1, 1), 8, 10),           # global 12³ both
    ((1, 0, 0), 8, (10, 9, 9)),   # mixed: x periodic (12), y/z walls (14)
])
def test_comm_every2_bitwise_equal(periods, n1, n2):
    nt = 12
    a = _run(n1, 1, nt, periods)
    b = _run(n2, 2, nt, periods)
    # mixed-period case: per-dim global sizes differ between formulas
    assert a.shape == b.shape
    assert np.array_equal(a, b), (
        f"max diff {np.max(np.abs(a - b))} — deep-halo trajectory diverged")


def test_comm_every2_2d_bitwise_equal():
    """The 2-D step shares `_fresh_mask`/`make_run_deep` — same bitwise
    contract on a 2x2 decomposition (4 of the pool's 8 devices)."""
    from implicitglobalgrid_tpu.models import init_diffusion2d

    def run2d(n, k, nt=8):
        igg.init_global_grid(n, n, 1, dimx=2, dimy=2, dimz=0,
                             periodx=1, periody=1,
                             overlaps=(2 * k, 2 * k, 2 * k),
                             halowidths=(k, k, k), quiet=True)
        try:
            import dataclasses

            _, _, p = init_diffusion2d(dtype=np.float64)
            p = dataclasses.replace(p, comm_every=k)
            S3 = _stacked_from_global_index((n, n, 2), k, (2, 2, 1),
                                            (1, 1, 0),
                                            lambda x, y, z: 100 * np.exp(
                                                -((x / 7.0 - 1) ** 2)
                                                - ((y / 5.0 - 1) ** 2)))
            T = igg.device_put_g(S3[:, :, 0])
            Cp = igg.device_put_g(np.full_like(S3[:, :, 0], 2.0))
            out = run_diffusion(T, Cp, p, nt, nt_chunk=nt)
            return np.asarray(igg.gather_interior(out))
        finally:
            igg.finalize_global_grid()

    a = run2d(8, 1)
    b = run2d(10, 2)
    assert a.shape == b.shape
    assert np.array_equal(a, b)


def test_comm_every3_bitwise_equal():
    # k=3 (halowidth 3, overlap 6): three masked sub-steps per exchange;
    # global 12³ needs local 2*(n-6)=12 -> n=12
    a = _run(8, 1, 12, (1, 1, 1))
    b = _run(12, 3, 12, (1, 1, 1))
    assert np.array_equal(a, b)


@pytest.mark.parametrize("periods,n1,n2", [
    ((1, 1, 1), 8, 10),           # fully periodic
    ((0, 0, 0), 8, 9),            # walls: boundary faces never update
    ((1, 0, 0), 8, (10, 9, 9)),   # mixed
])
def test_comm_every2_acoustic_bitwise_equal(periods, n1, n2):
    """Deep halos for the staggered LEAPFROG: V retreats j (base offset 1
    in its staggered dim), P retreats j+1 — one 4-field 2-wide exchange
    per 2 steps must reproduce the per-step-exchange trajectory exactly,
    for all four fields, on every boundary topology."""
    from implicitglobalgrid_tpu.models import init_acoustic3d, run_acoustic

    def run(n, k, nt=8):
        ln = tuple(n) if isinstance(n, (tuple, list)) else (n,) * 3
        igg.init_global_grid(ln[0], ln[1], ln[2], dimx=2, dimy=2, dimz=2,
                             periodx=periods[0], periody=periods[1],
                             periodz=periods[2],
                             overlaps=(2 * k,) * 3, halowidths=(k,) * 3,
                             quiet=True)
        try:
            state, p = init_acoustic3d(dtype=np.float64, comm_every=k)
            P = igg.device_put_g(_stacked_from_global_index(
                ln, k, (2, 2, 2), periods,
                lambda x, y, z: np.exp(-((x / 7.0 - 1) ** 2)
                                       - ((y / 5.0 - 1) ** 2)
                                       - ((z / 6.0 - 1) ** 2))))
            state = (P.astype(state[0].dtype), *state[1:])  # V stays 0
            out = run_acoustic(state, p, nt, nt_chunk=nt)
            return [np.asarray(igg.gather_interior(f)) for f in out]
        finally:
            igg.finalize_global_grid()

    a = run(n1, 1)
    b = run(n2, 2)
    for fa, fb, name in zip(a, b, ("P", "Vx", "Vy", "Vz")):
        assert fa.shape == fb.shape, (name, fa.shape, fb.shape)
        assert np.array_equal(fa, fb), (
            f"{name} diverged: max {np.max(np.abs(fa - fb))}")


@pytest.mark.parametrize("periods,n1,n2", [
    # tier-1 budget (ISSUE 8 trim): one Stokes deep-halo flavor is the
    # fast representative; the periodic deep-grid flavor (a second ~6 s
    # compile) rides the slow tier
    pytest.param((1, 1, 1), 9, 15, marks=pytest.mark.slow),
    ((0, 0, 0), 9, 12),   # global 16³ both
])
def test_comm_every2_stokes_equal(periods, n1, n2):
    """Deep halos for the PT STOKES iteration: dependency radius 2 per
    iteration (V ← stresses ← V), so k=2 runs on a halowidth-4 grid and
    the super-step exchange carries 7 fields incl. the damped dV state.

    Contract (see `StokesParams` docstring): all evolving fields agree
    to <= 1e-12 relative (measured ~1e-17..1e-16). The residual is ~1
    ulp at a handful of vector-lane-boundary positions on XLA:CPU — the
    masked scheme substitutes a locally computed cell for the exchanged
    copy of the same physical cell, which the CPU backend's loop
    epilogues round 1 ulp apart on this model's long expression chain
    (the k=1 degenerate deep runner IS bit-exact vs the base scheme, and
    one super-step pair keeps P bit-exact, so the scheme itself is
    sound; the ulps feed P over longer horizons)."""
    from implicitglobalgrid_tpu.models import init_stokes3d, run_stokes

    def run(n, k, nt=6):
        hw = 2 * k if k > 1 else 1
        igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2,
                             periodx=periods[0], periody=periods[1],
                             periodz=periods[2],
                             overlaps=(2 * hw,) * 3, halowidths=(hw,) * 3,
                             quiet=True)
        try:
            state, p = init_stokes3d(dtype=np.float64, comm_every=k)
            rhog = igg.device_put_g(_stacked_from_global_index(
                n, hw, (2, 2, 2), periods,
                lambda x, y, z: np.exp(-((x / 6.0 - 1) ** 2)
                                       - ((y / 5.0 - 1) ** 2)
                                       - ((z / 7.0 - 1) ** 2))))
            state = (*state[:7], rhog.astype(state[7].dtype))
            out = run_stokes(state, p, nt, nt_chunk=nt)
            return [np.asarray(igg.gather_interior(f)) for f in out]
        finally:
            igg.finalize_global_grid()

    a = run(n1, 1)
    b = run(n2, 2)
    names = ("P", "Vx", "Vy", "Vz", "dVx", "dVy", "dVz", "rhog")
    for fa, fb, name in zip(a, b, names):
        assert fa.shape == fb.shape, (name, fa.shape, fb.shape)
        if name.startswith("dV"):
            # dV's HALO copies are undefined state in the base scheme (it
            # never exchanges dV; they hold stale zeros) while the deep
            # scheme refreshes them — and the non-periodic gather keeps a
            # later block's halo copy at overlap positions, so gathered
            # dV is not comparable. Its interior-face values are
            # validated implicitly through V (V += dt_v*dV_i every
            # iteration).
            continue
        if name == "rhog":
            assert np.array_equal(fa, fb)
        else:
            scale = max(1e-30, np.abs(fa).max())
            rel = np.max(np.abs(fa - fb)) / scale
            assert rel < 1e-12, f"{name}: rel {rel:.2e} exceeds ulp budget"


def test_comm_every_validation():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        # halowidth 1 grid cannot carry a 2-deep exchange
        with pytest.raises(IncoherentArgumentError):
            run_diffusion(T, Cp, p, 4)
    finally:
        igg.finalize_global_grid()
    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        with pytest.raises(InvalidArgumentError):
            run_diffusion(T, Cp, p, 7)      # nt not a multiple of k
        with pytest.raises(InvalidArgumentError):
            run_diffusion(T, Cp, p, 4, impl="pallas")
        # the plain builders exchange every step: they must refuse the
        # cadence instead of silently ignoring it
        from implicitglobalgrid_tpu.models import make_run, make_step
        with pytest.raises(InvalidArgumentError):
            make_run(p, 2)
        with pytest.raises(InvalidArgumentError):
            make_step(p)
    finally:
        igg.finalize_global_grid()


def test_comm_every_freshness_bound():
    """An interior shard whose local size is below overlap + k would ship
    one-sub-step-stale send slabs — the deep runner must refuse."""
    igg.init_global_grid(5, 8, 8, dimx=3, dimy=1, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        with pytest.raises(IncoherentArgumentError):
            run_diffusion(T, Cp, p, 4)   # n_x=5 < ol+k=6
    finally:
        igg.finalize_global_grid()


def test_comm_every_halves_permutes():
    """The collective count per PHYSICAL step drops k-fold: audit the
    compiled super-step program — 6 permutes per super-step = 3 per
    physical step at k=2 (vs 6 at k=1)."""
    import jax

    from implicitglobalgrid_tpu.models import make_run_deep

    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        run = make_run_deep(p, 1)
        txt = jax.jit(run).lower(T, Cp).compile().as_text()
        n_perm = txt.count("collective-permute-start(")
        if n_perm == 0:  # compiler naming variant
            n_perm = txt.count(" collective-permute(")
        # ONE 2-wide exchange per super-step: one permute pair per axis
        assert n_perm == 6, f"expected 6 permutes per super-step, got {n_perm}"
    finally:
        igg.finalize_global_grid()
