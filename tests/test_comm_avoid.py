"""Communication-avoiding deep-halo stepping (`comm_every=k`): the interior
trajectory must be BIT-IDENTICAL to the exchange-every-step scheme — the
skipped halo-band cells are exactly the cells the k-wide exchange
overwrites, so the masked sub-steps (`diffusion._fresh_mask`) change the
collective cadence, never the numbers."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)


def _stacked_from_global_index(n, k, dims, periods, fn):
    """Host-built stacked array whose every cell is ``fn(gx, gy, gz)`` of
    its INTEGER global-grid index — the same float lands at the same
    physical position no matter which overlap width maps it, so two
    decompositions of one implicit grid start bit-identical.
    (Coordinate-based ICs cannot guarantee this: different
    ``ix + coord*(n-ol)`` float groupings of one global position round
    ~1 ulp apart, especially through the periodic wrap.)

    Per dim: ``g = ix + b*(n-ol)``; periodic dims shift by ONE ghost cell
    (the gather/x_g convention, reference `tools.jl:102-104` — independent
    of halowidth) and WRAP ``g`` mod the global size, so halo cells carry
    exactly the values of the interior cells they mirror."""
    ol = 2 * k
    n = tuple(n) if isinstance(n, (tuple, list)) else (n,) * 3
    S = np.zeros(tuple(d * m for d, m in zip(dims, n)))

    def gidx(b, d):
        g = np.arange(n[d]) + b * (n[d] - ol)
        if periods[d]:
            g = (g - 1) % (dims[d] * (n[d] - ol))
        return g

    for bx in range(dims[0]):
        for by in range(dims[1]):
            for bz in range(dims[2]):
                S[bx * n[0]:(bx + 1) * n[0], by * n[1]:(by + 1) * n[1],
                  bz * n[2]:(bz + 1) * n[2]] = fn(
                      gidx(bx, 0)[:, None, None],
                      gidx(by, 1)[None, :, None],
                      gidx(bz, 2)[None, None, :])
    return S


def _run(local_n, k, nt, periods, dims=(2, 2, 2)):
    """Run nt steps with exchange cadence k (halowidth k, overlap 2k)."""
    ln = (tuple(local_n) if isinstance(local_n, (tuple, list))
          else (local_n,) * 3)
    igg.init_global_grid(ln[0], ln[1], ln[2],
                         dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2],
                         overlaps=(2 * k,) * 3, halowidths=(k,) * 3,
                         quiet=True)
    try:
        _, _, p = init_diffusion3d(dtype=np.float64, comm_every=k)
        T = igg.device_put_g(_stacked_from_global_index(
            ln, k, dims, periods,
            lambda x, y, z: 100 * np.exp(-((x / 7.0 - 1) ** 2)
                                         - ((y / 5.0 - 1) ** 2)
                                         - ((z / 6.0 - 1) ** 2))))
        Cp = igg.device_put_g(_stacked_from_global_index(
            ln, k, dims, periods,
            lambda x, y, z: 1.0 + np.exp(-((x / 9.0 - 1) ** 2)
                                         - ((y / 8.0 - 1) ** 2)
                                         - ((z / 7.0 - 1) ** 2))))
        out = run_diffusion(T, Cp, p, nt, nt_chunk=max(k, 4 * k))
        return np.asarray(igg.gather_interior(out))
    finally:
        igg.finalize_global_grid()


# local sizes giving the SAME implicit global grid for k=1 (ol=2) and
# k=2 (ol=4): non-periodic  dims*(n-ol)+ol,  periodic  dims*(n-ol)
@pytest.mark.parametrize("periods,n1,n2", [
    ((0, 0, 0), 8, 9),            # global 14³ both
    ((1, 1, 1), 8, 10),           # global 12³ both
    ((1, 0, 0), 8, (10, 9, 9)),   # mixed: x periodic (12), y/z walls (14)
])
def test_comm_every2_bitwise_equal(periods, n1, n2):
    nt = 12
    a = _run(n1, 1, nt, periods)
    b = _run(n2, 2, nt, periods)
    # mixed-period case: per-dim global sizes differ between formulas
    assert a.shape == b.shape
    assert np.array_equal(a, b), (
        f"max diff {np.max(np.abs(a - b))} — deep-halo trajectory diverged")


def test_comm_every2_2d_bitwise_equal():
    """The 2-D step shares `_fresh_mask`/`make_run_deep` — same bitwise
    contract on a 2x2 decomposition (4 of the pool's 8 devices)."""
    from implicitglobalgrid_tpu.models import init_diffusion2d

    def run2d(n, k, nt=8):
        igg.init_global_grid(n, n, 1, dimx=2, dimy=2, dimz=0,
                             periodx=1, periody=1,
                             overlaps=(2 * k, 2 * k, 2 * k),
                             halowidths=(k, k, k), quiet=True)
        try:
            import dataclasses

            _, _, p = init_diffusion2d(dtype=np.float64)
            p = dataclasses.replace(p, comm_every=k)
            S3 = _stacked_from_global_index((n, n, 2), k, (2, 2, 1),
                                            (1, 1, 0),
                                            lambda x, y, z: 100 * np.exp(
                                                -((x / 7.0 - 1) ** 2)
                                                - ((y / 5.0 - 1) ** 2)))
            T = igg.device_put_g(S3[:, :, 0])
            Cp = igg.device_put_g(np.full_like(S3[:, :, 0], 2.0))
            out = run_diffusion(T, Cp, p, nt, nt_chunk=nt)
            return np.asarray(igg.gather_interior(out))
        finally:
            igg.finalize_global_grid()

    a = run2d(8, 1)
    b = run2d(10, 2)
    assert a.shape == b.shape
    assert np.array_equal(a, b)


def test_comm_every3_bitwise_equal():
    # k=3 (halowidth 3, overlap 6): three masked sub-steps per exchange;
    # global 12³ needs local 2*(n-6)=12 -> n=12
    a = _run(8, 1, 12, (1, 1, 1))
    b = _run(12, 3, 12, (1, 1, 1))
    assert np.array_equal(a, b)


@pytest.mark.parametrize("periods,n1,n2", [
    ((1, 1, 1), 8, 10),           # fully periodic
    ((0, 0, 0), 8, 9),            # walls: boundary faces never update
    ((1, 0, 0), 8, (10, 9, 9)),   # mixed
])
def test_comm_every2_acoustic_bitwise_equal(periods, n1, n2):
    """Deep halos for the staggered LEAPFROG: V retreats j (base offset 1
    in its staggered dim), P retreats j+1 — one 4-field 2-wide exchange
    per 2 steps must reproduce the per-step-exchange trajectory exactly,
    for all four fields, on every boundary topology."""
    from implicitglobalgrid_tpu.models import init_acoustic3d, run_acoustic

    def run(n, k, nt=8):
        ln = tuple(n) if isinstance(n, (tuple, list)) else (n,) * 3
        igg.init_global_grid(ln[0], ln[1], ln[2], dimx=2, dimy=2, dimz=2,
                             periodx=periods[0], periody=periods[1],
                             periodz=periods[2],
                             overlaps=(2 * k,) * 3, halowidths=(k,) * 3,
                             quiet=True)
        try:
            state, p = init_acoustic3d(dtype=np.float64, comm_every=k)
            P = igg.device_put_g(_stacked_from_global_index(
                ln, k, (2, 2, 2), periods,
                lambda x, y, z: np.exp(-((x / 7.0 - 1) ** 2)
                                       - ((y / 5.0 - 1) ** 2)
                                       - ((z / 6.0 - 1) ** 2))))
            state = (P.astype(state[0].dtype), *state[1:])  # V stays 0
            out = run_acoustic(state, p, nt, nt_chunk=nt)
            return [np.asarray(igg.gather_interior(f)) for f in out]
        finally:
            igg.finalize_global_grid()

    a = run(n1, 1)
    b = run(n2, 2)
    for fa, fb, name in zip(a, b, ("P", "Vx", "Vy", "Vz")):
        assert fa.shape == fb.shape, (name, fa.shape, fb.shape)
        assert np.array_equal(fa, fb), (
            f"{name} diverged: max {np.max(np.abs(fa - fb))}")


@pytest.mark.parametrize("periods,n1,n2", [
    # tier-1 budget (ISSUE 8 trim): one Stokes deep-halo flavor is the
    # fast representative; the periodic deep-grid flavor (a second ~6 s
    # compile) rides the slow tier
    pytest.param((1, 1, 1), 9, 15, marks=pytest.mark.slow),
    ((0, 0, 0), 9, 12),   # global 16³ both
])
def test_comm_every2_stokes_equal(periods, n1, n2):
    """Deep halos for the PT STOKES iteration: dependency radius 2 per
    iteration (V ← stresses ← V), so k=2 runs on a halowidth-4 grid and
    the super-step exchange carries 7 fields incl. the damped dV state.

    Contract (see `StokesParams` docstring): all evolving fields agree
    to <= 1e-12 relative (measured ~1e-17..1e-16). The residual is ~1
    ulp at a handful of vector-lane-boundary positions on XLA:CPU — the
    masked scheme substitutes a locally computed cell for the exchanged
    copy of the same physical cell, which the CPU backend's loop
    epilogues round 1 ulp apart on this model's long expression chain
    (the k=1 degenerate deep runner IS bit-exact vs the base scheme, and
    one super-step pair keeps P bit-exact, so the scheme itself is
    sound; the ulps feed P over longer horizons)."""
    from implicitglobalgrid_tpu.models import init_stokes3d, run_stokes

    def run(n, k, nt=6):
        hw = 2 * k if k > 1 else 1
        igg.init_global_grid(n, n, n, dimx=2, dimy=2, dimz=2,
                             periodx=periods[0], periody=periods[1],
                             periodz=periods[2],
                             overlaps=(2 * hw,) * 3, halowidths=(hw,) * 3,
                             quiet=True)
        try:
            state, p = init_stokes3d(dtype=np.float64, comm_every=k)
            rhog = igg.device_put_g(_stacked_from_global_index(
                n, hw, (2, 2, 2), periods,
                lambda x, y, z: np.exp(-((x / 6.0 - 1) ** 2)
                                       - ((y / 5.0 - 1) ** 2)
                                       - ((z / 7.0 - 1) ** 2))))
            state = (*state[:7], rhog.astype(state[7].dtype))
            out = run_stokes(state, p, nt, nt_chunk=nt)
            return [np.asarray(igg.gather_interior(f)) for f in out]
        finally:
            igg.finalize_global_grid()

    a = run(n1, 1)
    b = run(n2, 2)
    names = ("P", "Vx", "Vy", "Vz", "dVx", "dVy", "dVz", "rhog")
    for fa, fb, name in zip(a, b, names):
        assert fa.shape == fb.shape, (name, fa.shape, fb.shape)
        if name.startswith("dV"):
            # dV's HALO copies are undefined state in the base scheme (it
            # never exchanges dV; they hold stale zeros) while the deep
            # scheme refreshes them — and the non-periodic gather keeps a
            # later block's halo copy at overlap positions, so gathered
            # dV is not comparable. Its interior-face values are
            # validated implicitly through V (V += dt_v*dV_i every
            # iteration).
            continue
        if name == "rhog":
            assert np.array_equal(fa, fb)
        else:
            scale = max(1e-30, np.abs(fa).max())
            rel = np.max(np.abs(fa - fb)) / scale
            assert rel < 1e-12, f"{name}: rel {rel:.2e} exceeds ulp budget"


# ---------------------------------------------------------------------------
# per-axis cadence (ISSUE 13): each mesh axis exchanges at its own rate
# ---------------------------------------------------------------------------

def _stacked_per_dim(n, ol, dims, periods, fn):
    """`_stacked_from_global_index` with PER-DIM overlaps (per-axis
    cadence grids mix halo depths, so the mapping needs each dim's own
    ``n - ol``)."""
    S = np.zeros(tuple(d * m for d, m in zip(dims, n)))

    def gidx(b, d):
        g = np.arange(n[d]) + b * (n[d] - ol[d])
        if periods[d]:
            g = (g - 1) % (dims[d] * (n[d] - ol[d]))
        return g

    for bx in range(dims[0]):
        for by in range(dims[1]):
            for bz in range(dims[2]):
                S[bx * n[0]:(bx + 1) * n[0], by * n[1]:(by + 1) * n[1],
                  bz * n[2]:(bz + 1) * n[2]] = fn(
                      gidx(bx, 0)[:, None, None],
                      gidx(by, 1)[None, :, None],
                      gidx(bz, 2)[None, None, :])
    return S


def _run_per_axis(ln, comm_every, hw, nt, periods=(1, 1, 1)):
    """Diffusion run under a per-axis cadence grid (halowidths ``hw``,
    overlaps ``2*hw`` per dim), same implicit global grid convention as
    `_run` (per dim: ``n - 2*hw`` invariant)."""
    ol = tuple(2 * h for h in hw)
    igg.init_global_grid(ln[0], ln[1], ln[2], dimx=2, dimy=2, dimz=2,
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2],
                         overlaps=ol, halowidths=hw, quiet=True)
    try:
        _, _, p = init_diffusion3d(dtype=np.float64,
                                   comm_every=comm_every)
        T = igg.device_put_g(_stacked_per_dim(
            ln, ol, (2, 2, 2), periods,
            lambda x, y, z: 100 * np.exp(-((x / 7.0 - 1) ** 2)
                                         - ((y / 5.0 - 1) ** 2)
                                         - ((z / 6.0 - 1) ** 2))))
        Cp = igg.device_put_g(_stacked_per_dim(
            ln, ol, (2, 2, 2), periods,
            lambda x, y, z: 1.0 + np.exp(-((x / 9.0 - 1) ** 2)
                                         - ((y / 8.0 - 1) ** 2)
                                         - ((z / 7.0 - 1) ** 2))))
        out = run_diffusion(T, Cp, p, nt, nt_chunk=nt)
        return np.asarray(igg.gather_interior(out))
    finally:
        igg.finalize_global_grid()


def test_comm_every_per_axis_bitwise_equal():
    """MIXED cadence ``y:2,z:3`` (cycle 6: the y axis exchanges every 2
    sub-steps with 2-wide slabs, z every 3 with 3-wide, x every sub-step
    with 1-wide) reproduces the exchange-every-step trajectory
    BIT-EXACTLY — each axis's masked retreat advances at its own
    staleness and its k-wide exchange overwrites exactly the cells that
    axis's masks skipped."""
    nt = 6  # one full cadence cycle
    a = _run_per_axis((8, 8, 8), 1, (1, 1, 1), nt)
    b = _run_per_axis((8, 10, 12), "y:2,z:3", (1, 2, 3), nt)
    assert a.shape == b.shape
    assert np.array_equal(a, b), (
        f"max diff {np.max(np.abs(a - b))} — per-axis deep-halo "
        "trajectory diverged")


def test_comm_every_per_axis_spelling_matches_uniform():
    """The uniform-k path and the SAME cadence spelled per-axis build
    identical trajectories on one grid — the two spellings are one
    scheme, not two implementations."""
    nt = 4
    a = _run_per_axis((10, 10, 10), 2, (2, 2, 2), nt)
    b = _run_per_axis((10, 10, 10), "x:2,y:2,z:2", (2, 2, 2), nt)
    assert np.array_equal(a, b)


def test_comm_every_per_axis_ensemble():
    """ROADMAP ensemble rung d: the deep-halo cadence composes with the
    member axis on the XLA tier — every batched member's trajectory is
    bit-identical to its solo deep run, and the unsupported combos stay
    loud."""
    from implicitglobalgrid_tpu.models.common import ensemble_state

    igg.init_global_grid(9, 9, 10, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float32, comm_every=2)
        solo = run_diffusion(T, Cp, p, 4, nt_chunk=4)
        E = 2
        Tb, Cpb = ensemble_state((T, Cp), E)
        out = run_diffusion(Tb, Cpb, p, 4, nt_chunk=4, ensemble=E)
        for m in range(E):
            assert np.array_equal(np.asarray(out[m]), np.asarray(solo)), (
                f"member {m} diverged from the solo deep run")
        with pytest.raises(InvalidArgumentError):
            run_diffusion(Tb, Cpb, p, 4, ensemble=E, impl="pallas")
        import dataclasses

        p_sr = dataclasses.replace(p, sr=True)
        with pytest.raises(InvalidArgumentError):
            run_diffusion(Tb, Cpb, p_sr, 4, ensemble=E)
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
def test_comm_every_per_axis_acoustic_bitwise_equal():
    """The staggered leapfrog under a z-only cadence: per-dim V/P
    retreats at per-axis staleness, 4-field exchange on the due axes
    only — still bit-identical."""
    from implicitglobalgrid_tpu.models import init_acoustic3d, run_acoustic

    def run(ln, ce, hw, nt=8):
        ol = tuple(2 * h for h in hw)
        igg.init_global_grid(ln[0], ln[1], ln[2], dimx=2, dimy=2, dimz=2,
                             periodx=1, periody=0, periodz=1,
                             overlaps=ol, halowidths=hw, quiet=True)
        try:
            state, p = init_acoustic3d(dtype=np.float64, comm_every=ce)
            P = igg.device_put_g(_stacked_per_dim(
                ln, ol, (2, 2, 2), (1, 0, 1),
                lambda x, y, z: np.exp(-((x / 7.0 - 1) ** 2)
                                       - ((y / 5.0 - 1) ** 2)
                                       - ((z / 6.0 - 1) ** 2))))
            state = (P.astype(state[0].dtype), *state[1:])
            out = run_acoustic(state, p, nt, nt_chunk=nt)
            return [np.asarray(igg.gather_interior(f)) for f in out]
        finally:
            igg.finalize_global_grid()

    a = run((8, 8, 8), 1, (1, 1, 1))
    b = run((8, 8, 10), "z:2", (1, 1, 2))
    for fa, fb, name in zip(a, b, ("P", "Vx", "Vy", "Vz")):
        assert np.array_equal(fa, fb), (
            f"{name} diverged: max {np.max(np.abs(fa - fb))}")


@pytest.mark.slow
def test_comm_every_per_axis_stokes_equal():
    """The COMM_AVOID.json rescue configuration: a z-only Stokes cadence
    (halowidths (2,2,4) — the radius-2 scheme needs depth 2 even on
    cadence-1 axes) agrees with the per-iteration-exchange scheme to the
    documented ulp budget."""
    from implicitglobalgrid_tpu.models import init_stokes3d, run_stokes

    def run(ln, ce, hw, nt=4):
        ol = tuple(2 * h for h in hw)
        igg.init_global_grid(ln[0], ln[1], ln[2], dimx=2, dimy=2, dimz=2,
                             periodx=0, periody=0, periodz=0,
                             overlaps=ol, halowidths=hw, quiet=True)
        try:
            state, p = init_stokes3d(dtype=np.float64, comm_every=ce)
            rhog = igg.device_put_g(_stacked_per_dim(
                ln, ol, (2, 2, 2), (0, 0, 0),
                lambda x, y, z: np.exp(-((x / 6.0 - 1) ** 2)
                                       - ((y / 5.0 - 1) ** 2)
                                       - ((z / 7.0 - 1) ** 2))))
            state = (*state[:7], rhog.astype(state[7].dtype))
            out = run_stokes(state, p, nt, nt_chunk=nt)
            return [np.asarray(igg.gather_interior(f)) for f in out]
        finally:
            igg.finalize_global_grid()

    a = run((9, 9, 9), 1, (1, 1, 1))
    b = run((10, 10, 12), "z:2", (2, 2, 4))
    names = ("P", "Vx", "Vy", "Vz", "dVx", "dVy", "dVz", "rhog")
    for fa, fb, name in zip(a, b, names):
        if name.startswith("dV"):
            continue  # halo copies undefined in the base scheme (above)
        if name == "rhog":
            assert np.array_equal(fa, fb)
        else:
            scale = max(1e-30, np.abs(fa).max())
            rel = np.max(np.abs(fa - fb)) / scale
            assert rel < 1e-12, f"{name}: rel {rel:.2e}"


@pytest.mark.audit
def test_comm_every_per_axis_contract_byte_exact():
    """ISSUE 13 acceptance: the compiled mixed-cadence super-step issues
    EXACTLY the planned per-axis permute counts and wire bytes — cadence
    alone, and composed with the per-axis quantized wire policy
    ``z:int8,x:f32`` (`audit_model(comm_every=)`: contract +
    `perfmodel_crosscheck` both byte-exact)."""
    from implicitglobalgrid_tpu.analysis import audit_model

    igg.init_global_grid(9, 9, 10, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         overlaps=(2, 2, 4), halowidths=(1, 1, 2),
                         quiet=True)
    try:
        rep = audit_model("diffusion3d", comm_every="z:2")
        assert rep.ok, [f.message for f in rep.findings]
        cc = rep.crosscheck
        assert cc["ok"] and cc["comm_every"] == "z:2"
        # the cycle (2 steps): x/y fire twice (2 pairs), z once (1 pair)
        assert cc["axes"]["gx"]["parsed_pairs"] == 2.0
        assert cc["axes"]["gz"]["parsed_pairs"] == 1.0
        assert (cc["axes"]["gz"]["modeled_wire_bytes"]
                == cc["axes"]["gz"]["parsed_wire_bytes"])
        # composed with the per-axis wire policy: z ships quantized
        # int8+scale payloads at its own cadence, x stays exact f32
        rep_q = audit_model("diffusion3d", comm_every="z:2",
                            wire_dtype="z:int8,x:f32")
        assert rep_q.ok, [f.message for f in rep_q.findings]
        assert rep_q.crosscheck["ok"]
        assert (rep_q.crosscheck["axes"]["gz"]["parsed_wire_bytes"]
                < cc["axes"]["gz"]["parsed_wire_bytes"])
    finally:
        igg.finalize_global_grid()


def test_comm_every_per_axis_validation():
    """Per-axis halo-geometry checks fire per AXIS: a grid whose z halos
    cannot carry the z cadence is rejected even when x/y are fine, and
    malformed cadence spellings fail loudly."""
    from implicitglobalgrid_tpu.models.common import resolve_comm_every

    with pytest.raises(InvalidArgumentError):
        resolve_comm_every("w:2")
    with pytest.raises(InvalidArgumentError):
        resolve_comm_every("z:0")
    with pytest.raises(InvalidArgumentError):
        resolve_comm_every("z:2,gz:4")  # one axis named twice
    assert str(resolve_comm_every("gz:3")) == "z:3"
    assert resolve_comm_every({"z": 4, "x": 2}).cycle == 4
    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 2), halowidths=(2, 2, 1),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every="z:2")
        with pytest.raises(IncoherentArgumentError):
            run_diffusion(T, Cp, p, 4)  # z halo too shallow for z:2
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every="x:2")
        out = run_diffusion(T, Cp, p, 4, nt_chunk=4)  # x carries it
        assert np.isfinite(np.asarray(out)).all()
    finally:
        igg.finalize_global_grid()


def test_comm_every_validation():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        # halowidth 1 grid cannot carry a 2-deep exchange
        with pytest.raises(IncoherentArgumentError):
            run_diffusion(T, Cp, p, 4)
    finally:
        igg.finalize_global_grid()
    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        with pytest.raises(InvalidArgumentError):
            run_diffusion(T, Cp, p, 7)      # nt not a multiple of k
        with pytest.raises(InvalidArgumentError):
            run_diffusion(T, Cp, p, 4, impl="pallas")
        # the plain builders exchange every step: they must refuse the
        # cadence instead of silently ignoring it
        from implicitglobalgrid_tpu.models import make_run, make_step
        with pytest.raises(InvalidArgumentError):
            make_run(p, 2)
        with pytest.raises(InvalidArgumentError):
            make_step(p)
    finally:
        igg.finalize_global_grid()


def test_comm_every_freshness_bound():
    """An interior shard whose local size is below overlap + k would ship
    one-sub-step-stale send slabs — the deep runner must refuse."""
    igg.init_global_grid(5, 8, 8, dimx=3, dimy=1, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        with pytest.raises(IncoherentArgumentError):
            run_diffusion(T, Cp, p, 4)   # n_x=5 < ol+k=6
    finally:
        igg.finalize_global_grid()


def test_comm_every_halves_permutes():
    """The collective count per PHYSICAL step drops k-fold: audit the
    compiled super-step program — 6 permutes per super-step = 3 per
    physical step at k=2 (vs 6 at k=1)."""
    import jax

    from implicitglobalgrid_tpu.models import make_run_deep

    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2),
                         quiet=True)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float64, comm_every=2)
        run = make_run_deep(p, 1)
        txt = jax.jit(run).lower(T, Cp).compile().as_text()
        n_perm = txt.count("collective-permute-start(")
        if n_perm == 0:  # compiler naming variant
            n_perm = txt.count(" collective-permute(")
        # ONE 2-wide exchange per super-step: one permute pair per axis
        assert n_perm == 6, f"expected 6 permutes per super-step, got {n_perm}"
    finally:
        igg.finalize_global_grid()
