"""Unit tests of the `analysis` subsystem (ISSUE 7): parser against the
checked-in golden dumps, contract serialization, lint rules on synthetic
programs.

The golden fixtures under tests/data/hlo/ are REAL captured programs
(optimized HLO + lowered StableHLO of the halo exchange and the guarded
chunk, captured on the 8-device CPU mesh) so parser robustness is testable
host-only — no grid, no compile, numpy-only imports. The one exception is
`test_fixture_format_matches_live_compile`, the canary that makes an XLA
upgrade which changes the dump format fail LOUDLY here, in one place,
instead of silently degrading every audit.
"""

import os

import numpy as np
import pytest

from implicitglobalgrid_tpu.analysis import (
    CollectiveContract, LINT_RULES, check_contract, guard_contract,
    parse_program, parse_text, run_lints,
)
from implicitglobalgrid_tpu.analysis.contracts import (
    attribute_axis, hlo_dtype, measure_axes, sort_findings,
)
from implicitglobalgrid_tpu.analysis.hlo import Shape
from implicitglobalgrid_tpu.analysis.lints import LintConfig
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.audit

_DATA = os.path.join(os.path.dirname(__file__), "data", "hlo")


def _fixture(name):
    with open(os.path.join(_DATA, name), encoding="utf-8") as f:
        return parse_text(f.read())


# 8-shard ring routes in linearized mesh positions (dims=(8,1,1) periodic):
# the two exchange directions of the single fixture axis
_RING_P = frozenset((i, (i + 1) % 8) for i in range(8))
_RING_M = frozenset((i, (i - 1) % 8) for i in range(8))
_ROUTES = {"gx": (_RING_P, _RING_M)}


def test_parse_single_axis_fixture():
    """One f32 field on a dims=(8,1,1) periodic mesh: exactly one permute
    pair, slab payloads f32[1,8,8] = 256 B x 8 directed links = 2048 B on
    the wire each, riding the two x-axis ring routes."""
    ir = _fixture("exchange_single_axis.hlo.txt")
    assert ir.dialect == "hlo" and ir.module == "jit_exchange"
    assert ir.entry and ir.entry.startswith("main")
    assert len(ir.permutes) == 2
    assert not ir.all_reduces and not ir.all_gathers and not ir.all_to_alls
    for op in ir.permutes:
        pay = ir.payload_of(op)
        assert (pay.dtype, pay.dims) == ("f32", (1, 8, 8))
        assert pay.nbytes == 256 and ir.wire_bytes_of(op) == 2048
        pairs = op.attrs["source_target_pairs"]
        assert frozenset(pairs) in (_RING_P, _RING_M)
        # the parser keeps the compiler's provenance metadata
        assert op.metadata.get("source_file", "").endswith("halo.py")
    assert {op.attrs["channel_id"] for op in ir.permutes} == {1, 2}
    assert len(ir.parameters()) == 1
    # route attribution over an explicit (grid-free) route table
    axes = measure_axes(ir, _ROUTES)
    assert axes == {"gx": {"permutes": 2, "pairs": 16, "wire_bytes": 4096,
                           "dtypes": ("f32",)}}
    assert attribute_axis(_ROUTES, [(0, 3)]) is None


def test_parse_coalesced_fixture():
    """Four coalesced f32 fields: STILL one permute pair, the payload now
    the packed 4 x 64-cell slab buffer (f32[256])."""
    ir = _fixture("exchange_coalesced_4field.hlo.txt")
    assert len(ir.permutes) == 2
    for op in ir.permutes:
        pay = ir.payload_of(op)
        assert (pay.dtype, pay.cells) == ("f32", 256)
        assert ir.wire_bytes_of(op) == 8192
    assert len(ir.parameters()) == 4
    # the slab bound: 4 fields x 512-cell blocks = 2048; payloads within
    assert check_contract(ir, CollectiveContract(
        routes=_ROUTES, max_payload_cells=4 * 512)) == []


def test_parse_ensemble_coalesced_fixture():
    """E=4 member-batched two-field coalesced exchange (ISSUE 12): STILL
    exactly one permute pair on the ring — the vmapped member axis rides
    the payload (f32[4,2,8,8]: members x packed fields x slab), 4 x the
    solo bytes behind the solo pair count. Host-only twin of the live
    contract check in tests/test_ensemble.py."""
    ir = _fixture("exchange_ensemble_coalesced.hlo.txt")
    assert len(ir.permutes) == 2
    for op in ir.permutes:
        pay = ir.payload_of(op)
        assert (pay.dtype, pay.dims) == ("f32", (4, 2, 8, 8))
        assert pay.nbytes == 2048 and ir.wire_bytes_of(op) == 16384
        assert attribute_axis(
            _ROUTES, op.attrs["source_target_pairs"]) == "gx"
    assert not ir.all_reduces and not ir.all_gathers and not ir.all_to_alls
    # slab bound at E=4: 4 members x 2 fields x 256-cell blocks
    assert check_contract(ir, CollectiveContract(
        routes=_ROUTES, max_payload_cells=4 * 2 * 256)) == []


def test_parse_comm_every_mixed_fixture():
    """Per-axis cadence (ISSUE 13): the deep diffusion SUPER-STEP at
    ``comm_every="z:2"`` on a dims=(4,1,2) periodic mesh. One compiled
    super-cycle = 2 physical steps: the x axis exchanges at EVERY
    sub-step (2 events -> 4 permutes of the 1-wide slab) while the z
    axis exchanges ONCE with its 2-wide slab (2 permutes) — the per-axis
    permute counts and k-wide payloads the live contract leg
    (tests/test_comm_avoid.py) pins against `exchange_contract`."""
    ir = _fixture("exchange_comm_every_mixed.hlo.txt")
    assert ir.dialect == "hlo"
    assert len(ir.permutes) == 6
    assert not ir.all_reduces and not ir.all_gathers and not ir.all_to_alls
    # routes of the (4,1,2) mesh in linearized positions (idx = 2x + z)
    x_fwd = frozenset((2 * x + z, 2 * ((x + 1) % 4) + z)
                      for x in range(4) for z in range(2))
    x_bwd = frozenset((2 * x + z, 2 * ((x - 1) % 4) + z)
                      for x in range(4) for z in range(2))
    z_ring = frozenset((2 * x + z, 2 * x + (z + 1) % 2)
                       for x in range(4) for z in range(2))
    routes = {"gx": (x_fwd, x_bwd), "gz": (z_ring, z_ring)}
    axes = measure_axes(ir, routes)
    # x: 2 exchange events x 2 directions, 1-wide slab (8x10 cells,
    # 320 B) over 8 directed links each; z: ONE event, 2-wide slab
    # (9x8x2 cells, 576 B) over 8 directed links each
    assert axes["gx"] == {"permutes": 4, "pairs": 32,
                          "wire_bytes": 4 * 2560, "dtypes": ("f32",)}
    assert axes["gz"] == {"permutes": 2, "pairs": 16,
                          "wire_bytes": 2 * 4608, "dtypes": ("f32",)}
    for op in ir.permutes:
        pay = ir.payload_of(op)
        assert pay.dims in ((1, 8, 10), (9, 8, 2))


def test_parse_guarded_chunk_fixture():
    """The guarded 2-field chunk on the 2x2x2 mesh honors the structural
    guard contract host-only: exactly one f32[4] psum, six permutes, no
    gathers — and the def-use closure walks through the while-loop
    computations the chunk lowers to."""
    ir = _fixture("guarded_chunk.hlo.txt")
    assert ir.module == "jit_chunk"
    assert len(ir.permutes) == 6 and len(ir.all_reduces) == 1
    ar = ir.all_reduces[0]
    pay = ir.payload_of(ar)
    assert (pay.dtype, pay.cells) == ("f32", 4)
    assert check_contract(ir, guard_contract(2)) == []
    # a wrong guard expectation is CAUGHT (3 fields -> f32[6] psum)
    bad = check_contract(ir, guard_contract(3))
    assert {f.rule for f in bad} == {"allreduce-payload"}
    # the psum has producers: the stats vector is computed, not a constant
    assert ir.closure([ar], "up")
    with pytest.raises(InvalidArgumentError):
        ir.closure([ar], "sideways")


def test_parse_all_self_fixture():
    """All-self periodic mesh: the exchange is pure local copies — zero
    collectives of any kind, and the copy/slice/dynamic-update-slice
    machinery is what remains."""
    ir = _fixture("exchange_all_self.hlo.txt")
    assert not ir.collectives()
    inv = ir.inventory()
    assert inv.get("dynamic-update-slice", 0) > 0
    assert check_contract(ir, CollectiveContract(axes={})) == []


def test_parse_bf16_stablehlo_fixture():
    """The LOWERED StableHLO dialect: bf16 wire payloads visible (the CPU
    backend's float-normalization would rewrite them in optimized text),
    converts feeding the wire, partitioner custom-calls recognized as
    benign."""
    ir = _fixture("exchange_bf16_wire.stablehlo.txt")
    assert ir.dialect == "stablehlo"
    assert len(ir.permutes) == 2
    for op in ir.permutes:
        pay = ir.payload_of(op)
        assert (pay.dtype, pay.cells) == ("bf16", 128)
        assert pay.nbytes == 256 and ir.wire_bytes_of(op) == 2048
        assert len(op.attrs["source_target_pairs"]) == 8
    assert ir.count("convert") >= 2
    cfg = LintConfig(state_dtypes=("f32",), wire_dtype="bf16")
    assert run_lints(ir, config=cfg, rules=("wire-downcast-missing",)) == []
    # Sharding/SPMD* partitioner custom-calls never flag as opaque
    assert run_lints(ir, config=cfg, rules=("custom-call",)) == []


def test_fixture_format_matches_live_compile():
    """THE format canary: recompile the single-axis exchange the fixture
    captured and require the freshly parsed program to agree with the
    golden one on everything the audits rely on — an XLA upgrade that
    changes the dump format (or the exchange's lowering) fails HERE, in
    one place, not as silent audit degradation."""
    import jax
    import jax.numpy as jnp

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.ops import halo as halo_mod
    from implicitglobalgrid_tpu.ops.fields import field_partition_spec
    from implicitglobalgrid_tpu.utils.compat import shard_map

    golden = _fixture("exchange_single_axis.hlo.txt")
    igg.init_global_grid(8, 8, 8, dimx=8, dimy=1, dimz=1, periodx=1,
                         quiet=True)
    gg = igg.global_grid()

    def exchange(A):
        return halo_mod._exchange_arrays(
            gg, [A], [gg.halowidths],
            halo_mod._normalize_dims_order(None), coalesce=None,
            wire=None)[0]

    spec = (field_partition_spec(3),)
    fn = jax.jit(shard_map(exchange, mesh=gg.mesh, in_specs=spec,
                           out_specs=spec[0]))
    live = parse_program(fn, jnp.zeros((64, 8, 8), np.float32))
    assert live.dialect == golden.dialect == "hlo"
    assert len(live.permutes) == len(golden.permutes) == 2
    assert (sorted(str(live.payload_of(p)) for p in live.permutes)
            == sorted(str(golden.payload_of(p)) for p in golden.permutes))
    assert (sorted(frozenset(p.attrs["source_target_pairs"])
                   for p in live.permutes)
            == sorted(frozenset(p.attrs["source_target_pairs"])
                      for p in golden.permutes))
    assert measure_axes(live, _ROUTES) == measure_axes(golden, _ROUTES)
    igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# parser/IR primitives

def test_shape_helpers_are_dtype_generic():
    assert Shape("bf16", (1, 8, 8)).nbytes == 128
    assert Shape("f64", (4, 4)).nbytes == 128
    assert Shape("pred", (7,)).nbytes == 7
    assert Shape("f32", ()).cells == 1
    assert str(Shape("s32", (2, 3))) == "s32[2,3]"
    assert hlo_dtype("float64") == "f64" and hlo_dtype("bfloat16") == "bf16"
    assert hlo_dtype("bf16") == "bf16"  # HLO spellings pass through


def test_parse_text_rejects_garbage():
    with pytest.raises(InvalidArgumentError):
        parse_text("")
    with pytest.raises(InvalidArgumentError):
        parse_text("this is not a program dump")
    with pytest.raises(InvalidArgumentError):
        parse_program(42)


def test_contract_json_roundtrip():
    c = CollectiveContract(
        axes={"gx": {"permutes": 2, "wire_bytes": 4096,
                     "dtypes": ("f32",)}},
        routes=_ROUTES, allreduces=1, allreduce_payload=("f32", 4),
        max_payload_cells=512, meta={"model": "diffusion3d"})
    back = CollectiveContract.from_json(c.to_json())
    assert back.axes == c.axes
    assert back.routes == c.routes
    assert back.allreduce_payload == ("f32", 4)
    assert back.max_payload_cells == 512
    import json

    assert CollectiveContract.from_json(
        json.dumps(c.to_json())).axes == c.axes
    with pytest.raises(InvalidArgumentError):
        CollectiveContract.from_json({"axes": {"gx": {"permutes": "NaN?"}}})


def test_stablehlo_dotted_custom_call_target():
    """REGRESSION: dotted symbol names (`@xla.sdy.FuncResultSharding`,
    the Shardy partitioner's marker) must parse whole — a truncated
    target ('xla') would miss the benign carve-out and spam every audit
    with spurious opaque-custom-call warnings."""
    text = """module @jit_f attributes {mhlo.num_partitions = 8 : i32} {
  func.func public @main(%arg0: tensor<4x4xf32>) -> tensor<4x4xf32> {
    %0 = stablehlo.custom_call @xla.sdy.FuncResultSharding(%arg0) {backend_config = ""} : (tensor<4x4xf32>) -> tensor<4x4xf32>
    return %0 : tensor<4x4xf32>
  }
}
"""
    ir = parse_text(text)
    (cc,) = ir.find("custom-call")
    assert cc.attrs["custom_call_target"] == "xla.sdy.FuncResultSharding"
    assert run_lints(ir, config=LintConfig(), rules=("custom-call",)) == []


def test_contract_axes_without_routes_rejected():
    """A contract with per-axis expectations but no route table is
    unsatisfiable (no permute can be attributed, every axis would
    falsely report got=0) — a caller error, not a finding."""
    ir = _fixture("exchange_single_axis.hlo.txt")
    bad = CollectiveContract(axes={"gx": {"permutes": 2}})
    with pytest.raises(InvalidArgumentError):
        check_contract(ir, bad)
    # with routes the same expectation verifies cleanly
    ok = CollectiveContract(axes={"gx": {"permutes": 2}}, routes=_ROUTES)
    assert check_contract(ir, ok) == []


def test_findings_sort_most_severe_first():
    from implicitglobalgrid_tpu.analysis.contracts import AuditFinding

    fs = [AuditFinding("b-rule", "info", "i"),
          AuditFinding("a-rule", "warning", "w"),
          AuditFinding("z-rule", "error", "e")]
    assert [f.severity for f in sort_findings(fs)] \
        == ["error", "warning", "info"]


# ---------------------------------------------------------------------------
# lint rules on synthetic programs (host-only)

def _synth(body, params="p0: f32[4,4]", result="f32[4,4]", module_attrs=""):
    return (f"HloModule synthetic{module_attrs}\n\n"
            f"ENTRY %main ({params}) -> {result} {{\n{body}\n}}\n")


def test_lint_global_materialization():
    text = _synth("  %p0 = f32[4,4] parameter(0)\n"
                  "  ROOT %big = f32[16,16] broadcast(f32[4,4] %p0)",
                  result="f32[16,16]")
    cfg = LintConfig(global_shape=(16, 16), local_shape=(4, 4))
    out = run_lints(parse_text(text), config=cfg,
                    rules=("global-materialization",))
    assert [f.rule for f in out] == ["global-materialization"]
    assert out[0].severity == "error"
    # single-shard grids (global == local) never flag
    cfg1 = LintConfig(global_shape=(4, 4), local_shape=(4, 4))
    assert run_lints(parse_text(text), config=cfg1,
                     rules=("global-materialization",)) == []


def test_lint_host_transfer_and_custom_call():
    text = _synth(
        "  %p0 = f32[4,4] parameter(0)\n"
        "  %cb = f32[4,4] custom-call(f32[4,4] %p0), "
        'custom_call_target="xla_python_cpu_callback"\n'
        "  %oq = f32[4,4] custom-call(f32[4,4] %cb), "
        'custom_call_target="my_opaque_kernel"\n'
        "  ROOT %of = token[] outfeed(f32[4,4] %oq)",
        result="token[]")
    ir = parse_text(text)
    host = run_lints(ir, config=LintConfig(), rules=("host-transfer",))
    assert len(host) == 2  # the callback custom-call AND the outfeed
    assert all(f.severity == "error" for f in host)
    opaque = run_lints(ir, config=LintConfig(), rules=("custom-call",))
    assert [f.details["target"] for f in opaque] == ["my_opaque_kernel"]
    assert opaque[0].severity == "warning"


def test_lint_f64_leakage():
    text = _synth("  %p0 = f32[4,4] parameter(0)\n"
                  "  ROOT %c = f64[4,4] convert(f32[4,4] %p0)",
                  result="f64[4,4]")
    ir = parse_text(text)
    out = run_lints(ir, config=LintConfig(state_dtypes=("f32",)),
                    rules=("f64-leakage",))
    assert [f.rule for f in out] == ["f64-leakage"]
    # a legitimately-f64 program never flags
    assert run_lints(ir, config=LintConfig(state_dtypes=("f32", "f64")),
                     rules=("f64-leakage",)) == []


def test_lint_copy_feeds_collective():
    text = _synth(
        "  %p0 = f32[4,4] parameter(0)\n"
        "  %cp = f32[4,4] copy(f32[4,4] %p0)\n"
        "  ROOT %perm = f32[4,4] collective-permute(f32[4,4] %cp), "
        "source_target_pairs={{0,1},{1,0}}")
    out = run_lints(parse_text(text), config=LintConfig(),
                    rules=("copy-feeds-collective",))
    assert [f.rule for f in out] == ["copy-feeds-collective"]
    assert out[0].details["copy"] == "cp"


def test_lint_donation_unaliased():
    text = _synth(
        "  %p0 = f32[4,4] parameter(0)\n"
        "  ROOT %n = f32[4,4] negate(f32[4,4] %p0)",
        module_attrs=", input_output_alias={ {0}: (0, {}, may-alias) }")
    ir = parse_text(text)
    assert run_lints(ir, config=LintConfig(expect_donation=1),
                     rules=("donation-unaliased",)) == []
    out = run_lints(ir, config=LintConfig(expect_donation=2),
                    rules=("donation-unaliased",))
    assert [f.rule for f in out] == ["donation-unaliased"]
    assert out[0].details == {"expected": 2, "aliased": 1}


def test_lint_wire_downcast_partial_regression_flagged():
    """A PARTIAL downcast regression — one axis narrowed to the wire
    dtype, another still full precision — is as real a bandwidth loss as
    a total one and must flag (the first lint cut passed if ANY payload
    carried the wire dtype). Width, not equality: an f16 payload under
    bf16 wire is legal (`wire_dtype_for` never widens)."""
    mixed = _synth(
        "  %p0 = f32[4,4] parameter(0)\n"
        "  %cv = bf16[1,4] convert(f32[1,4] %s0)\n"
        "  %s0 = f32[1,4] slice(f32[4,4] %p0), slice={[0:1], [0:4]}\n"
        "  %cp0 = bf16[1,4] collective-permute(bf16[1,4] %cv), "
        "channel_id=1, source_target_pairs={{0,1},{1,0}}\n"
        "  %s1 = f32[1,4] slice(f32[4,4] %p0), slice={[3:4], [0:4]}\n"
        "  %cp1 = f32[1,4] collective-permute(f32[1,4] %s1), "
        "channel_id=2, source_target_pairs={{0,1},{1,0}}\n"
        "  ROOT %t = (bf16[1,4], f32[1,4]) tuple(bf16[1,4] %cp0, "
        "f32[1,4] %cp1)",
        result="(bf16[1,4], f32[1,4])")
    cfg = LintConfig(state_dtypes=("f32",), wire_dtype="bf16")
    out = run_lints(parse_text(mixed), config=cfg,
                    rules=("wire-downcast-missing",))
    assert [f.rule for f in out] == ["wire-downcast-missing"]
    assert out[0].severity == "error"
    assert out[0].details["stale"] == 1
    assert out[0].details["float_permutes"] == 2
    # an f16 payload under bf16 wire is at the wire width: clean
    f16 = _synth(
        "  %p0 = f16[4,4] parameter(0)\n"
        "  %s0 = f16[1,4] slice(f16[4,4] %p0), slice={[0:1], [0:4]}\n"
        "  ROOT %cp0 = f16[1,4] collective-permute(f16[1,4] %s0), "
        "channel_id=1, source_target_pairs={{0,1},{1,0}}",
        params="p0: f16[4,4]", result="f16[1,4]")
    assert run_lints(parse_text(f16), config=cfg,
                     rules=("wire-downcast-missing",)) == []


def test_lint_wire_downcast_per_axis_policy_asymmetry():
    """REGRESSION (ISSUE 10 satellite): under a PER-AXIS policy a float
    payload at full width on an axis the policy leaves exact is LEGAL —
    the old global `wire_dtype_for` width check flagged it. With
    ``wire_axes``+``routes`` the lint judges each permute against ITS
    axis: an s8 payload on the quantized axis and an f32 payload on the
    exact axis are both clean, while a stale f32 payload on the
    quantized axis still flags (host-only: explicit route table, no
    grid)."""
    routes = {"gx": (frozenset({(0, 1), (1, 0)}),),
              "gz": (frozenset({(0, 2), (2, 0)}),)}
    mixed_ok = _synth(
        "  %p0 = f32[4,4] parameter(0)\n"
        "  %s0 = f32[1,4] slice(f32[4,4] %p0), slice={[0:1], [0:4]}\n"
        "  %cpx = f32[1,4] collective-permute(f32[1,4] %s0), "
        "channel_id=1, source_target_pairs={{0,1},{1,0}}\n"
        "  %q = s8[8] bitcast(f32[1,4] %s0)\n"
        "  %cpz = s8[8] collective-permute(s8[8] %q), "
        "channel_id=2, source_target_pairs={{0,2},{2,0}}\n"
        "  ROOT %t = (f32[1,4], s8[8]) tuple(f32[1,4] %cpx, s8[8] %cpz)",
        result="(f32[1,4], s8[8])")
    cfg = LintConfig(state_dtypes=("f32",), wire_dtype="f32",
                     wire_axes={"gz": "s8"}, routes=routes)
    assert run_lints(parse_text(mixed_ok), config=cfg,
                     rules=("wire-downcast-missing",)) == []
    # stale: the z permute still carries f32 under the z:int8 policy
    stale = _synth(
        "  %p0 = f32[4,4] parameter(0)\n"
        "  %s0 = f32[1,4] slice(f32[4,4] %p0), slice={[0:1], [0:4]}\n"
        "  %cpx = f32[1,4] collective-permute(f32[1,4] %s0), "
        "channel_id=1, source_target_pairs={{0,1},{1,0}}\n"
        "  %s1 = f32[1,4] slice(f32[4,4] %p0), slice={[3:4], [0:4]}\n"
        "  %cpz = f32[1,4] collective-permute(f32[1,4] %s1), "
        "channel_id=2, source_target_pairs={{0,2},{2,0}}\n"
        "  ROOT %t = (f32[1,4], f32[1,4]) tuple(f32[1,4] %cpx, "
        "f32[1,4] %cpz)",
        result="(f32[1,4], f32[1,4])")
    out = run_lints(parse_text(stale), config=cfg,
                    rules=("wire-downcast-missing",))
    assert [f.rule for f in out] == ["wire-downcast-missing"]
    assert out[0].details["stale"] == 1  # only the z permute
    # a MALFORMED policy spec must raise loudly, not silently disable
    # the lint via the legacy-string fallback (which would judge every
    # payload against a width-4 default and flag nothing); the known
    # legacy HLO spellings the policy parser doesn't know still pass
    from implicitglobalgrid_tpu.analysis import default_lint_config

    for bad in ("w:int8", "z:int3", "int3"):
        with pytest.raises(InvalidArgumentError):
            default_lint_config(wire_dtype=bad)
    assert default_lint_config(wire_dtype="f64").wire_dtype == "f64"
    # NO routes (host-only dump audit, or an unattributable permute):
    # a per-axis policy can never soundly flag a full-width payload —
    # it may belong to an exact-by-policy axis — so nothing flags (the
    # old widest-format fallback judged everything against one width)
    cfg_noroutes = LintConfig(state_dtypes=("f32",), wire_dtype="s8",
                              wire_axes={"gz": "s8"}, routes=None)
    assert run_lints(parse_text(stale), config=cfg_noroutes,
                     rules=("wire-downcast-missing",)) == []
    # live-grid path: `default_lint_config` builds wire_axes + routes
    # from a policy spec when a grid is initialized
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.analysis import default_lint_config

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=4, periodx=1,
                         periodz=1, quiet=True)
    try:
        live = default_lint_config(state_dtypes=("f32",),
                                   wire_dtype="z:int8,x:f32")
        assert live.wire_axes == {"gx": "f32", "gz": "s8"}
        assert sorted(live.routes) == ["gx", "gz"]
        assert live.wire_dtype == "f32"  # widest fallback, never false-flags
    finally:
        igg.finalize_global_grid()


def test_parse_int8_quant_fixture():
    """Golden quantized single-axis exchange (dims=(8,1,1) periodic,
    ``wire_dtype="int8"``, OPTIMIZED HLO — int8 payloads survive the CPU
    backend, unlike bf16): one permute pair whose payloads are the packed
    s8[68] buffer = 64 slab cells + 4 bitcast scale bytes, 544 B on the
    wire per direction — 4x fewer slab bytes than the f32 fixture's
    s8-equivalent, byte-exact against `quant_slab_bytes` + SCALE_BYTES."""
    from implicitglobalgrid_tpu.ops.precision import (
        SCALE_BYTES, WireFormat, quant_slab_bytes,
    )

    ir = _fixture("exchange_int8_quant.hlo.txt")
    assert ir.dialect == "hlo"
    assert len(ir.permutes) == 2
    assert not ir.all_reduces and not ir.all_gathers
    expect = quant_slab_bytes(8 * 8, WireFormat("int8")) + SCALE_BYTES
    for op in ir.permutes:
        pay = ir.payload_of(op)
        assert pay.dtype == "s8" and pay.cells == expect == 68
        assert ir.wire_bytes_of(op) == expect * 8
        pairs = op.attrs["source_target_pairs"]
        assert frozenset(pairs) in (_RING_P, _RING_M)
    axes = measure_axes(ir, _ROUTES)
    assert axes == {"gx": {"permutes": 2, "pairs": 16,
                           "wire_bytes": 2 * expect * 8,
                           "dtypes": ("s8",)}}
    # vs the exact fixture: 4 bytes/cell -> 1 + scales = 3.76x down
    exact = _fixture("exchange_single_axis.hlo.txt")
    exact_bytes = sum(exact.wire_bytes_of(p) for p in exact.permutes)
    assert exact_bytes / (2 * expect * 8) > 3.5


def test_parse_interior_first_fixture():
    """Golden INTERIOR-FIRST chunk program (ISSUE 11): the lowered
    StableHLO of the overlapped diffusion step on the 2x2x2 periodic mesh
    (16^3 local blocks, ol=2 -> 12^3 interior). The fixture proves —
    host-only, via `ProgramIR.closure` — the structural claim of the
    interior-first step shape: one ppermute pair per exchanging axis,
    every permute slab-sized, an `optimization_barrier` guarding the
    stitch, and interior-sized compute with NO SSA path to or from any
    collective-permute (what lets the latency-hiding scheduler run the
    interior under the wire)."""
    ir = _fixture("overlap_interior_first.stablehlo.txt")
    assert ir.dialect == "stablehlo"
    permutes = ir.permutes
    assert len(permutes) == 6  # one pair per exchanging axis
    assert not ir.all_reduces and not ir.all_gathers
    for op in permutes:
        assert ir.payload_of(op).cells < 16 ** 3  # slab-sized
    assert ir.find("optimization-barrier")
    tainted = ir.closure(permutes, "up") | ir.closure(permutes, "down") \
        | set(permutes)

    def interior_sized(op):
        return any(s.dtype == "f32" and s.dims == (12, 12, 12)
                   for s in op.shapes)

    interior_ops = {"add", "multiply", "subtract", "divide", "select",
                    "dynamic-update-slice"}
    independent = [op for op in ir.ops
                   if op.op in interior_ops and interior_sized(op)
                   and op not in tainted]
    assert independent, (
        "no interior-sized compute is independent of the permutes — the "
        "interior-first shape degraded to a serialized exchange")


def test_run_lints_unknown_rule_raises():
    ir = _fixture("exchange_all_self.hlo.txt")
    with pytest.raises(InvalidArgumentError):
        run_lints(ir, config=LintConfig(), rules=("no-such-rule",))
    assert set(LINT_RULES) >= {
        "global-materialization", "wire-downcast-missing",
        "donation-unaliased", "host-transfer", "custom-call",
        "f64-leakage", "copy-feeds-collective"}
