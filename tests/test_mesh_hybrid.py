"""Hybrid (multi-slice / DCN) mesh arrangement and controller coords.

The reference scales over any MPI interconnect (`/root/reference/README.md:6-8`)
with per-rank Cartesian coords from `MPI.Cart_coords`
(`init_global_grid.jl:101-106`). The TPU analog: `arrange_devices` lays out
multi-slice device pools so slice boundaries fall only between blocks of the
axes named in ``IGG_TPU_DCN_AXES``, and `controller_coords_of` gives each
controller its first addressable device's mesh position. Tested here with
fake device objects (no multi-slice hardware needed).
"""

import pytest

from implicitglobalgrid_tpu.parallel.mesh import (
    arrange_devices, controller_coords_of,
)
from implicitglobalgrid_tpu.utils.exceptions import IncoherentArgumentError


class FakeDev:
    """Duck-typed device: id + slice/process membership."""

    def __init__(self, id, slice_index=None, process_index=0):
        self.id = id
        if slice_index is not None:
            self.slice_index = slice_index
        self.process_index = process_index

    def __repr__(self):
        return f"d{self.id}"


def _pool(n_slices, per_slice):
    return [FakeDev(s * per_slice + i, slice_index=s, process_index=s)
            for s in range(n_slices) for i in range(per_slice)]


def _slice_of(d):
    return d.slice_index


def test_single_slice_plain_order():
    devs = [FakeDev(i) for i in range(8)]
    arr = arrange_devices((2, 2, 2), devs, reorder=0)
    assert arr.shape == (2, 2, 2)
    assert [d.id for d in arr.ravel()] == list(range(8))


def test_two_slices_split_along_x():
    """2 slices x 4 devices, dcn axis x, dims (4,2,1): slice boundary must
    fall only between x-blocks 0-1 and 2-3."""
    devs = _pool(2, 4)
    arr = arrange_devices((4, 2, 1), devs, reorder=0, dcn_axes=("x",))
    # x blocks [0,2) from slice 0, [2,4) from slice 1
    for x in range(4):
        for y in range(2):
            assert _slice_of(arr[x, y, 0]) == (0 if x < 2 else 1)
    # interior x-neighbor hops within a slice stay intra-slice
    assert _slice_of(arr[0, 0, 0]) == _slice_of(arr[1, 0, 0])
    assert _slice_of(arr[2, 0, 0]) == _slice_of(arr[3, 0, 0])


def test_four_slices_two_dcn_axes():
    """4 slices over axes (x, y) with dims (4,4,1): 2x2 DCN grid of 2x2 ICI
    blocks."""
    devs = _pool(4, 4)
    arr = arrange_devices((4, 4, 1), devs, reorder=0, dcn_axes=("x", "y"))
    for x in range(4):
        for y in range(4):
            expected = (x // 2) * 2 + (y // 2)
            assert _slice_of(arr[x, y, 0]) == expected


def test_all_slices_on_one_axis():
    """4 slices all along z (dims (1,1,8), 2 devices each)."""
    devs = _pool(4, 2)
    arr = arrange_devices((1, 1, 8), devs, reorder=0, dcn_axes=("z",))
    for z in range(8):
        assert _slice_of(arr[0, 0, z]) == z // 2


def test_indivisible_slice_count_raises():
    devs = _pool(3, 4)  # 3 slices cannot split dims (4,1,1) along x
    with pytest.raises(IncoherentArgumentError):
        arrange_devices((4, 3, 1), devs, reorder=0, dcn_axes=("x",))


def test_unequal_slices_raise():
    devs = _pool(2, 4)[:-1] + [FakeDev(99, slice_index=0)]  # 5 + 3
    with pytest.raises(IncoherentArgumentError):
        arrange_devices((4, 2, 1), devs, reorder=0, dcn_axes=("x",))


def test_no_dcn_axes_ignores_slices():
    """Without IGG_TPU_DCN_AXES, multi-granule pools arrange in plain order
    (the round-1 behavior, preserved for explicit layouts)."""
    devs = _pool(2, 4)
    arr = arrange_devices((2, 2, 2), devs, reorder=0)
    assert [d.id for d in arr.ravel()] == list(range(8))


def test_process_granules_without_slice_index():
    """CPU/GPU multi-host pools have no slice_index; process_index is the
    DCN granule."""
    devs = [FakeDev(i, process_index=i // 4) for i in range(8)]
    arr = arrange_devices((2, 2, 2), devs, reorder=0, dcn_axes=("x",))
    for x in range(2):
        for y in range(2):
            for z in range(2):
                assert arr[x, y, z].process_index == x


def test_controller_coords():
    devs = _pool(2, 4)
    arr = arrange_devices((4, 2, 1), devs, reorder=0, dcn_axes=("x",))
    assert tuple(controller_coords_of(arr, 0)) == (0, 0, 0)
    assert tuple(controller_coords_of(arr, 1)) == (2, 0, 0)
    # unknown process: zeros (single-controller semantics)
    assert tuple(controller_coords_of(arr, 7)) == (0, 0, 0)


def test_duplicate_dcn_axes_rejected():
    import os

    from implicitglobalgrid_tpu.utils.config import read_env_config
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    os.environ["IGG_TPU_DCN_AXES"] = "x,x"
    try:
        with pytest.raises(InvalidArgumentError):
            read_env_config()
    finally:
        del os.environ["IGG_TPU_DCN_AXES"]
