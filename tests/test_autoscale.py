"""Closed-loop autoscaler tests (ISSUE 19): the mesh resizes itself.

The acceptance bar is the DRILL: a queue-pressured high-priority job is
grown and an idle one shrunk with NO operator input, every resize
preceded by a journaled ``autoscale_decision`` whose priced break-even
is satisfied, the post-resize re-tune recorded, all tenants BIT-
IDENTICAL to their solo (no-autoscale) reference runs, and the decision
chain reconstructable from the journal alone (`explain_autoscale` /
``tools autoscale explain``). The thrash test proves hysteresis: a
bounced signal files NOTHING.

Budget note (ROADMAP tier-1): one end-to-end drill is the fast
representative; everything else here is host-only dict arithmetic.
"""

import json
import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.service import (
    Autoscaler, AutoscalePolicy, FairSharePolicy, Job, JobSpec,
    MeshScheduler, ScaleBounds, builtin_setup, explain_autoscale,
    service_report,
)
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.service

# hot: compute-dominated single-device grid with room to grow (glob span
# 64 per axis re-blocks evenly at dims 1/2/4); idle: small grid spread
# over 4 devices it does not need
GRID_HOT = dict(nx=66, ny=66, nz=66, dimx=1, dimy=1, dimz=1,
                overlaps=(2, 2, 2))
GRID_IDLE = dict(nx=18, ny=18, nz=18, dimx=2, dimy=2, dimz=1,
                 overlaps=(2, 2, 2))


def _signals(slack, *, pending=0, name="hot", devices=1, priority=2):
    """A `MeshScheduler._live_signals`-shaped synthetic snapshot."""
    return {"jobs": {name: {"state": "running",
                            "deadline_slack_s": slack,
                            "priority": priority, "devices": devices}},
            "queue": {"pending": pending, "queued": 0}}


class _StubSched:
    """The minimal scheduler surface the policy engine touches —
    journal sink, job table, queue backend."""

    def __init__(self):
        self.jobs = {}
        self.queue = None
        self.events = []

    def _log(self, kind, **fields):
        self.events.append(dict(kind=kind, **fields))


# ---------------------------------------------------------------------------
# Public API / validation (host-only)
# ---------------------------------------------------------------------------

def test_public_api_exports():
    from implicitglobalgrid_tpu import service

    for sym in ("Autoscaler", "AutoscalePolicy", "ScaleBounds",
                "explain_autoscale"):
        assert hasattr(service, sym), sym
        assert sym in service.__all__, sym


def test_policy_and_bounds_validation():
    with pytest.raises(InvalidArgumentError, match="min_devices"):
        ScaleBounds(min_devices=0)
    with pytest.raises(InvalidArgumentError, match="max_devices"):
        ScaleBounds(min_devices=4, max_devices=2)
    with pytest.raises(InvalidArgumentError, match="via"):
        AutoscalePolicy(via="sideways")
    with pytest.raises(InvalidArgumentError, match="hysteresis"):
        AutoscalePolicy(hysteresis_slices=0)
    with pytest.raises(InvalidArgumentError, match="cooldown"):
        AutoscalePolicy(cooldown_slices=-1)
    with pytest.raises(InvalidArgumentError, match="ScaleBounds"):
        AutoscalePolicy(bounds={"j": (1, 2)})
    with pytest.raises(InvalidArgumentError, match="AutoscalePolicy"):
        Autoscaler(42)
    # kwargs-dict form (the MeshScheduler(autoscale={...}) path) and the
    # JSON policy echo both round-trip
    a = Autoscaler({"grow_slack_s": 5.0,
                    "bounds": {"hot": ScaleBounds(2, 6)}})
    echo = a.policy.describe()
    assert json.loads(json.dumps(echo))["bounds"]["hot"] == {
        "min_devices": 2, "max_devices": 6}
    assert a.policy.bounds_for("other") == ScaleBounds()


def test_scheduler_rejects_bogus_autoscale_arg(tmp_path):
    with pytest.raises(InvalidArgumentError, match="autoscale"):
        MeshScheduler(flight_dir=str(tmp_path), autoscale=123)


def test_fair_share_slack_boost_reprioritizes():
    """Satellite: `fair` spends mesh time where deadline pressure is —
    BEFORE the alert engine's hard cancel — via a slack-weighted stride
    boost, smoothly and reversibly (`granted` accounting untouched)."""
    import types

    pol = FairSharePolicy(low_slack_s=10.0, slack_boost=4.0,
                          slack_horizon_s=20.0)
    jobs = []
    for i, slack in enumerate([None, 25.0, -15.0]):
        spec = JobSpec(name=f"j{i}", setup=lambda: None, nt=10)
        j = Job(spec, i)
        j.run = types.SimpleNamespace(deadline_slack_s=slack)
        jobs.append(j)
    # equal shares: only the starved job (slack -15 < 10) boosts; its
    # deficit 25s saturates the 20s horizon -> full 1 + 4.0 stride
    for j in jobs:
        pol.granted(j, 8.0)
    assert pol._boost(jobs[0]) == 1.0      # no deadline: plain fair share
    assert pol._boost(jobs[1]) == 1.0      # comfortable slack
    assert pol._boost(jobs[2]) == 5.0      # saturated boost
    assert pol.pick(jobs) is jobs[2]
    # recovery is reversible: slack back above the bar, boost gone
    jobs[2].run.deadline_slack_s = 11.0
    assert pol._boost(jobs[2]) == 1.0
    assert pol.pick(jobs) is jobs[0]
    with pytest.raises(InvalidArgumentError, match="slack_boost"):
        FairSharePolicy(slack_boost=-1)
    with pytest.raises(InvalidArgumentError, match="slack_horizon_s"):
        FairSharePolicy(slack_horizon_s=0)


# ---------------------------------------------------------------------------
# Hysteresis / cooldown (synthetic signals, host-only)
# ---------------------------------------------------------------------------

def test_bounced_signal_never_files_thrash_proof():
    """An oscillating starvation signal (slack dips below the bar on
    alternate boundaries) NEVER matures past hysteresis: zero moves
    filed, every rejection is ``hysteresis`` — the mesh cannot thrash."""
    a = Autoscaler(AutoscalePolicy(grow_slack_s=0.0, hysteresis_slices=3))
    reasons = []
    for i in range(12):
        slack = -1.0 if i % 2 == 0 else 1.0
        for d in a.evaluate(_signals(slack)):
            reasons.append((d["verdict"], d["reason"]))
    assert reasons and set(reasons) == {("rejected", "hysteresis")}
    assert a.moves_filed == 0
    assert a.evaluations == 12
    assert a.decision_s_total > 0 and a.last_decision_s >= 0


def test_constant_pressure_matures_and_journal_dedups():
    """A PERSISTENT signal matures exactly at ``hysteresis_slices``
    consecutive votes; repeated identical rejections collapse to one
    journal record while the counters count every verdict."""
    from implicitglobalgrid_tpu.telemetry import hooks

    reg = igg.metrics_registry()
    reg.reset(hooks.AUTOSCALE_DECISIONS)
    reg.reset(hooks.AUTOSCALE_REJECTED)
    sched = _StubSched()
    a = Autoscaler(AutoscalePolicy(grow_slack_s=0.0, hysteresis_slices=2),
                   scheduler=sched)
    verdicts = []
    for _ in range(5):
        for d in a.evaluate(_signals(-1.0)):
            verdicts.append(d["reason"])
    # boundary 1 rejects on hysteresis; 2..5 mature but find no live job
    # in the (empty) stub table — the plan stage WAS reached
    assert verdicts == ["hysteresis"] + ["no_live_job"] * 4
    journaled = [e for e in sched.events
                 if e["kind"] == "autoscale_decision"]
    assert [e["reason"] for e in journaled] == ["hysteresis",
                                                "no_live_job"]
    fam = reg.get(hooks.AUTOSCALE_DECISIONS)
    assert fam.value(action="grow", verdict="rejected") == 5.0
    rej = reg.get(hooks.AUTOSCALE_REJECTED)
    assert rej.value(reason="hysteresis") == 1.0
    assert rej.value(reason="no_live_job") == 4.0


def test_vote_reset_on_non_consecutive_boundary():
    """The hysteresis contract is CONSECUTIVE boundaries: a healthy
    boundary between two starved ones resets the streak."""
    a = Autoscaler(AutoscalePolicy(grow_slack_s=0.0, hysteresis_slices=2))
    assert a.evaluate(_signals(-1.0))[0]["streak"] == 1
    assert a.evaluate(_signals(5.0)) == []          # vote did not repeat
    assert a.evaluate(_signals(-1.0))[0]["streak"] == 1  # back to one


# ---------------------------------------------------------------------------
# The drill: end-to-end closed loop (tier-1 fast representative)
# ---------------------------------------------------------------------------

def _drill_job(name, grid, *, priority=1, deadline_s=None):
    return JobSpec(name=name, setup=builtin_setup("diffusion3d"),
                   model="diffusion3d", nt=60, grid=grid,
                   run=igg.RunSpec(nt_chunk=5, key=("autoscale", name)),
                   priority=priority, deadline_s=deadline_s)


def _interior(sched, name):
    from implicitglobalgrid_tpu.parallel import topology as top

    job = sched.job(name)
    prev = top.swap_global_grid(job.gg)
    try:
        return igg.gather_interior(job.result["T"])
    finally:
        top.swap_global_grid(prev)


def _solo_interior(tmp_path, name, grid, **spec_kw):
    """The job's gathered interior from a NO-autoscale scheduler run —
    the bit-identity reference."""
    d = str(tmp_path / f"solo_{name}")
    with MeshScheduler(policy="fair", flight_dir=d) as sched:
        sched.submit(_drill_job(name, grid, **spec_kw))
        sched.run()
        assert sched.job(name).state == "done"
        return _interior(sched, name)


def test_autoscale_drill_grow_shrink_explainable_bit_identical(tmp_path):
    """THE ISSUE-19 acceptance drill. Two tenants on one 8-device pool:
    ``hot`` (high priority, deadline, one device, compute-dominated) and
    ``idle`` (no deadline, 4 devices it does not need). With
    ``grow_slack_s`` above any live slack, every boundary votes grow-hot
    / shrink-idle; the policy must grow hot to its 4-device cap and
    shrink idle to one device with no operator input — every resize
    preceded by a journaled, PRICED decision, re-tuned after applying,
    both results bit-identical to their solo no-autoscale runs, and the
    whole story reconstructable from the journal alone."""
    from implicitglobalgrid_tpu.telemetry import hooks

    reg = igg.metrics_registry()
    for fam in (hooks.AUTOSCALE_DECISIONS, hooks.AUTOSCALE_RESIZES,
                hooks.AUTOSCALE_REJECTED, hooks.JOB_TARGET_DEVICES):
        reg.reset(fam)
    ref_hot = _solo_interior(tmp_path, "hot", GRID_HOT, priority=2,
                             deadline_s=120.0)
    ref_idle = _solo_interior(tmp_path, "idle", GRID_IDLE)

    d = str(tmp_path / "svc")
    pol = AutoscalePolicy(grow_slack_s=1e9,  # any live slack = starved
                          shrink_queue_pending=1, hysteresis_slices=2,
                          cooldown_slices=2,
                          bounds={"hot": ScaleBounds(1, 4),
                                  "idle": ScaleBounds(1, 8)})
    with MeshScheduler(policy="fair", flight_dir=d,
                       autoscale=pol) as sched:
        sched.submit(_drill_job("hot", GRID_HOT, priority=2,
                                deadline_s=120.0))
        sched.submit(_drill_job("idle", GRID_IDLE))
        sched.run()
        hot, idle = sched.job("hot"), sched.job("idle")
        assert (hot.state, hot.error) == ("done", None)
        assert (idle.state, idle.error) == ("done", None)
        # the loop converged with no operator input
        assert tuple(int(x) for x in hot.gg.dims) == (4, 1, 1)
        assert tuple(int(x) for x in idle.gg.dims) == (1, 1, 1)
        # bit-identity: the resizes were exact re-blockings and the
        # re-tuned knobs are bit-exact transport knobs
        np.testing.assert_array_equal(_interior(sched, "hot"), ref_hot)
        np.testing.assert_array_equal(_interior(sched, "idle"), ref_idle)
        # per-job target gauge tracks the final allocation (scoped
        # series retire when the scheduler closes — read them live)
        tgt = reg.get(hooks.JOB_TARGET_DEVICES)
        assert tgt.value(job="hot") == 4.0
        assert tgt.value(job="idle") == 1.0

    # -- explainability: the journal alone reconstructs the WHY --------
    rec = explain_autoscale(d)
    assert rec["policy"]["grow_slack_s"] == 1e9
    assert rec["filed"] >= 4 and rec["decisions"] > rec["filed"]
    assert rec["rejected_by_reason"].get("hysteresis", 0) >= 1
    applied = [m for m in rec["moves"] if m["applied"]]
    assert {(m["job"], m["action"]) for m in applied} >= {
        ("hot", "grow"), ("idle", "shrink")}
    full_chain = ["autoscale_decision", "control", "resize_requested",
                  "job_resized", "job_retuned"]
    for m in applied:
        # actuation went through the public control path and re-tuned
        assert m["chain"] == full_chain, m
        be = m["pricing"]["break_even"]
        if m["action"] == "grow":
            # a grow files only when priced break-even lands inside the
            # job's remaining horizon
            assert be["within_horizon"] is True
            assert be["break_even_steps"] <= be["nt_remaining"]
        assert m["pricing"]["new_dims"] == m["new_dims"]
        assert m["signals"]["queue"] is not None
    # every applied resize traces back to a filed decision: no private
    # path into the mesh
    events = [json.loads(line) for line in
              open(os.path.join(d, "scheduler.jsonl"))]
    resized = [e for e in events if e.get("kind") == "job_resized"]
    assert len(resized) == len(applied)
    # every applied resize re-tuned (plus possibly extra perf-drift
    # re-tunes — the stale-config path now re-tunes instead of clearing)
    retuned = [e for e in events if e.get("kind") == "job_retuned"]
    assert len([e for e in retuned if e["reason"] == "resize"]) \
        == len(applied)
    assert all("predicted_step_s" in e for e in retuned)

    # -- the report folds the same story -------------------------------
    rep = service_report(d, include_jobs=False)
    assert rep["autoscale"]["filed"] == rec["filed"]
    assert rep["jobs"]["hot"]["resizes"] >= 1
    assert rep["jobs"]["idle"]["resizes"] >= 1

    # -- counters track the journal ------------------------------------
    fam = reg.get(hooks.AUTOSCALE_DECISIONS)
    # the counters count EVERY verdict; the journal collapses repeated
    # identical rejections — so the family can only run ahead of it
    assert sum(v for _, v in fam.samples()) >= rec["decisions"]
    assert fam.value(action="grow", verdict="filed") >= 1
    assert fam.value(action="shrink", verdict="filed") >= 1
    assert reg.get(hooks.AUTOSCALE_RESIZES).value() == rec["filed"]


def test_autoscale_drill_hlo_untouched(tmp_path):
    """HLO audit: the chunk program a geometry compiles to is identical
    before and after the autoscaler has priced, filed, and re-tuned
    moves in the same process — the policy engine lives entirely outside
    the compiled artifact."""
    import jax
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )
    from implicitglobalgrid_tpu.parallel.topology import AXIS_NAMES
    from implicitglobalgrid_tpu.utils.compat import shard_map

    def _hlo():
        igg.init_global_grid(quiet=True, nx=18, ny=18, nz=18,
                             dimx=2, dimy=2, dimz=1, overlaps=(2, 2, 2))
        try:
            from implicitglobalgrid_tpu.parallel.topology import (
                global_grid,
            )

            gg = global_grid()
            T, Cp, p = init_diffusion3d(dtype=np.float32)
            spec = P(*AXIS_NAMES)

            def run(T, Cp):
                return diffusion_step_local(T, Cp, p, "xla")

            fn = jax.jit(shard_map(run, mesh=gg.mesh,
                                   in_specs=(spec, spec),
                                   out_specs=spec))
            return fn.lower(T, Cp).compile().as_text()
        finally:
            igg.finalize_global_grid()

    before = _hlo()
    d = str(tmp_path / "svc")
    pol = AutoscalePolicy(grow_slack_s=1e9, shrink_queue_pending=0,
                          hysteresis_slices=1, cooldown_slices=0,
                          bounds={"idle": ScaleBounds(1, 8)})
    with MeshScheduler(policy="fair", flight_dir=d,
                       autoscale=pol) as sched:
        sched.submit(_drill_job("idle", GRID_IDLE))
        sched.run()
    assert explain_autoscale(d)["decisions"] > 0  # the policy DID run
    assert _hlo() == before
