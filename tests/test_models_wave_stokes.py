"""Acoustic-wave and PT-Stokes model tests: distributed == single-device on
the implicit global grid, plus physics sanity (wave propagates, PT iteration
converges, buoyancy drives flow)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import (
    init_acoustic3d, init_stokes3d, run_acoustic, run_stokes,
    stokes_residuals,
)


def _acoustic(nx, dims, nt, overlap=False, periods=(0, 0, 0)):
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    state, p = init_acoustic3d(dtype=np.float64, overlap=overlap)
    state = run_acoustic(state, p, nt, nt_chunk=5)
    out = [igg.gather_interior(a) for a in state]
    igg.finalize_global_grid()
    return out


def test_acoustic_distributed_matches_single():
    multi = _acoustic(6, (2, 2, 2), nt=12)
    single = _acoustic(10, (1, 1, 1), nt=12)
    for m, s in zip(multi, single):
        assert m.shape == s.shape
        assert np.allclose(m, s, rtol=0, atol=1e-12)


def test_acoustic_overlap_matches_plain():
    a = _acoustic(8, (2, 2, 2), nt=10, overlap=False)
    b = _acoustic(8, (2, 2, 2), nt=10, overlap=True)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


def test_acoustic_f32_stays_f32_under_x64():
    """Params must be weak python floats: a np.float64 scalar would promote
    f32 state to f64 under jax_enable_x64 (regression: hide_communication
    dtype mismatch)."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    state, p = init_acoustic3d(dtype=np.float32, overlap=True)
    out = run_acoustic(state, p, 4, nt_chunk=2)
    assert all(a.dtype == np.float32 for a in out)


def test_acoustic_wave_propagates():
    P0 = _acoustic(8, (2, 2, 2), nt=0)[0]
    P1 = _acoustic(8, (2, 2, 2), nt=20)[0]
    # pulse leaves the center, energy radiates outward
    c = P0.shape[0] // 2
    assert P1[c, c, c] < P0[c, c, c]
    assert np.abs(P1).sum() > 0


def _stokes(nx, dims, nt):
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         quiet=True)
    state, p = init_stokes3d(dtype=np.float64)
    state = run_stokes(state, p, nt, nt_chunk=10)
    res = stokes_residuals(state, p)
    out = [igg.gather_interior(state[i]) for i in range(4)]  # P, Vx, Vy, Vz
    igg.finalize_global_grid()
    return out, res


def test_stokes_distributed_matches_single():
    multi, _ = _stokes(6, (2, 2, 2), nt=10)
    single, _ = _stokes(10, (1, 1, 1), nt=10)
    for m, s in zip(multi, single):
        assert m.shape == s.shape
        assert np.allclose(m, s, rtol=0, atol=1e-12)


def test_stokes_converges_and_buoyancy_drives_flow():
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2, quiet=True)
    state, p = init_stokes3d(dtype=np.float64)
    r0 = stokes_residuals(state, p)
    state = run_stokes(state, p, 60, nt_chunk=30)
    r1 = stokes_residuals(state, p)
    # momentum residual drops as the PT iteration relaxes
    assert r1[1] < r0[1]
    # the buoyant sphere drives upward flow at the domain center
    Vz = igg.gather_interior(state[3])
    c = Vz.shape[0] // 2
    assert Vz[c, c, c] > 0


@pytest.mark.parametrize("dims,periods,label", [
    ((1, 1, 1), (1, 1, 1), "all self-neighbor"),
    ((2, 2, 2), (1, 1, 1), "all multi-shard periodic"),
    ((2, 2, 2), (0, 0, 0), "all multi-shard PROC_NULL edges"),
    ((1, 2, 4), (1, 0, 1), "self x + PROC_NULL y + 4-shard z"),
    ((1, 1, 1), (0, 0, 0), "no exchange at all"),
])
def test_acoustic_pallas_fused_matches_xla(dims, periods, label):
    """The fused acoustic Pallas pass (updates + 4-field exchange in ONE
    kernel, `ops/pallas_wave.py`) must reproduce the XLA step + sequential
    per-field exchanges over a multi-step run — staggered send slabs,
    PROC_NULL masking, and cross-field corner semantics included."""
    from implicitglobalgrid_tpu.ops.pallas_wave import wave_exchange_modes

    igg.init_global_grid(8, 8, 16, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    gg = igg.global_grid()
    state, p = init_acoustic3d(dtype=np.float32)
    shapes = tuple(
        tuple(int(s) // int(gg.dims[d]) for d, s in enumerate(a.shape))
        for a in state)
    modes = wave_exchange_modes(gg, shapes)
    assert modes is not None, label
    if periods == (0, 0, 0) and dims == (1, 1, 1):
        # nothing exchanges: all-False modes -> pure fused update
        assert not any(any(m) for m in modes.values()), label
    a = run_acoustic(state, p, 6, nt_chunk=3, impl="xla")
    b = run_acoustic(state, p, 6, nt_chunk=3, impl="pallas_interpret")
    for fa, fb, name in zip(a, b, ("P", "Vx", "Vy", "Vz")):
        ga, gb = np.asarray(igg.gather(fa)), np.asarray(igg.gather(fb))
        assert np.allclose(ga, gb, rtol=1e-5, atol=1e-5), (label, name)


def test_acoustic_plane_form_relay_matches_xla(monkeypatch):
    """The plane-per-program wave kernel (local nx=10: indivisible by any
    mp plane count, so the mp gate rejects) with the P[i-1] VMEM relay —
    and with IGG_PLANE_RELAY=0 restoring the third pressure stream; both
    must match the XLA formulation."""
    from implicitglobalgrid_tpu.ops.pallas_wave import wave_mp_planes

    monkeypatch.delenv("IGG_PLANE_RELAY", raising=False)
    igg.init_global_grid(10, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    assert wave_mp_planes((10, 8, 16), np.float32, interpret=True) is None
    state, p = init_acoustic3d(dtype=np.float32)
    a = run_acoustic(state, p, 6, nt_chunk=3, impl="xla")
    b = run_acoustic(state, p, 6, nt_chunk=3, impl="pallas_interpret")
    for fa, fb, name in zip(a, b, ("P", "Vx", "Vy", "Vz")):
        ga, gb = np.asarray(igg.gather(fa)), np.asarray(igg.gather(fb))
        assert np.allclose(ga, gb, rtol=1e-5, atol=1e-5), name
    # flag off IN-EPOCH: retraced (runner keys on kernel_flags) and equal
    monkeypatch.setenv("IGG_PLANE_RELAY", "0")
    c = run_acoustic(state, p, 6, nt_chunk=3, impl="pallas_interpret")
    for fb, fc in zip(b, c):
        assert np.array_equal(np.asarray(fb), np.asarray(fc))


def test_stokes_relay_flag_equivalence(monkeypatch):
    """The Stokes [i-1]-stream relay: flag on vs off produces identical
    kernel output (same grid epoch; the runner cache keys on the flag)."""
    monkeypatch.delenv("IGG_PLANE_RELAY", raising=False)
    igg.init_global_grid(8, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    state, p = init_stokes3d(dtype=np.float32)
    b = run_stokes(state, p, 4, nt_chunk=2, impl="pallas_interpret")
    monkeypatch.setenv("IGG_PLANE_RELAY", "0")
    c = run_stokes(state, p, 4, nt_chunk=2, impl="pallas_interpret")
    for fb, fc in zip(b, c):
        assert np.array_equal(np.asarray(fb), np.asarray(fc))


def test_acoustic_pallas_window_handoff_matches_xla(monkeypatch):
    """The acoustic pressure window with the VMEM overlap handoff
    (local nx=12, P=4 -> 3 windows): fused pass equality vs the XLA
    formulation."""
    monkeypatch.delenv("IGG_MP_HANDOFF", raising=False)
    from implicitglobalgrid_tpu.ops.pallas_wave import wave_mp_planes

    igg.init_global_grid(12, 8, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    assert wave_mp_planes((12, 8, 16), np.float32, interpret=True) == 4
    state, p = init_acoustic3d(dtype=np.float32)
    a = run_acoustic(state, p, 6, nt_chunk=3, impl="xla")
    b = run_acoustic(state, p, 6, nt_chunk=3, impl="pallas_interpret")
    for fa, fb, name in zip(a, b, ("P", "Vx", "Vy", "Vz")):
        ga, gb = np.asarray(igg.gather(fa)), np.asarray(igg.gather(fb))
        assert np.allclose(ga, gb, rtol=1e-5, atol=1e-5), name


@pytest.mark.parametrize("dims,periods,label", [
    ((1, 1, 1), (1, 1, 1), "all self-neighbor"),
    ((2, 2, 2), (0, 0, 0), "all multi-shard PROC_NULL edges"),
    ((2, 2, 2), (1, 1, 1), "all multi-shard periodic"),
    ((1, 2, 4), (1, 0, 1), "self x + PROC_NULL y + 4-shard z"),
])
def test_stokes_pallas_fused_matches_xla(dims, periods, label):
    """The fused Stokes Pallas pass (all PT updates + 4-field exchange in
    ONE kernel, `ops/pallas_stokes.py`) must reproduce the XLA step +
    sequential exchanges over a multi-iteration run."""
    from implicitglobalgrid_tpu.ops.pallas_stokes import stokes_exchange_modes

    igg.init_global_grid(8, 8, 16, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    gg = igg.global_grid()
    state, p = init_stokes3d(dtype=np.float32)
    shapes = tuple(
        tuple(int(s) // int(gg.dims[d]) for d, s in enumerate(a.shape))
        for a in state)
    assert stokes_exchange_modes(gg, shapes) is not None, label
    a = run_stokes(state, p, 4, nt_chunk=2, impl="xla")
    b = run_stokes(state, p, 4, nt_chunk=2, impl="pallas_interpret")
    names = ("P", "Vx", "Vy", "Vz", "dVx", "dVy", "dVz", "rhog")
    for fa, fb, name in zip(a, b, names):
        ga, gb = np.asarray(igg.gather(fa)), np.asarray(igg.gather(fb))
        scale = max(1e-30, np.abs(ga).max())
        assert np.allclose(ga, gb, rtol=1e-4, atol=1e-5 * scale), (
            label, name, np.abs(ga - gb).max())
