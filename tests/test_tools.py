"""Tests of the query layer — port of `test/test_tools.jl` ideas: global
sizes incl. staggered-array overloads (`test_tools.jl` / reference
`tools.jl:24-59`), and the x_g/y_g/z_g coordinate math with staggering and
periodic wrap, swept over simulated shard coordinates (the reference's
simulated-topology technique, `test_tools.jl:116-163`)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg


def test_nx_g_plain_and_staggered():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    assert (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (8, 8, 8)
    A = np.zeros((5, 5, 5))
    Vx = np.zeros((6, 5, 5))
    Vy = np.zeros((5, 6, 5))
    Vz = np.zeros((5, 5, 6))
    assert igg.nx_g(A) == 8 and igg.nx_g(Vx) == 9
    assert igg.ny_g(Vy) == 9 and igg.ny_g(Vx) == 8
    assert igg.nz_g(Vz) == 9
    # stacked-global arrays give the same answers
    assert igg.nx_g(igg.zeros_g()) == 8
    assert igg.nx_g(igg.zeros_g((6, 5, 5))) == 9


def test_x_g_doctest_values():
    # reference doctest (tools.jl:67-96): lx=4, nx=ny=nz=3, 1 "process"
    igg.init_global_grid(3, 3, 3, dimx=1, dimy=1, dimz=1, quiet=True)
    dx = 4 / (igg.nx_g() - 1)
    assert dx == 2.0
    A = np.zeros((3, 3, 3))
    Vx = np.zeros((4, 3, 3))
    assert [igg.x_g(i, dx, A) for i in range(3)] == [0.0, 2.0, 4.0]
    assert [igg.x_g(i, dx, Vx) for i in range(4)] == [-1.0, 1.0, 3.0, 5.0]
    assert [igg.y_g(i, dx, np.zeros((3, 4, 3))) for i in range(4)] == [-1.0, 1.0, 3.0, 5.0]
    assert [igg.z_g(i, dx, np.zeros((3, 3, 4))) for i in range(4)] == [-1.0, 1.0, 3.0, 5.0]


def test_x_g_multi_shard_coverage():
    # dims=(3,1,1), nx=4, ol=2: nxyz_g = 3*2+2 = 8; block c covers (c*2 .. c*2+3)
    igg.init_global_grid(4, 3, 3, dimx=3, dimy=1, dimz=1, quiet=True)
    assert igg.nx_g() == 8
    A = np.zeros((4, 3, 3))
    for c in range(3):
        xs = [igg.x_g(i, 1.0, A, coords=c) for i in range(4)]
        assert xs == [c * 2 + i for i in range(4)]


def test_x_g_periodic_wrap():
    # periodic: ghost-cell shift by -dx then wrap into [0, nx_g*dx) (tools.jl:102-104)
    igg.init_global_grid(4, 3, 3, dimx=3, dimy=1, dimz=1, periodx=1, quiet=True)
    assert igg.nx_g() == 6
    A = np.zeros((4, 3, 3))
    assert [igg.x_g(i, 1.0, A, coords=0) for i in range(4)] == [5.0, 0.0, 1.0, 2.0]
    assert [igg.x_g(i, 1.0, A, coords=2) for i in range(4)] == [3.0, 4.0, 5.0, 0.0]
    # every global cell covered exactly once by the interior cells
    cover = sorted(
        igg.x_g(i, 1.0, A, coords=c) for c in range(3) for i in range(1, 3)
    )
    assert cover == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]


def test_x_g_stacked_equals_local():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.zeros_g()
    A = np.zeros((5, 5, 5))
    for c in range(2):
        for i in range(5):
            assert igg.x_g(c * 5 + i, 0.5, T) == igg.x_g(i, 0.5, A, coords=c)
            assert igg.y_g(c * 5 + i, 0.5, T) == igg.y_g(i, 0.5, A, coords=c)


def test_coords_g_broadcastable():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.zeros_g()
    x, y, z = igg.coords_g(1.0, 1.0, 1.0, T)
    assert x.shape == (10, 1, 1) and y.shape == (1, 10, 1) and z.shape == (1, 1, 10)
    assert float(x[5, 0, 0]) == igg.x_g(5, 1.0, T)
    # staggered
    Vx = igg.zeros_g((6, 5, 5))
    xs, _, _ = igg.coords_g(1.0, 1.0, 1.0, Vx)
    assert xs.shape == (12, 1, 1)
    assert float(xs[0, 0, 0]) == igg.x_g(0, 1.0, Vx)


def test_x_g_vec_matches_scalar():
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, periody=1, quiet=True)
    T = igg.zeros_g()
    xv = np.asarray(igg.x_g_vec(0.25, T))
    yv = np.asarray(igg.y_g_vec(0.25, T))
    for i in range(8):
        assert xv[i] == igg.x_g(i, 0.25, T)
        assert yv[i] == igg.y_g(i, 0.25, T)


def test_simulated_topology_mutation():
    # the reference mutates the (intentionally mutable) grid vectors to fake
    # topologies (shared.jl:57 comment; test_tools.jl:116-134) — same here.
    igg.init_global_grid(4, 4, 4, dimx=1, dimy=1, dimz=1, quiet=True)
    gg = igg.global_grid()
    gg.dims[:] = [3, 3, 3]
    gg.nxyz_g[:] = gg.dims * (gg.nxyz - gg.overlaps) + gg.overlaps * (gg.periods == 0)
    assert igg.nx_g() == 3 * 2 + 2
    A = np.zeros((4, 4, 4))
    # sweep all simulated coordinates: consistent overlap between neighbors
    for c in range(2):
        right_edge = [igg.x_g(i, 1.0, A, coords=c) for i in (2, 3)]
        left_edge = [igg.x_g(i, 1.0, A, coords=c + 1) for i in (0, 1)]
        assert right_edge == left_edge


def test_tic_toc():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.tic()
    t = igg.toc()
    assert t >= 0.0
    with pytest.raises(Exception):
        igg.finalize_global_grid(); igg.tic()


def test_layout_override_disambiguates_small_blocks():
    """Explicit layout= kwarg vs the `local_shape_of` inference heuristic:
    a block whose size equals dims*nxyz is read as stacked by default; the
    override forces the local reading (and validates stacked divisibility)."""
    from implicitglobalgrid_tpu.ops.fields import local_shape_of
    from implicitglobalgrid_tpu.utils.exceptions import (
        IncoherentArgumentError, InvalidArgumentError,
    )

    igg.init_global_grid(4, 4, 4, dimx=2, dimy=1, dimz=1, quiet=True)
    # ambiguous: 8 == 2*4 (stacked) but could be a heavily staggered local
    assert local_shape_of((8, 4, 4)) == (4, 4, 4)            # inferred stacked
    assert local_shape_of((8, 4, 4), "local") == (8, 4, 4)
    assert local_shape_of((8, 4, 4), "stacked") == (4, 4, 4)
    # nx_g follows: nxyz_g = 2*(4-2)+2 = 6
    A = np.zeros((8, 4, 4))
    assert igg.nx_g(A) == 6
    assert igg.nx_g(A, layout="local") == 6 + (8 - 4)
    with pytest.raises(IncoherentArgumentError):
        local_shape_of((7, 4, 4), "stacked")
    with pytest.raises(InvalidArgumentError):
        local_shape_of((8, 4, 4), "global")


@pytest.mark.audit
def test_audit_cli_json_schema_and_model_smoke(capsys):
    """`tools audit` smoke on both main model families in one invocation:
    rc 0, and the --json schema carries the contract verdict, the
    findings list, the collective summary, and the perfmodel crosscheck
    per program."""
    import json

    from implicitglobalgrid_tpu.tools import _cli

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1,
                         periody=1, periodz=1, quiet=True)
    rc = _cli(["audit", "diffusion3d", "acoustic3d", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["ok"] is True
    assert [p["name"] for p in out["programs"]] \
        == ["diffusion3d", "acoustic3d"]
    for prog in out["programs"]:
        assert prog["ok"] is True and prog["dialect"] == "hlo"
        assert prog["errors"] == 0 and prog["findings"] == []
        assert prog["collectives"]["all_gathers"] == 0
        assert prog["collectives"]["permutes"] > 0
        assert prog["crosscheck"]["ok"] is True
        assert set(prog["crosscheck"]["axes"]) == {"gx", "gy", "gz"}
        assert isinstance(prog["inventory"], dict)
    # the human-readable form of the same audit also exits 0
    assert _cli(["audit", "diffusion3d"]) == 0
    assert "diffusion3d: OK" in capsys.readouterr().out


@pytest.mark.audit
def test_audit_cli_exit_1_on_contract_violation(tmp_path, capsys):
    """An injected contract violation (the golden single-axis exchange
    checked against a contract demanding a guard psum it doesn't have)
    EXITS 1 and names the broken rule — host-only, no grid, no compile."""
    import json
    import os
    import shutil

    from implicitglobalgrid_tpu.tools import _cli

    fixture = os.path.join(os.path.dirname(__file__), "data", "hlo",
                           "exchange_single_axis.hlo.txt")
    hlo = tmp_path / "prog.hlo.txt"
    shutil.copy(fixture, hlo)
    contract = tmp_path / "contract.json"
    contract.write_text(json.dumps(
        {"allreduces": 1, "allreduce_payload": ["f32", 4]}))
    rc = _cli(["audit", "--hlo", str(hlo), "--contract", str(contract),
               "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["ok"] is False
    rules = [f["rule"] for f in out["programs"][0]["findings"]]
    assert "allreduce-count" in rules
    assert all(f["severity"] in ("error", "warning", "info")
               for f in out["programs"][0]["findings"])
    # without the contract the same dump lints clean -> rc 0
    assert _cli(["audit", "--hlo", str(hlo), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True
    # --wire-dtype applies to captured dumps too: this dump's payloads
    # are f32, so a claimed bf16 wire is a caught downcast-missing error
    rc = _cli(["audit", "--hlo", str(hlo), "--wire-dtype", "bfloat16",
               "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in out["programs"][0]["findings"]] \
        == ["wire-downcast-missing"]


@pytest.mark.audit
def test_audit_cli_argument_validation():
    from implicitglobalgrid_tpu.tools import _cli
    from implicitglobalgrid_tpu.utils.exceptions import (
        InvalidArgumentError,
    )

    with pytest.raises(InvalidArgumentError):
        _cli(["audit"])  # neither models nor --hlo
    with pytest.raises(InvalidArgumentError):
        _cli(["audit", "diffusion3d", "--hlo", "x.txt"])  # both


@pytest.mark.service
def test_jobs_cli_submit_list_status_control(tmp_path, capsys):
    """`tools jobs` smoke, exit codes included: submit runs a
    JSON-described queue through one scheduler (rc 1 when a job fails —
    here an unsatisfiable grid fails at admission while the good job
    completes), list/status answer post-hoc from the journal (rc 3 for
    an unknown name), cancel/drain file control requests (rc 4 for an
    already-finished job)."""
    import json

    from implicitglobalgrid_tpu.tools import _cli

    fd = str(tmp_path / "fd")
    queue = tmp_path / "queue.json"
    queue.write_text(json.dumps({"policy": "fifo", "jobs": [
        {"name": "ok", "model": "diffusion3d", "dtype": "float64",
         "nt": 4, "grid": {"nx": 6, "ny": 6, "nz": 6, "dimx": 2,
                           "dimy": 2, "dimz": 1},
         "run": {"nt_chunk": 2}},
        # 16 shards > the 8-device pool: fails at admission, no compile
        {"name": "toobig", "model": "diffusion3d", "nt": 4,
         "grid": {"nx": 6, "ny": 6, "nz": 6, "dimx": 16, "dimy": 1,
                  "dimz": 1}},
    ]}))
    rc = _cli(["jobs", "submit", str(queue), "--flight-dir", fd,
               "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # one job failed -> the batch entry is not ok
    assert out["ok"] is False
    by_name = {j["name"]: j for j in out["jobs"]}
    assert by_name["ok"]["state"] == "done"
    assert by_name["ok"]["step"] == 4
    assert by_name["toobig"]["state"] == "failed"
    assert "InvalidArgumentError" in by_name["toobig"]["error"]

    assert _cli(["jobs", "list", fd]) == 0
    listing = capsys.readouterr().out
    assert "ok" in listing and "toobig" in listing
    assert _cli(["jobs", "status", fd, "ok"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["state"] == "done"
    assert rec["report"]["steps"]["completed"] == 4
    assert _cli(["jobs", "status", fd, "nope"]) == 3
    capsys.readouterr()
    # control requests: unknown -> 3, finished -> 4, drain files its
    # request for a live scheduler to consume
    assert _cli(["jobs", "cancel", fd, "nope"]) == 3
    assert _cli(["jobs", "cancel", fd, "ok"]) == 4
    assert _cli(["jobs", "drain", fd]) == 0
    capsys.readouterr()
    import os

    assert os.path.exists(os.path.join(fd, "control", "drain"))
    # queue JSON validation: a typo'd/misplaced knob must fail loudly,
    # never silently run with defaults
    from implicitglobalgrid_tpu.utils.exceptions import (
        InvalidArgumentError,
    )

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"jobs": [
        {"name": "x", "model": "diffusion3d", "nt": 4, "nt_chunk": 2}]}))
    with pytest.raises(InvalidArgumentError, match="unknown key"):
        _cli(["jobs", "submit", str(bad)])
    bad.write_text(json.dumps({"jobs": [{"name": "x", "nt": 4}]}))
    with pytest.raises(InvalidArgumentError, match="missing required"):
        _cli(["jobs", "submit", str(bad)])


def test_layout_override_coordinate_helpers():
    """x_g must honor layout= for the same ambiguous block the nx_g test
    documents: a (8,4,4) LOCAL block on a dims=(2,1,1) grid reads as stacked
    by default (divmod over the inferred shard), but layout='local' +
    explicit coords gives the true local-block coordinates."""
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=1, dimz=1, quiet=True)
    A = np.zeros((8, 4, 4))
    # default inference: stacked -> ix=5 is shard 1, local 1 -> (1*(4-2)+1)
    assert igg.x_g(5, 1.0, A) == 1 * (4 - 2) + 1
    # forced local reading on shard 0: ix=5 is local index 5 of a staggered
    # block (x0 offset = 0.5*(4-8)*dx = -2)
    assert igg.x_g(5, 1.0, A, coords=0, layout="local") == 5 - 2.0
    v = igg.x_g_vec(1.0, A, layout="local")
    assert v.shape[0] == 2 * 8  # stacked vector over the local size
