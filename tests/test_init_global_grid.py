"""Tests of grid initialization — port of the reference's
`test/test_init_global_grid.jl` ideas: return values, implicit-global-size
formula, argument defaults, and the full error-path catalog
(`test_init_global_grid.jl:96-116`)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import (
    AlreadyInitializedError, IncoherentArgumentError, InvalidArgumentError,
    NotInitializedError,
)


def test_basic_init_returns():
    me, dims, nprocs, coords, mesh = igg.init_global_grid(4, 4, 4, quiet=True)
    assert me == 0
    assert nprocs == 8 and int(np.prod(dims)) == 8
    assert mesh.shape == {"gx": int(dims[0]), "gy": int(dims[1]), "gz": int(dims[2])}
    assert igg.grid_is_initialized()


def test_implicit_global_size_formula():
    # nxyz_g = dims*(nxyz-overlaps) + overlaps*(periods==0)  (init_global_grid.jl:107)
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    assert (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (8, 8, 8)
    igg.finalize_global_grid()

    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    assert igg.nx_g() == 2 * (5 - 2)  # periodic: no +overlap term
    assert igg.ny_g() == 8
    igg.finalize_global_grid()

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), quiet=True)
    assert igg.nx_g() == 2 * (8 - 4) + 4


def test_degenerate_dims_pinned():
    # nxyz==1 pins the corresponding dims entry to 1 (init_global_grid.jl:91)
    me, dims, nprocs, *_ = igg.init_global_grid(16, 16, 1, quiet=True)
    assert dims[2] == 1
    assert nprocs == 8 and dims[0] * dims[1] == 8


def test_fixed_dims_use_device_subset():
    me, dims, nprocs, *_ = igg.init_global_grid(4, 4, 4, dimx=2, dimy=1, dimz=1, quiet=True)
    assert nprocs == 2 and list(dims) == [2, 1, 1]


def test_partially_fixed_dims_use_device_subset():
    # 8-device pool, dimx=3: 8 is not a multiple of 3 — fall back to the
    # largest usable subset (6 devices, free dims filled over 6/3=2)
    # instead of a divisibility error (round-3 verdict item 9).
    me, dims, nprocs, *_ = igg.init_global_grid(
        4, 4, 4, dimx=3, quiet=True)
    assert nprocs == 6 and dims[0] == 3 and int(np.prod(dims)) == 6
    igg.finalize_global_grid()

    # prime fixed dim larger than any divisor: subset of exactly `fixed`
    me, dims, nprocs, *_ = igg.init_global_grid(8, 8, 8, dimx=5, quiet=True)
    assert nprocs == 5 and list(dims) == [5, 1, 1]
    igg.finalize_global_grid()

    # fixed dims exceeding the pool: actionable error
    with pytest.raises(InvalidArgumentError, match="device pool"):
        igg.init_global_grid(32, 32, 32, dimx=16, quiet=True)


def test_default_halowidths():
    igg.init_global_grid(8, 8, 8, overlaps=(4, 4, 2), quiet=True)
    gg = igg.global_grid()
    assert list(gg.halowidths) == [2, 2, 1]  # max(1, overlaps//2)


def test_quiet_banner(capsys):
    igg.init_global_grid(4, 4, 4, quiet=True)
    assert capsys.readouterr().out == ""
    igg.finalize_global_grid()
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2)
    out = capsys.readouterr().out
    assert "Global grid: 8x8x8" in out and "nprocs: 8" in out and "2x2x2" in out


def test_error_paths():
    # catalog from test_init_global_grid.jl:96-116
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(0, 4, 4, quiet=True)      # nxyz < 1
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(1, 4, 4, quiet=True)      # nx can never be 1
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 1, 4, quiet=True)      # ny==1 while nz>1
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 4, 4, dimx=-1, quiet=True)
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 4, 4, periodx=2, quiet=True)
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 4, 4, halowidths=(0, 1, 1), quiet=True)
    with pytest.raises(IncoherentArgumentError):
        igg.init_global_grid(4, 4, 1, dimz=2, quiet=True)       # nz==1 but dimz=2
    with pytest.raises(IncoherentArgumentError):
        igg.init_global_grid(2, 4, 4, periodx=1, quiet=True)    # nx < 2*ol-1 with periodic
    with pytest.raises(IncoherentArgumentError):
        igg.init_global_grid(8, 8, 8, halowidths=(2, 1, 1), quiet=True)  # hw > ol//2
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 4, 4, device_type="rocm", quiet=True)
    with pytest.raises(InvalidArgumentError, match="device pool"):
        igg.init_global_grid(4, 4, 4, dimx=5, dimy=2, quiet=True)  # fixed 10 > 8 devices
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 4, 4, dimx=5, dimy=2, dimz=1, quiet=True)  # 10 > 8 devices
    assert not igg.grid_is_initialized()


def test_double_init_and_not_initialized():
    igg.init_global_grid(4, 4, 4, quiet=True)
    with pytest.raises(AlreadyInitializedError):
        igg.init_global_grid(4, 4, 4, quiet=True)
    igg.finalize_global_grid()
    with pytest.raises(NotInitializedError):
        igg.nx_g()
    with pytest.raises(NotInitializedError):
        igg.finalize_global_grid()


def test_rejected_env_vars(monkeypatch):
    # reference rejects legacy env vars (init_global_grid.jl:57); the TPU
    # build rejects the GPU-aware-MPI family (N/A on ICI).
    monkeypatch.setenv("IGG_CUDAAWARE_MPI", "1")
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(4, 4, 4, quiet=True)


def test_select_device_shim():
    igg.init_global_grid(4, 4, 4, quiet=True)
    assert isinstance(igg.select_device(), int)
