"""True multi-process (multi-controller) run — the analog of the reference's
`mpirun -np N` test technique (`/root/reference/test/runtests.jl`,
SURVEY.md §4 item 2).

Spawns 2 OS processes that `jax.distributed.initialize` against a local
coordinator, each contributing 4 virtual CPU devices, then runs the full
framework flow over the 8-device 2-process mesh:

- `init_global_grid` with `init_dist=False` (runtime already initialized)
- per-controller `coords` (reference per-rank `Cart_coords` semantics)
- `device_put_g` / `update_halo` over the multi-process mesh
- `gather` through the `process_allgather` path (non-addressable shards)
- `tic`/`toc` cross-process barrier

Exercises exactly the paths VERDICT round 1 flagged as untested.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent("""
    import os, sys
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    dcn = sys.argv[4] if len(sys.argv) > 4 else ""
    ndev = int(sys.argv[5]) if len(sys.argv) > 5 else 4
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={ndev}")
    if dcn:
        os.environ["IGG_TPU_DCN_AXES"] = dcn
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)
    import numpy as np
    import implicitglobalgrid_tpu as igg

    me, dims, nprocs, coords, mesh = igg.init_global_grid(
        5, 5, 5, dimx=2, dimy=2, dimz=2, periodx=1, periody=1, periodz=1,
        quiet=True, init_dist=False, reorder=0)
    assert me == pid, (me, pid)
    assert nprocs == 8
    assert tuple(dims) == (2, 2, 2)
    if dcn == "z":
        # hybrid layout: each process (DCN granule) owns one z-block —
        # every x/y ppermute is intra-process, only z crosses the "DCN"
        for idx in np.ndindex(2, 2, 2):
            assert mesh.devices[idx].process_index == idx[2], (idx,)
        expect_coords = (0, 0, 0) if pid == 0 else (0, 0, 1)
    elif dcn == "y,z":
        # two DCN axes, 4 granules: _dcn_factorization gives dcn=(1,2,2),
        # ici=(2,1,1) — granule g owns the (y, z) = (g//2, g%2) block,
        # spanning the full x axis intra-process; only y/z boundary
        # permutes cross the "DCN"
        for idx in np.ndindex(2, 2, 2):
            assert mesh.devices[idx].process_index == idx[1] * 2 + idx[2], (idx,)
        expect_coords = (0, pid // 2, pid % 2)
    else:
        # plain order: process 1's first device is mesh position (1,0,0)
        expect_coords = (0, 0, 0) if pid == 0 else (1, 0, 0)
    assert tuple(coords) == expect_coords, (tuple(coords), expect_coords)

    # encoded restoration through the multi-process exchange + allgather
    A = igg.zeros_g(dtype=np.float32)
    x, y, z = igg.coords_g(1.0, 1.0, 1.0, A)
    enc = (x + 1e3 * y + 1e6 * z).astype(np.float32)
    enc = np.broadcast_to(enc, (10, 10, 10)).copy()
    zeroed = enc.copy()
    for d in range(3):            # zero every block's halos
        for c in range(2):
            sl = [slice(None)] * 3
            sl[d] = slice(c * 5, c * 5 + 1)
            zeroed[tuple(sl)] = 0
            sl[d] = slice((c + 1) * 5 - 1, (c + 1) * 5)
            zeroed[tuple(sl)] = 0
    Ad = igg.device_put_g(zeroed)
    res = igg.update_halo(Ad)
    g = igg.gather(res, root=0)   # process_allgather path (not addressable)
    if pid == 0:
        assert g is not None
        assert np.array_equal(np.asarray(g), enc), "halo restoration failed"
    else:
        assert g is None

    igg.tic()
    t = igg.toc(sync_on=res)
    assert t >= 0.0

    # node-local grouping (Comm_split_type analog): all children share this
    # host, so the rank must be pid and the device pool the full mesh
    from implicitglobalgrid_tpu.parallel.grid import node_local_rank
    me_l, nprocs_node, dev_node = node_local_rank()
    assert me_l == pid and nprocs_node == nproc, (me_l, nprocs_node)
    assert dev_node == 8
    assert igg.select_device() >= 0

    # sub-communicator gather: root-coordinates shard only
    sub = igg.gather_sub(res, ((0, 1), (0, 1), (0, 1)), root=0)
    if pid == 0:
        assert np.array_equal(np.asarray(sub), enc[0:5, 0:5, 0:5])
    else:
        assert sub is None

    # sharded checkpoint: each process writes ONLY its addressable shards
    # (no full gather anywhere — the pod-scale path, verdict r3 item 7),
    # then restore reassembles the exact state by block coordinates
    ckdir = os.path.join(os.path.dirname(os.path.abspath(sys.argv[0])),
                         "ckpt_sharded")
    igg.save_checkpoint_sharded(ckdir, {"A": res}, step=3)
    with np.load(os.path.join(ckdir, f"shards_p{pid}.npz")) as z:
        own_blocks = [k for k in z.files if k.startswith("__igg_arr__A__")]
        assert len(own_blocks) == ndev, own_blocks   # only OUR shards
    st, sp = igg.restore_checkpoint_sharded(ckdir)
    assert sp == 3
    g2 = igg.gather(st["A"], root=0)
    if pid == 0:
        assert np.array_equal(np.asarray(g2), enc), "sharded restore failed"

    igg.finalize_global_grid()
    print(f"MP_OK {pid}", flush=True)
""")


_CHILD_DEEP = textwrap.dedent("""
    import os, sys
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    ndev = int(sys.argv[5]) if len(sys.argv) > 5 else 4
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={ndev}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)
    import numpy as np
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

    # the canonical integer-global-index IC builder (same float at the
    # same physical cell for any overlap width) — one copy of the subtle
    # wrap math, shared with the single-process bitwise tests
    from tests.test_comm_avoid import _stacked_from_global_index

    def stacked(n, k, fn):
        return _stacked_from_global_index(n, k, (2, 2, 2), (1, 1, 1), fn)

    def run(nl, k):
        igg.init_global_grid(nl, nl, nl, dimx=2, dimy=2, dimz=2,
                             periodx=1, periody=1, periodz=1,
                             overlaps=(2*k,)*3, halowidths=(k,)*3,
                             quiet=True, init_dist=False, reorder=0)
        _, _, p = init_diffusion3d(dtype=np.float64, comm_every=k)
        T = igg.device_put_g(stacked(nl, k,
            lambda x, y, z: 100*np.exp(-((x/7.0-1)**2) - ((y/5.0-1)**2)
                                       - ((z/6.0-1)**2))))
        Cp = igg.device_put_g(stacked(nl, k,
            lambda x, y, z: 1.0 + np.exp(-((x/9.0-1)**2) - ((y/8.0-1)**2)
                                         - ((z/7.0-1)**2))))
        out = run_diffusion(T, Cp, p, 8, nt_chunk=8)
        g = igg.gather_interior(out, root=0)
        igg.finalize_global_grid()
        return g

    a = run(8, 1)    # global 12**3, exchange every step
    b = run(10, 2)   # same global grid, 2-wide exchange every 2 steps
    if pid == 0:
        assert a.shape == b.shape == (12, 12, 12), (a.shape, b.shape)
        assert np.array_equal(a, b), (
            f"deep-halo diverged across processes: {np.abs(a-b).max()}")
    print(f"MP_OK {pid}", flush=True)
""")


_CHILD_FLIGHT = textwrap.dedent("""
    import os, sys, time
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    ndev = int(sys.argv[5]) if len(sys.argv) > 5 else 4
    flight_dir = os.path.join(
        os.path.dirname(os.path.abspath(sys.argv[0])), "flights")
    os.makedirs(flight_dir, exist_ok=True)
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={ndev}")
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=pid)
    import numpy as np
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1,
                         quiet=True, init_dist=False, reorder=0)
    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    # the directory convention: every process writes flight_p<i>.jsonl
    igg.start_flight_recorder(flight_dir, run_id="mpflight")
    assert os.path.basename(igg.flight_recorder().path) \\
        == f"flight_p{pid}.jsonl"

    # the straggler poke: process 1 stalls HOST-side at every chunk
    # boundary (on_report runs between chunks) — the aggregated analysis
    # must attribute exactly this process
    def on_report(rep):
        if pid == 1:
            time.sleep(0.25)

    igg.run_resilient(step, {"T": T, "Cp": Cp}, 30, nt_chunk=5,
                      key="mp_flight", on_report=on_report)
    igg.stop_flight_recorder()
    igg.finalize_global_grid()
    print(f"MP_OK {pid}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_children(tmp_path, nproc, dcn, ndev, timeout=240, child=_CHILD):
    script = tmp_path / "child.py"
    script.write_text(child)
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = ""
    env["PYTHONPATH"] = "/root/repo" + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(nproc), str(port),
             dcn, str(ndev)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd="/root/repo",
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    unsupported = "Multiprocess computations aren't implemented on the CPU backend"
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0 and unsupported in out:
            # environment capability, not a framework bug: jax 0.4.x's CPU
            # backend has no cross-process computations (they landed with
            # the jax>=0.5 CPU collectives) — nothing the framework can do
            pytest.skip("this jaxlib's CPU backend cannot run cross-process "
                        "computations (needs the jax>=0.5 CPU collectives)")
        assert p.returncode == 0, f"process {pid} failed:\n{out[-3000:]}"
        assert f"MP_OK {pid}" in out


@pytest.mark.parametrize("dcn", [
    "",
    # tier-1 budget (ISSUE 8 trim): the single-DCN-axis flavor adds a
    # second ~6 s two-subprocess spawn; the DCN layout logic keeps fast
    # coverage in test_mesh_hybrid.py and the four-process flavor below
    # exercises the multi-axis branch on the slow tier
    pytest.param("z", marks=pytest.mark.slow),
])
def test_two_process_distributed_run(tmp_path, dcn):
    _run_children(tmp_path, 2, dcn, 4)


def test_two_process_deep_halo_bitwise(tmp_path):
    """comm_every=2 across REAL process boundaries: the k-wide exchange's
    ppermutes cross the controller split, and the trajectory must still be
    bit-identical to exchange-every-step on the same implicit grid."""
    _run_children(tmp_path, 2, "", 4, timeout=300, child=_CHILD_DEEP)


@pytest.mark.mesh
def test_two_process_flight_aggregation_names_the_straggler(tmp_path):
    """Mesh-wide observability end-to-end (ISSUE 5): two REAL controllers
    run a supervised diffusion under per-process flight recorders (the
    ``flight_p<i>.jsonl`` directory convention), process 1 stalls
    host-side at every chunk boundary, and the post-hoc aggregation must
    (a) merge into one run-id-consistent sequence with matching per-
    process chunk counts, (b) attribute the injected delay to process 1,
    and (c) export a two-track Chrome trace with barrier-aligned chunk
    spans."""
    import implicitglobalgrid_tpu as igg

    _run_children(tmp_path, 2, "", 4, timeout=300, child=_CHILD_FLIGHT)
    d = str(tmp_path / "flights")
    assert sorted(os.listdir(d)) == ["flight_p0.jsonl", "flight_p1.jsonl"]

    agg = igg.aggregate_flight(d)
    assert agg["run_id"] == "mpflight"
    assert agg["processes"] == [0, 1]
    assert agg["align"]["method"][1] == "chunk-barrier"
    assert agg["per_process"][0]["chunks"] == agg["per_process"][1]["chunks"] == 6
    seqs = {e["seq"] for e in agg["events"] if e["proc"] == 0}
    assert seqs == set(range(len(seqs)))  # gapless, validated

    rep = igg.straggler_report(agg, window=4)
    # process 1 slept 0.25s at 5 of 6 boundaries (none after the last
    # chunk's report): it must dominate the slowest attribution and the
    # mean spread must resolve the injected stall (compute per chunk is
    # far smaller on this toy grid)
    assert rep["summary"]["worst_proc"] == 1
    assert rep["slowest_counts"][1] >= 4
    assert rep["summary"]["spread_s_max"] > 0.1
    assert rep["imbalance"][0]["wait_s_total"] \
        > rep["imbalance"][1]["wait_s_total"]
    assert rep["persistent"] and rep["persistent"][0]["proc"] == 1

    # the unified report over the directory carries the mesh section
    report = igg.run_report(d, include_metrics=False)
    assert report["mesh"]["summary"]["worst_proc"] == 1
    assert report["chunks"]["count"] == 6  # anchor process's view

    # Perfetto export: two tracks, chunk spans end barrier-aligned
    doc = igg.export_chrome_trace(agg)
    pids = {e["pid"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert pids == {0, 1}
    for c in (1, 3, 5):
        ends = sorted(e["ts"] + e["dur"] for e in doc["traceEvents"]
                      if e.get("ph") == "X" and e["name"] == f"chunk {c}")
        assert len(ends) == 2
        # aligned to well under the injected 250 ms skew (fetch jitter)
        assert ends[1] - ends[0] < 100e3  # µs


@pytest.mark.slow
def test_four_process_two_dcn_axes(tmp_path):
    """slow (tier-1 budget, ISSUE 8 trim: a ~11 s four-subprocess spawn;
    the two-process spawns remain tier-1). 4 controllers x 2 devices over
    TWO DCN axes (y, z): exercises the
    multi-axis branch of `_dcn_factorization` (balanced (1,2,2) granule
    layout) end-to-end — block layout asserted per device, halo restoration
    through x (intra-granule) and y/z (cross-granule) exchanges."""
    _run_children(tmp_path, 4, "y,z", 2, timeout=300)
