"""Multi-run scheduler tests (ISSUE 8): the mesh as a persistent service.

The acceptance bar is the resilience one, lifted to tenants: N queued
jobs (different models/grid sizes) multiplexed chunk-granularly through
ONE device pool must each finish BIT-IDENTICAL to their solo
`run_resilient` runs, under every shipped policy — and a fault injected
into one job must drive that job's recovery path ONLY (the PR-2
fault-injection harness as the tenant-isolation test bed). Everything
post-hoc (service report, per-job Perfetto tracks) reconstructs from the
flight JSONLs alone.

Budget note (ROADMAP tier-1): the one end-to-end multiplex+fault test is
the fast representative; the policy × fault matrix rides `slow`.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.service import (
    FairSharePolicy, FifoPolicy, Job, JobSpec, JobState, MeshScheduler,
    RoundRobinPolicy,
)
from implicitglobalgrid_tpu.utils.exceptions import (
    InvalidArgumentError, ResilienceError,
)

from conftest import (
    health_counters_from_registry as _health_counters,
    reset_health_counters_in_registry as _reset_health_counters,
)

GRID_A = dict(nx=6, ny=6, nz=6, dimx=2, dimy=2, dimz=1)
GRID_B = dict(nx=8, ny=8, nz=8, dimx=2, dimy=2, dimz=1)


def _diffusion_setup():
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float64)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


_SOLO_CACHE: dict = {}


def _solo_reference(grid: dict, nt: int, nt_chunk: int):
    """Gathered interior of the uninterrupted solo `run_resilient` for one
    job config (memoized — the isolation matrix compares several tenants
    against the same references)."""
    key = (tuple(sorted(grid.items())), nt, nt_chunk)
    if key in _SOLO_CACHE:
        return _SOLO_CACHE[key]
    igg.init_global_grid(quiet=True, **grid)
    step, state = _diffusion_setup()
    out, reports = igg.run_resilient(step, state, nt, nt_chunk=nt_chunk,
                                     key=("svc_solo", key))
    assert all(r.ok for r in reports)
    P = igg.gather_interior(out["T"])
    igg.finalize_global_grid()
    _SOLO_CACHE[key] = P
    return P


def _job(name, grid, nt, nt_chunk, *, priority=1, **run_kwargs):
    return JobSpec(name=name, setup=_diffusion_setup, nt=nt, grid=grid,
                   priority=priority,
                   run=igg.RunSpec(nt_chunk=nt_chunk, key=("svc", name),
                                   **run_kwargs))


def _interior(sched, name):
    """Gathered interior of a finished job's result, under ITS grid."""
    from implicitglobalgrid_tpu.parallel import topology as top

    job = sched.job(name)
    prev = top.swap_global_grid(job.gg)
    try:
        return igg.gather_interior(job.result["T"])
    finally:
        top.swap_global_grid(prev)


# ---------------------------------------------------------------------------
# Public API / RunSpec satellite
# ---------------------------------------------------------------------------

def test_public_api_exports():
    for sym in ("service", "MeshScheduler", "JobSpec", "JobState",
                "RunSpec", "ResilientRun", "service_report",
                "export_service_trace"):
        assert hasattr(igg, sym), sym
        assert sym in igg.__all__, sym


def test_runspec_shim_and_validation():
    """`run_resilient` keeps its keyword surface as a thin shim over
    RunSpec; spec= and keywords are mutually exclusive; JobSpec embeds a
    RunSpec instead of re-declaring the knobs."""
    igg.init_global_grid(**GRID_A, quiet=True)
    step, state = _diffusion_setup()
    with pytest.raises(InvalidArgumentError, match="not both"):
        igg.run_resilient(step, state, 4, spec=igg.RunSpec(), nt_chunk=2)
    with pytest.raises(TypeError):  # unknown knob: same failure as before
        igg.run_resilient(step, state, 4, nt_chunkz=2)
    # spec validation still runs (the historical error surface)
    with pytest.raises(InvalidArgumentError, match="needs audit=True"):
        igg.run_resilient(step, state, 4,
                          spec=igg.RunSpec(audit_lints=("host-transfer",)))
    with pytest.raises(InvalidArgumentError, match="RunSpec"):
        JobSpec(name="x", setup=_diffusion_setup, nt=4,
                run={"nt_chunk": 2})
    with pytest.raises(InvalidArgumentError, match="priority"):
        JobSpec(name="x", setup=_diffusion_setup, nt=4, priority=0)
    with pytest.raises(InvalidArgumentError, match="name"):
        JobSpec(name="a/b", setup=_diffusion_setup, nt=4)
    # non-default serializable knobs travel into journals
    js = igg.RunSpec(nt_chunk=7, audit=True).to_json()
    assert js == {"nt_chunk": 7, "audit": True}


# ---------------------------------------------------------------------------
# Policies (host-only)
# ---------------------------------------------------------------------------

def _fake_jobs(*priorities):
    jobs = []
    for i, pr in enumerate(priorities):
        spec = JobSpec(name=f"j{i}", setup=lambda: None, nt=10,
                       priority=pr)
        jobs.append(Job(spec, i))
    return jobs


def test_fifo_runs_to_completion_in_order():
    jobs = _fake_jobs(1, 1, 1)
    pol = FifoPolicy()
    assert pol.pick(jobs) is jobs[0]
    assert pol.pick(jobs) is jobs[0]  # owns the mesh until it finishes
    jobs[0].state = JobState.DONE
    assert pol.pick(jobs[1:]) is jobs[1]


def test_round_robin_cycles():
    jobs = _fake_jobs(1, 1, 1)
    pol = RoundRobinPolicy()
    picked = [pol.pick(jobs).name for _ in range(6)]
    assert picked == ["j0", "j1", "j2", "j0", "j1", "j2"]
    # a finished job drops out of the rotation
    sub = [jobs[0], jobs[2]]
    assert [pol.pick(sub).name for _ in range(3)] == ["j0", "j2", "j0"]


def test_fair_share_weights_mesh_time_by_priority():
    jobs = _fake_jobs(1, 3)  # j1 deserves 3x the mesh time
    pol = FairSharePolicy()
    granted = {"j0": 0, "j1": 0}
    for _ in range(40):
        j = pol.pick(jobs)
        granted[j.name] += 1
        pol.granted(j, 0.1)  # equal slice durations
    assert granted["j1"] == 3 * granted["j0"]
    # a late arrival starts at the current floor (not zero), so it ties
    # with — not starves — the incumbents
    late = _fake_jobs(1, 1, 1)[2]
    late.index = 99
    assert pol.pick(jobs + [late]) is not late
    # ... and the floor is the RUNNABLE minimum: a job that finished long
    # ago with a tiny frozen share must not seed a later arrival below
    # the live tenants (which would hand it the mesh for the whole gap)
    early = _fake_jobs(1)[0]
    early.index = 50
    pol._share[early.index] = 0.001  # finished ages ago; NOT a candidate
    later = _fake_jobs(1)[0]
    later.index = 100
    pol.pick(jobs + [later])
    assert pol._share[later.index] == min(
        pol._share[j.index] for j in jobs)


def test_resolve_policy_errors():
    from implicitglobalgrid_tpu.service import resolve_policy

    assert resolve_policy("fair").name == "fair"
    assert resolve_policy(FifoPolicy).name == "fifo"
    with pytest.raises(InvalidArgumentError, match="Unknown scheduling"):
        resolve_policy("sjf")


# ---------------------------------------------------------------------------
# Scoped registry (per-job label namespacing satellite)
# ---------------------------------------------------------------------------

def test_scoped_registry_namespaces_series():
    reg = igg.MetricsRegistry()
    a = reg.scoped(job="a")
    b = reg.scoped(job="b")
    ga = a.gauge("svc_step", "s")
    gb = b.gauge("svc_step", "s")
    ga.set(5)
    gb.set(9)
    fam = reg.get("svc_step")
    assert fam.labelnames == ("job",)
    assert {tuple(lbl.items()): v for lbl, v in fam.samples()} == {
        (("job", "a"),): 5.0, (("job", "b"),): 9.0}
    # extra labels compose with the scope's
    a.counter("svc_evt", "e", ("kind",)).inc(2, kind="x")
    assert reg.get("svc_evt").value(kind="x", job="a") == 2.0
    # the scope's labels cannot be overridden or shadowed
    with pytest.raises(InvalidArgumentError, match="fixed by the registry"):
        ga.set(1, job="c")
    with pytest.raises(InvalidArgumentError, match="collide"):
        a.gauge("svc_bad", "x", ("job",))
    # retiring one scope leaves the other's series intact
    a.remove_scope()
    assert {lbl["job"] for lbl, _ in fam.samples()} == {"b"}
    assert reg.get("svc_evt").value(kind="x", job="a") == 0.0


def test_scoped_registry_validation():
    reg = igg.MetricsRegistry()
    with pytest.raises(InvalidArgumentError, match="at least one"):
        reg.scoped()
    with pytest.raises(InvalidArgumentError, match="Invalid scope label"):
        reg.scoped(**{"bad-label": "x"})


# ---------------------------------------------------------------------------
# THE acceptance test: multiplexed jobs, fault isolation, bit-identity
# ---------------------------------------------------------------------------

@pytest.mark.service
@pytest.mark.faults
def test_three_jobs_multiplexed_fault_isolated_bit_identical(tmp_path):
    """Three queued jobs (two grid sizes) multiplexed chunk-granularly
    through one device pool under round_robin; a NaN injected into job C
    trips C's guard ONLY, C rolls back against ITS checkpoints, and every
    job's final interior is bit-identical to its solo run. The flight
    directory reconstructs the interleaved schedule and renders one
    Perfetto track per job."""
    ref_a = _solo_reference(GRID_A, 12, 4)
    ref_b = _solo_reference(GRID_B, 12, 4)

    _reset_health_counters()
    d = str(tmp_path / "svc")
    with MeshScheduler(policy="round_robin", flight_dir=d) as sched:
        sched.submit(_job("a", GRID_A, 12, 4))
        sched.submit(_job("b", GRID_B, 12, 4))
        # C: same config as A, plus an injected fault + its own recovery
        sched.submit(_job(
            "c", GRID_A, 12, 4,
            checkpoint_dir=str(tmp_path / "ck_c"),
            faults=(igg.NaNPoke(step=8, name="T"),)))
        sched.run()

        st = sched.status()
        assert st["states"] == {"done": 3}
        # isolation: exactly ONE guard trip in the whole service, and it
        # belongs to C (A and B sailed through)
        c = _health_counters()
        assert c["guard_trips"] == 1 and c["rollbacks"] == 1
        assert all(r.ok for r in sched.job("a").reports)
        assert all(r.ok for r in sched.job("b").reports)
        assert sum(1 for r in sched.job("c").reports if not r.ok) == 1
        # bit-identity vs the solo runs, on every tenant — C's recovery
        # included
        assert np.array_equal(_interior(sched, "a"), ref_a)
        assert np.array_equal(_interior(sched, "b"), ref_b)
        assert np.array_equal(_interior(sched, "c"), ref_a)
        # chunk-granular interleaving actually happened
        assert sched.slices >= 9

    # post-hoc: the service report reconstructs the interleaved schedule
    # from the JSONLs alone (run_report delegates on a service dir)
    rep = igg.run_report(d)
    assert rep["policy"] == "round_robin"
    assert set(rep["jobs"]) == {"a", "b", "c"}
    assert rep["switches"] > 0
    assert [s["job"] for s in rep["schedule"][:3]] == ["a", "b", "c"]
    assert rep["jobs"]["c"]["report"]["guards"]["trips"] == 1
    assert rep["jobs"]["a"]["report"]["guards"]["trips"] == 0
    assert rep["jobs"]["a"]["report"]["steps"]["completed"] == 12
    # the fault event landed in C's stream only
    assert any(e["kind"] == "fault_injected"
               for e in rep["jobs"]["c"]["report"]["sequence"])
    assert not any(e["kind"] == "fault_injected"
                   for e in rep["jobs"]["a"]["report"]["sequence"])
    # one Perfetto track per job (+ the scheduler track)
    tr = igg.export_service_trace(d)
    assert tr["otherData"]["jobs"] == ["a", "b", "c"]
    names = {m["args"]["name"] for m in tr["traceEvents"]
             if m.get("name") == "process_name"}
    assert names == {"scheduler", "job a", "job b", "job c"}
    slices = [e for e in tr["traceEvents"] if e.get("cat") == "slice"]
    assert len(slices) == rep["slices"]


@pytest.mark.service
@pytest.mark.faults
@pytest.mark.slow
@pytest.mark.parametrize("policy", ["fifo", "fair"])
def test_policy_matrix_bit_identical(tmp_path, policy):
    """The remaining shipped policies: same three-job queue, same fault,
    same bit-identity bar (round_robin is the fast representative)."""
    ref_a = _solo_reference(GRID_A, 12, 4)
    ref_b = _solo_reference(GRID_B, 12, 4)

    with MeshScheduler(policy=policy,
                       flight_dir=str(tmp_path / "svc")) as sched:
        sched.submit(_job("a", GRID_A, 12, 4, priority=2))
        sched.submit(_job("b", GRID_B, 12, 4))
        sched.submit(_job(
            "c", GRID_A, 12, 4,
            checkpoint_dir=str(tmp_path / "ck_c"),
            faults=(igg.NaNPoke(step=8, name="T"),)))
        sched.run()
        assert sched.status()["states"] == {"done": 3}
        assert np.array_equal(_interior(sched, "a"), ref_a)
        assert np.array_equal(_interior(sched, "b"), ref_b)
        assert np.array_equal(_interior(sched, "c"), ref_a)


@pytest.mark.service
@pytest.mark.slow
def test_corrupted_checkpoint_isolated_to_one_tenant(tmp_path):
    """Storage fault flavor of isolation: job C's newest checkpoint is
    corrupted on disk; C detects it (checksums), falls back to its other
    slot, recomputes — neighbors untouched, all bit-identical."""
    ref_a = _solo_reference(GRID_A, 12, 4)

    _reset_health_counters()
    with MeshScheduler(policy="round_robin") as sched:
        sched.submit(_job("a", GRID_A, 12, 4))
        sched.submit(_job(
            "c", GRID_A, 12, 4,
            checkpoint_dir=str(tmp_path / "ck_c"),
            faults=(igg.CheckpointCorruption(save_index=2, kind="bitflip"),
                    igg.NaNPoke(step=8, name="T"))))
        sched.run()
        assert sched.status()["states"] == {"done": 2}
        c = _health_counters()
        assert c["restore_fallbacks"] == 1
        assert np.array_equal(_interior(sched, "a"), ref_a)
        assert np.array_equal(_interior(sched, "c"), ref_a)


# ---------------------------------------------------------------------------
# Failure containment, cancel/drain, lifecycle
# ---------------------------------------------------------------------------

@pytest.mark.service
def test_failed_job_contained_cancel_and_drain(tmp_path):
    """One slice grant, then: a job whose guard trips with no
    checkpoint_dir FAILS alone (error recorded, service keeps going); a
    queued job cancels instantly; drain cancels the rest of the queue
    while the running job completes."""
    _reset_health_counters()
    with MeshScheduler(policy="fifo",
                       flight_dir=str(tmp_path / "svc")) as sched:
        # fatal-by-design: poisoned from step 0, nothing to roll back to
        bad = JobSpec(
            name="bad", setup=_poisoned_setup, nt=8, grid=GRID_A,
            run=igg.RunSpec(nt_chunk=4, key=("svc", "bad")))
        sched.submit(bad)
        sched.submit(_job("good", GRID_A, 8, 4))
        sched.submit(_job("queued1", GRID_A, 8, 4))
        sched.submit(_job("queued2", GRID_B, 8, 4))
        # slice 1 goes to 'bad' (fifo), which fails alone; slice 2 starts
        # 'good' (RUNNING — drain below must let it finish)
        sched.run(max_slices=2)
        assert sched.job("bad").state == JobState.FAILED
        assert "ResilienceError" in sched.job("bad").error
        assert sched.job("good").state == JobState.RUNNING
        sched.cancel("queued2")
        assert sched.job("queued2").state == JobState.CANCELLED
        sched.drain()  # cancels still-queued queued1, lets 'good' finish
        assert sched.job("queued1").state == JobState.CANCELLED
        with pytest.raises(InvalidArgumentError, match="draining"):
            sched.submit(_job("late", GRID_A, 8, 4))
        sched.run()
        st = sched.status()
        assert st["states"] == {"failed": 1, "done": 1, "cancelled": 2}
        assert sched.job("good").result is not None
    rep = igg.service_report(str(tmp_path / "svc"))
    assert rep["states"] == {"cancelled": 2, "done": 1, "failed": 1}
    assert rep["jobs"]["bad"]["error"]
    # the trace's queue-depth counter returns to 0: jobs cancelled while
    # still QUEUED leave the queue at their terminal event, not at an
    # admission they never had
    tr = igg.export_service_trace(str(tmp_path / "svc"))
    depths = [c["args"]["jobs"] for c in tr["traceEvents"]
              if c.get("name") == "igg_jobs_queued"]
    assert depths[-1] == 0 and min(depths) >= 0
    # duplicate names and closed-scheduler use are typed errors
    with pytest.raises(InvalidArgumentError, match="closed"):
        sched.submit(_job("x", GRID_A, 4, 2))


def _poisoned_setup():
    step, state = _diffusion_setup()
    state = dict(state)
    state["T"] = igg.poke_nan(state["T"], (0, 0, 0))
    return step, state


@pytest.mark.service
@pytest.mark.faults
def test_elastic_restart_isolated_and_neighbors_stay_warm(tmp_path):
    """The heavyweight recovery move under multiplexing: job B suffers a
    ProcessLoss (elastic restart onto new dims — finalize/re-init of the
    live grid INSIDE B's slice). The scheduler re-tracks B's new grid,
    job A's warm compiled programs survive the restart's cache clears
    (retained epochs), and both jobs still end bit-identical to the solo
    run."""
    ref_a = _solo_reference(GRID_A, 12, 4)

    igg.reset_metrics()
    _reset_health_counters()
    with MeshScheduler(policy="round_robin") as sched:
        sched.submit(_job("a", GRID_A, 12, 4))
        sched.submit(_job(
            "b", GRID_A, 12, 4,
            checkpoint_dir=str(tmp_path / "ck_b"),
            faults=(igg.ProcessLoss(step=8, new_dims=(1, 2, 2)),)))
        sched.run()
        assert sched.status()["states"] == {"done": 2}
        assert _health_counters()["elastic_restarts"] == 1
        # B ended on ITS restarted decomposition; A untouched on its own
        bgg = sched.job("b").gg
        assert tuple(int(d) for d in bgg.dims) == (1, 2, 2)
        assert tuple(int(d) for d in sched.job("a").gg.dims) \
            == (2, 2, 1)
        assert np.array_equal(_interior(sched, "a"), ref_a)
        assert np.array_equal(_interior(sched, "b"), ref_a)
        # A never recompiled: exactly one runner miss belongs to A, the
        # rest are B's (initial + fault-split + rebuilt-decomposition
        # programs) — A's post-restart slices must all be HITS
        fam = igg.metrics_registry().get("igg_runner_cache_total")
        assert fam.value(result="hit") >= 2


@pytest.mark.service
def test_scheduler_slice_counter_counts_grants_only():
    """igg_scheduler_slices_total reconciles against the journal: idle
    polls and construction stamp the heartbeat but never the counter."""
    igg.reset_metrics()
    with MeshScheduler() as sched:
        assert sched.step() is False  # nothing runnable
        assert sched.step() is False
        fam = igg.metrics_registry().get(
            "igg_scheduler_slices_total")
        assert fam is None or fam.value() == 0
        ts = igg.metrics_registry().get(
            "igg_scheduler_heartbeat_timestamp_seconds")
        assert ts.value() > 0  # liveness still stamped


@pytest.mark.service
@pytest.mark.io
def test_async_snapshot_events_attributed_to_owning_job(tmp_path):
    """The snapshot writer's BACKGROUND thread commits while another
    tenant's recorder (or none) holds the global slot — its events must
    still land in the owning job's stream (thread-bound recorder)."""
    d = str(tmp_path / "svc")
    with MeshScheduler(policy="round_robin", flight_dir=d) as sched:
        for name in ("a", "b"):
            sched.submit(_job(
                name, GRID_A, 8, 4,
                snapshot_dir=str(tmp_path / f"snaps_{name}"),
                snapshot_every=4))
        sched.run()
        assert sched.status()["states"] == {"done": 2}
    for name in ("a", "b"):
        evs = igg.read_flight_events(
            os.path.join(d, f"job_{name}.jsonl"))
        writes = [e for e in evs if e["kind"] == "snapshot_write"]
        assert len(writes) == 2, (name, [e["kind"] for e in evs])
        assert all(f"snaps_{name}" in e["path"] for e in writes)
        # the drain summary rode the right stream too
        close = [e for e in evs if e["kind"] == "snapshot_writer_close"]
        assert len(close) == 1 and close[0]["written"] == 2


@pytest.mark.service
def test_submit_validation():
    with MeshScheduler() as sched:
        with pytest.raises(InvalidArgumentError, match="JobSpec"):
            sched.submit("nope")
        sched.submit(_job("a", GRID_A, 4, 2))
        with pytest.raises(InvalidArgumentError, match="already submitted"):
            sched.submit(_job("a", GRID_A, 4, 2))
        sched.cancel("a")  # queued: cancelled instantly, no admission
        assert sched.job("a").state == JobState.CANCELLED
        assert sched.run().status()["states"] == {"cancelled": 1}


# ---------------------------------------------------------------------------
# Scheduler-owned ops surface (metrics endpoint across job lifetimes)
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


@pytest.mark.service
@pytest.mark.mesh
def test_scheduler_owned_metrics_server_per_job_gauges(tmp_path):
    """The scheduler-owned endpoint outlives individual jobs: per-job
    labeled gauges + queue depth are scrapeable after tenants finished,
    /healthz judges the SCHEDULER heartbeat (source=scheduler, per-job
    ages attached), and a nested run_resilient(metrics_port=...) ATTACHES
    to the running server instead of failing to bind."""
    igg.reset_metrics()
    with MeshScheduler(policy="round_robin", metrics_port=0) as sched:
        port = igg.metrics_server().port
        assert port > 0
        # metrics_port inside a job's RunSpec attaches to the scheduler's
        # server (the old behavior raised "already running")
        sched.submit(_job("a", GRID_A, 8, 4, metrics_port=0))
        sched.submit(_job("b", GRID_A, 8, 4))
        sched.run()
        assert igg.metrics_server() is not None  # survived the tenants
        status, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200
        assert 'igg_job_step{job="a"} 8' in body
        assert 'igg_job_step{job="b"} 8' in body
        assert 'igg_job_heartbeat_timestamp_seconds{job="a"}' in body
        assert "igg_jobs_queued 0" in body
        assert "igg_scheduler_slices_total" in body
        assert 'igg_jobs_total{state="done"} 2' in body
        status, body = _get(f"http://127.0.0.1:{port}/healthz")
        rec = json.loads(body)
        assert status == 200 and rec["source"] == "scheduler"
        assert set(rec["job_ages_s"]) == {"a", "b"}
        assert rec["job_ages_s"]["a"] >= 0
    assert igg.metrics_server() is None  # last hold released on close
    # the per-job series die WITH the service: after close every
    # igg_job_* family is empty (no unbounded growth across schedulers)
    for name in ("igg_job_step", "igg_job_heartbeat_timestamp_seconds",
                 "igg_job_slice_seconds"):
        fam = igg.metrics_registry().get(name)
        assert fam is None or fam.samples() == [], name
    # with the scheduler heartbeat retired, a later plain server judges
    # the driver heartbeat again
    srv = igg.start_metrics_server(0)
    try:
        from implicitglobalgrid_tpu import telemetry

        telemetry.note_heartbeat(3)
        _, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert json.loads(body)["source"] == "driver"
    finally:
        igg.stop_metrics_server()


# ---------------------------------------------------------------------------
# Warm context switches (the runner-cache contract behind the scheduler)
# ---------------------------------------------------------------------------

@pytest.mark.service
def test_context_switches_stay_warm(tmp_path):
    """Each job pays its XLA compile exactly once: under round_robin
    interleaving, every runner-cache MISS beyond the per-job first one
    would recompile at each switch — the epoch-retention fix makes every
    later slice a HIT (cold-compile cost attributed to the job that pays
    it, warm switches near-free; gated <2% in bench_service.py)."""
    igg.reset_metrics()
    with MeshScheduler(policy="round_robin") as sched:
        sched.submit(_job("a", GRID_A, 16, 4))
        sched.submit(_job("b", GRID_B, 16, 4))
        sched.run()
        assert sched.status()["states"] == {"done": 2}
        assert sched.slices >= 8
    fam = igg.metrics_registry().get("igg_runner_cache_total")
    assert fam.value(result="miss") == 2  # one compile per job, ever
    assert fam.value(result="hit") >= 6  # every other slice stayed warm


@pytest.mark.service
def test_swap_global_grid_preserves_epoch_and_outer_grid():
    """The context-switch primitive itself: swapping keeps each grid's
    epoch (no cache invalidation), and the scheduler restores the
    caller's grid around its public calls."""
    from implicitglobalgrid_tpu.parallel import topology as top

    igg.init_global_grid(**GRID_A, quiet=True)
    outer = top.global_grid()
    epoch = outer.epoch
    with MeshScheduler() as sched:
        sched.submit(_job("a", GRID_A, 4, 2))
        sched.run()
        assert top.global_grid() is outer  # restored after every step
        assert outer.epoch == epoch
    assert igg.grid_is_initialized()
    assert top.global_grid() is outer
