"""Mesh-wide observability tests (ISSUE 5): cross-process flight
aggregation (clock-offset recovery at the chunk-boundary barriers,
run-id/seq validation), the straggler & imbalance analyzer, Chrome/
Perfetto trace export, the ``mesh`` section of `run_report`, the
aggregate/trace/stragglers CLI, and the live metrics endpoint
(`/metrics` + `/healthz`, driver heartbeat, `run_resilient(metrics_port)`).

Cross-process streams are synthesized here with EXACT known skews (the
one place ground truth exists); the true two-controller end-to-end run
lives in tests/test_multiprocess.py."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu import telemetry
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = [pytest.mark.mesh, pytest.mark.telemetry]


@pytest.fixture(autouse=True)
def _clean_telemetry():
    igg.stop_flight_recorder()
    igg.stop_metrics_server()
    igg.reset_metrics()
    yield
    igg.stop_flight_recorder()
    igg.stop_metrics_server()
    igg.reset_metrics()


# ---------------------------------------------------------------------------
# Synthetic per-process streams with exact ground truth
# ---------------------------------------------------------------------------

def _write_stream(dirpath, proc, *, clock0, wall0, n_chunks=6,
                  start_delay=0.0, compute=0.1, worst_delay=0.05,
                  run_id="r1", drop_last_chunk=False, seq_start=0,
                  extra=()):
    """One process's flight JSONL with a barrier-consistent chunk
    schedule: every chunk's TRUE barrier release is common to all
    processes (the slowest arriver, delayed by ``worst_delay``, sets it);
    this process dispatches ``start_delay`` after the boundary, so its
    ``exec_s`` is the barrier release minus its own start. ``clock0`` is
    the process's (arbitrary) monotonic origin, ``wall0`` its wall clock
    at recorder open — aggregation must undo both."""
    path = os.path.join(dirpath, f"flight_p{proc}.jsonl")
    seq = seq_start
    recs = []

    def ev(kind, t, **kw):
        nonlocal seq
        recs.append({"t": t, "kind": kind, "run": run_id, "pid": 10 + proc,
                     "proc": proc, "seq": seq, **kw})
        seq += 1

    t = clock0
    ev("recorder_open", t, wall=wall0, version=1)
    ev("run_begin", t, nt=n_chunks * 10, nt_chunk=10, names=["T"],
       checkpoint_every=10)
    for c in range(n_chunks):
        start = t + start_delay
        t = t + worst_delay + compute          # the mesh barrier release
        if drop_last_chunk and c == n_chunks - 1:
            continue
        ev("chunk", t, chunk=c, step_begin=c * 10, step_end=(c + 1) * 10,
           n=10, ok=True, reasons=[], build_s=0.004, exec_s=t - start)
    for kind, kw in extra:
        ev(kind, t, **kw)
    ev("run_end", t, completed=n_chunks * 10, chunks=n_chunks)
    ev("recorder_close", t)
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return path


def _two_proc_dir(tmp_path, **kw):
    d = str(tmp_path / "flights")
    os.makedirs(d, exist_ok=True)
    # proc 1 is the straggler: it dispatches 0.05s late every boundary;
    # its monotonic clock origin and wall clock are wildly/slightly off
    _write_stream(d, 0, clock0=1000.0, wall0=5000.0, **kw)
    _write_stream(d, 1, clock0=987654.0, wall0=5000.25,
                  start_delay=0.05, **kw)
    return d


# ---------------------------------------------------------------------------
# aggregate_flight
# ---------------------------------------------------------------------------

def test_aggregate_recovers_offsets_and_merges(tmp_path):
    d = _two_proc_dir(tmp_path)
    agg = igg.aggregate_flight(d)
    assert agg["run_id"] == "r1"
    assert agg["processes"] == [0, 1] and agg["anchor_proc"] == 0
    assert agg["align"]["method"] == {0: "anchor", 1: "chunk-barrier"}
    # both processes stamp the SAME physical barrier instants, so after
    # wall anchoring the residual offset is exactly the wall skew (0.25s)
    assert agg["offsets"][0] == 0.0
    assert abs(agg["offsets"][1] - 0.25) < 1e-6
    assert agg["align"]["residual_s"][1] < 1e-9
    assert agg["align"]["chunks_used"][1] == 6
    # merged events are time-sorted on ONE corrected clock; each chunk's
    # two per-process records land at the same corrected barrier time
    evs = agg["events"]
    ts = [e["t"] for e in evs if "t" in e]
    assert ts == sorted(ts)
    for c in range(6):
        pair = [e for e in evs if e.get("kind") == "chunk"
                and e.get("chunk") == c]
        assert len(pair) == 2
        assert abs(pair[0]["t"] - pair[1]["t"]) < 1e-6
    assert all("t_mono" in e and "t_offset" in e for e in evs)
    meta = agg["per_process"]
    assert meta[0]["chunks"] == meta[1]["chunks"] == 6


def test_aggregate_accepts_explicit_paths_and_single_file(tmp_path):
    d = _two_proc_dir(tmp_path)
    paths = sorted(os.path.join(d, f) for f in os.listdir(d))
    agg = igg.aggregate_flight(paths)
    assert agg["processes"] == [0, 1]
    # single-process stream: aggregation degenerates gracefully
    one = igg.aggregate_flight(paths[0])
    assert one["processes"] == [0] and one["offsets"] == {0: 0.0}


def test_aggregate_validation_errors(tmp_path):
    d = str(tmp_path / "bad")
    os.makedirs(d)
    with pytest.raises(InvalidArgumentError, match="no .*jsonl"):
        igg.aggregate_flight(d)
    _write_stream(d, 0, clock0=0.0, wall0=100.0)
    _write_stream(d, 1, clock0=0.0, wall0=100.0, run_id="OTHER")
    # two run ids without an explicit choice must never silently mix
    with pytest.raises(InvalidArgumentError, match="run ids"):
        igg.aggregate_flight(d)
    agg = igg.aggregate_flight(d, run_id="OTHER")
    assert agg["processes"] == [1]
    with pytest.raises(InvalidArgumentError, match="no events"):
        igg.aggregate_flight(d, run_id="nope")
    # a seq gap (stream truncated mid-run / file missing) is detected
    gap = str(tmp_path / "gap")
    os.makedirs(gap)
    p = _write_stream(gap, 0, clock0=0.0, wall0=100.0)
    lines = open(p).read().splitlines()
    open(p, "w").write("\n".join(lines[:3] + lines[4:]) + "\n")
    with pytest.raises(InvalidArgumentError, match="gaps"):
        igg.aggregate_flight(gap)
    # duplicate seqs (two writers interleaved one file) are detected
    dup = str(tmp_path / "dup")
    os.makedirs(dup)
    p = _write_stream(dup, 0, clock0=0.0, wall0=100.0)
    first = open(p).read().splitlines()
    open(p, "a").write(first[1] + "\n")
    with pytest.raises(InvalidArgumentError, match="duplicate"):
        igg.aggregate_flight(dup)
    # a head-truncated stream (lost recorder_open wall anchor) is refused,
    # not silently mis-aligned
    head = str(tmp_path / "head")
    os.makedirs(head)
    p = _write_stream(head, 0, clock0=0.0, wall0=100.0)
    lines = open(p).read().splitlines()
    open(p, "w").write("\n".join(lines[3:]) + "\n")
    with pytest.raises(InvalidArgumentError, match="start at 0"):
        igg.aggregate_flight(head)


def test_run_report_aligns_preloaded_multiprocess_events(tmp_path):
    """A multi-process stream passed as an EVENT LIST (not a directory)
    must go through the same clock alignment — raw monotonic stamps are
    not comparable across hosts, and a straggler verdict on them would be
    silently wrong."""
    d = _two_proc_dir(tmp_path)
    events = []
    for f in sorted(os.listdir(d)):
        events.extend(igg.read_flight_events(os.path.join(d, f)))
    rep = igg.run_report(events, include_metrics=False)
    assert rep["mesh"]["summary"]["worst_proc"] == 1
    assert abs(rep["mesh"]["offsets"][1] - 0.25) < 1e-6
    assert rep["chunks"]["count"] == 6
    # and aggregate_events is the public path to the same alignment
    agg = igg.aggregate_events(events)
    assert abs(agg["offsets"][1] - 0.25) < 1e-6


# ---------------------------------------------------------------------------
# straggler_report
# ---------------------------------------------------------------------------

def test_straggler_attribution_and_imbalance(tmp_path):
    d = _two_proc_dir(tmp_path)
    agg = igg.aggregate_flight(d)
    rep = igg.straggler_report(agg, window=4)
    assert rep["processes"] == [0, 1]
    # proc 1 dispatches 0.05s late at every boundary: it is the slowest
    # arriver on every chunk, the spread IS the injected delay, and all
    # the barrier wait lands on proc 0
    assert rep["slowest_counts"] == {0: 0, 1: 6}
    assert rep["summary"]["worst_proc"] == 1
    assert abs(rep["summary"]["spread_s_mean"] - 0.05) < 1e-6
    for ch in rep["chunks"]:
        assert ch["slowest"] == 1
        assert abs(ch["spread_s"] - 0.05) < 1e-6
        assert abs(ch["arrival_s"][1] - 0.05) < 1e-6
        assert ch["arrival_s"][0] == 0.0
        assert abs(ch["compute_s"] - 0.1) < 1e-6
    imb = rep["imbalance"]
    assert imb[1]["wait_s_total"] < 1e-9          # straggler never waits
    assert abs(imb[0]["wait_s_total"] - 6 * 0.05) < 1e-6
    assert 0.3 < imb[0]["wait_frac"] < 0.4        # 0.05 / 0.15
    # persistent: slowest in 100% of every rolling window -> ONE merged
    # span whose chunks/share describe the whole span, not one window
    assert rep["persistent"] == [{"proc": 1, "first_chunk": 0,
                                  "last_chunk": 5, "chunks": 6,
                                  "share": 1.0}]


def test_straggler_needs_two_processes_and_common_chunks(tmp_path):
    d = str(tmp_path / "one")
    os.makedirs(d)
    _write_stream(d, 0, clock0=0.0, wall0=100.0)
    with pytest.raises(InvalidArgumentError, match="two"):
        igg.straggler_report(igg.aggregate_flight(d))
    # a chunk one process never logged is excluded, not mis-attributed
    d2 = str(tmp_path / "partial")
    os.makedirs(d2)
    _write_stream(d2, 0, clock0=0.0, wall0=100.0)
    _write_stream(d2, 1, clock0=0.0, wall0=100.0, start_delay=0.05,
                  drop_last_chunk=True)
    rep = igg.straggler_report(igg.aggregate_flight(d2))
    assert rep["summary"]["chunks"] == 5
    assert rep["slowest_counts"] == {0: 0, 1: 5}
    # a process sharing NO chunk with the anchor falls back to its wall
    # anchor alone — without degrading the aligned processes' metadata
    d3 = str(tmp_path / "nocommon")
    os.makedirs(d3)
    _write_stream(d3, 0, clock0=0.0, wall0=100.0)
    _write_stream(d3, 1, clock0=50.0, wall0=100.0, start_delay=0.05)
    _write_stream(d3, 2, clock0=0.0, wall0=100.0, drop_last_chunk=True,
                  n_chunks=1)  # its only chunk is dropped: none shared
    agg3 = igg.aggregate_flight(d3)
    assert agg3["align"]["method"] == {0: "anchor", 1: "chunk-barrier",
                                       2: "wall-anchor"}
    assert agg3["align"]["residual_s"][2] is None
    assert agg3["align"]["residual_s"][1] is not None


def test_straggler_single_process_stream_explicit(tmp_path):
    """A single-process stream must fail the straggler analysis with a
    typed error naming the fix (aggregate more streams) — and the
    report/mesh layers must degrade cleanly instead of fabricating a
    one-horse race: mesh_section is None, run_report has no 'mesh'."""
    d = str(tmp_path / "solo")
    os.makedirs(d)
    _write_stream(d, 0, clock0=10.0, wall0=100.0)
    agg = igg.aggregate_flight(d)
    with pytest.raises(InvalidArgumentError,
                       match="at least two"):
        igg.straggler_report(agg)
    assert telemetry.mesh_section(agg["events"]) is None
    rep = igg.run_report(d)
    assert "mesh" not in rep and rep["chunks"]["count"] == 6


def test_straggler_process_missing_middle_chunk_events(tmp_path):
    """A process whose stream lost ONE chunk's record mid-run (e.g. the
    event was never written because the driver was wedged) keeps its seq
    gapless — the analyzer must exclude exactly that chunk from the
    barrier analysis and keep every other chunk attributed."""
    d = str(tmp_path / "hole")
    os.makedirs(d)
    _write_stream(d, 0, clock0=0.0, wall0=100.0)
    # proc 1's stream: chunk 3's record is simply absent (seq contiguous)
    path = _write_stream(d, 1, clock0=0.0, wall0=100.0, start_delay=0.05)
    recs = [json.loads(ln) for ln in open(path)]
    recs = [r for r in recs if not (r["kind"] == "chunk"
                                    and r.get("chunk") == 3)]
    for seq, r in enumerate(recs):
        r["seq"] = seq
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = igg.straggler_report(igg.aggregate_flight(d))
    assert rep["summary"]["chunks"] == 5
    assert [c["chunk"] for c in rep["chunks"]] == [0, 1, 2, 4, 5]
    assert rep["slowest_counts"] == {0: 0, 1: 5}


def test_zero_chunk_crashed_at_start_stream(tmp_path):
    """A process that died before its first chunk (recorder_open +
    run_begin only) must not poison the mesh view: it aligns by wall
    anchor, appears in per_process with zero chunks, and the straggler
    analysis runs over the surviving processes only."""
    d = str(tmp_path / "crash")
    os.makedirs(d)
    _write_stream(d, 0, clock0=0.0, wall0=100.0)
    _write_stream(d, 1, clock0=0.0, wall0=100.0, start_delay=0.05)
    _write_stream(d, 2, clock0=500.0, wall0=100.1, n_chunks=0)
    agg = igg.aggregate_flight(d)
    assert agg["processes"] == [0, 1, 2]
    assert agg["per_process"][2]["chunks"] == 0
    assert agg["align"]["method"][2] == "wall-anchor"
    rep = igg.straggler_report(agg)
    assert rep["processes"] == [0, 1]  # the dead stream has no arrivals
    assert rep["summary"]["chunks"] == 6
    assert 2 not in rep["imbalance"]
    # the trace still renders all three tracks (the dead process's
    # run_begin instant is evidence of WHEN it died)
    doc = igg.export_chrome_trace(agg)
    assert sorted(doc["otherData"]["processes"]) == [0, 1, 2]


# ---------------------------------------------------------------------------
# export_chrome_trace
# ---------------------------------------------------------------------------

def test_chrome_trace_structure_and_barrier_alignment(tmp_path):
    d = _two_proc_dir(tmp_path, extra=[
        ("guard_trip", {"step_end": 60, "reasons": ["nonfinite:T"],
                        "retries": 1}),
        ("checkpoint_save", {"op": "save_sharded", "step": 60,
                             "dur_s": 0.02, "path": "x"}),
        ("snapshot_write", {"step": 60, "dur_s": 0.01, "nbytes": 4096,
                            "queue_depth": 1, "path": "y"}),
        ("halo_exchange", {"fields": 1, "ppermutes": 6,
                           "wire_bytes": 1234, "local_copy_bytes": 0}),
    ])
    out = str(tmp_path / "trace.json")
    assert igg.export_chrome_trace(d, out) == out
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    assert doc["otherData"]["run_id"] == "r1"
    assert doc["otherData"]["processes"] == [0, 1]
    # one named track per process
    names = {(e["pid"], e["args"]["name"]) for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {(0, "igg process 0"), (1, "igg process 1")}
    # chunk spans exist per process and END barrier-aligned across them
    for c in range(6):
        spans = [e for e in evs if e.get("ph") == "X"
                 and e["name"] == f"chunk {c}"]
        assert len(spans) == 2 and {s["pid"] for s in spans} == {0, 1}
        ends = [s["ts"] + s["dur"] for s in spans]
        assert abs(ends[0] - ends[1]) < 5  # microseconds
        for s in spans:
            assert s["ts"] >= 0 and s["dur"] > 0
    # nested build/exec phases, checkpoint + snapshot spans on their tracks
    assert any(e.get("ph") == "X" and e["name"] == "exec" for e in evs)
    ck = next(e for e in evs if e.get("ph") == "X"
              and e["name"] == "save_sharded")
    assert ck["cat"] == "checkpoint" and ck["dur"] == pytest.approx(2e4)
    snap = next(e for e in evs if e.get("ph") == "X"
                and e["cat"] == "io")
    assert snap["tid"] != ck["tid"]  # io writer has its own thread track
    # instants and counter samples
    assert any(e.get("ph") == "i" and e["name"] == "guard_trip"
               for e in evs)
    depth = [e for e in evs if e.get("ph") == "C"
             and e["name"] == "igg_io_queue_depth"]
    assert depth and depth[0]["args"]["depth"] == 1
    wire = [e for e in evs if e.get("ph") == "C"
            and e["name"] == "igg_halo_wire_bytes_total"]
    assert wire and wire[-1]["args"]["bytes"] == 1234
    # returns the dict (no file) when out is omitted
    doc2 = igg.export_chrome_trace(igg.aggregate_flight(d))
    assert len(doc2["traceEvents"]) == len(evs)


def test_chrome_trace_aligns_single_file_and_event_list(tmp_path):
    """A multi-process stream arriving as ONE concatenated file (or a
    pre-loaded event list) must be clock-aligned exactly like a
    directory — a Perfetto timeline on raw per-process monotonic clocks
    would look authoritative and be silently uncorrelatable."""
    d = _two_proc_dir(tmp_path)
    cat = str(tmp_path / "all.jsonl")
    with open(cat, "w") as out:
        for f in sorted(os.listdir(d)):
            out.write(open(os.path.join(d, f)).read())
    for source in (cat, igg.read_flight_events(cat)):
        doc = igg.export_chrome_trace(source)
        assert doc["otherData"]["align"]["method"][1] == "chunk-barrier"
        for c in range(6):
            ends = [e["ts"] + e["dur"] for e in doc["traceEvents"]
                    if e.get("ph") == "X" and e["name"] == f"chunk {c}"]
            assert len(ends) == 2 and abs(ends[0] - ends[1]) < 5  # µs


# ---------------------------------------------------------------------------
# run_report: the "mesh" section
# ---------------------------------------------------------------------------

def test_run_report_mesh_section_from_directory(tmp_path):
    d = _two_proc_dir(tmp_path)
    rep = igg.run_report(d, include_metrics=False)
    assert rep["run_id"] == "r1"
    mesh = rep["mesh"]
    assert mesh["processes"] == [0, 1]
    assert mesh["summary"]["worst_proc"] == 1
    assert abs(mesh["offsets"][1] - 0.25) < 1e-6
    assert mesh["persistent_stragglers"][0]["proc"] == 1
    # the per-run sections reconstruct the ANCHOR process's view — chunk
    # counts are per process, not multiplied by the process count
    assert rep["chunks"]["count"] == 6
    kinds = [e["kind"] for e in rep["sequence"]]
    assert kinds.count("run_begin") == 1 and kinds.count("run_end") == 1
    # single-process report stays mesh-free
    rep1 = igg.run_report(os.path.join(d, "flight_p0.jsonl"),
                          include_metrics=False)
    assert "mesh" not in rep1


# ---------------------------------------------------------------------------
# CLI: aggregate | trace | stragglers
# ---------------------------------------------------------------------------

def test_mesh_cli_subcommands(tmp_path, capsys):
    from implicitglobalgrid_tpu.tools import _cli

    d = _two_proc_dir(tmp_path)
    merged = str(tmp_path / "merged.jsonl")
    assert _cli(["aggregate", d, "--out", merged]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["processes"] == [0, 1] and summary["out"] == merged
    assert summary["events"] > 0 and "offsets" in summary
    n_lines = sum(1 for _ in open(merged))
    assert n_lines == summary["events"]

    out = str(tmp_path / "t.json")
    assert _cli(["trace", d, "-o", out]) == 0
    assert capsys.readouterr().out.strip() == out
    assert json.load(open(out))["traceEvents"]

    assert _cli(["stragglers", d, "--window", "4"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["summary"]["worst_proc"] == 1
    assert rep["slowest_counts"] == {"0": 0, "1": 6}


# ---------------------------------------------------------------------------
# Live metrics endpoint
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode(), r.headers


def test_metrics_server_serves_prometheus_and_healthz():
    igg.metrics_registry().counter("mesh_test_total", "t").inc(3)
    srv = igg.start_metrics_server(0)  # ephemeral port
    try:
        assert igg.metrics_server() is srv and srv.port > 0
        status, body, headers = _get(
            f"http://127.0.0.1:{srv.port}/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert "# TYPE mesh_test_total counter" in body
        assert "mesh_test_total 3" in body
        # healthz before any heartbeat: alive, age unknown
        status, body, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
        rec = json.loads(body)
        assert status == 200 and rec["ok"] is True
        assert rec["heartbeat_age_s"] is None
        telemetry.note_heartbeat(70)
        _, body, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
        rec = json.loads(body)
        assert rec["step"] == 70 and 0 <= rec["heartbeat_age_s"] < 60
        status, _, _ = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert status == 200  # snapshot includes the heartbeat gauges now
        # a second start ATTACHES to the running server (refcounted —
        # the scheduler-owned-endpoint contract, ISSUE 8); a genuinely
        # conflicting explicit port still refuses
        assert igg.start_metrics_server(0) is srv
        assert igg.start_metrics_server(srv.port) is srv
        with pytest.raises(InvalidArgumentError, match="already running"):
            igg.start_metrics_server(srv.port + 1)
        igg.stop_metrics_server()  # balance the two attaches...
        igg.stop_metrics_server()
        assert igg.metrics_server() is srv  # ...owner's hold remains
        status, _, _ = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert status == 200
    finally:
        igg.stop_metrics_server()
    assert igg.metrics_server() is None
    igg.stop_metrics_server()  # idempotent


def test_healthz_stale_heartbeat_returns_503():
    from implicitglobalgrid_tpu.telemetry.hooks import HEARTBEAT_TS

    srv = igg.start_metrics_server(0, healthz_max_age_s=2.0)
    try:
        # no heartbeat at all -> not ok under a max age
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert exc.value.code == 503
        telemetry.note_heartbeat(1)
        status, body, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert status == 200 and json.loads(body)["ok"] is True
        # stamp an OLD heartbeat directly: stale -> 503 again
        import time as _time

        igg.metrics_registry().gauge(HEARTBEAT_TS, "").set(
            _time.time() - 5.0)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert exc.value.code == 503
        assert json.loads(exc.value.read().decode())["ok"] is False
    finally:
        igg.stop_metrics_server()


def test_run_resilient_metrics_port_serves_during_run(tmp_path):
    """`run_resilient(metrics_port=0)`: the endpoint is LIVE during the
    run (scraped from an on_report callback — a real mid-run Prometheus
    exposition with the driver heartbeat), and torn down afterwards."""
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float64)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    scraped = []

    def on_report(rep):
        srv = igg.metrics_server()
        assert srv is not None
        assert srv.healthz_max_age_s == 120.0  # forwarded to /healthz
        _, metrics, _ = _get(f"http://127.0.0.1:{srv.port}/metrics")
        _, health, _ = _get(f"http://127.0.0.1:{srv.port}/healthz")
        scraped.append((metrics, json.loads(health)))

    with pytest.raises(InvalidArgumentError, match="metrics_port"):
        igg.run_resilient(step, {"T": T, "Cp": Cp}, 6, nt_chunk=2,
                          key="mesh_srv", healthz_max_age_s=120.0)
    igg.run_resilient(step, {"T": T, "Cp": Cp}, 6, nt_chunk=2,
                      key="mesh_srv", on_report=on_report, metrics_port=0,
                      healthz_max_age_s=120.0)
    assert len(scraped) == 3
    metrics, health = scraped[-1]
    assert "igg_driver_heartbeat_timestamp_seconds" in metrics
    assert "igg_health_events_total" in metrics
    assert health["heartbeat_age_s"] is not None
    assert health["step"] == 4.0  # last COMMITTED step at the final chunk
    assert igg.metrics_server() is None  # torn down with the run
    # the run's boundary heartbeats landed in the gauges
    from implicitglobalgrid_tpu.telemetry.hooks import HEARTBEAT_STEP

    assert igg.metrics_registry().get(HEARTBEAT_STEP).value() == 6
