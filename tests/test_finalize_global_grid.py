"""Tests of `finalize_global_grid` — analog of the reference's
`test/test_finalize_global_grid.jl` (finalization resets the singleton;
finalize-before-init throws), widened with the TPU-specific teardown
obligations: the compiled-exchange cache (the buffer-pool analog,
reference `update_halo.jl:103-108`) and the timing probes are freed,
and re-initialization afterwards works.
"""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.ops import halo
from implicitglobalgrid_tpu.utils import timing
from implicitglobalgrid_tpu.utils.exceptions import NotInitializedError


def test_finalize_resets_singleton():
    igg.init_global_grid(4, 4, 4, quiet=True)
    assert igg.grid_is_initialized()
    igg.finalize_global_grid()
    assert not igg.grid_is_initialized()


def test_finalize_before_init_throws():
    # Finalize can never come before initialize (reference test 2).
    assert not igg.grid_is_initialized()
    with pytest.raises(NotInitializedError):
        igg.finalize_global_grid()


def test_finalize_frees_exchange_cache_and_probes():
    igg.init_global_grid(5, 5, 5, periodx=1, periody=1, periodz=1, quiet=True)
    A = igg.zeros_g()
    igg.update_halo(A)
    igg.tic(); igg.toc()
    assert len(halo._exchange_cache) > 0
    assert len(timing._probe_cache) > 0
    igg.finalize_global_grid()
    assert len(halo._exchange_cache) == 0
    assert len(timing._probe_cache) == 0


def test_reinit_after_finalize():
    # Each reference test file re-inits/finalizes many times in one process
    # (init_MPI=false pattern) — the lifecycle must be fully cyclable.
    for nx in (4, 6, 8):
        igg.init_global_grid(nx, nx, nx, periodx=1, quiet=True)
        A = igg.ones_g()
        A = igg.update_halo(A)
        gg = igg.global_grid()
        assert np.asarray(igg.gather(A)).shape == tuple(
            int(d * n) for d, n in zip(gg.dims, gg.nxyz)
        )
        assert np.asarray(igg.gather_interior(A)).shape == (
            igg.nx_g(), igg.ny_g(), igg.nz_g(),
        )
        igg.finalize_global_grid()
        assert not igg.grid_is_initialized()


def test_double_finalize_throws():
    igg.init_global_grid(4, 4, 4, quiet=True)
    igg.finalize_global_grid()
    with pytest.raises(NotInitializedError):
        igg.finalize_global_grid()
