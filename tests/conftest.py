"""Test harness configuration.

TPU analog of the reference's test strategy (`SURVEY.md` §4,
`/root/reference/test/runtests.jl`): nearly all functionality is verified on
one HOST by emulating a multi-device mesh — 8 virtual CPU devices via
``--xla_force_host_platform_device_count`` (the analog of the reference's
"1 process + periodic self-neighbors" and `mpirun -np N` techniques,
`test/test_update_halo.jl:1-3`).

Must configure JAX before any backend initialization: set the flags at import
time, before any test module imports jax.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # reference default dtype is Float64

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_grid():
    """Ensure no grid state leaks between tests (each reference test file
    re-inits/finalizes repeatedly with `init_MPI=false` — same hygiene here)."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.parallel import topology

    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    topology._retained_epochs.clear()  # scheduler-held grids don't leak
    yield
    if igg.grid_is_initialized():
        igg.finalize_global_grid()
    topology._retained_epochs.clear()


def health_counters_from_registry():
    """The ``igg_health_events_total{kind=...}`` family as a dict — the
    registry IS the API since the PR-2 shims were retired (shared by
    test_resilience.py / test_service.py)."""
    import implicitglobalgrid_tpu as igg

    fam = igg.metrics_registry().get("igg_health_events_total")
    if fam is None:
        return {}
    return {labels["kind"]: int(v) for labels, v in fam.samples()}


def reset_health_counters_in_registry():
    import implicitglobalgrid_tpu as igg

    igg.metrics_registry().reset("igg_health_events_total")
