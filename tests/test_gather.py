"""Tests of `gather`/`gather_interior` — port of `test/test_gather.jl` ideas:
assembly of the stacked global array (reference `gather!` semantics: halo NOT
stripped, global size = dims .* local size, `gather.jl:33`), the in-place
`A_global` form, size-mismatch errors, plus the interior (implicit-grid)
assembly that the reference leaves to user code (`README.md:147-148`)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import IncoherentArgumentError


def _encoded():
    A = igg.zeros_g()
    cs = igg.coords_g(1.0, 1.0, 1.0, A)
    enc = sum(np.asarray(c) * 10.0 ** (3 * d) for d, c in enumerate(cs))
    return igg.device_put_g(np.ascontiguousarray(enc + np.zeros(A.shape)))


def test_gather_stacked():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = _encoded()
    G = igg.gather(P)
    assert isinstance(G, np.ndarray) and G.shape == (10, 10, 10)
    assert np.array_equal(G, np.asarray(P))


def test_gather_in_place_and_size_check():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = _encoded()
    out = np.zeros((10, 10, 10))
    ret = igg.gather(P, out)
    assert ret is out and np.array_equal(out, np.asarray(P))
    with pytest.raises(IncoherentArgumentError):
        igg.gather(P, np.zeros((9, 10, 10)))


def test_gather_2d():
    igg.init_global_grid(6, 6, 1, dimx=4, dimy=2, quiet=True)
    A = igg.zeros_g((6, 6)) + 3.0
    G = igg.gather(A)
    assert G.shape == (24, 12) and np.all(G == 3.0)


def test_gather_interior_nonperiodic():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = igg.update_halo(_encoded())
    GI = igg.gather_interior(P)
    assert GI.shape == (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (8, 8, 8)
    # interior values are exactly the coordinate encoding of the implicit grid
    idx = np.arange(8)
    exp = (idx[:, None, None] + 1e3 * idx[None, :, None] + 1e6 * idx[None, None, :])
    assert np.array_equal(GI, exp)


def test_gather_interior_periodic():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    P = igg.update_halo(_encoded())
    GI = igg.gather_interior(P)
    assert GI.shape == (6, 6, 6)
    idx = np.arange(6)
    exp = (idx[:, None, None] + 1e3 * idx[None, :, None] + 1e6 * idx[None, None, :])
    assert np.array_equal(GI, exp)


def test_gather_interior_staggered():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    Vx = igg.zeros_g((6, 5, 5)) + 7.0
    GI = igg.gather_interior(Vx)
    assert GI.shape == (igg.nx_g(Vx), igg.ny_g(), igg.nz_g()) == (9, 8, 8)
    assert np.all(GI == 7.0)


def test_gather_sub_block():
    """gather_sub selects the shard block of a coordinate box (the analog of
    the reference's explicit sub-communicator overload, `gather.jl:25-33`)."""
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = _encoded()
    full = np.asarray(P)
    # one shard
    S = igg.gather_sub(P, ((0, 1), (1, 2), (0, 1)))
    assert S.shape == (5, 5, 5)
    assert np.array_equal(S, full[0:5, 5:10, 0:5])
    # a 2x1x2 sub-grid; None selects the full axis
    S = igg.gather_sub(P, (None, (0, 1), (0, 2)))
    assert S.shape == (10, 5, 10)
    assert np.array_equal(S, full[:, 0:5, :])
    # in-place form + shape check
    out = np.empty((10, 5, 10), np.float32)
    r = igg.gather_sub(P, (None, (0, 1), None),
                       out.astype(np.asarray(P).dtype))
    assert np.array_equal(np.asarray(r), full[:, 0:5, :])
    with pytest.raises(IncoherentArgumentError):
        igg.gather_sub(P, (None, (0, 1), None), np.empty((3, 3, 3)))
    # invalid boxes
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(P, ((0, 3), None, None))
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(P, ((1, 1), None, None))


def test_gather_sub_extra_box_dim_rejected():
    """A box entry beyond the array's rank is a typo, not a no-op."""
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    igg.init_global_grid(8, 8, 1, dimx=2, dimy=2, dimz=1, quiet=True)
    A = igg.ones_g((8, 8), np.float32)
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(A, ((0, 1), (0, 1), (0, 1)))
    S = igg.gather_sub(A, ((0, 1), (0, 2)))
    assert S.shape == (8, 16)


def test_copy_wrapped_split_copies_1d():
    """`_copy_wrapped` (gather_interior's periodic-placement guard): a
    destination slice crossing the end must split into a tail copy and a
    wrapped head copy. For every decomposition the framework can
    construct, the periodic placement aligns exactly (stride s = n - ol
    divides the global size), so the helper is exercised directly at the
    wrap case it guards."""
    from implicitglobalgrid_tpu.ops.gather import _copy_wrapped

    host = np.arange(10.0)
    out = np.full((6,), -1.0)
    # dst [4, 8) over a length-6 axis: cells 4,5 then wrap to 0,1
    _copy_wrapped(out, host, [slice(2, 6)], [slice(4, 8)], (6,))
    assert np.array_equal(out, [4.0, 5.0, -1.0, -1.0, 2.0, 3.0])


def test_copy_wrapped_split_copies_2d_both_dims():
    """Wrap on BOTH dims recurses into four quadrant copies."""
    from implicitglobalgrid_tpu.ops.gather import _copy_wrapped

    host = np.arange(8.0 * 8.0).reshape(8, 8)
    out = np.full((5, 5), -1.0)
    src = [slice(1, 4), slice(2, 5)]
    dst = [slice(3, 6), slice(4, 7)]          # crosses the end on x and y
    _copy_wrapped(out, host, src, dst, (5, 5))
    expect = np.full((5, 5), -1.0)
    for a, ga in enumerate(range(3, 6)):
        for b, gb in enumerate(range(4, 7)):
            expect[ga % 5, gb % 5] = host[1 + a, 2 + b]
    assert np.array_equal(out, expect)


def test_copy_wrapped_no_wrap_is_plain_copy():
    from implicitglobalgrid_tpu.ops.gather import _copy_wrapped

    host = np.arange(6.0)
    out = np.zeros((6,))
    _copy_wrapped(out, host, [slice(1, 3)], [slice(4, 6)], (6,))
    assert np.array_equal(out, [0, 0, 0, 0, 1, 2])


def test_gather_interior_periodic_staggered_wrap_alignment():
    """Periodic + staggered: the per-field overlap (grid overlap plus the
    staggering extra) keeps the periodic stride s = n - ol_f equal across
    fields, so placement still tiles the wrapped axis exactly and the
    interior matches a shard-by-shard reference assembly."""
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    Vx = igg.update_halo(igg.device_put_g(
        np.random.default_rng(3).normal(size=(12, 10, 10))))
    GI = igg.gather_interior(Vx)
    assert GI.shape == (6, 6, 6)
    # owner formula (later shards win; ghost shift by one): global cell
    # g of dim with stride s=3 belongs to shard g//3, local index g%3+1
    full = np.asarray(Vx)
    for g in ((0, 0, 0), (2, 3, 5), (5, 5, 5), (3, 0, 4)):
        c = tuple(gi // 3 for gi in g)
        i = tuple(gi - ci * 3 + 1 for gi, ci in zip(g, c))
        src = tuple(ci * 6 + ii if d == 0 else ci * 5 + ii
                    for d, (ci, ii) in enumerate(zip(c, i)))
        assert GI[g] == full[src], (g, c, i)


def test_gather_sub_rejects_local_layout():
    """A local-layout array into gather_sub would silently clamp slices —
    the box math is defined on the stacked layout only."""
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(np.zeros((5, 5, 5), np.float32), ((1, 2), None, None),
                       layout="local")
