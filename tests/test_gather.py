"""Tests of `gather`/`gather_interior` — port of `test/test_gather.jl` ideas:
assembly of the stacked global array (reference `gather!` semantics: halo NOT
stripped, global size = dims .* local size, `gather.jl:33`), the in-place
`A_global` form, size-mismatch errors, plus the interior (implicit-grid)
assembly that the reference leaves to user code (`README.md:147-148`)."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import IncoherentArgumentError


def _encoded():
    A = igg.zeros_g()
    cs = igg.coords_g(1.0, 1.0, 1.0, A)
    enc = sum(np.asarray(c) * 10.0 ** (3 * d) for d, c in enumerate(cs))
    return igg.device_put_g(np.ascontiguousarray(enc + np.zeros(A.shape)))


def test_gather_stacked():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = _encoded()
    G = igg.gather(P)
    assert isinstance(G, np.ndarray) and G.shape == (10, 10, 10)
    assert np.array_equal(G, np.asarray(P))


def test_gather_in_place_and_size_check():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = _encoded()
    out = np.zeros((10, 10, 10))
    ret = igg.gather(P, out)
    assert ret is out and np.array_equal(out, np.asarray(P))
    with pytest.raises(IncoherentArgumentError):
        igg.gather(P, np.zeros((9, 10, 10)))


def test_gather_2d():
    igg.init_global_grid(6, 6, 1, dimx=4, dimy=2, quiet=True)
    A = igg.zeros_g((6, 6)) + 3.0
    G = igg.gather(A)
    assert G.shape == (24, 12) and np.all(G == 3.0)


def test_gather_interior_nonperiodic():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = igg.update_halo(_encoded())
    GI = igg.gather_interior(P)
    assert GI.shape == (igg.nx_g(), igg.ny_g(), igg.nz_g()) == (8, 8, 8)
    # interior values are exactly the coordinate encoding of the implicit grid
    idx = np.arange(8)
    exp = (idx[:, None, None] + 1e3 * idx[None, :, None] + 1e6 * idx[None, None, :])
    assert np.array_equal(GI, exp)


def test_gather_interior_periodic():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    P = igg.update_halo(_encoded())
    GI = igg.gather_interior(P)
    assert GI.shape == (6, 6, 6)
    idx = np.arange(6)
    exp = (idx[:, None, None] + 1e3 * idx[None, :, None] + 1e6 * idx[None, None, :])
    assert np.array_equal(GI, exp)


def test_gather_interior_staggered():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    Vx = igg.zeros_g((6, 5, 5)) + 7.0
    GI = igg.gather_interior(Vx)
    assert GI.shape == (igg.nx_g(Vx), igg.ny_g(), igg.nz_g()) == (9, 8, 8)
    assert np.all(GI == 7.0)


def test_gather_sub_block():
    """gather_sub selects the shard block of a coordinate box (the analog of
    the reference's explicit sub-communicator overload, `gather.jl:25-33`)."""
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = _encoded()
    full = np.asarray(P)
    # one shard
    S = igg.gather_sub(P, ((0, 1), (1, 2), (0, 1)))
    assert S.shape == (5, 5, 5)
    assert np.array_equal(S, full[0:5, 5:10, 0:5])
    # a 2x1x2 sub-grid; None selects the full axis
    S = igg.gather_sub(P, (None, (0, 1), (0, 2)))
    assert S.shape == (10, 5, 10)
    assert np.array_equal(S, full[:, 0:5, :])
    # in-place form + shape check
    out = np.empty((10, 5, 10), np.float32)
    r = igg.gather_sub(P, (None, (0, 1), None),
                       out.astype(np.asarray(P).dtype))
    assert np.array_equal(np.asarray(r), full[:, 0:5, :])
    with pytest.raises(IncoherentArgumentError):
        igg.gather_sub(P, (None, (0, 1), None), np.empty((3, 3, 3)))
    # invalid boxes
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(P, ((0, 3), None, None))
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(P, ((1, 1), None, None))


def test_gather_sub_extra_box_dim_rejected():
    """A box entry beyond the array's rank is a typo, not a no-op."""
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    igg.init_global_grid(8, 8, 1, dimx=2, dimy=2, dimz=1, quiet=True)
    A = igg.ones_g((8, 8), np.float32)
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(A, ((0, 1), (0, 1), (0, 1)))
    S = igg.gather_sub(A, ((0, 1), (0, 2)))
    assert S.shape == (8, 16)


def test_gather_sub_rejects_local_layout():
    """A local-layout array into gather_sub would silently clamp slices —
    the box math is defined on the stacked layout only."""
    from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    with pytest.raises(InvalidArgumentError):
        igg.gather_sub(np.zeros((5, 5, 5), np.float32), ((1, 2), None, None),
                       layout="local")
