"""Pallas stencil kernel tests (interpret mode on the CPU mesh) — the analog
of the reference testing its hand-written GPU pack kernels on every backend
(`test_update_halo.jl:497-634`): the fused Pallas step must reproduce the XLA
flux-form step to ulp accuracy, standalone and composed with the halo
exchange inside a whole-loop run."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import (
    init_diffusion3d, make_run, make_step, run_diffusion,
)


def test_pallas_step_matches_xla():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(make_step(p, impl="xla")(T, Cp))
    b = np.asarray(make_step(p, impl="pallas_interpret")(T, Cp))
    assert np.allclose(a, b, rtol=2e-6, atol=2e-5)


def test_pallas_whole_loop_matches_xla():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(run_diffusion(T, Cp, p, 3, nt_chunk=3, impl="xla"))
    b = np.asarray(run_diffusion(T, Cp, p, 3, nt_chunk=3, impl="pallas_interpret"))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)
    assert not np.allclose(a, np.asarray(T))  # it did something


def test_pallas_f64():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    a = np.asarray(make_step(p, impl="xla")(T, Cp))
    b = np.asarray(make_step(p, impl="pallas_interpret")(T, Cp))
    assert np.allclose(a, b, rtol=1e-13, atol=1e-12)


def test_impl_resolution_from_env_flag():
    from implicitglobalgrid_tpu.models.diffusion import _resolve_impl

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    # on the CPU test mesh, default stays xla even if the flag is set
    assert _resolve_impl(None) == "xla"
    assert _resolve_impl("pallas") == "pallas"
    gg = igg.global_grid()
    gg.use_pallas[:] = True
    assert _resolve_impl(None) == "xla"  # device_type is cpu here
