"""Pallas stencil kernel tests (interpret mode on the CPU mesh) — the analog
of the reference testing its hand-written GPU pack kernels on every backend
(`test_update_halo.jl:497-634`): the fused Pallas step must reproduce the XLA
flux-form step to ulp accuracy, standalone and composed with the halo
exchange inside a whole-loop run."""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import (
    init_diffusion3d, make_run, make_step, run_diffusion,
)


def test_pallas_step_matches_xla():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(make_step(p, impl="xla")(T, Cp))
    b = np.asarray(make_step(p, impl="pallas_interpret")(T, Cp))
    assert np.allclose(a, b, rtol=2e-6, atol=2e-5)


def test_pallas_whole_loop_matches_xla():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(run_diffusion(T, Cp, p, 3, nt_chunk=3, impl="xla"))
    b = np.asarray(run_diffusion(T, Cp, p, 3, nt_chunk=3, impl="pallas_interpret"))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)
    assert not np.allclose(a, np.asarray(T))  # it did something


def test_pallas_bf16():
    """TPU-native dtype through both step implementations."""
    import jax.numpy as jnp

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=jnp.bfloat16)
    a = np.asarray(make_step(p, impl="xla")(T, Cp)).astype(np.float32)
    b = np.asarray(make_step(p, impl="pallas_interpret")(T, Cp)).astype(np.float32)
    assert np.allclose(a, b, rtol=2e-2, atol=0.5)


def test_pallas_f64():
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float64)
    a = np.asarray(make_step(p, impl="xla")(T, Cp))
    b = np.asarray(make_step(p, impl="pallas_interpret")(T, Cp))
    assert np.allclose(a, b, rtol=1e-13, atol=1e-12)


@pytest.mark.parametrize("dims,periods,expected_fuse", [
    ((1, 1, 1), (1, 1, 1), (True, True, True)),    # all self-neighbor
    ((2, 1, 1), (1, 1, 1), (False, False, True)),  # z fuses; x multi-shard blocks y
    ((1, 1, 2), (1, 1, 1), None),                  # z multi-shard blocks everything
    ((1, 1, 1), (0, 0, 0), None),                  # nothing exchanges
    ((1, 2, 1), (1, 0, 1), (True, False, True)),   # z,x fuse; y (multi-shard) breaks
    ((1, 1, 1), (1, 1, 0), (True, True, False)),   # z exchanges nothing -> x,y still fuse
])
def test_fusable_halo_dims(dims, periods, expected_fuse):
    """Fusion must cover only a prefix of the z, x, y exchange order
    (reference `update_halo.jl:45` sequencing — corners propagate dim by
    dim)."""
    from implicitglobalgrid_tpu.ops.pallas_stencil import fusable_halo_dims

    igg.init_global_grid(8, 8, 8, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    assert fusable_halo_dims(igg.global_grid()) == expected_fuse


@pytest.mark.parametrize("nx", [16, 12])  # 16: multi-plane kernel; 12: plane-per-program
@pytest.mark.parametrize("dims,periods", [
    ((1, 1, 1), (1, 1, 1)),  # all dims fused in-kernel
    ((2, 1, 1), (1, 1, 1)),  # mixed: fused z + ppermute x + local y
    ((1, 1, 1), (0, 0, 0)),  # no exchange at all
])
def test_pallas_fused_halo_matches_xla(dims, periods, nx):
    """The fused step+halo kernels (both the multi-plane and the
    plane-per-program form) must reproduce the XLA step followed by the
    sequential exchange — including corner propagation through the dims."""
    igg.init_global_grid(nx, 16, 16, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(igg.gather(make_run(p, 10, impl="xla")(T, Cp)[0]))
    b = np.asarray(igg.gather(make_run(p, 10, impl="pallas_interpret")(T, Cp)[0]))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)


def test_mp_window_handoff_selection_and_equivalence(monkeypatch):
    """The VMEM window handoff (1.0x T reads) engages only with >= 3
    windows, honors IGG_MP_HANDOFF=0, and changes the traffic model —
    while the kernel output stays identical to the plain pipeline and the
    XLA reference over a multi-step run (nx=12, P=4 -> 3 windows)."""
    import jax

    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        mp_bytes_per_cell, mp_handoff, mp_planes,
    )

    monkeypatch.delenv("IGG_MP_HANDOFF", raising=False)
    s12 = jax.ShapeDtypeStruct((12, 16, 16), np.float32)
    s8 = jax.ShapeDtypeStruct((8, 16, 16), np.float32)
    assert mp_planes(s12, interpret=True) == 4
    assert mp_handoff(s12, interpret=True)          # 3 windows
    assert not mp_handoff(s8, interpret=True)       # 2 windows: plain
    assert mp_bytes_per_cell(s12, interpret=True) == 3.0 * 4
    monkeypatch.setenv("IGG_MP_HANDOFF", "0")
    assert not mp_handoff(s12, interpret=True)
    assert mp_bytes_per_cell(s12, interpret=True) == (3.0 + 2.0 / 4) * 4
    monkeypatch.delenv("IGG_MP_HANDOFF")

    igg.init_global_grid(12, 16, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(igg.gather(make_run(p, 10, impl="xla")(T, Cp)[0]))
    b = np.asarray(igg.gather(
        make_run(p, 10, impl="pallas_interpret")(T, Cp)[0]))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)
    # plain pipeline (flag off) produces the SAME kernel output — flipped
    # IN-EPOCH: the runner cache keys on the flag, so this retraces
    # instead of replaying the cached handoff program
    monkeypatch.setenv("IGG_MP_HANDOFF", "0")
    c = np.asarray(igg.gather(
        make_run(p, 10, impl="pallas_interpret")(T, Cp)[0]))
    assert np.array_equal(b, c)


def test_mp_handoff_multishard_matches_xla(monkeypatch):
    """The handoff window inside the multi-shard fused step+exchange
    kernel (`_mp_step_recv_kernel`, local nx=12 -> 3 windows): 10-step
    whole-loop equality with the XLA step + sequential exchange."""
    import jax

    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        mp_handoff, step_exchange_modes,
    )

    monkeypatch.delenv("IGG_MP_HANDOFF", raising=False)
    igg.init_global_grid(12, 12, 16, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    gg = igg.global_grid()
    sds = jax.ShapeDtypeStruct((12, 12, 16), np.float32)
    assert mp_handoff(sds, interpret=True)
    assert step_exchange_modes(gg, sds) == (True, True, True)
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(igg.gather(make_run(p, 10, impl="xla")(T, Cp)[0]))
    b = np.asarray(igg.gather(
        make_run(p, 10, impl="pallas_interpret")(T, Cp)[0]))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)


def test_impl_resolution_from_env_flag():
    from implicitglobalgrid_tpu.models.diffusion import _resolve_impl

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    # on the CPU test mesh, default stays xla even if the flag is set
    assert _resolve_impl(None) == "xla"
    assert _resolve_impl("pallas") == "pallas"
    gg = igg.global_grid()
    gg.use_pallas[:] = True
    assert _resolve_impl(None) == "xla"  # device_type is cpu here


@pytest.mark.parametrize("dims,periods,label", [
    ((2, 2, 2), (1, 1, 1), "all multi-shard periodic"),
    ((2, 2, 2), (0, 0, 0), "all multi-shard PROC_NULL edges"),
    ((2, 1, 1), (1, 0, 0), "multi x only: partial modes (True,False,False)"),
    ((1, 2, 4), (1, 0, 1), "self x + PROC_NULL y + 4-shard z"),
])
def test_step_exchange_fused_matches_xla(dims, periods, label):
    """The fused step+exchange path (thin-slab sends -> ppermute -> one
    delivery pass) must reproduce the XLA step followed by the sequential
    exchange over a 10-step whole loop — corners propagate through mixed
    self/multi-shard dims."""
    from implicitglobalgrid_tpu.ops.pallas_stencil import step_exchange_modes

    igg.init_global_grid(8, 8, 16, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    # the config must actually take the new path
    from implicitglobalgrid_tpu.ops.fields import local_shape_of
    import jax

    loc = local_shape_of(tuple(int(s) for s in T.shape))
    assert step_exchange_modes(
        gg, jax.ShapeDtypeStruct(loc, T.dtype)) is not None, label
    a = np.asarray(igg.gather(make_run(p, 10, impl="xla")(T, Cp)[0]))
    b = np.asarray(igg.gather(make_run(p, 10, impl="pallas_interpret")(T, Cp)[0]))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4), label


def test_partial_fuse_with_nonstandard_dim_matches_xla():
    """A self-neighbor prefix (z) fuses in-kernel while a nonstandard dim
    (x with halowidth 2 — ineligible for the fused exchange) is exchanged
    afterwards over only the remaining dims — results must match the XLA
    step + sequential exchange."""
    igg.init_global_grid(12, 12, 16, dimx=2, dimy=1, dimz=1,
                         periodx=1, periodz=1,
                         overlaps=(4, 2, 2), halowidths=(2, 1, 1), quiet=True)
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        fusable_halo_dims, step_exchange_modes,
    )
    import jax

    gg = igg.global_grid()
    assert fusable_halo_dims(gg) == (False, False, True)
    assert step_exchange_modes(
        gg, jax.ShapeDtypeStruct((12, 12, 16), np.float32)) is None
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    a = np.asarray(igg.gather(make_run(p, 5, impl="xla")(T, Cp)[0]))
    b = np.asarray(igg.gather(
        make_run(p, 5, impl="pallas_interpret")(T, Cp)[0]))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("dims,periods,label", [
    ((1, 1), (1, 1), "2-D all self-neighbor"),
    ((2, 2), (1, 1), "2-D all multi-shard periodic"),
    ((2, 2), (0, 0), "2-D PROC_NULL edges"),
    ((2, 1), (1, 0), "2-D multi x only"),
])
def test_step_exchange_2d_matches_xla(dims, periods, label):
    """The 2-D fused step+exchange strip kernel (BASELINE config 2) must
    reproduce the XLA 2-D step followed by the sequential exchange."""
    from implicitglobalgrid_tpu.models import init_diffusion2d
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        step_exchange_modes, strip_rows_2d,
    )
    import jax

    igg.init_global_grid(16, 16, 1, dimx=dims[0], dimy=dims[1], dimz=1,
                         periodx=periods[0], periody=periods[1], quiet=True)
    gg = igg.global_grid()
    T, Cp, p = init_diffusion2d(dtype=np.float32)
    from implicitglobalgrid_tpu.ops.fields import local_shape_of

    loc = local_shape_of(tuple(int(s) for s in T.shape))
    sds = jax.ShapeDtypeStruct(loc, T.dtype)
    assert step_exchange_modes(gg, sds) is not None, label
    # compiled mode requires tile-aligned shapes; interpret (this test) not
    assert strip_rows_2d(sds, interpret=True) is not None, label
    assert strip_rows_2d(sds) is None, label
    a = np.asarray(igg.gather(make_run(p, 10, ndim=2, impl="xla")(T, Cp)[0]))
    b = np.asarray(igg.gather(
        make_run(p, 10, ndim=2, impl="pallas_interpret")(T, Cp)[0]))
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4), label


def test_step_exchange_modes_gates():
    from implicitglobalgrid_tpu.ops.pallas_stencil import step_exchange_modes
    import jax

    # nonstandard halowidth: ineligible
    igg.init_global_grid(12, 12, 12, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), halowidths=(2, 2, 2), quiet=True)
    gg = igg.global_grid()
    s = jax.ShapeDtypeStruct((12, 12, 12), np.float32)
    assert step_exchange_modes(gg, s) is None
    igg.finalize_global_grid()
    # staggered block: ineligible
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=1, dimz=1, periodx=1,
                         quiet=True)
    gg = igg.global_grid()
    assert step_exchange_modes(
        gg, jax.ShapeDtypeStruct((9, 8, 8), np.float32)) is None
    # unstaggered, only x multi-shard (y/z single-shard non-periodic)
    assert step_exchange_modes(
        gg, jax.ShapeDtypeStruct((8, 8, 8), np.float32)) == (True, False, False)


def test_mp_planes_vmem_selection():
    """Plane-count selection respects the VMEM budget: f32 256-cube picks a
    smaller P than bf16 (half the plane bytes), tiny blocks fall back."""
    import jax

    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        _MP_VMEM_BUDGET, _MP_TEMP_PLANES, mp_planes, strip_rows_2d,
    )

    import jax.numpy as jnp

    P32 = mp_planes(jax.ShapeDtypeStruct((256, 256, 256), np.float32))
    P16 = mp_planes(jax.ShapeDtypeStruct((256, 256, 256), jnp.bfloat16))
    assert P32 is not None and P16 is not None and P16 >= P32
    ws = (6 * P32 + 4 + _MP_TEMP_PLANES) * 256 * 256 * 4
    assert ws <= _MP_VMEM_BUDGET  # the chosen P actually fits the budget
    # bf16 temporaries cost f32 (compute dtype): the model accounts for it
    from implicitglobalgrid_tpu.ops.pallas_stencil import _compute_itemsize
    assert _compute_itemsize(np.dtype(jnp.bfloat16)) == 4
    # indivisible plane axis -> None
    assert mp_planes(jax.ShapeDtypeStruct((7, 256, 256), np.float32)) is None
    # lane-unaligned blocks cannot use the window DMA (Mosaic rejects the
    # dynamic-start HBM slice on partially-tiled shapes; verified on v5e)
    assert mp_planes(jax.ShapeDtypeStruct((192, 192, 192), np.float32)) is None
    from implicitglobalgrid_tpu.ops.pallas_wave import wave_mp_planes
    assert wave_mp_planes((192, 192, 192), np.float32) is None
    assert wave_mp_planes((128, 128, 128), np.float32) is not None
    # 2-D strip selection fits the budget too
    R = strip_rows_2d(jax.ShapeDtypeStruct((4096, 4096), np.float32))
    assert R is not None and (12 * R + 8) * 4096 * 4 <= _MP_VMEM_BUDGET
    # bf16 strips: f32 temporaries halve R vs the naive bf16-only estimate
    Rb = strip_rows_2d(jax.ShapeDtypeStruct((8192, 8192), jnp.bfloat16))
    assert Rb is not None
    assert (6 * Rb + 8) * 8192 * 2 + 6 * Rb * 8192 * 4 <= _MP_VMEM_BUDGET


def test_pallas_bf16_f32_accumulation_beats_plain_bf16():
    """The kernels compute bf16 states in f32 (storage stays bf16): over a
    multi-step run they must track the f32 solution at least as well as
    the plain bf16 XLA arithmetic."""
    import jax.numpy as jnp

    igg.init_global_grid(16, 16, 16, dimx=1, dimy=1, dimz=1,
                         periodx=1, periody=1, periodz=1, quiet=True)
    T32, Cp32, p = init_diffusion3d(dtype=np.float32)
    ref = np.asarray(run_diffusion(T32, Cp32, p, 20, nt_chunk=10,
                                   impl="xla")).astype(np.float64)
    T16, Cp16, p16 = init_diffusion3d(dtype=jnp.bfloat16)
    a = np.asarray(run_diffusion(T16, Cp16, p16, 20, nt_chunk=10,
                                 impl="xla")).astype(np.float64)
    b = np.asarray(run_diffusion(T16, Cp16, p16, 20, nt_chunk=10,
                                 impl="pallas_interpret")).astype(np.float64)
    err_xla = np.abs(a - ref).max()
    err_pal = np.abs(b - ref).max()
    assert err_pal <= err_xla * 1.05, (err_pal, err_xla)
