"""The example scripts stay green: run them as subprocesses on the
8-device CPU mesh (the reference keeps its examples working the same way —
they double as documentation; `/root/reference/examples/`)."""

import os
import pathlib
import re
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


def _run(script, tmp_path, timeout=600):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / script), "--cpu"],
        capture_output=True, text=True, timeout=timeout,
        cwd=tmp_path, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_novis_example(tmp_path):
    out = _run("diffusion3D_multixpu_novis.py", tmp_path)
    assert "cell-updates/s" in out
    m = re.search(r"T interior mean: ([0-9.]+)", out)
    assert m is not None
    # the example's physics is deterministic: the 126^3 global interior
    # mean after 100 steps (pinned within f32 run-to-run tolerance)
    assert abs(float(m.group(1)) - 6.457611) < 5e-4


@pytest.mark.slow
def test_vis_example(tmp_path):
    """slow (tier-1 budget, ISSUE 8 trim): the vis flavor adds a ~12 s
    subprocess on top of the novis smoke, which stays tier-1 as the
    diffusion example's fast representative (gather-for-vis itself is
    unit-tested in test_gather.py)."""
    out = _run("diffusion3D_multixpu.py", tmp_path)
    wrote = [p.name for p in tmp_path.iterdir()]
    assert any(n.startswith("diffusion3D") for n in wrote), (out, wrote)


def test_acoustic_example(tmp_path):
    out = _run("acoustic3D_multixpu.py", tmp_path)
    assert "P interior" in out


@pytest.mark.slow
def test_advanced_modes_example(tmp_path):
    out = _run("diffusion3D_advanced_modes.py", tmp_path)
    # SR must beat plain bf16 against the f32 trajectory
    errs = {m.group(1): float(m.group(2)) for m in re.finditer(
        r"(bf16(?:_sr)?)\s+vs f32 after \d+ steps: max_rel=([0-9.e+-]+)",
        out)}
    assert errs["bf16_sr"] < errs["bf16"], errs
    assert "comm_every=2" in out
    assert "overlap[" in out


@pytest.mark.slow
def test_stokes_example(tmp_path):
    """slow (tier-1 budget, ISSUE 8 trim): a ~9 s subprocess smoke over a
    model family whose step/exchange/deep-halo behaviors all have
    dedicated tier-1 suites; novis + acoustic remain the fast example
    representatives."""
    out = _run("stokes3D_multixpu.py", tmp_path)
    assert "PT iterations" in out
    # residuals must DROP across the printed checks
    errs = [float(m) for m in re.findall(r"max\|divV\|=([0-9.e+-]+)", out)]
    assert len(errs) >= 2 and errs[-1] < errs[0]
