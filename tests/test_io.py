"""Tests of the sharded snapshot & in-situ analysis pipeline (`io/`).

The tentpole contracts (ISSUE 4 acceptance):

- `read_global` of a written snapshot is BIT-IDENTICAL to
  `gather_interior` on the same state — including periodic dims and
  staggered fields — and sub-box reads equal the matching slice;
- an interrupted writer never leaves a committed-but-corrupt snapshot
  (staged-rename commit; checksum-verified reads);
- the async writer keeps the step loop off the disk path: bounded queue,
  `block`/`drop_oldest` backpressure, drained on close;
- in-situ reducers (probe / axis slice / global stats) match the values
  a gather-based analysis would compute, with ZERO gathers (their wire
  cost is audited in tests/test_hlo_audit.py);
- the events surface in `igg.run_report` and the `tools` CLI.
"""

import os
import threading

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu import io as iggio
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)

pytestmark = pytest.mark.io


def _encoded(dtype=np.float64):
    """Coordinate-encoded field: cell value identifies its global cell
    (same idiom as tests/test_gather.py)."""
    A = igg.zeros_g(dtype=dtype)
    cs = igg.coords_g(1.0, 1.0, 1.0, A)
    enc = sum(np.asarray(c) * 10.0 ** (3 * d) for d, c in enumerate(cs))
    return igg.device_put_g((enc + np.zeros(A.shape)).astype(dtype))


# ---------------------------------------------------------------------------
# Reader vs gather_interior: the bit-identity contract
# ---------------------------------------------------------------------------

def test_read_global_bit_identical_nonperiodic(tmp_path):
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = igg.update_halo(_encoded())
    path = iggio.write_snapshot(tmp_path / "snaps", {"T": P}, step=7)
    snap = iggio.open_snapshot(path)
    assert snap.step == 7 and snap.names == ["T"]
    GI = igg.gather_interior(P)
    assert snap.global_shape("T") == GI.shape
    G = snap.read_global("T")
    assert G.dtype == GI.dtype
    assert np.array_equal(G, GI)
    # O(box) sub-reads equal the matching slice of the implicit grid
    box = ((1, 4), (0, 8), (5, 8))
    assert np.array_equal(snap.read_global("T", box=box),
                          GI[1:4, 0:8, 5:8])
    assert snap.read_point("T", (3, 4, 5)) == GI[3, 4, 5]


def test_read_global_bit_identical_periodic(tmp_path):
    """The acceptance case: periodic dims — ghost shift and wrap must
    reproduce gather_interior exactly."""
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    P = igg.update_halo(_encoded())
    path = iggio.write_snapshot(tmp_path / "snaps", {"T": P}, step=1)
    snap = iggio.open_snapshot(path)
    GI = igg.gather_interior(P)
    assert GI.shape == (6, 6, 6)
    assert np.array_equal(snap.read_global("T"), GI)
    assert np.array_equal(snap.read_global("T", box=((4, 6), None, (0, 1))),
                          GI[4:6, :, 0:1])


def test_read_global_mixed_periodic_and_staggered(tmp_path):
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2,
                         periodx=1, quiet=True)
    T = igg.update_halo(_encoded(np.float32))
    Vx = igg.device_put_g(  # x-staggered: local (6,5,5), stacked (12,10,10)
        np.random.default_rng(0).normal(size=(12, 10, 10))
        .astype(np.float32))
    path = iggio.write_snapshot(tmp_path / "s", {"T": T, "Vx": Vx}, step=0)
    snap = iggio.open_snapshot(path)
    for name, arr in (("T", T), ("Vx", Vx)):
        GI = igg.gather_interior(arr)
        assert snap.global_shape(name) == GI.shape
        assert np.array_equal(snap.read_global(name), GI)


def test_reader_is_host_only(tmp_path):
    """Analysis-side contract: reads work with NO initialized grid (the
    topology travels in meta.npz)."""
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    P = igg.update_halo(_encoded())
    GI = igg.gather_interior(P)
    path = iggio.write_snapshot(tmp_path / "snaps", {"T": P}, step=3)
    igg.finalize_global_grid()
    snap = iggio.open_snapshot(path)
    assert np.array_equal(snap.read_global("T"), GI)
    topo = snap.topology()
    assert list(topo["dims"]) == [2, 2, 2] and topo["step"] == 3


def test_reader_opens_checkpoint_dirs(tmp_path):
    """Snapshots share the PR-2 checkpoint container, so the lazy reader
    is also the post-hoc analysis path for sharded checkpoints."""
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.update_halo(_encoded())
    igg.save_checkpoint_sharded(str(tmp_path / "ckpt"), {"T": T}, step=9)
    GI = igg.gather_interior(T)
    snap = iggio.open_snapshot(tmp_path / "ckpt")
    assert snap.step == 9
    assert np.array_equal(snap.read_global("T"), GI)


# ---------------------------------------------------------------------------
# Durability: commit protocol + checksums
# ---------------------------------------------------------------------------

def test_interrupted_writer_leaves_no_committed_snapshot(tmp_path, monkeypatch):
    """Kill the writer before the meta.npz commit record: the staged
    directory must never surface as a snapshot."""
    from implicitglobalgrid_tpu.io import snapshot as snap_mod

    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.ones_g()
    root = tmp_path / "snaps"

    orig = snap_mod.write_npz_synced

    def dying(path, payload):
        if os.path.basename(path) == "meta.npz":
            raise OSError("simulated crash before commit")
        return orig(path, payload)

    monkeypatch.setattr(snap_mod, "write_npz_synced", dying)
    with pytest.raises(OSError):
        iggio.write_snapshot(root, {"T": T}, step=5)
    monkeypatch.setattr(snap_mod, "write_npz_synced", orig)

    assert iggio.list_snapshots(root) == []  # nothing committed
    with pytest.raises(InvalidArgumentError):
        iggio.open_snapshot(root / "step_0000000005")
    # the shard data staged before the crash is still there (forensics),
    # clearly marked as uncommitted
    assert any(".tmp-" in d for d in os.listdir(root))
    # and a later successful snapshot of the same step commits cleanly
    path = iggio.write_snapshot(root, {"T": T}, step=5)
    assert iggio.list_snapshots(root) == [(5, path)]


def test_corrupt_committed_snapshot_is_detected(tmp_path):
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.ones_g()
    path = iggio.write_snapshot(tmp_path / "s", {"T": T}, step=0)
    shard = os.path.join(path, "shards_p0.npz")
    data = bytearray(open(shard, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(shard, "wb") as f:
        f.write(data)
    snap = iggio.open_snapshot(path)  # meta is fine; blocks are not
    with pytest.raises(IncoherentArgumentError):
        snap.read_global("T")


def test_list_snapshots_skips_foreign_entries(tmp_path):
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.ones_g()
    root = tmp_path / "s"
    path = iggio.write_snapshot(root, {"T": T}, step=2)
    os.makedirs(root / "step_0000000009.tmp-x")     # staged leftovers
    os.makedirs(root / "step_0000000008")           # no meta.npz commit
    os.makedirs(root / "notasnap")
    assert iggio.list_snapshots(root) == [(2, str(path))]


# ---------------------------------------------------------------------------
# Async writer: queue, backpressure, drain
# ---------------------------------------------------------------------------

def test_snapshot_writer_async_roundtrip(tmp_path):
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    igg.reset_metrics()
    T = igg.update_halo(_encoded())
    with iggio.SnapshotWriter(tmp_path / "s", queue_depth=2) as w:
        for step in (10, 20, 30):
            assert w.submit({"T": T}, step)
        assert w.flush(timeout=30.0)
    assert [s for s, _ in iggio.list_snapshots(tmp_path / "s")] \
        == [10, 20, 30]
    st = w.stats
    assert st["submitted"] == st["written"] == 3
    assert st["dropped"] == st["errors"] == 0 and st["bytes"] > 0
    snap = iggio.open_snapshot(iggio.list_snapshots(tmp_path / "s")[0][1])
    assert np.array_equal(snap.read_global("T"), igg.gather_interior(T))
    # telemetry: bytes counter and seconds histogram moved
    reg = igg.metrics_registry()
    assert reg.get("igg_snapshot_bytes_total").value() == st["bytes"]
    assert reg.get("igg_snapshots_total").value(result="written") == 3


def test_snapshot_writer_drop_oldest(tmp_path, monkeypatch):
    """A stalled disk with policy=drop_oldest sheds the OLDEST queued
    snapshot and keeps the newest — bounded memory, bounded stall."""
    from implicitglobalgrid_tpu.io import snapshot as snap_mod

    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    igg.reset_metrics()
    T = igg.ones_g()
    gate = threading.Event()
    orig = snap_mod._write_captured

    def slow(root, step, cap, **kw):
        gate.wait(timeout=30.0)
        return orig(root, step, cap, **kw)

    monkeypatch.setattr(snap_mod, "_write_captured", slow)
    w = iggio.SnapshotWriter(tmp_path / "s", queue_depth=1,
                             policy="drop_oldest")
    try:
        import time as _time

        assert w.submit({"T": T}, 1)          # writer thread picks it up
        for _ in range(500):                   # wait until it is mid-write
            if w._busy:
                break
            _time.sleep(0.01)
        assert w._busy                         # stalled inside the gate
        assert w.submit({"T": T}, 2)           # queued
        assert not w.submit({"T": T}, 3)       # displaces step 2
        gate.set()
        assert w.flush(timeout=30.0)
    finally:
        gate.set()
        w.close(timeout=30.0)
    steps = [s for s, _ in iggio.list_snapshots(tmp_path / "s")]
    assert steps == [1, 3]
    st = w.stats
    assert st["dropped"] == 1 and st["written"] == 2
    assert igg.metrics_registry().get("igg_snapshots_total") \
        .value(result="dropped") == 1


def test_snapshot_writer_block_policy_never_drops(tmp_path, monkeypatch):
    from implicitglobalgrid_tpu.io import snapshot as snap_mod

    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.ones_g()
    orig = snap_mod._write_captured

    def slow(root, step, cap, **kw):
        import time as _time

        _time.sleep(0.02)
        return orig(root, step, cap, **kw)

    monkeypatch.setattr(snap_mod, "_write_captured", slow)
    with iggio.SnapshotWriter(tmp_path / "s", queue_depth=1,
                              policy="block") as w:
        for step in range(5):
            assert w.submit({"T": T}, step)    # waits instead of dropping
        assert w.flush(timeout=30.0)
    assert w.stats["dropped"] == 0 and w.stats["written"] == 5
    assert len(iggio.list_snapshots(tmp_path / "s")) == 5


def test_snapshot_writer_validation(tmp_path):
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.ones_g()
    with pytest.raises(InvalidArgumentError):
        iggio.SnapshotWriter(tmp_path / "s", policy="nope")
    with pytest.raises(InvalidArgumentError):
        iggio.SnapshotWriter(tmp_path / "s", queue_depth=0)
    with pytest.raises(InvalidArgumentError):
        iggio.write_snapshot(tmp_path / "s", {}, step=0)
    with pytest.raises(InvalidArgumentError):
        iggio.write_snapshot(tmp_path / "s", {"T": T}, step=0,
                             fields=("missing",))
    w = iggio.SnapshotWriter(tmp_path / "s2")
    w.close()
    with pytest.raises(InvalidArgumentError):
        w.submit({"T": T}, 0)


# ---------------------------------------------------------------------------
# In-situ reducers
# ---------------------------------------------------------------------------

def _diffusion_setup():
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


def test_reducers_match_gather_analysis(tmp_path):
    """Probe/slice/stats computed in-situ equal what a gather-based
    analysis computes from the final state."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    step, state = _diffusion_setup()
    seen = []
    st, reports = igg.run_resilient(
        step, state, 8, nt_chunk=4, key="io_red",
        reducers=[iggio.Probe("T", (3, 4, 5)),
                  iggio.AxisSlice("T", 1, (2, 0, 3), name="line"),
                  iggio.Stats("T")],
        on_reduce=lambda s, v: seen.append((s, v)))
    assert [s for s, _ in seen] == [4, 8]
    GI = igg.gather_interior(st["T"]).astype(np.float64)
    s_, v = seen[-1]
    assert v["probe:T@3,4,5"] == np.float32(GI[3, 4, 5])
    assert np.allclose(v["line"], GI[2, :, 3], rtol=1e-6, atol=0)
    stats = v["stats:T"]
    assert stats["min"] == np.float32(GI.min())
    assert stats["max"] == np.float32(GI.max())
    assert abs(stats["mean"] - GI.mean()) < 1e-5 * max(1.0, abs(GI.mean()))
    assert abs(stats["rms"] - np.sqrt((GI ** 2).mean())) \
        < 1e-5 * np.sqrt((GI ** 2).mean())
    # gauges carry the latest scalars
    g = igg.metrics_registry().get("igg_reducer_value")
    assert g.value(name="probe:T@3,4,5") == v["probe:T@3,4,5"]
    assert g.value(name="stats:T:max") == stats["max"]


def test_reducers_on_replicated_low_rank_field():
    """Fields of rank < 3 are replicated over the unused mesh axes; the
    replica guard must keep sums and probes single-counted."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, quiet=True)
    A2 = igg.update_halo(igg.device_put_g(
        np.random.default_rng(1).normal(size=(12, 12))
        .astype(np.float32)))

    def step(s):
        return {"A": s["A"]}

    seen = []
    igg.run_resilient(step, {"A": A2}, 1, nt_chunk=1, key="io_red2d",
                      reducers=[iggio.Probe("A", (5, 7)),
                                iggio.Stats("A", which=("min", "max",
                                                        "mean"))],
                      on_reduce=lambda s, v: seen.append(v))
    GI = igg.gather_interior(A2).astype(np.float64)
    v = seen[-1]
    assert v["probe:A@5,7"] == np.float32(GI[5, 7])
    assert v["stats:A"]["min"] == np.float32(GI.min())
    assert v["stats:A"]["max"] == np.float32(GI.max())
    assert abs(v["stats:A"]["mean"] - GI.mean()) < 1e-6


def test_reducer_validation():
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, quiet=True)
    from implicitglobalgrid_tpu.io.reducers import build_reducer_plan

    T = igg.ones_g()
    with pytest.raises(InvalidArgumentError):
        build_reducer_plan([iggio.Probe("missing", (0, 0, 0))],
                           ["T"], {"T": T})
    with pytest.raises(InvalidArgumentError):
        build_reducer_plan([iggio.Probe("T", (0, 0))], ["T"], {"T": T})
    with pytest.raises(InvalidArgumentError):
        build_reducer_plan([iggio.Probe("T", (99, 0, 0))], ["T"], {"T": T})
    with pytest.raises(InvalidArgumentError):
        build_reducer_plan([iggio.AxisSlice("T", 5, (0, 0, 0))],
                           ["T"], {"T": T})
    with pytest.raises(InvalidArgumentError):
        iggio.Stats("T", which=("median",))
    with pytest.raises(InvalidArgumentError):
        build_reducer_plan([iggio.Probe("T", (0, 0, 0), name="x"),
                            iggio.Probe("T", (1, 1, 1), name="x")],
                           ["T"], {"T": T})


# ---------------------------------------------------------------------------
# Driver integration: events, report, CLI, program identity
# ---------------------------------------------------------------------------

def test_run_resilient_snapshot_events_and_report(tmp_path, capsys):
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    step, state = _diffusion_setup()
    jsonl = tmp_path / "fr.jsonl"
    igg.start_flight_recorder(str(jsonl))
    try:
        st, _ = igg.run_resilient(
            step, state, 12, nt_chunk=4, key="io_evt",
            snapshot_dir=str(tmp_path / "snaps"), snapshot_every=4,
            reducers=[iggio.Probe("T", (1, 1, 1))])
    finally:
        igg.stop_flight_recorder()
    kinds = [e["kind"] for e in igg.read_flight_events(jsonl)]
    for k in ("snapshot", "snapshot_write", "reducers",
              "snapshot_writer_close"):
        assert k in kinds, (k, kinds)
    rep = igg.run_report(str(jsonl))
    assert rep["io"]["snapshots_submitted"] == 3
    assert rep["io"]["snapshots_written"] == 3
    assert rep["io"]["snapshots_dropped"] == 0
    assert rep["io"]["snapshot_bytes"] > 0
    assert rep["io"]["reducer_points"] == 3
    assert any(s["kind"] == "snapshot_write" for s in rep["sequence"])

    # CLI: report surfaces io, snapshots lists, probe reads the series
    from implicitglobalgrid_tpu.tools import _cli

    assert _cli(["report", str(jsonl), "--no-metrics"]) == 0
    out = capsys.readouterr().out
    assert '"snapshots_written": 3' in out
    assert _cli(["snapshots", str(tmp_path / "snaps")]) == 0
    out = capsys.readouterr().out
    assert out.count("step ") == 3 and "T(" in out
    assert _cli(["probe", str(tmp_path / "snaps"), "T",
                 "2", "3", "4"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 3 and lines[-1].startswith("12 ")
    GI = igg.gather_interior(st["T"])
    assert float(lines[-1].split()[1]) == pytest.approx(float(GI[2, 3, 4]))


def test_snapshots_reuse_the_compiled_chunk(tmp_path):
    """THE zero-collectives claim, program-identity form: a run WITH
    snapshots reuses the exact compiled chunk of a run WITHOUT them
    (same runner-cache key -> cache hit), so snapshots cannot have
    changed the chunk program."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    step, state = _diffusion_setup()
    igg.reset_metrics()
    reg = igg.metrics_registry()
    igg.run_resilient(step, dict(state), 4, nt_chunk=4, key="io_hit")
    misses0 = reg.get("igg_runner_cache_total").value(result="miss")
    igg.run_resilient(step, dict(state), 4, nt_chunk=4, key="io_hit",
                      snapshot_dir=str(tmp_path / "s"), snapshot_every=4)
    assert reg.get("igg_runner_cache_total").value(result="miss") == misses0
    assert reg.get("igg_runner_cache_total").value(result="hit") >= 1
    assert len(iggio.list_snapshots(tmp_path / "s")) == 1


def test_snapshot_without_dir_rejected():
    igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
    T = igg.ones_g()
    with pytest.raises(InvalidArgumentError):
        igg.run_resilient(lambda s: s, {"T": T}, 1, snapshot_every=5)
