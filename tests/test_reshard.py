"""On-device elastic resharding (ISSUE 14): the redistribution plan, the
compiled collective program, `ResilientRun.resize`, the scheduler
decision, and the ensemble pass-through.

The acceptance bar everywhere is BIT-IDENTITY: the plan's host oracle
against an independently-built global field, the device program against
the oracle, the on-device resize against the checkpoint-based elastic
path (the verified fallback) AND against the unresized run — the
redistribution moves raw bytes, so a single differing byte anywhere is a
failure, never a tolerance."""

import json
import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.reshard import (
    apply_plan_host, build_reshard_plan, fields_of_state, live_topology,
    reshard_contract, reshard_state,
)
from implicitglobalgrid_tpu.utils.checkpoint import AxisRedistribution
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)

from conftest import (
    health_counters_from_registry as _health_counters,
    reset_health_counters_in_registry as _reset_health_counters,
)

_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "hlo",
                        "reshard_2x2x1_to_1x2x2.hlo.txt")


def _topo(nxyz=(6, 6, 6), dims=(2, 2, 1), ol=(2, 2, 2), per=(0, 0, 0)):
    return {"nxyz": np.array(nxyz), "dims": np.array(dims),
            "overlaps": np.array(ol), "periods": np.array(per),
            "halowidths": np.maximum(1, np.array(ol) // 2)}


def _blocks_from_global(G, dims, loc, ol, per):
    """Exchange-fresh stacked layout of global field ``G``: block c's
    cell i holds G[phys(c, i)] — the independent reference every
    re-block must reproduce exactly."""
    import itertools

    nd = len(loc)
    axes = [AxisRedistribution(loc[d], loc[d], dims[d], dims[d], ol[d],
                               bool(per[d])) for d in range(nd)]
    out = np.zeros([dims[d] * loc[d] for d in range(nd)], dtype=G.dtype)
    for c in itertools.product(*[range(dims[d]) for d in range(nd)]):
        idx = np.ix_(*[axes[d].new_phys(c[d]) for d in range(nd)])
        sel = tuple(slice(c[d] * loc[d], (c[d] + 1) * loc[d])
                    for d in range(nd))
        out[sel] = G[idx]
    return out


def _ng(dims, loc, ol, per):
    return tuple(dims[d] * (loc[d] - ol[d]) + (0 if per[d] else ol[d])
                 for d in range(len(loc)))


# ---------------------------------------------------------------------------
# the plan: host-only coverage/partition proofs (no grid, no devices)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("src,dst,per", [
    ((2, 2, 1), (1, 2, 2), (0, 0, 0)),   # rotate (the re-balance move)
    ((2, 2, 1), (2, 1, 1), (0, 0, 0)),   # shrink (lost-capacity move)
    ((1, 2, 1), (2, 2, 2), (1, 0, 1)),   # grow, periodic axes
])
def test_plan_host_oracle_matches_global_field(src, dst, per):
    """Every destination cell ends holding exactly the global-field value
    its physical coordinate names — for plain, staggered, and
    member-stacked fields, across grow/shrink/periodic re-blockings."""
    nx, ol = (6, 6, 6), (2, 2, 2)
    topo = _topo(nx, src, ol, per)
    rng = np.random.default_rng(3)

    loc_T = (6, 6, 6)
    loc_P = (7, 6, 6)                    # x-staggered: ol_f = 3 on x
    ol_P = (3, 2, 2)
    GT = rng.normal(size=_ng(src, loc_T, ol, per))
    GP = rng.normal(size=_ng(src, loc_P, ol_P, per))
    T = _blocks_from_global(GT, src, loc_T, ol, per)
    P = _blocks_from_global(GP, src, loc_P, ol_P, per)
    E = np.stack([T, 2.0 * T, -T])       # member axis passes through

    fields = {"T": (T.shape, "float64", 0), "P": (P.shape, "float64", 0),
              "E": (E.shape, "float64", 1)}
    plan = build_reshard_plan(topo, dst, fields)
    out = apply_plan_host(plan, {"T": T, "P": P, "E": E})

    from implicitglobalgrid_tpu.utils.checkpoint import elastic_local_size

    nxyz_dst = elastic_local_size(topo, dst)
    loc_Td = tuple(nxyz_dst)
    loc_Pd = (nxyz_dst[0] + 1, nxyz_dst[1], nxyz_dst[2])
    T_ref = _blocks_from_global(GT, dst, loc_Td, ol, per)
    P_ref = _blocks_from_global(GP, dst, loc_Pd, ol_P, per)
    assert np.array_equal(out["T"], T_ref)
    assert np.array_equal(out["P"], P_ref)
    assert np.array_equal(out["E"],
                          np.stack([T_ref, 2.0 * T_ref, -T_ref]))


def test_plan_rounds_are_partial_permutations():
    """Each scheduled round is one legal ppermute: unique sources, unique
    destinations, no self-pairs (those are local rounds), slots inside
    the flat mesh; byte accounting consistent with the round shapes."""
    plan = build_reshard_plan(
        _topo(), (1, 2, 2),
        {"T": ((12, 12, 6), "float32", 0), "P": ((14, 12, 6), "float32", 0)})
    assert plan.n_flat == 4 and plan.rounds > 0
    for sig in plan.sigs:
        for r in sig.rounds:
            srcs = [a for a, _ in r.pairs]
            dsts = [b for _, b in r.pairs]
            assert len(set(srcs)) == len(srcs)
            assert len(set(dsts)) == len(dsts)
            assert all(a != b for a, b in r.pairs)
            assert all(0 <= s < plan.n_flat for s in srcs + dsts)
            for p in r.pieces:
                assert all(p.size[d] <= r.pad[d]
                           for d in range(len(r.pad)))
        assert all(p.src_rank == p.dst_rank for p in sig.local)
    expected = sum(
        int(np.prod(r.pad)) * len(r.pairs)
        * len(sig.names) * np.dtype(sig.dtype).itemsize
        for sig in plan.sigs for r in sig.rounds)
    assert plan.wire_bytes == expected
    assert plan.payload_bytes <= plan.wire_bytes


def test_plan_validation_errors():
    topo = _topo()
    fields = {"T": ((12, 12, 6), "float32", 0)}
    with pytest.raises(InvalidArgumentError, match="nothing to re-block"):
        build_reshard_plan(topo, (2, 2, 1), fields)
    with pytest.raises(IncoherentArgumentError, match="divide"):
        build_reshard_plan(topo, (3, 1, 1), fields)  # interior 10-2=8, not /3
    with pytest.raises(IncoherentArgumentError, match="not divisible"):
        build_reshard_plan(topo, (1, 2, 2),
                           {"T": ((13, 12, 6), "float32", 0)})
    with pytest.raises(IncoherentArgumentError, match="inconsistent"):
        # local blocks of 3 over dims 2 on an nxyz=6 grid: stag = -3
        build_reshard_plan(topo, (1, 2, 2),
                           {"T": ((6, 6, 6), "float32", 0)})
    with pytest.raises(InvalidArgumentError, match="positive"):
        build_reshard_plan(topo, (0, 2, 2), fields)


def test_predict_reshard_static_record():
    plan = build_reshard_plan(
        _topo(), (1, 2, 2), {"T": ((12, 12, 6), "float32", 0)})
    rec = igg.predict_reshard(plan)
    assert rec["rounds"] == plan.rounds
    assert rec["wire_bytes"] == plan.wire_bytes
    assert rec["seconds"] > 0
    assert rec["seconds"] == pytest.approx(
        rec["latency_s"] + rec["wire_s"] + rec["local_s"])
    assert rec["profile_source"] in ("default", "calibrated")


# ---------------------------------------------------------------------------
# the contract + golden fixture (host-only)
# ---------------------------------------------------------------------------

def _fixture_plan():
    return build_reshard_plan(
        _topo(), (1, 2, 2),
        {"T": ((12, 12, 6), "float32", 0), "P": ((14, 12, 6), "float32", 0)})


def test_golden_fixture_contract_byte_exact():
    """The committed optimized-HLO dump of the canonical transfer program
    honors the HOST-DERIVED contract to the byte: one collective-permute
    per scheduled round, routes matching the plan's pair sets verbatim,
    padded payload bytes exact, zero reductions/gathers."""
    from implicitglobalgrid_tpu.analysis import audit_program, parse_program

    plan = _fixture_plan()
    with open(_FIXTURE, encoding="utf-8") as f:
        text = f.read()
    rep = audit_program(text, contract=reshard_contract(plan))
    assert rep.ok, [f.message for f in rep.findings]
    ir = parse_program(text)
    assert len(ir.permutes) == plan.rounds
    assert sum(ir.wire_bytes_of(p) for p in ir.permutes) == plan.wire_bytes
    assert not ir.all_reduces and not ir.all_gathers and not ir.all_to_alls


def test_golden_fixture_detects_drift():
    """The gate has teeth: a contract for a DIFFERENT re-blocking (other
    destination dims — different rounds/routes/bytes) must fail against
    the committed program."""
    from implicitglobalgrid_tpu.analysis import audit_program

    other = build_reshard_plan(
        _topo(), (2, 1, 1),
        {"T": ((12, 12, 6), "float32", 0), "P": ((14, 12, 6), "float32", 0)})
    with open(_FIXTURE, encoding="utf-8") as f:
        text = f.read()
    rep = audit_program(text, contract=reshard_contract(other))
    assert not rep.ok
    assert any(f.rule in ("permute-route", "permute-count", "wire-bytes")
               for f in rep.findings)


def test_reshard_cli_plan_host_only(capsys):
    from implicitglobalgrid_tpu.tools import _cli

    rc = _cli(["reshard", "plan", "--src-dims", "2,2,1",
               "--dst-dims", "1,2,2", "--nx", "6", "--indent", "0"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["plan"]["rounds"] > 0
    assert rec["predicted"]["seconds"] > 0
    assert rec["plan"]["src_dims"] == [2, 2, 1]


# ---------------------------------------------------------------------------
# the driver: resize fast path vs the checkpoint oracle (tier-1 rep)
# ---------------------------------------------------------------------------

def _diffusion_step():
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float64)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


def _run_resized(tmp_path, tag, via, nt=12, resize_at=6, tuned=None,
                 audit=False):
    from implicitglobalgrid_tpu.runtime.driver import ResilientRun

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    step, state = _diffusion_step()
    run = ResilientRun(step, state, nt, igg.RunSpec(
        nt_chunk=3, key=("reshard_t", tag),
        checkpoint_dir=str(tmp_path / f"ck_{tag}"), tuned=tuned,
        audit=audit))
    recs = []
    try:
        while run.advance():
            if via is not None and run.step == resize_at:
                recs.append(run.resize((1, 2, 2), via=via))
                via = None
    finally:
        run.close()
    out = np.asarray(igg.gather_interior(run.state["T"]))
    stale = run.tuned_stale_reason
    igg.finalize_global_grid()
    return out, recs, stale


@pytest.mark.faults
def test_resize_device_vs_checkpoint_vs_unresized(tmp_path):
    """THE acceptance loop: a mid-run dims change through the on-device
    collective program ends bit-identical to the checkpoint-based
    elastic path AND to the never-resized run — with the reshard program
    contract-audited in-flight, the resize span + metrics recorded, and
    an applied TunedConfig marked stale."""
    from implicitglobalgrid_tpu.telemetry import TunedConfig

    ref, _, _ = _run_resized(tmp_path, "ref", via=None)

    _reset_health_counters()
    igg.start_flight_recorder(str(tmp_path / "fr.jsonl"))
    try:
        dev, recs, stale = _run_resized(
            tmp_path, "dev", via="device", audit=True,
            tuned=TunedConfig(model="diffusion3d"))
    finally:
        igg.stop_flight_recorder()
    assert _health_counters()["resizes"] == 1
    ckp, _, _ = _run_resized(tmp_path, "ckp", via="checkpoint")

    assert np.array_equal(dev, ckp)
    assert np.array_equal(dev, ref)
    assert recs[0]["via"] == "device" and recs[0]["rounds"] > 0
    assert stale == "resize"   # re-tune trigger satellite

    evs = igg.read_flight_events(str(tmp_path / "fr.jsonl"))
    resize = [e for e in evs if e.get("kind") == "resize"]
    assert len(resize) == 1 and resize[0]["via"] == "device"
    assert resize[0]["wire_bytes"] > 0 and resize[0]["dur_s"] > 0
    stale_evs = [e for e in evs if e.get("kind") == "tuned_stale"]
    assert len(stale_evs) == 1 and stale_evs[0]["reason"] == "resize"
    audits = [e for e in evs if e.get("kind") == "audit"
              and e.get("program") == "reshard"]
    assert len(audits) == 1 and audits[0]["ok"]
    fam = igg.metrics_registry().get("igg_reshard_rounds")
    assert fam is not None and fam.samples()[0][1] == recs[0]["rounds"]
    fam = igg.metrics_registry().get("igg_reshard_bytes_total")
    kinds = {labels["kind"] for labels, _ in fam.samples()}
    assert "wire" in kinds


def test_resize_validation():
    from implicitglobalgrid_tpu.runtime.driver import ResilientRun

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    step, state = _diffusion_step()
    run = ResilientRun(step, state, 6, igg.RunSpec(nt_chunk=3,
                                                   key="reshard_val"))
    try:
        with pytest.raises(InvalidArgumentError, match="via"):
            run.resize((1, 2, 2), via="nope")
        rec = run.resize((2, 2, 1))          # same dims: recorded no-op
        assert rec["via"] == "noop"
        # dims that cannot decompose the grid, or that exceed the device
        # pool, are ARGUMENT errors — rejected before ANY path touches
        # the grid (the elastic fallback tears the grid down before its
        # init would fail, so letting them through would kill the run)
        with pytest.raises(IncoherentArgumentError, match="divide"):
            run.resize((3, 1, 1))
        with pytest.raises(InvalidArgumentError, match="device"):
            run.resize((8, 2, 1))   # divides (interior 8,8,4) but > pool
        assert igg.grid_is_initialized()   # pre-checks never touch it
        from implicitglobalgrid_tpu.utils.exceptions import ResilienceError

        with pytest.raises(ResilienceError, match="no checkpoint_dir"):
            run.resize((1, 2, 2), via="checkpoint")
    finally:
        run.close()


# ---------------------------------------------------------------------------
# ensemble: the member axis passes through (ROADMAP ensemble rung c)
# ---------------------------------------------------------------------------

@pytest.mark.ensemble
def test_ensemble_elastic_restore_per_member_bit_identity(tmp_path):
    """The satellite's literal check: a member-stacked checkpoint
    restores onto DIFFERENT dims with every member bit-identical to the
    solo elastic restore of that member's own field."""
    from implicitglobalgrid_tpu.models import ensemble_state
    from implicitglobalgrid_tpu.utils.checkpoint import (
        elastic_local_size, saved_topology,
    )

    E = 3
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    x, y, z = igg.coords_g(0.5, 0.5, 0.5, igg.zeros_g())
    T = igg.device_put_g(np.asarray(x + 10 * y + 100 * z))
    Te = ensemble_state(T, E, perturb=0.01)
    members = [np.asarray(Te[m]) for m in range(E)]
    igg.save_checkpoint_sharded(str(tmp_path / "ens"), {"T": Te}, step=7)
    for m in range(E):
        igg.save_checkpoint_sharded(str(tmp_path / f"solo{m}"),
                                    {"T": igg.device_put_g(members[m])})
    igg.finalize_global_grid()

    topo = saved_topology(str(tmp_path / "ens"))
    nx = elastic_local_size(topo, (1, 2, 2))
    igg.init_global_grid(*nx, dimx=1, dimy=2, dimz=2, quiet=True)
    st, step = igg.restore_checkpoint_elastic(str(tmp_path / "ens"))
    assert step == 7
    assert tuple(st["T"].sharding.spec) == (None, "gx", "gy", "gz")
    got = np.asarray(st["T"])
    for m in range(E):
        solo, _ = igg.restore_checkpoint_elastic(str(tmp_path / f"solo{m}"))
        assert np.array_equal(got[m], np.asarray(solo["T"])), f"member {m}"


@pytest.mark.faults
@pytest.mark.ensemble
def test_ensemble_process_loss_elastic_restart(tmp_path):
    """ProcessLoss under ensemble=E (previously rejected): the batch
    restarts elastically on the new dims and ends bit-identical to the
    unfaulted ensemble run."""
    from implicitglobalgrid_tpu.models import ensemble_state

    E = 2

    def setup():
        step, state = _diffusion_step()
        return step, {"T": ensemble_state(state["T"], E, perturb=0.01),
                      "Cp": ensemble_state(state["Cp"], E)}

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    step, est = setup()
    ref, _ = igg.run_resilient(step, est, 9, nt_chunk=3, key="ens_pl",
                               ensemble=E,
                               checkpoint_dir=str(tmp_path / "ref"))
    ref_m = [np.asarray(igg.gather_interior(ref["T"][m]))
             for m in range(E)]
    igg.finalize_global_grid()

    _reset_health_counters()
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    step, est = setup()
    out, _ = igg.run_resilient(
        step, est, 9, nt_chunk=3, key="ens_pl", ensemble=E,
        checkpoint_dir=str(tmp_path / "pl"),
        faults=[igg.ProcessLoss(step=4, new_dims=(1, 2, 2))])
    assert tuple(int(d) for d in igg.global_grid().dims) == (1, 2, 2)
    assert _health_counters()["elastic_restarts"] == 1
    for m in range(E):
        got = np.asarray(igg.gather_interior(out["T"][m]))
        assert np.array_equal(got, ref_m[m]), f"member {m}"


# ---------------------------------------------------------------------------
# the scheduler decision (+ control file, + tuned clearing)
# ---------------------------------------------------------------------------

def test_scheduler_resize_at_slice_boundary(tmp_path, capsys):
    """A `tools jobs resize` request re-blocks one tenant at its next
    slice boundary (journaled ``job_resized``, on-device path) while the
    OTHER tenant stays bit-identical to its solo run; the resized job's
    final state equals its solo state re-blocked (the exact-transfer
    identity), and the job's stale TunedConfig is cleared at the
    boundary (``job_tuned_cleared``)."""
    from implicitglobalgrid_tpu.service import (
        JobSpec, MeshScheduler, builtin_setup,
    )
    from implicitglobalgrid_tpu.telemetry import TunedConfig
    from implicitglobalgrid_tpu.tools import _cli

    grid = dict(nx=6, ny=6, nz=6, dimx=2, dimy=2, dimz=1)

    def solo(name):
        sched = MeshScheduler(policy="fifo")
        try:
            sched.submit(JobSpec(
                name=name, setup=builtin_setup("diffusion3d", "float64"),
                nt=12, grid=dict(grid),
                run=igg.RunSpec(nt_chunk=3, key=("rs_svc", name))))
            sched.run()
            return np.asarray(sched.results()[name]["T"])
        finally:
            sched.close()

    a_solo, b_solo = solo("a"), solo("b")

    fd = str(tmp_path / "svc")
    sched = MeshScheduler(policy="round_robin", flight_dir=fd)
    try:
        sched.submit(JobSpec(
            name="a", setup=builtin_setup("diffusion3d", "float64"),
            nt=12, grid=dict(grid),
            run=igg.RunSpec(nt_chunk=3, key=("rs_svc", "a"),
                            checkpoint_dir=str(tmp_path / "ck_a"),
                            tuned=TunedConfig(model="diffusion3d"))))
        sched.submit(JobSpec(
            name="b", setup=builtin_setup("diffusion3d", "float64"),
            nt=12, grid=dict(grid),
            run=igg.RunSpec(nt_chunk=3, key=("rs_svc", "b"))))
        for _ in range(4):
            sched.step()
        # the CLI files the control request; the live scheduler consumes
        # it at the next slice boundary
        assert _cli(["jobs", "resize", fd, "a", "1,2,2"]) == 0
        req = json.loads(capsys.readouterr().out)
        assert req["requested"] == "resize" and req["new_dims"] == [1, 2, 2]
        # an INFEASIBLE request must be rejected at the slice boundary,
        # never fail the healthy tenant (journaled resize_rejected)
        sched.resize("b", (3, 1, 1))
        sched.run()
        res = sched.results()
        assert sched.job("a").state == "done"
        assert sched.job("b").state == "done"
    finally:
        sched.close()

    assert np.array_equal(np.asarray(res["b"]["T"]), b_solo)
    plan = build_reshard_plan(
        _topo(), (1, 2, 2), {"T": (a_solo.shape, str(a_solo.dtype), 0)})
    assert np.array_equal(np.asarray(res["a"]["T"]),
                          apply_plan_host(plan, {"T": a_solo})["T"])

    evs = [json.loads(line)
           for line in open(os.path.join(fd, "scheduler.jsonl"))]
    kinds = [e.get("kind") for e in evs]
    assert "control" in kinds
    jr = next(e for e in evs if e.get("kind") == "job_resized")
    assert jr["job"] == "a" and jr["new_dims"] == [1, 2, 2]
    assert jr["via"] == "device" and jr["rounds"] > 0
    tc = next(e for e in evs if e.get("kind") == "job_tuned_cleared")
    assert tc["job"] == "a" and tc["reason"] == "resize"
    rj = next(e for e in evs if e.get("kind") == "resize_rejected")
    assert rj["job"] == "b" and "divide" in rj["error"]
    # unknown job / finished job exit codes
    assert _cli(["jobs", "resize", fd, "nope", "1,2,2"]) == 3
    assert _cli(["jobs", "resize", fd, "a", "2,2,1"]) == 4
    capsys.readouterr()


# ---------------------------------------------------------------------------
# the slow matrix: dims x dtype x periodicity on device
# ---------------------------------------------------------------------------

def test_scheduler_survives_malformed_resize_dims(tmp_path):
    """A hand-written control file whose ``new_dims`` are not integers
    (an operator typo) must journal ``resize_rejected`` — it is a valid
    JSON dict, so only `MeshScheduler.resize`'s int() coercion catches
    it, and that ValueError must not take down the scheduler."""
    from implicitglobalgrid_tpu.service import (
        JobSpec, MeshScheduler, builtin_setup,
    )

    fd = str(tmp_path / "svc")
    sched = MeshScheduler(policy="fifo", flight_dir=fd)
    try:
        sched.submit(JobSpec(
            name="a", setup=builtin_setup("diffusion3d", "float32"),
            nt=6, grid=dict(nx=6, ny=6, nz=6, dimx=2, dimy=2, dimz=1),
            run=igg.RunSpec(nt_chunk=3, key=("rs_badctl", "a"))))
        ctl = os.path.join(fd, "control")
        os.makedirs(ctl, exist_ok=True)
        with open(os.path.join(ctl, "resize_a"), "w",
                  encoding="utf-8") as f:
            json.dump({"new_dims": ["two", 2, 2]}, f)
        sched.run()                      # must not raise
        assert sched.job("a").state == "done"
    finally:
        sched.close()

    evs = [json.loads(line)
           for line in open(os.path.join(fd, "scheduler.jsonl"))]
    rj = [e for e in evs if e.get("kind") == "resize_rejected"]
    assert len(rj) == 1 and rj[0]["job"] == "a"
    assert "two" in rj[0]["error"]


@pytest.mark.slow
@pytest.mark.parametrize("src,dst,per", [
    ((2, 2, 1), (2, 1, 1), (0, 0, 0)),   # shrink: 4 -> 2 devices
    ((1, 2, 1), (2, 2, 2), (1, 0, 1)),   # grow: 2 -> 8, periodic axes
    ((2, 2, 2), (4, 2, 1), (0, 1, 0)),   # cubic fold
])
@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_on_device_matrix_matches_oracle(src, dst, per, dtype):
    """The dims x dtype x periodicity matrix: the compiled collective
    program reproduces the host oracle byte-for-byte, staggered field
    included, grow and shrink both directions."""
    igg.init_global_grid(6, 6, 6, dimx=src[0], dimy=src[1], dimz=src[2],
                         periodx=per[0], periody=per[1], periodz=per[2],
                         quiet=True)
    rng = np.random.default_rng(7)
    T = igg.device_put_g(rng.normal(
        size=tuple(src[d] * 6 for d in range(3))).astype(dtype))
    P = igg.device_put_g(rng.normal(
        size=(src[0] * 7, src[1] * 6, src[2] * 6)).astype(dtype))
    state = {"T": T, "P": P}
    host = {k: np.asarray(v) for k, v in state.items()}
    plan = build_reshard_plan(live_topology(), dst,
                              fields_of_state(state))
    expect = apply_plan_host(plan, host)
    new_state, info = reshard_state(state, dst, audit=True)
    assert info["audit_report"].ok, \
        [f.message for f in info["audit_report"].findings]
    assert tuple(int(d) for d in igg.global_grid().dims) == dst
    for k in state:
        assert np.array_equal(np.asarray(new_state[k]), expect[k]), k


@pytest.mark.slow
@pytest.mark.ensemble
def test_ensemble_on_device_resize_per_member(tmp_path):
    """resize under ensemble=E: the batched state re-blocks on device
    with each member bit-identical to the re-block of its own slice."""
    from implicitglobalgrid_tpu.models import ensemble_state

    E = 3
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, quiet=True)
    x, y, z = igg.coords_g(0.5, 0.5, 0.5, igg.zeros_g())
    T = igg.device_put_g(np.asarray(x + 10 * y + 100 * z))
    Te = ensemble_state(T, E, perturb=0.01)
    members = [np.asarray(Te[m]) for m in range(E)]
    state = {"T": Te}
    plan = build_reshard_plan(live_topology(), (1, 2, 2),
                              fields_of_state(state))
    new_state, _ = reshard_state(state, (1, 2, 2))
    got = np.asarray(new_state["T"])
    solo_plan = build_reshard_plan(
        _topo(), (1, 2, 2),
        {"T": (members[0].shape, str(members[0].dtype), 0)})
    for m in range(E):
        expect = apply_plan_host(solo_plan, {"T": members[m]})["T"]
        assert np.array_equal(got[m], expect), f"member {m}"
    igg.finalize_global_grid()


@pytest.mark.slow
def test_reshard_cli_run_audits_and_verifies(capsys):
    from implicitglobalgrid_tpu.tools import _cli

    rc = _cli(["reshard", "run", "--src-dims", "2,2,1",
               "--dst-dims", "1,2,2", "--nx", "6", "--ensemble", "2",
               "--json"])
    out = capsys.readouterr().out
    assert rc == 0, out
    rec = json.loads(out)
    assert rec["ok"] and rec["verified"] and rec["audit"]["ok"]
    assert rec["plan"]["rounds"] > 0
