"""Accuracy tier of the quantized halo wire (ISSUE 10).

The exact wire stays bitwise tier-1-contracted (`tests/test_update_halo.py`);
the quantized path gets an ACCURACY-BOUNDED tier instead, riding the
`bench_f64_accuracy.py` harness: diffusion3D advanced with per-slab-scaled
int8 halo payloads must track the exact-wire trajectory within a documented
drift bound — F64_ACCURACY.json records `int8_wire` max_rel orders of
magnitude inside the `bf16_xla` row's 0.85 (the acceptance bar is 10x;
the documented bound asserted here is 0.02, ~40x). One fast representative
runs in tier-1 (`quant` marker); the full bench config (48³ local → 92³
interior, nt=400, f64 ground truth) rides `slow`.
"""

import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.models import init_diffusion3d, run_diffusion

pytestmark = pytest.mark.quant

# The documented drift bound for diffusion3D with int8 halo wire (max_rel
# vs the exact-wire trajectory): docs/performance.md error-model table.
# bf16_xla storage records 0.85 in F64_ACCURACY.json — the quantized WIRE
# must sit at least 10x inside it (acceptance); measured ~6e-3 at both the
# fast and the full bench config, bounded here with ~3x slack.
INT8_WIRE_MAX_REL = 0.02


def _final(wire, nx, nt, dtype=np.float32):
    igg.init_global_grid(nx, nx, nx, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    # scope the wire env var like audit_model does: the exact baseline
    # (wire=None) must run with it CLEARED even if the invoking shell
    # exported one, and the caller's value is restored after
    saved = os.environ.pop("IGG_HALO_WIRE_DTYPE", None)
    try:
        if wire is not None:
            os.environ["IGG_HALO_WIRE_DTYPE"] = wire
        T, Cp, p = init_diffusion3d(dtype=dtype)
        out = run_diffusion(T, Cp, p, nt, nt_chunk=max(1, nt // 4))
        return np.asarray(igg.gather_interior(out), np.float64)
    finally:
        if saved is None:
            os.environ.pop("IGG_HALO_WIRE_DTYPE", None)
        else:
            os.environ["IGG_HALO_WIRE_DTYPE"] = saved
        igg.finalize_global_grid()


def test_int8_wire_drift_within_documented_bound_fast():
    """Fast tier-1 representative (24³, nt=100): the int8 halo wire's
    whole-trajectory drift vs the exact-wire f32 run stays within the
    documented bound, actually quantizes, and the per-axis policy's
    drift is bounded by the all-axes one (fewer quantized links can only
    shrink the error)."""
    exact = _final(None, 24, 100)
    q8 = _final("int8", 24, 100)
    scale = np.abs(exact).max()
    drift = np.abs(q8 - exact).max() / scale
    assert 0 < drift < INT8_WIRE_MAX_REL, drift
    z8 = _final("z:int8", 24, 100)
    drift_z = np.abs(z8 - exact).max() / scale
    assert 0 < drift_z <= drift * 1.05, (drift_z, drift)


@pytest.mark.slow
def test_int8_wire_drift_full_bench_config():
    """THE acceptance assertion at the bench config (48³ local → 92³
    interior, nt=400, f64 ground truth — the exact F64_ACCURACY.json
    `int8_wire` leg): documented bound 0.02, at least 10x inside the
    recorded bf16_xla 0.85 row. Slow: two full 400-step runs, one in
    f64."""
    f64 = _final(None, 48, 400, dtype=np.float64)
    q8 = _final("int8", 48, 400)
    drift = np.abs(q8 - f64).max() / np.abs(f64).max()
    assert 0 < drift < INT8_WIRE_MAX_REL, drift
    assert drift < 0.85 / 10  # the ISSUE acceptance bar, explicitly
