"""Live observability plane tests (ISSUE 18): incremental flight
tailing (byte-offset checkpoints, torn-line/truncation/seq-gap
tolerance), `LiveAggregate`'s rolling derived signals (incremental ==
one-shot), the declarative `AlertRule`/`AlertEngine` (every kind,
hysteresis, wildcard fan-out, metric signals, ``igg_alerts_total``),
the pluggable sinks (control-file, webhook against a real local
endpoint, error containment), the `MetricsServer` ``routes=`` error
paths + chunked streaming (the PR's satellite), and the ``tools
watch``/``tools alerts`` CLI.

Everything here is HOST-ONLY synthetics (exact ground truth, no grid,
no accelerator); the end-to-end alert-driven cancellation under a live
scheduler rides tests/test_serve.py."""

import json
import os
import urllib.error
import urllib.request

import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.telemetry.live import (
    AlertEngine, AlertRule, ControlFileSink, FlightTail, LiveAggregate,
    WebhookSink, default_rule_pack,
)
from implicitglobalgrid_tpu.telemetry.server import MetricsServer
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    igg.stop_flight_recorder()
    igg.stop_metrics_server()
    igg.reset_metrics()
    yield
    igg.stop_flight_recorder()
    igg.stop_metrics_server()
    igg.reset_metrics()


# ---------------------------------------------------------------------------
# Synthetic streams (appendable — the tail's whole point)
# ---------------------------------------------------------------------------

class _Stream:
    """One flight JSONL written record-by-record, so tests control
    exactly what is on disk between polls."""

    def __init__(self, path, run_id, *, proc=0, wall0=5000.0,
                 clock0=100.0):
        self.path = str(path)
        self.run = run_id
        self.proc = proc
        self.seq = 0
        self.t = clock0
        self.append("recorder_open", wall=wall0, version=1)

    def append(self, kind, *, dt=0.0, raw=None, seq=None, **kw):
        self.t += dt
        rec = {"t": self.t, "kind": kind, "run": self.run, "pid": 1,
               "proc": self.proc,
               "seq": self.seq if seq is None else seq, **kw}
        self.seq = rec["seq"] + 1
        with open(self.path, "a") as f:
            f.write((json.dumps(rec) if raw is None else raw) + "\n")
        return rec

    def chunk(self, c, *, n=4, exec_s=0.4, ok=True, dt=0.5, **kw):
        return self.append("chunk", dt=dt, chunk=c, step_begin=c * n,
                           step_end=(c + 1) * n, n=n, ok=ok, reasons=[],
                           build_s=0.01, exec_s=exec_s, **kw)


# ---------------------------------------------------------------------------
# FlightTail
# ---------------------------------------------------------------------------

def test_tail_incremental_offsets_and_new_files(tmp_path):
    """Polls return only what was appended since the last poll, and a
    file created between polls joins the tail (the scheduler admitting a
    new job mid-flight)."""
    d = str(tmp_path)
    s = _Stream(os.path.join(d, "job_a.jsonl"), "a")
    s.append("run_begin", nt=8)
    tail = FlightTail(d)
    first = tail.poll()
    assert [e["kind"] for e in first] == ["recorder_open", "run_begin"]
    assert all(e["_file"].endswith("job_a.jsonl") for e in first)
    assert tail.poll() == []  # nothing new
    s.chunk(0)
    s2 = _Stream(os.path.join(d, "job_b.jsonl"), "b")
    more = tail.poll()
    assert {(e["run"], e["kind"]) for e in more} == {
        ("a", "chunk"), ("b", "recorder_open")}
    assert tail.gaps == [] and tail.events_read == 4
    assert s2.seq == 1  # the new stream really was fresh


def test_tail_torn_final_line_reread_next_poll(tmp_path):
    """A torn (partial) final line is NOT consumed — the offset stays
    before it, and the completed record arrives on a later poll intact
    (no gap recorded: tearing is the normal case mid-write)."""
    p = str(tmp_path / "job_a.jsonl")
    s = _Stream(p, "a")
    tail = FlightTail(p)
    assert len(tail.poll()) == 1
    # a torn write: half a record, no newline
    rec = {"t": s.t + 1, "kind": "chunk", "run": "a", "pid": 1,
           "proc": 0, "seq": 1, "chunk": 0}
    line = json.dumps(rec)
    with open(p, "a") as f:
        f.write(line[:13])
    assert tail.poll() == []
    assert tail.gaps == []
    with open(p, "a") as f:
        f.write(line[13:] + "\n")
    evs = tail.poll()
    assert [e["seq"] for e in evs] == [1] and evs[0]["chunk"] == 0
    assert tail.gaps == []


def test_tail_truncation_and_seq_gap_are_observations(tmp_path):
    """A shrunk file restarts from its head with a ``truncated`` gap; a
    sequence jump records a ``seq_gap``; neither raises and the tail
    keeps following."""
    p = str(tmp_path / "job_a.jsonl")
    s = _Stream(p, "a")
    s.chunk(0)
    tail = FlightTail(p)
    assert len(tail.poll()) == 2
    # replace the file with a shorter one (rotation/rewrite)
    os.truncate(p, 0)
    s.seq = 0
    s.append("recorder_open", wall=6000.0)
    evs = tail.poll()
    assert [e["kind"] for e in evs] == ["recorder_open"]
    assert [g["kind"] for g in tail.gaps] == ["truncated"]
    # drop seq 1-2: the hole is recorded, the event still delivered
    s.append("chunk", seq=3, chunk=3, n=4, ok=True, exec_s=0.1)
    evs = tail.poll()
    assert [e["seq"] for e in evs] == [3]
    assert [g["kind"] for g in tail.gaps] == ["truncated", "seq_gap"]
    assert tail.gaps[-1] == {
        "file": p, "run": "a", "proc": 0, "kind": "seq_gap",
        "expected": 1, "got": 3, "t": tail.gaps[-1]["t"]}


def test_tail_corrupt_interior_skips_file_not_tail(tmp_path):
    """Interior corruption (invalid JSON with a complete line after it —
    a torn line would just be re-read) records one ``corrupt`` gap and
    skips that file to its end; other streams are unaffected and the bad
    file resumes from later appends."""
    d = str(tmp_path)
    s = _Stream(os.path.join(d, "job_a.jsonl"), "a")
    with open(s.path, "a") as f:
        f.write("{not json}\n")
    s.append("chunk", chunk=0, n=4, ok=True, exec_s=0.1)
    b = _Stream(os.path.join(d, "job_b.jsonl"), "b")
    tail = FlightTail(d)
    evs = tail.poll()
    assert {e["run"] for e in evs} == {"b"}
    assert [g["kind"] for g in tail.gaps] == ["corrupt"]
    s.append("chunk", chunk=1, n=4, ok=True, exec_s=0.1)
    evs = tail.poll()
    assert [(e["run"], e["chunk"]) for e in evs] == [("a", 1)]
    assert b.seq == 1


# ---------------------------------------------------------------------------
# LiveAggregate: derived signals
# ---------------------------------------------------------------------------

def test_live_aggregate_derived_signals_and_incremental_equivalence(
        tmp_path):
    """The rolling per-job signals (quantiles, z, slack, counters,
    rates) from a single-run stream — polled incrementally after every
    append — match the one-shot read of the finished file."""
    def _drive(agg, stream_ops):
        for op in stream_ops:
            op()
            agg.poll()
        return agg.snapshot()

    def _ops(path):
        s = _Stream(path, "a")
        ops = [lambda: s.append("run_begin", nt=32, nt_chunk=4)]
        for c in range(6):
            ex = 0.4 if c < 5 else 4.0   # the last chunk is 10x slower
            ops.append(lambda c=c, ex=ex: s.chunk(c, exec_s=ex))
        ops += [
            lambda: s.append("checkpoint_save", op="save", dur_s=0.2),
            lambda: s.append("snapshot_write", step=20, nbytes=1000,
                             queue_depth=2, dur_s=0.01, dt=1.0),
            lambda: s.append("snapshot_write", step=24, nbytes=3000,
                             queue_depth=1, dur_s=0.01, dt=1.0),
            lambda: s.append("snapshot_drop", step=28, queue_depth=4),
            lambda: s.append("deadline_slack", step=24, slack_s=3.5,
                             budget_s=10.0, priced_step_s=0.1,
                             priced_by="measured", remaining_steps=8),
            lambda: s.append("run_end", completed=32, chunks=6),
        ]
        return ops

    inc = LiveAggregate(str(tmp_path / "inc.jsonl"), window=8,
                        min_samples=4)
    snap = _drive(inc, _ops(str(tmp_path / "inc.jsonl")))
    oneshot = LiveAggregate(str(tmp_path / "one.jsonl"), window=8,
                            min_samples=4)
    for op in _ops(str(tmp_path / "one.jsonl")):
        op()
    oneshot.poll()

    j = snap["jobs"]["a"]
    assert j["state"] == "done" and j["nt"] == 32
    assert j["chunks"] == 6 and j["step"] == 24
    assert j["step_s_last"] == pytest.approx(1.0)   # 4.0 / 4
    assert j["step_s_p50"] == pytest.approx(0.1)
    assert j["step_s_p90"] == pytest.approx(1.0)
    # the blowout chunk against the warm window: a huge robust z
    assert j["z"] is not None and j["z"] > 10
    assert j["deadline_slack_s"] == 3.5 and j["deadline_budget_s"] == 10
    assert j["checkpoint_s"] == pytest.approx(0.2)
    assert j["snapshot_drops"] == 1 and j["snapshot_queue_depth"] == 4
    assert j["snapshot_bytes_total"] == 4000
    assert j["snapshot_bytes_rate"] == pytest.approx(3000.0)  # 3000B/1s
    assert snap["cursor"] == 13  # 14 merged events, zero-based
    # incremental == one-shot (timestamps aside)
    s2 = oneshot.snapshot()
    for k in ("jobs", "procs", "queue", "gaps"):
        assert snap[k] == s2[k], k
    # the merged feed is resumable by cursor
    evs, cur = inc.events_since(5)
    assert [e["live_seq"] for e in evs] == list(range(6, 14))
    assert cur == 13
    assert inc.events_since(cur) == ([], cur)


def test_live_aggregate_two_proc_alignment_and_straggler(tmp_path):
    """Two processes with wildly different monotonic origins and a
    known wall skew: the incremental aligner merges them onto one
    clock, and the barrier-spread window attributes the persistent
    straggler (proc 1, late every chunk)."""
    d = str(tmp_path)
    a = _Stream(os.path.join(d, "flight_p0.jsonl"), "r", proc=0,
                wall0=5000.0, clock0=1000.0)
    b = _Stream(os.path.join(d, "flight_p1.jsonl"), "r", proc=1,
                wall0=5000.25, clock0=987654.0)
    agg = LiveAggregate(d, straggler_window=4)
    for c in range(5):
        # barrier-consistent schedule: proc 1 dispatches 0.05s late, so
        # its exec_s is 0.05 shorter against the same barrier release
        a.chunk(c, dt=0.55, exec_s=0.55)
        b.chunk(c, dt=0.55, exec_s=0.50)
        agg.poll()
    snap = agg.snapshot()
    assert snap["gaps"] == []
    # alignment metadata recovered the skew for the run
    assert snap["align"]["r"]["anchor_proc"] == 0
    # proc 1 is the slowest arriver at (almost) every observed barrier
    assert snap["procs"][1]["slowest_share"] > 0.6
    assert snap["procs"][0]["slowest_share"] < 0.5
    # merged feed is clock-ordered across both files
    evs, _ = agg.events_since(None)
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts)


def test_live_aggregate_mid_stream_attach_degrades_not_raises(tmp_path):
    """Attaching to a stream that already lost its head (first seen seq
    > 0, no recorder_open wall anchor) still tails: events merge via the
    shift-only fallback and the integrity observation is recorded."""
    p = str(tmp_path / "job_a.jsonl")
    s = _Stream(p, "a")
    for c in range(3):
        s.chunk(c)
    # a consumer that starts late: simulate by pre-consuming the file
    # head into a different tail, then truncating the head away
    with open(p) as f:
        lines = f.readlines()
    with open(p, "w") as f:
        f.writelines(lines[2:])   # recorder_open + run? gone
    agg = LiveAggregate(p)
    evs = agg.poll()
    assert [e["kind"] for e in evs] == ["chunk", "chunk"]
    assert agg.snapshot()["jobs"]["a"]["chunks"] == 2
    s.chunk(3)
    assert [e["chunk"] for e in agg.poll()] == [3]


def test_live_aggregate_scheduler_journal_and_queue_pressure(tmp_path):
    """The scheduler journal drives job states, slice counts, slack
    mirrors, and alert records; a `DirectoryBackend` adds live
    pending/oldest-age queue pressure."""
    from implicitglobalgrid_tpu.service import DirectoryBackend

    d = str(tmp_path)
    backend = DirectoryBackend(d)
    backend.submit({"name": "queued1", "model": "diffusion3d", "nt": 4})
    s = _Stream(os.path.join(d, "scheduler.jsonl"), "scheduler")
    s.append("scheduler_start", policy="fifo")
    s.append("job_submitted", job="a", nt=8, priority=1)
    s.append("job_admitted", job="a")
    s.append("slice", job="a", slice=0, step=4, dur_s=0.4, wait_s=0.0,
             policy="fifo", slack_s=2.5)
    s.append("alert", rule="guard_trip_storm", severity="critical",
             state="firing", job="a", signal="jobs.*.guard_trips",
             value=1.0, threshold=1.0)
    s.append("alert", rule="guard_trip_storm", severity="critical",
             state="resolved", job="a", signal="jobs.*.guard_trips",
             value=1.0, threshold=1.0)
    s.append("job_done", job="a")
    agg = LiveAggregate(d, backend=backend)
    agg.poll()
    snap = agg.snapshot()
    j = snap["jobs"]["a"]
    assert j["state"] == "done" and j["slices"] == 1
    assert j["step"] == 4 and j["deadline_slack_s"] == 2.5
    assert snap["scheduler"]["slices"] == 1
    assert snap["queue"]["pending"] == 1
    assert snap["queue"]["oldest_age_s"] >= 0
    # the resolved transition cleared the active set; both are recent
    assert snap["alerts"]["active"] == []
    assert [a["state"] for a in snap["alerts"]["recent"]] == [
        "firing", "resolved"]


# ---------------------------------------------------------------------------
# AlertRule / AlertEngine
# ---------------------------------------------------------------------------

def test_alert_rule_validation():
    with pytest.raises(InvalidArgumentError, match="kind"):
        AlertRule("r", "jobs.*.z", kind="nope")
    with pytest.raises(InvalidArgumentError, match="op"):
        AlertRule("r", "jobs.*.z", op="~")
    with pytest.raises(InvalidArgumentError, match="wildcard"):
        AlertRule("r", "jobs.*.sub.*.z")
    with pytest.raises(InvalidArgumentError, match="name"):
        AlertRule("", "jobs.*.z")
    with pytest.raises(InvalidArgumentError, match=">= 1"):
        AlertRule("r", "jobs.*.z", for_count=0)
    with pytest.raises(InvalidArgumentError, match="duplicate"):
        AlertEngine([AlertRule("r", "a"), AlertRule("r", "b")])
    with pytest.raises(InvalidArgumentError, match="AlertRule"):
        AlertEngine(["not a rule"])
    pack = default_rule_pack()
    assert len(pack) == 6
    assert len({r.name for r in pack}) == 6


def _snap(t, **jobs):
    return {"t": t, "jobs": jobs, "procs": {}, "queue": {},
            "scheduler": {}}


def test_threshold_hysteresis_fire_and_resolve():
    """for_count consecutive breaches fire; resolve_count consecutive
    clears resolve; flapping below either count transitions nothing."""
    eng = AlertEngine([AlertRule("hot", "jobs.*.z", op=">",
                                 threshold=3.0, for_count=2,
                                 resolve_count=2)])
    assert eng.evaluate(_snap(1, a={"z": 5.0})) == []     # breach 1/2
    trs = eng.evaluate(_snap(2, a={"z": 6.0}))            # fires
    assert [(t["state"], t["job"]) for t in trs] == [("firing", "a")]
    assert eng.active()[0]["rule"] == "hot"
    assert eng.evaluate(_snap(3, a={"z": 1.0})) == []     # clear 1/2
    assert eng.evaluate(_snap(4, a={"z": 9.0})) == []     # already firing
    assert eng.evaluate(_snap(5, a={"z": 0.0})) == []     # clear 1/2
    trs = eng.evaluate(_snap(6, a={"z": 0.0}))            # resolves
    assert [t["state"] for t in trs] == ["resolved"]
    assert eng.active() == []
    # a missing signal neither breaches nor clears
    assert eng.evaluate(_snap(7)) == []
    assert eng.transitions == 2 and eng.evaluations == 7


def test_rate_burn_rate_zscore_and_metric_signals():
    reg = igg.metrics_registry()
    eng = AlertEngine([
        AlertRule("trips", "jobs.*.guard_trips", kind="rate",
                  threshold=1.0, window=4),
        AlertRule("slack", "jobs.*.deadline_slack_s", kind="burn_rate",
                  horizon_s=60.0),
        AlertRule("ckpt", "jobs.*.checkpoint_s", kind="zscore",
                  threshold=4.0, min_samples=3),
        AlertRule("metric", "metric:igg_live_test_total",
                  kind="threshold", op=">=", threshold=2.0),
    ], registry=reg)
    c = reg.counter("igg_live_test_total", "t", ("k",))

    def ev(t, trips, slack, ck):
        return eng.evaluate(_snap(
            t, a={"guard_trips": trips, "deadline_slack_s": slack,
                  "checkpoint_s": ck}))

    # warmup: counters flat, slack huge and steady, ckpt stable
    for t in range(1, 5):
        assert ev(t, 0, 1e4, 0.2) == []
    # rate: the counter grew by 1 within the window -> trips fires
    trs = ev(5, 1, 1e4, 0.2)
    assert [t["rule"] for t in trs] == ["trips"]
    # burn_rate: slack collapsing 1e4 -> 50 in 1s projects exhaustion
    # far inside the horizon -> slack fires (value still > 0)
    trs = ev(6, 1, 50.0, 0.2)
    assert [t["rule"] for t in trs] == ["slack"]
    assert trs[0]["severity"] == "warning" and trs[0]["job"] == "a"
    # zscore: a 10x checkpoint against the stable window
    trs = ev(7, 1, 30.0, 2.5)
    assert [t["rule"] for t in trs] == ["ckpt"]
    # metric: family SUM across label sets
    c.inc(1, k="x")
    c.inc(1, k="y")
    trs = eng.evaluate(_snap(8))
    assert [t["rule"] for t in trs] == ["metric"]
    assert trs[0]["job"] is None  # scalar signal: no attribution
    # every transition counted in igg_alerts_total{rule,severity,state}
    fam = reg.get("igg_alerts_total")
    counted = {lbl["rule"]: v for lbl, v in fam.samples()}
    assert counted == {"trips": 1, "slack": 1, "ckpt": 1, "metric": 1}


def test_burn_rate_fires_immediately_on_negative_slack():
    eng = AlertEngine([AlertRule("slack", "jobs.*.deadline_slack_s",
                                 kind="burn_rate")])
    trs = eng.evaluate(_snap(1, a={"deadline_slack_s": -0.5}))
    assert [(t["rule"], t["state"]) for t in trs] == [("slack", "firing")]


def test_wildcard_fanout_is_per_job_state():
    """One rule, independent state machines per wildcard match: job b
    firing does not disturb job a's ok state."""
    eng = AlertEngine([AlertRule("hot", "jobs.*.z", threshold=3.0)])
    trs = eng.evaluate(_snap(1, a={"z": 0.1}, b={"z": 9.0}))
    assert [(t["job"], t["state"]) for t in trs] == [("b", "firing")]
    trs = eng.evaluate(_snap(2, a={"z": 9.0}, b={"z": 9.0}))
    assert [(t["job"], t["state"]) for t in trs] == [("a", "firing")]
    assert {a["job"] for a in eng.active()} == {"a", "b"}


def test_engine_journals_transitions_and_contains_sink_errors():
    """Transitions reach the journal callable as ``alert`` events; a
    raising sink is counted, journaled once, and never propagates."""
    journaled = []

    def journal(kind, **fields):
        journaled.append({"kind": kind, **fields})

    def bad_sink(tr):
        raise RuntimeError("boom")

    good = []
    eng = AlertEngine([AlertRule("hot", "jobs.*.z", threshold=3.0)],
                      sinks=(bad_sink, good.append), journal=journal)
    eng.evaluate(_snap(1, a={"z": 9.0}))
    eng.evaluate(_snap(2, b={"z": 9.0}))
    alerts = [e for e in journaled if e["kind"] == "alert"]
    assert [(e["rule"], e["job"], e["state"]) for e in alerts] == [
        ("hot", "a", "firing"), ("hot", "b", "firing")]
    assert "t" not in alerts[0]  # the journal stamps its own clock
    # the broken sink: both errors counted, journaled ONCE, good sink fed
    errs = [e for e in journaled if e["kind"] == "alert_sink_error"]
    assert len(errs) == 1 and "boom" in errs[0]["error"]
    assert eng.sink_errors == 2
    assert [tr["job"] for tr in good] == ["a", "b"]


def test_control_file_sink_files_cancel_once(tmp_path):
    from implicitglobalgrid_tpu.service import DirectoryBackend

    backend = DirectoryBackend(str(tmp_path))
    sink = ControlFileSink(backend, rules=("deadline_slack_burn",))
    fire = {"rule": "deadline_slack_burn", "state": "firing", "job": "a"}
    sink(fire)
    sink(fire)                                        # dedup
    sink(dict(fire, rule="other_rule"))               # filtered
    sink(dict(fire, state="resolved"))                # not firing
    sink(dict(fire, job=None))                        # unattributed
    assert sink.filed == [{"rule": "deadline_slack_burn", "job": "a",
                           "action": "cancel"}]
    assert backend.poll_control() == [{"request": "cancel", "job": "a"}]
    with pytest.raises(InvalidArgumentError, match="resize"):
        ControlFileSink(backend, action="resize")     # payload required
    with pytest.raises(InvalidArgumentError, match="action"):
        ControlFileSink(backend, action="nuke")


def test_webhook_sink_posts_and_swallows_errors():
    """Delivery against a REAL local endpoint (a MetricsServer route);
    an unreachable URL is swallowed and counted."""
    seen = []

    def routes(method, path, query, body):
        if method == "POST" and path == "/hook":
            seen.append(json.loads(body))
            return 200, b"{}", "application/json"
        return None

    with MetricsServer(0, routes=routes) as srv:
        sink = WebhookSink(f"http://127.0.0.1:{srv.port}/hook")
        sink({"rule": "hot", "state": "firing", "job": "a"})
        assert sink.delivered == 1 and sink.errors == 0
        assert seen == [{"rule": "hot", "state": "firing", "job": "a"}]
        bad = WebhookSink(f"http://127.0.0.1:{srv.port}/nope",
                          timeout_s=2.0)
        bad({"rule": "hot", "state": "firing"})
        assert (bad.delivered, bad.errors) == (0, 1)
        assert "404" in bad.last_error


# ---------------------------------------------------------------------------
# MetricsServer routes=: error paths + chunked streaming (the satellite)
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read(), dict(r.headers)


def test_routes_error_paths_500_404_and_server_survives():
    """A raising handler answers a JSON 500 and the server thread
    survives to answer the next request; an unowned path answers a JSON
    404; /metrics is untouched; the refcounted process-server bookkeeping
    is unaffected by a standalone routed server."""
    def routes(method, path, query, body):
        if path == "/boom":
            raise RuntimeError("handler bug")
        if path == "/ok":
            return 200, b'{"ok": true}', "application/json"
        return None

    assert igg.metrics_server() is None
    with MetricsServer(0, routes=routes) as srv:
        u = f"http://127.0.0.1:{srv.port}"
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(u + "/boom")
        assert exc.value.code == 500
        rec = json.loads(exc.value.read())
        assert "RuntimeError" in rec["error"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(u + "/unknown")
        assert exc.value.code == 404
        assert "no route" in json.loads(exc.value.read())["error"]
        # the thread survived both: normal requests still answer
        status, body, _ = _get(u + "/ok")
        assert (status, json.loads(body)) == (200, {"ok": True})
        status, body, _ = _get(u + "/metrics")
        assert status == 200
        # a standalone routed server never touches the refcounted
        # process singleton
        assert igg.metrics_server() is None
    igg.stop_metrics_server()  # no-op: nothing was registered


def test_routes_iterator_payload_streams_chunked():
    """A route returning a bytes iterator streams as HTTP/1.1 chunked
    transfer — the client sees every yielded block, in order."""
    def routes(method, path, query, body):
        if path == "/stream":
            return 200, (f"line {i}\n".encode() for i in range(5)), \
                "application/x-ndjson"
        return None

    with MetricsServer(0, routes=routes) as srv:
        u = f"http://127.0.0.1:{srv.port}/stream"
        with urllib.request.urlopen(u, timeout=10) as r:
            assert r.status == 200
            assert r.headers.get("Transfer-Encoding") == "chunked"
            assert r.headers.get("Content-Length") is None
            lines = [ln.decode().strip() for ln in r]
    assert lines == [f"line {i}" for i in range(5)]


# ---------------------------------------------------------------------------
# CLI: tools watch / tools alerts
# ---------------------------------------------------------------------------

def test_cli_watch_once_and_alerts_ack(tmp_path, capsys):
    from implicitglobalgrid_tpu.tools import _cli

    d = str(tmp_path)
    s = _Stream(os.path.join(d, "job_a.jsonl"), "a")
    s.append("run_begin", nt=8)
    s.chunk(0)
    s.append("deadline_slack", step=4, slack_s=-1.5, budget_s=2.0)
    j = _Stream(os.path.join(d, "scheduler.jsonl"), "scheduler")
    j.append("scheduler_start", policy="fifo")
    j.append("alert", rule="deadline_slack_burn", severity="critical",
             state="firing", job="a", value=-1.5, threshold=0.0)

    assert _cli(["watch", d, "--once"]) == 0
    frame = capsys.readouterr().out
    assert "JOB" in frame and "a " in frame
    assert "-1.5s" in frame
    assert "ALERT CRITICAL deadline_slack_burn" in frame
    assert "\x1b[2J" not in frame  # --once never clears the screen

    assert _cli(["watch", d, "--once", "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["jobs"]["a"]["deadline_slack_s"] == -1.5

    assert _cli(["alerts", d]) == 0
    out = capsys.readouterr().out
    assert "deadline_slack_burn" in out and "firing" in out
    assert _cli(["alerts", d, "--ack", "deadline_slack_burn:a"]) == 0
    capsys.readouterr()
    assert _cli(["alerts", d, "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["alerts"][0]["acked"] is True
    # the ack landed in the SIDE file, not any journal
    assert os.path.exists(os.path.join(d, "alerts_ack.json"))
    tail = FlightTail(d)
    tail.poll()
    assert tail.gaps == []  # journals untouched, seq still gapless
