"""Topology-staged hierarchical wire (ISSUE 16) — on `ops.wire`'s
`StagedWireSchema`, `parallel.topology`'s `staged_wire_layout`,
`ops.halo`'s staged exchange path, the staged multi-stage contracts, the
staged `predict_step` pricing, and the tuner's staged-vs-flat selection.

THE claim under test: a DCN-crossing axis's exchange can be re-routed as
ICI leader-gather -> ONE striped DCN transfer per granule pair -> ICI
scatter (HiCCL-style hierarchical composition, arXiv:2408.05962), cutting
the per-DCN-link message count by the ICI fold while delivering halos
BIT-IDENTICAL to the flat wire — with the flat path byte-for-byte
untouched when staging is off.

Tier-1 keeps one fast representative per behavior (policy parsing, the
layout geometry, ONE live bit-identity leg, the golden-fixture contract,
the staged pricing verdict, the model-only tuner selection); the
composition matrix (quantized x staggered x ensemble x non-periodic), the
compiled audit legs, and the subprocess exit-1 gate ride the slow tier.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.stage

_FIXTURE_DIMS = dict(dimx=4, dimy=1, dimz=2)  # z = DCN axis, x = gather


def _init_fixture_grid(monkeypatch, periodz=1, periodx=1):
    """The canonical two-granule mesh of the golden fixture: 4x1x2 with
    z split into 2 DCN granules (x is the fold-4 ICI gather axis)."""
    monkeypatch.setenv("IGG_TPU_DCN_GRANULES", "z:2")
    igg.init_global_grid(8, 8, 8, periodx=periodx, periody=1,
                         periodz=periodz, quiet=True, **_FIXTURE_DIMS)


# ---------------------------------------------------------------------------
# policy + layout units (host-only)

def test_resolve_wire_stage_spellings():
    """`resolve_wire_stage` mirrors the wire-dtype policy family: bare /
    per-axis / dict spellings, off synonyms, env fallback, passthrough —
    and every all-off spelling collapses to None (the flat wire)."""
    from implicitglobalgrid_tpu.ops.wire import (
        WireStagePolicy, resolve_wire_stage,
    )

    p = resolve_wire_stage("z:staged")
    assert isinstance(p, WireStagePolicy)
    assert p.staged_dims == (2,)
    assert str(p) == "z:staged"
    assert resolve_wire_stage(p) is p  # passthrough
    assert resolve_wire_stage({"z": "staged"}).staged_dims == (2,)
    assert resolve_wire_stage("staged").staged_dims == (0, 1, 2)
    for off in (None, "", "0", "off", "none", "flat", "z:off"):
        assert resolve_wire_stage(off) is None, off
    with pytest.raises(InvalidArgumentError):
        resolve_wire_stage("z:sideways")
    # env fallback: resolve(None) reads IGG_HALO_WIRE_STAGE
    saved = os.environ.get("IGG_HALO_WIRE_STAGE")
    try:
        os.environ["IGG_HALO_WIRE_STAGE"] = "z:staged"
        assert str(resolve_wire_stage(None)) == "z:staged"
    finally:
        if saved is None:
            os.environ.pop("IGG_HALO_WIRE_STAGE", None)
        else:
            os.environ["IGG_HALO_WIRE_STAGE"] = saved


def test_staged_wire_layout_geometry(monkeypatch):
    """On the fixture mesh the z layout gathers over x (the largest
    perpendicular pure-ICI axis): fold 4, 2 granules, and exactly ONE
    DCN-crossing transfer per granule pair per direction — while
    degenerate axes (unsplit, undeclared, or no perpendicular ICI
    candidate) carry no layout at all."""
    from implicitglobalgrid_tpu.parallel.topology import staged_wire_layout

    _init_fixture_grid(monkeypatch)
    try:
        gg = igg.global_grid()
        lay = staged_wire_layout(gg, 2)
        assert lay is not None
        assert (lay.gather_dim, lay.fold, lay.granules) == (0, 4, 2)
        for dr in lay.directions:
            # one striped DCN transfer per crossing granule pair: the
            # leaders' pairs only (non-leaders ride PROC_NULL), where the
            # flat wire pays fold device-pairs per granule pair
            assert len(dr.dcn_pairs) == len(dr.cross_pairs)
            assert len(dr.gather_pairs) > 0 and len(dr.scatter_pairs) > 0
        assert lay.dcn_pair_count * lay.fold == sum(
            len(dr.cross_pairs) * lay.fold for dr in lay.directions)
        # x and y are not staged axes: x has granules=1, y is unsplit
        assert staged_wire_layout(gg, 0) is None
        assert staged_wire_layout(gg, 1) is None
    finally:
        igg.finalize_global_grid()


def test_undeclared_granules_mean_no_staging():
    """Without declared DCN granules every axis is flat: staging resolves
    but degrades to the identical flat wire (zero behavior change on
    single-slice meshes — the degenerate-consistency guarantee)."""
    from implicitglobalgrid_tpu.parallel.topology import staged_wire_layout

    igg.init_global_grid(8, 8, 8, dimx=4, dimy=1, dimz=2, periodx=1,
                         periody=1, periodz=1, quiet=True)
    try:
        gg = igg.global_grid()
        assert gg.dcn_granules == (1, 1, 1)
        assert staged_wire_layout(gg, 2) is None
        A = igg.ones_g((8, 8, 8), np.float32)
        plan = igg.halo_comm_plan(A, wire_stage="z:staged")
        assert plan["staged_axes"] == ()
        assert "staged" not in plan["axes"]["gz"]
        assert plan["axes"]["gz"]["ppermutes"] == 2  # the flat pair
    finally:
        igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# the static plan + pricing (host-only: nothing compiled)

def test_staged_plan_counts_and_fold(monkeypatch):
    """`halo_comm_plan(wire_stage="z:staged")` on the fixture mesh: the z
    axis pays 2*(2F-1) = 14 permute ops per round (F=4 gather fold), the
    DCN-crossing pair count drops 16 -> 4 (the fold), and the stage table
    carries per-stage {direction, stage, ops, pairs, payload_bytes}."""
    _init_fixture_grid(monkeypatch)
    try:
        A = igg.ones_g((8, 8, 8), np.float32)
        plan = igg.halo_comm_plan(A, wire_stage="z:staged")
        assert plan["wire_stage"] == "z:staged"
        assert plan["staged_axes"] == ("gz",)
        rec = plan["axes"]["gz"]
        assert rec["ppermutes"] == 14
        det = rec["staged"]
        assert (det["fold"], det["granules"]) == (4, 2)
        assert det["gather_axis"] == "gx"
        assert (det["dcn_pairs"], det["flat_dcn_pairs"]) == (4, 16)
        stages = {s["stage"] for s in det["stages"]}
        assert stages == {"gather", "dcn", "scatter"}
        # the DCN stage ships the F-slab stripe: payload = fold x slab
        slab = next(s for s in det["stages"] if s["stage"] == "gather")
        dcn = next(s for s in det["stages"] if s["stage"] == "dcn")
        assert dcn["payload_bytes"] == det["fold"] * slab["payload_bytes"]
        # the flat x axis is untouched by z staging
        assert plan["axes"]["gx"]["ppermutes"] == 2
        assert "staged" not in plan["axes"]["gx"]
        # staging OFF: the very same plan as never having the knob
        flat = igg.halo_comm_plan(A)
        assert flat["wire_stage"] is None and flat["staged_axes"] == ()
        assert flat["axes"]["gz"]["ppermutes"] == 2
    finally:
        igg.finalize_global_grid()


def test_staged_pricing_verdict_on_hierarchical_profile(monkeypatch):
    """`predict_step(wire_stage="z:staged")` on the canned hierarchical
    ICI+DCN profile: each stage priced against its own link class, the
    staged-vs-flat verdict says staged WINS on the DCN axis (the flat
    alternative pays fold serialized messages per DCN bundle), the
    embedded flat price equals the standalone flat pricing exactly, and
    `bound_detail` names the wire_stage knob."""
    import jax

    from implicitglobalgrid_tpu.telemetry.perfmodel import (
        hierarchical_machine_profile, predict_step,
    )

    _init_fixture_grid(monkeypatch)
    try:
        prof = hierarchical_machine_profile()
        assert prof.meta.get("dcn_axes") == ["z"]
        stacked = (32, 8, 16)
        T = jax.ShapeDtypeStruct(stacked, np.float32)
        Cp = jax.ShapeDtypeStruct(stacked, np.float32)
        flat = predict_step("diffusion3d", (T, Cp), profile=prof)
        staged = predict_step("diffusion3d", (T, Cp), profile=prof,
                              wire_stage="z:staged")
        assert staged["wire_stage"] == "z:staged"
        det = staged["comm"]["gz"]["staged"]
        assert det["wins"] is True
        assert det["dcn_msgs_ratio"] == 4.0
        assert det["staged_s"] < det["flat_s"]
        # the flat alternative embedded in the verdict IS the flat
        # pricing (fold messages serialize through one DCN bundle)
        assert det["flat_s"] == pytest.approx(
            flat["comm"]["gz"]["latency_s"] + flat["comm"]["gz"]["wire_s"],
            rel=1e-9)
        assert flat["comm"]["gz"]["dcn_msgs_per_link"] == 4
        assert staged["step_s"] < flat["step_s"]
        assert "wire_stage[z]" in (flat["bound_detail"] or "")
    finally:
        igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# golden fixture: host-only parse + multi-stage contract

def test_parse_staged_dcn_fixture(monkeypatch):
    """The checked-in staged exchange program (4x1x2 mesh, z staged over
    2 granules): 16 permutes total — the flat x pair plus z's
    2*(2F-1)=14 staged ops — honoring the multi-stage contract
    byte-exactly, with exactly one DCN-crossing stripe transfer per
    granule pair per direction; an injected WRONG-stage (flat) contract
    is caught."""
    from implicitglobalgrid_tpu.analysis import (
        check_contract, exchange_contract, parse_text,
    )

    fix = os.path.join(os.path.dirname(__file__), "data", "hlo",
                       "exchange_staged_dcn.hlo.txt")
    with open(fix, encoding="utf-8") as f:
        ir = parse_text(f.read())
    assert ir.dialect == "hlo" and ir.module == "jit_exchange"
    assert len(ir.permutes) == 16
    assert not ir.all_reduces and not ir.all_gathers and not ir.all_to_alls
    # the two DCN stripes: payload f32[4,8,8,1] (fold x slab), one
    # directed leader pair per granule pair per direction
    leaders = frozenset({(0, 1), (1, 0)})
    stripes = [op for op in ir.permutes
               if ir.payload_of(op).dims[0] == 4
               and frozenset(op.attrs["source_target_pairs"]) == leaders]
    assert len(stripes) == 2
    for op in stripes:
        pay = ir.payload_of(op)
        assert pay.dims == (4, 8, 8, 1) and pay.nbytes == 4 * 256

    _init_fixture_grid(monkeypatch)
    try:
        args = (np.zeros((32, 8, 16), np.float32),)
        contract = exchange_contract(*args, wire_stage="z:staged")
        assert check_contract(ir, contract) == []
        # wrong-stage injection: the FLAT contract must fail loudly
        wrong = exchange_contract(*args)
        findings = check_contract(ir, wrong)
        assert findings and all(f.severity == "error" for f in findings)
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
def test_tools_audit_exit1_on_wrong_stage_contract(monkeypatch, tmp_path):
    """The CLI gate end-to-end: ``tools audit --hlo <staged fixture>
    --contract <flat contract>`` exits 1 (the injected wrong-stage
    contract), and with the STAGED contract exits 0."""
    fix = os.path.join(os.path.dirname(__file__), "data", "hlo",
                       "exchange_staged_dcn.hlo.txt")
    from implicitglobalgrid_tpu.analysis import exchange_contract

    _init_fixture_grid(monkeypatch)
    try:
        args = (np.zeros((32, 8, 16), np.float32),)
        good = exchange_contract(*args, wire_stage="z:staged")
        wrong = exchange_contract(*args)
    finally:
        igg.finalize_global_grid()
    rcs = {}
    for name, contract in (("good", good), ("wrong", wrong)):
        path = tmp_path / f"{name}.json"
        path.write_text(json.dumps(contract.to_json()))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "implicitglobalgrid_tpu.tools",
             "audit", "--hlo", fix, "--contract", str(path)],
            capture_output=True, text=True, env=env)
        rcs[name] = r.returncode
    assert rcs == {"good": 0, "wrong": 1}, rcs


# ---------------------------------------------------------------------------
# live bit-identity: staged == flat

def _assert_bit_identical(fields, wire_dtype=None):
    flat = igg.update_halo(*fields, wire_dtype=wire_dtype)
    staged = igg.update_halo(*fields, wire_dtype=wire_dtype,
                             wire_stage="z:staged")
    flat = flat if isinstance(flat, tuple) else (flat,)
    staged = staged if isinstance(staged, tuple) else (staged,)
    for f, s in zip(flat, staged):
        assert np.array_equal(np.asarray(f), np.asarray(s))


def test_staged_bit_identical_fast_representative(monkeypatch):
    """ONE fast tier-1 leg of the bit-identity guarantee: periodic-z
    fixture mesh, a regular and a staggered field together — the staged
    route is pure re-routing of the same packed slabs, so delivered
    halos match the flat wire bit for bit."""
    rng = np.random.default_rng(16)
    _init_fixture_grid(monkeypatch)
    try:
        T = np.asarray(rng.normal(size=(32, 8, 16)), np.float32)
        V = np.asarray(rng.normal(size=(36, 8, 16)), np.float32)
        _assert_bit_identical((T, V))
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
@pytest.mark.parametrize("periodz,periodx,wire", [
    (0, 1, None),        # non-periodic staged axis (one-sided crossings)
    (1, 1, "z:int8"),    # quantized staged axis: scales ride in-band
    (0, 0, "int8"),      # all-axis quantized x non-periodic
    (1, 0, "bfloat16"),  # float-cast wire through the stripe
])
def test_staged_bit_identical_matrix(monkeypatch, periodz, periodx, wire):
    """The composition matrix behind the fast representative: staged ==
    flat bit-identical across periodicity and every wire-format family
    (the quantized per-slab scales ride in-band through all three
    stages)."""
    rng = np.random.default_rng(7)
    _init_fixture_grid(monkeypatch, periodz=periodz, periodx=periodx)
    try:
        T = np.asarray(rng.normal(size=(32, 8, 16)), np.float32)
        V = np.asarray(rng.normal(size=(36, 8, 16)), np.float32)
        _assert_bit_identical((T, V), wire_dtype=wire)
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
def test_staged_bit_identical_ensemble_leg(monkeypatch):
    """The ensemble leg: an E=2 member-batched exchange chunk delivers
    bit-identical state staged vs flat (the vmapped member axis rides
    each stage's payload exactly like the flat pair's)."""
    from implicitglobalgrid_tpu.models.common import (
        ensemble_state, make_state_runner,
    )

    rng = np.random.default_rng(3)
    _init_fixture_grid(monkeypatch)
    try:
        T = np.asarray(rng.normal(size=(32, 8, 16)), np.float32)
        ET = ensemble_state(igg.device_put_g(T), 2, perturb=0.25)
        outs = {}
        for mode, ws in (("flat", None), ("staged", "z:staged")):
            def step(s, ws=ws):
                return (igg.local_update_halo(s[0], wire_stage=ws),)

            run = make_state_runner(step, (3,), nt_chunk=2, ensemble=2,
                                    key=("stage_ens", mode))
            outs[mode] = np.asarray(run(ET)[0])
        assert np.array_equal(outs["flat"], outs["staged"])
    finally:
        igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# compiled audit legs (contract + crosscheck against the live compiler)

@pytest.mark.audit
def test_audit_model_staged_green(monkeypatch):
    """`audit_model(wire_stage="z:staged")` on the fixture mesh: the
    staged diffusion step honors the multi-stage contract and the
    `perfmodel_crosscheck` leg — and a flat audit of the SAME
    granule-declared grid right after stays green (no staged leakage
    through the runner cache)."""
    from implicitglobalgrid_tpu.analysis import audit_model

    _init_fixture_grid(monkeypatch)
    try:
        rep = audit_model("diffusion3d", wire_stage="z:staged")
        assert rep.ok, [f.to_json() for f in rep.findings]
        assert rep.crosscheck["ok"]
        assert rep.crosscheck["wire_stage"] == "z:staged"
        flat = audit_model("diffusion3d")
        assert flat.ok, [f.to_json() for f in flat.findings]
    finally:
        igg.finalize_global_grid()


@pytest.mark.slow
@pytest.mark.audit
def test_audit_model_staged_composed_with_quant(monkeypatch):
    """The acceptance composition: staged + ``wire_dtype="z:int8"`` —
    contract and crosscheck green with the quantized payload bytes
    riding every stage."""
    from implicitglobalgrid_tpu.analysis import audit_model

    _init_fixture_grid(monkeypatch)
    try:
        rep = audit_model("diffusion3d", wire_stage="z:staged",
                          wire_dtype="z:int8")
        assert rep.ok, [f.to_json() for f in rep.findings]
        assert rep.crosscheck["ok"]
    finally:
        igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# the tuner learns staged-vs-flat

@pytest.mark.tune
def test_tune_selects_staged_on_hierarchical_profile(monkeypatch):
    """Model-only search on the canned hierarchical ICI+DCN profile: the
    staged candidate prices ahead of flat for the DCN axis and wins —
    while the SAME search on a flat (grid-derived default) profile keeps
    the flat wire (staged never regresses where links are uniform)."""
    from implicitglobalgrid_tpu.telemetry.perfmodel import (
        hierarchical_machine_profile,
    )
    from implicitglobalgrid_tpu.telemetry.tune import tune_config

    monkeypatch.setenv("IGG_TPU_DCN_GRANULES", "z:2")
    grid = dict(nx=32, ny=8, nz=16, periodz=1, **_FIXTURE_DIMS)
    cfg = tune_config("diffusion3d", grid,
                      profile=hierarchical_machine_profile(),
                      comm_every_options=("1",),
                      wire_stage_options=(None, "z:staged"),
                      measure=False)
    assert cfg.wire_stage == "z:staged"
    assert cfg.env()["IGG_HALO_WIRE_STAGE"] == "z:staged"
    flat_cfg = tune_config("diffusion3d", grid,
                           comm_every_options=("1",),
                           wire_stage_options=(None, "z:staged"),
                           measure=False)
    assert flat_cfg.wire_stage is None
    # unset staging adds NO env key (the exact-3-key driver contract)
    assert "IGG_HALO_WIRE_STAGE" not in flat_cfg.env()
    # the knob round-trips through the persisted JSON record
    from implicitglobalgrid_tpu.telemetry.tune import TunedConfig

    assert TunedConfig.from_json(cfg.to_json()).wire_stage == "z:staged"


@pytest.mark.slow
@pytest.mark.tune
def test_tune_measured_staged_never_loses(monkeypatch):
    """Measured validation on the CPU mesh (no real DCN): the staged
    candidate may price well on a hierarchical profile but the MEASURED
    winner decides — `tune_config` keeps the >= 1.0 speedup guarantee
    with staged in the candidate set (model and measurement must agree
    before staged ships)."""
    from implicitglobalgrid_tpu.telemetry.perfmodel import (
        hierarchical_machine_profile,
    )
    from implicitglobalgrid_tpu.telemetry.tune import tune_config

    monkeypatch.setenv("IGG_TPU_DCN_GRANULES", "z:2")
    grid = dict(nx=16, ny=8, nz=8, periodz=1, **_FIXTURE_DIMS)
    cfg = tune_config("diffusion3d", grid,
                      profile=hierarchical_machine_profile(),
                      comm_every_options=("1",),
                      wire_stage_options=(None, "z:staged"),
                      measure=True, top_k=2, measure_steps=2, reps=2)
    assert cfg.speedup is not None and cfg.speedup >= 1.0
