"""Halo-exchange acceptance tests — port of the reference's strategy
(`/root/reference/test/test_update_halo.jl`):

- coordinate-encoding restoration: encode each cell's global coordinates into
  its value, zero the halos, `update_halo`, require exact restoration
  (`test_update_halo.jl:1004-1018`).
- periodic self-neighbor single-shard runs (the reference's "1 process +
  periodic" technique, `test_update_halo.jl:1-3`).
- a numpy ORACLE implementing the reference's exact per-dimension semantics
  (pack all send slabs from pre-exchange values, then deliver — matching
  `update_halo.jl:45-82`), checked against every configuration.
- staggered fields, halowidth>1, multi-field calls, 1-D/2-D grids, dtypes,
  and the `check_fields` error catalog (`update_halo.jl:410-472`).
"""

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def encode(A):
    """Cell value = x_g + 1e3*y_g + 1e6*z_g (reference encodes z*1e2+y*1e1+x,
    `test_update_halo.jl:1004`)."""
    cs = igg.coords_g(1.0, 1.0, 1.0, A)
    enc = np.zeros(tuple(int(s) for s in A.shape))
    for d, c in enumerate(cs):
        enc = enc + np.asarray(c) * (10.0 ** (3 * d))
    return enc


def zero_halos(P, local_shape, hw_list, dims_sel):
    """Zero the halo slabs of every block along the selected dims."""
    P = P.copy()
    gg = igg.global_grid()
    for d in dims_sel:
        if d >= P.ndim:
            continue
        s = int(local_shape[d])
        hw = int(hw_list[d])
        for c in range(int(gg.dims[d])):
            sl = [slice(None)] * P.ndim
            sl[d] = slice(c * s, c * s + hw)
            P[tuple(sl)] = 0
            sl[d] = slice((c + 1) * s - hw, (c + 1) * s)
            P[tuple(sl)] = 0
    return P


def _blk(c, s, lo, hi):
    return slice(c * s + lo, c * s + hi)


def oracle_update(P, local_shape, hw_list, order):
    """Reference-exact halo exchange on the stacked numpy array: per dim,
    snapshot, then deliver both sides (pack-before-deliver semantics of
    `update_halo.jl:46-48` vs `:72-74`)."""
    gg = igg.global_grid()
    P = P.copy()
    for dim in order:
        if dim >= P.ndim:
            continue
        s = int(local_shape[dim])
        hw = int(hw_list[dim])
        ol_d = int(gg.overlaps[dim]) + (s - int(gg.nxyz[dim]))
        if ol_d < 2 * hw:
            continue
        D = int(gg.dims[dim])
        per = bool(gg.periods[dim])
        disp = int(gg.disp)
        if D == 1 and not per:
            continue
        snap = P.copy()
        for c in range(D):
            ln = (c - disp) % D if per else c - disp
            if ln >= 0:
                src = [slice(None)] * P.ndim
                dst = [slice(None)] * P.ndim
                src[dim] = _blk(ln, s, s - ol_d, s - ol_d + hw)   # right send slab
                dst[dim] = _blk(c, s, 0, hw)                      # left halo
                P[tuple(dst)] = snap[tuple(src)]
            rn = (c + disp) % D if per else (c + disp if c + disp < D else -1)
            if rn >= 0:
                src = [slice(None)] * P.ndim
                dst = [slice(None)] * P.ndim
                src[dim] = _blk(rn, s, ol_d - hw, ol_d)           # left send slab
                dst[dim] = _blk(c, s, s - hw, s)                  # right halo
                P[tuple(dst)] = snap[tuple(src)]
    return P


def run_config(nx, ny, nz, *, dims=(0, 0, 0), periods=(0, 0, 0),
               overlaps=(2, 2, 2), halowidths=None, stagger=(0, 0, 0),
               dtype=np.float64, order=None, ndim=3, disp=1, reorder=1):
    """Init, build encoded field, zero halos, exchange, compare to oracle.
    Returns (result, oracle, reference_encoding)."""
    igg.init_global_grid(
        nx, ny, nz, dimx=dims[0], dimy=dims[1], dimz=dims[2],
        periodx=periods[0], periody=periods[1], periodz=periods[2],
        overlaps=overlaps, halowidths=halowidths, quiet=True,
        disp=disp, reorder=reorder,
    )
    gg = igg.global_grid()
    base = [nx, ny, nz][:ndim]
    local_shape = tuple(int(b) + int(st) for b, st in zip(base, stagger))
    hw_list = tuple(int(h) for h in gg.halowidths)
    A = igg.zeros_g(local_shape, dtype)
    enc = encode(A).astype(dtype)
    order = order if order is not None else igg.DEFAULT_DIMS_ORDER
    Pz = zero_halos(enc, local_shape, hw_list, [d for d in order if d < ndim])
    res = igg.update_halo(igg.device_put_g(Pz), dims=order)
    exp = oracle_update(Pz, local_shape, hw_list, order)
    return np.asarray(res), exp, enc


# ---------------------------------------------------------------------------
# restoration tests (the reference's headline acceptance tests)
# ---------------------------------------------------------------------------

def test_restore_3d_periodic_all_dims_2x2x2():
    res, exp, enc = run_config(5, 5, 5, dims=(2, 2, 2), periods=(1, 1, 1))
    assert np.array_equal(res, exp)
    # fully periodic ⇒ every halo cell restored to its encoding
    assert np.array_equal(res, enc)


def test_restore_3d_nonperiodic_2x2x2():
    res, exp, enc = run_config(5, 5, 5, dims=(2, 2, 2))
    assert np.array_equal(res, exp)
    # interior-facing halos restored: check the x-interface plane
    assert np.array_equal(res[4:6, 1:9, 1:9], enc[4:6, 1:9, 1:9])
    # physical-boundary halos keep their (zeroed) values: PROC_NULL no-op
    assert np.all(res[0, :, :] == 0) and np.all(res[-1, :, :] == 0)


def test_restore_self_neighbor_single_shard_periodic():
    # "1 process + periodic": the full machinery through the local-copy path
    # (reference update_halo.jl:62-68; test_update_halo.jl:839-924)
    res, exp, enc = run_config(5, 5, 5, dims=(1, 1, 1), periods=(1, 1, 1))
    assert np.array_equal(res, exp)
    assert np.array_equal(res, enc)


def test_restore_mixed_periodicity_4x2x1():
    res, exp, _ = run_config(5, 5, 5, dims=(4, 2, 1), periods=(1, 0, 1))
    assert np.array_equal(res, exp)


def test_restore_asymmetric_local_sizes():
    res, exp, _ = run_config(6, 4, 7, dims=(2, 2, 2), periods=(0, 1, 0))
    assert np.array_equal(res, exp)


def test_restore_staggered_fields():
    # Vx-like field: local (nx+1, ny, nz) — overlap grows to ol+1 (shared.jl:107)
    for stagger in [(1, 0, 0), (0, 1, 0), (0, 0, 1), (1, 1, 1)]:
        res, exp, _ = run_config(5, 5, 5, dims=(2, 2, 2), periods=(0, 0, 0),
                                 stagger=stagger)
        assert np.array_equal(res, exp), f"stagger={stagger}"
        igg.finalize_global_grid()


def test_restore_negative_stagger():
    # smaller-than-nxyz field: ol-1 = 1 < 2*hw ⇒ NO halo update in that dim
    res, exp, _ = run_config(6, 6, 6, dims=(2, 2, 2), stagger=(-1, 0, 0))
    assert np.array_equal(res, exp)
    gg = igg.global_grid()
    assert igg.ol(0, (5, 6, 6)) == 1  # below 2*hw ⇒ x untouched


def test_restore_halowidth_2_overlap_4():
    res, exp, enc = run_config(9, 9, 9, dims=(2, 2, 2), periods=(1, 1, 1),
                               overlaps=(4, 4, 4))
    gg_hw = 2
    assert np.array_equal(res, exp)
    assert np.array_equal(res, enc)


def test_restore_asymmetric_overlaps_and_hw():
    res, exp, _ = run_config(9, 8, 7, dims=(2, 2, 2), overlaps=(4, 2, 3),
                             halowidths=(2, 1, 1), periods=(1, 0, 0))
    assert np.array_equal(res, exp)


def test_restore_2d_grid():
    res, exp, enc = run_config(6, 6, 1, dims=(4, 2, 0), periods=(1, 1, 0), ndim=2)
    assert np.array_equal(res, exp)
    assert np.array_equal(res, enc)


def test_restore_1d_grid():
    res, exp, enc = run_config(8, 1, 1, dims=(8, 0, 0), periods=(1, 0, 0), ndim=1)
    assert np.array_equal(res, exp)
    assert np.array_equal(res, enc)


def _bf16():
    import jax.numpy as jnp

    return jnp.bfloat16


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.complex128, "bfloat16"])
def test_dtypes(dtype):
    if dtype == "bfloat16":  # TPU-native dtype (reference has no analog)
        dtype = _bf16()
    res, exp, _ = run_config(5, 5, 5, dims=(2, 2, 1), periods=(1, 1, 0), dtype=dtype)
    assert res.dtype == np.dtype(dtype)
    assert np.array_equal(res, exp)


def test_dims_order_subset():
    # dims=(0,): only the x exchange runs (reference's per-dim dims kwarg)
    res, exp, _ = run_config(5, 5, 5, dims=(2, 2, 2), periods=(1, 1, 1), order=(0,))
    assert np.array_equal(res, exp)


def test_multi_field_call():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, periodx=1, quiet=True)
    A = igg.zeros_g()
    enc = encode(A)
    Pz = zero_halos(enc, (5, 5, 5), (1, 1, 1), (0, 1, 2))
    Vx_enc = encode(igg.zeros_g((6, 5, 5)))
    Vz = zero_halos(Vx_enc, (6, 5, 5), (1, 1, 1), (0, 1, 2))
    a, b = igg.update_halo(igg.device_put_g(Pz), igg.device_put_g(Vz))
    assert np.array_equal(np.asarray(a), oracle_update(Pz, (5, 5, 5), (1, 1, 1),
                                                       igg.DEFAULT_DIMS_ORDER))
    assert np.array_equal(np.asarray(b), oracle_update(Vz, (6, 5, 5), (1, 1, 1),
                                                       igg.DEFAULT_DIMS_ORDER))


def test_per_field_halowidths():
    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), quiet=True)
    A = igg.zeros_g()
    enc = encode(A)
    Pz = zero_halos(enc, (9, 9, 9), (2, 2, 2), (0, 1, 2))
    # pass hw=(1,1,1) instead of default (2,2,2) via Field / tuple form
    r1 = igg.update_halo(igg.Field(igg.device_put_g(Pz), (1, 1, 1)))
    r2 = igg.update_halo((igg.device_put_g(Pz), (1, 1, 1)))
    exp = oracle_update(Pz, (9, 9, 9), (1, 1, 1), igg.DEFAULT_DIMS_ORDER)
    assert np.array_equal(np.asarray(r1), exp)
    assert np.array_equal(np.asarray(r2), exp)


def test_pytree_fields():
    # dict-of-arrays = the CellArray analog (reference extract, shared.jl:133-137)
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, periodz=1, quiet=True)
    enc = encode(igg.zeros_g())
    Pz = zero_halos(enc, (5, 5, 5), (1, 1, 1), (0, 1, 2))
    a, b = igg.update_halo({"u": igg.device_put_g(Pz), "v": igg.device_put_g(Pz + 1)})
    exp = oracle_update(Pz, (5, 5, 5), (1, 1, 1), igg.DEFAULT_DIMS_ORDER)
    assert np.array_equal(np.asarray(a), exp)


def test_local_update_halo_inside_shard_map():
    import jax
    from jax.sharding import PartitionSpec as P

    from implicitglobalgrid_tpu.utils.compat import shard_map

    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, periody=1, quiet=True)
    gg = igg.global_grid()
    enc = encode(igg.zeros_g())
    Pz = zero_halos(enc, (5, 5, 5), (1, 1, 1), (0, 1, 2))

    fn = jax.jit(shard_map(
        lambda a: igg.local_update_halo(a),
        mesh=gg.mesh, in_specs=P("gx", "gy", "gz"), out_specs=P("gx", "gy", "gz"),
    ))
    res = np.asarray(fn(igg.device_put_g(Pz)))
    ctrl = np.asarray(igg.update_halo(igg.device_put_g(Pz)))
    assert np.array_equal(res, ctrl)


def test_repeated_calls_reuse_cache():
    from implicitglobalgrid_tpu.ops import halo as halo_mod

    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    A = igg.zeros_g()
    igg.update_halo(A)
    n1 = len(halo_mod._exchange_cache)
    igg.update_halo(A + 1)
    assert len(halo_mod._exchange_cache) == n1  # same signature ⇒ cached program
    igg.update_halo(igg.zeros_g((6, 5, 5)))
    assert len(halo_mod._exchange_cache) == n1 + 1
    igg.finalize_global_grid()
    assert len(halo_mod._exchange_cache) == 0   # freed (finalize_global_grid.jl:17)


# ---------------------------------------------------------------------------
# error paths (check_fields catalog, update_halo.jl:410-472)
# ---------------------------------------------------------------------------

def test_error_no_halo_field():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    # hw=(2,2,2) with ol=2 < 2*hw everywhere ⇒ "has no halo; remove it"
    with pytest.raises(IncoherentArgumentError):
        igg.update_halo(igg.Field(igg.zeros_g(), (2, 2, 2)))


def test_error_duplicate_field():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    A = igg.zeros_g()
    with pytest.raises(IncoherentArgumentError):
        igg.update_halo(A, A)


def test_error_bad_halowidth():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    with pytest.raises(InvalidArgumentError):
        igg.update_halo(igg.Field(igg.zeros_g(), (0, 1, 1)))


def test_error_bad_ndim_and_bad_dims_arg():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    import jax.numpy as jnp

    with pytest.raises(InvalidArgumentError):
        igg.update_halo(jnp.zeros((2, 2, 2, 2)))
    with pytest.raises(InvalidArgumentError):
        igg.update_halo(igg.zeros_g(), dims=(3,))
    with pytest.raises(InvalidArgumentError):
        igg.update_halo()


def test_error_indivisible_stacked_shape():
    igg.init_global_grid(5, 5, 5, dimx=2, dimy=2, dimz=2, quiet=True)
    import jax.numpy as jnp

    with pytest.raises((IncoherentArgumentError, InvalidArgumentError)):
        igg.update_halo(jnp.zeros((11, 10, 10)))


# ---------------------------------------------------------------------------
# Pallas halo kernels (interpret mode) vs the XLA dynamic-update-slice path —
# the analog of the reference testing its GPU pack kernels against the CPU
# copies (`test_update_halo.jl:497-634`).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dims,periods,label", [
    ((1, 1, 1), (1, 1, 1), "self-neighbor all periodic (single-pass kernel)"),
    ((1, 1, 1), (1, 0, 1), "self-neighbor x,z only"),
    ((2, 2, 2), (1, 1, 1), "2x2x2 periodic (per-dim kernels)"),
    ((2, 2, 2), (0, 0, 0), "2x2x2 non-periodic (PROC_NULL edges)"),
    ((2, 1, 4), (1, 0, 1), "mixed multi/self/skip"),
])
def test_pallas_halo_kernels_match_dus(dims, periods, label):
    import implicitglobalgrid_tpu.ops.halo as halo_mod

    shape_local = (16, 16, 128)
    igg.init_global_grid(*shape_local, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True)
    rng = np.random.default_rng(0)
    stacked = tuple(int(d * n) for d, n in zip(dims, shape_local))
    A = igg.device_put_g(rng.standard_normal(stacked).astype(np.float32))
    try:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
        r_dus = np.asarray(igg.gather(igg.update_halo(A)))
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = True
        r_pal = np.asarray(igg.gather(igg.update_halo(A)))
    finally:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
    assert np.array_equal(r_dus, r_pal), label


@pytest.mark.parametrize("periods", [(1, 1, 1), (0, 0, 0), (1, 0, 1)])
def test_restore_disp2_4shard(periods):
    """disp=2 neighbor displacement (reference threads `disp` through
    `Cart_shift`, `init_global_grid.jl:104-106`): slabs travel two shards."""
    res, exp, enc = run_config(6, 5, 5, dims=(4, 1, 1), periods=periods,
                               disp=2)
    assert np.array_equal(res, exp)


def test_restore_disp2_periodic_wrap():
    """disp=2 on a 2-shard periodic axis wraps to self (coord+2 mod 2)."""
    res, exp, enc = run_config(6, 5, 5, dims=(2, 2, 1), periods=(1, 1, 0),
                               disp=2)
    assert np.array_equal(res, exp)


def test_reorder0_matches_reorder1():
    """reorder=0 (keep device order) must produce the same exchange result
    as the default reorder=1 (reference `Cart_create` reorder flag)."""
    res1, exp1, _ = run_config(5, 5, 5, dims=(2, 2, 2), periods=(1, 0, 1))
    igg.finalize_global_grid()
    res0, exp0, _ = run_config(5, 5, 5, dims=(2, 2, 2), periods=(1, 0, 1),
                               reorder=0)
    assert np.array_equal(res0, exp0)
    assert np.array_equal(res0, res1)


# Combined one-pass unpack path (dim 2 participating with ppermute dims):
# adversarial configs — staggering, disp, asymmetric halowidths, self/multi
# mixes — against the XLA path.
@pytest.mark.parametrize("dims,periods,kw,label", [
    ((2, 2, 2), (1, 1, 1), {}, "all-periodic all-multi"),
    ((2, 2, 2), (0, 0, 0), {}, "non-periodic PROC_NULL corners"),
    ((1, 2, 2), (1, 0, 1), {}, "x self-neighbor + y PROC_NULL + z multi"),
    ((2, 1, 2), (0, 1, 1), {}, "y self-neighbor mix"),
    ((4, 1, 2), (1, 1, 1), {"disp": 2}, "disp=2 combined"),
    ((2, 2, 2), (1, 1, 1),
     {"overlaps": (4, 2, 2), "halowidths": (2, 1, 1)},
     "halowidth 2 along x (whole-plane dim)"),
    ((2, 2, 2), (1, 1, 1),
     {"overlaps": (2, 4, 4), "halowidths": (1, 2, 2)},
     "halowidth 2 along y,z: combined unsupported, per-dim fallback"),
])
def test_pallas_combined_unpack_matches_dus(dims, periods, kw, label):
    import implicitglobalgrid_tpu.ops.halo as halo_mod

    shape_local = (16, 16, 128)
    igg.init_global_grid(*shape_local, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True, **kw)
    rng = np.random.default_rng(2)
    stacked = tuple(int(d * n) for d, n in zip(dims, shape_local))
    A = igg.device_put_g(rng.standard_normal(stacked).astype(np.float32))
    try:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
        r_dus = np.asarray(igg.gather(igg.update_halo(A)))
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = True
        r_pal = np.asarray(igg.gather(igg.update_halo(A)))
    finally:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
    assert np.array_equal(r_dus, r_pal), label


def test_pallas_combined_unpack_staggered_matches_dus():
    """Staggered field (+1 along x) through the combined path."""
    import implicitglobalgrid_tpu.ops.halo as halo_mod

    igg.init_global_grid(16, 16, 128, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    rng = np.random.default_rng(3)
    A = igg.device_put_g(rng.standard_normal((34, 32, 256)).astype(np.float32))
    try:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
        r_dus = np.asarray(igg.gather(igg.update_halo(A)))
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = True
        r_pal = np.asarray(igg.gather(igg.update_halo(A)))
    finally:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
    assert np.array_equal(r_dus, r_pal)


# ---------------------------------------------------------------------------
# Coalesced multi-field exchange (one packed ppermute pair per mesh axis and
# dtype group, `ops/halo.py` module docstring) — must be BIT-IDENTICAL to the
# per-field path on every configuration: packing is ravel/concat, the wire
# carries the same values.
# ---------------------------------------------------------------------------

def _exchange_both_ways(fields, **kw):
    """(coalesced, per_field) update_halo results as numpy arrays."""
    a = igg.update_halo(*fields, coalesce=True, **kw)
    b = igg.update_halo(*fields, coalesce=False, **kw)
    if len(fields) == 1:
        a, b = (a,), (b,)
    return ([np.asarray(x) for x in a], [np.asarray(x) for x in b])


@pytest.mark.parametrize("n,dims,periods,kw,label", [
    (6, (2, 2, 2), (1, 1, 1), {}, "all-periodic"),
    (6, (2, 2, 2), (0, 0, 0), {}, "non-periodic PROC_NULL edges"),
    (6, (1, 2, 2), (1, 0, 1), {}, "x self-neighbor + y PROC_NULL + z multi"),
    (6, (4, 2, 1), (1, 0, 1), {"disp": 2}, "disp=2"),
    (9, (2, 2, 2), (1, 0, 1),
     {"overlaps": (4, 4, 4), "halowidths": (2, 2, 2)}, "halowidth 2"),
])
def test_coalesced_matches_per_field(n, dims, periods, kw, label):
    igg.init_global_grid(n, n, n, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=periods[0], periody=periods[1],
                         periodz=periods[2], quiet=True, **kw)
    rng = np.random.default_rng(7)
    stacked = tuple(int(d) * n for d in igg.global_grid().dims)

    def mk(dtype):
        return igg.device_put_g(
            rng.standard_normal(stacked).astype(dtype))

    fields = [mk(np.float64) for _ in range(3)]
    co, pf = _exchange_both_ways(fields)
    for c, p in zip(co, pf):
        assert np.array_equal(c, p), label


def test_coalesced_mixed_dtypes_and_fallback():
    """3 f32 + 2 f64 + 1 int32: two packed groups plus a per-field
    fallback for the lone-dtype field — all bit-identical to the
    fully per-field path."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2,
                         periodx=1, quiet=True)
    rng = np.random.default_rng(8)
    fields = [igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(dt))
              for dt in [np.float32] * 3 + [np.float64] * 2 + [np.int32]]
    co, pf = _exchange_both_ways(fields)
    for c, p in zip(co, pf):
        assert np.array_equal(c, p)


def test_coalesced_per_field_halowidths_and_stagger():
    """Fields disagreeing on halowidths and shape (staggered +1) still
    pack — the flat packer carries per-field slab sizes; results equal
    the per-field path exactly."""
    igg.init_global_grid(9, 9, 9, dimx=2, dimy=2, dimz=2,
                         overlaps=(4, 4, 4), periodx=1, periody=1, quiet=True)
    rng = np.random.default_rng(9)
    A = igg.device_put_g(rng.standard_normal((18, 18, 18)))
    B = igg.device_put_g(rng.standard_normal((18, 18, 18)))   # hw (1,1,1)
    Vx = igg.device_put_g(rng.standard_normal((20, 18, 18)))  # staggered +1
    fields = [A, igg.Field(B, (1, 1, 1)), Vx]
    co, pf = _exchange_both_ways(fields)
    for c, p in zip(co, pf):
        assert np.array_equal(c, p)
    # and against the oracle (coalesced path is reference-exact, not just
    # per-field-path-exact)
    exp = oracle_update(np.asarray(A), (9, 9, 9), (2, 2, 2),
                        igg.DEFAULT_DIMS_ORDER)
    assert np.array_equal(co[0], exp)


def test_coalesced_2d_and_participation_mix():
    """2-D grid with a field that participates only along one dim (no halo
    along the other): group membership is per-dim; fallback engages where
    packing is inapplicable."""
    igg.init_global_grid(6, 6, 1, dimx=4, dimy=2,
                         periodx=1, periody=1, quiet=True)
    rng = np.random.default_rng(10)
    A = igg.device_put_g(rng.standard_normal((24, 12)))
    B = igg.device_put_g(rng.standard_normal((24, 12)))
    co, pf = _exchange_both_ways([A, B])
    for c, p in zip(co, pf):
        assert np.array_equal(c, p)


def test_coalesced_pallas_multi_unpack_matches_dus():
    """The multi-field Pallas unpack kernel (interpret mode) delivers the
    same bits as the XLA dynamic-update-slice unpack on the coalesced
    path."""
    import implicitglobalgrid_tpu.ops.halo as halo_mod

    igg.init_global_grid(16, 16, 128, dimx=2, dimy=2, dimz=2,
                         periodx=1, periody=1, periodz=1, quiet=True)
    rng = np.random.default_rng(11)
    fs = [igg.device_put_g(
        rng.standard_normal((32, 32, 256)).astype(np.float32))
        for _ in range(3)]
    fs.append(igg.device_put_g(                      # staggered +1 along x
        rng.standard_normal((34, 32, 256)).astype(np.float32)))
    try:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
        dus = [np.asarray(igg.gather(x)) for x in igg.update_halo(*fs)]
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = True
        pal = [np.asarray(igg.gather(x)) for x in igg.update_halo(*fs)]
    finally:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
    for d, p in zip(dus, pal):
        assert np.array_equal(d, p)


# ---------------------------------------------------------------------------
# Wire-precision mode (`IGG_HALO_WIRE_DTYPE` / wire_dtype=) — opt-in only.
# ---------------------------------------------------------------------------

def test_wire_precision_defaults_off_and_is_bit_identical_when_off():
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    rng = np.random.default_rng(12)
    A = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    B = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    r_default = igg.update_halo(A, B)
    r_off = igg.update_halo(A, B, wire_dtype="off")
    for x, y in zip(r_default, r_off):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_wire_precision_bf16_rounds_interior_keeps_boundary_exact():
    """bf16 wire: interior-facing halos carry bf16-rounded values (within
    bf16 eps of the exact exchange); PROC_NULL boundary halos never cross
    the wire and stay exact; the coalesced and per-field wire paths round
    identically (bit-identical to each other)."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, quiet=True)
    rng = np.random.default_rng(13)
    A = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    B = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    exact = [np.asarray(x) for x in igg.update_halo(A, B)]
    co = [np.asarray(x) for x in
          igg.update_halo(A, B, wire_dtype="bfloat16", coalesce=True)]
    pf = [np.asarray(x) for x in
          igg.update_halo(A, B, wire_dtype="bfloat16", coalesce=False)]
    for c, p in zip(co, pf):
        assert np.array_equal(c, p)  # packing never changes rounding
    for c, e in zip(co, exact):
        assert np.allclose(c, e, rtol=2 ** -7, atol=2 ** -7)  # bf16 eps
        assert not np.array_equal(c, e)  # the rounding actually happened
        # physical-boundary halo cells (PROC_NULL, non-periodic grid) never
        # cross the wire: exact. Restrict to cells of the x=0 plane that are
        # not ALSO y/z halo cells of their shard (those receive later y/z
        # exchange slabs, which do go through the wire).
        assert np.array_equal(c[0, 1:5, 1:5], e[0, 1:5, 1:5])
        assert np.array_equal(c[-1, 7:11, 7:11], e[-1, 7:11, 7:11])


def test_wire_precision_ignores_non_float_fields():
    """int32 payloads never convert (conversion would corrupt them)."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    rng = np.random.default_rng(14)
    A = igg.device_put_g(rng.integers(-1000, 1000, (12, 12, 12)).astype(np.int32))
    B = igg.device_put_g(rng.integers(-1000, 1000, (12, 12, 12)).astype(np.int32))
    r_wire = igg.update_halo(A, B, wire_dtype="bfloat16")
    r_exact = igg.update_halo(A, B)
    for x, y in zip(r_wire, r_exact):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_wire_precision_env_var():
    import os

    import implicitglobalgrid_tpu.ops.halo as halo_mod

    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    rng = np.random.default_rng(15)
    A = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    B = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    explicit = [np.asarray(x)
                for x in igg.update_halo(A, B, wire_dtype="bfloat16")]
    os.environ["IGG_HALO_WIRE_DTYPE"] = "bfloat16"
    try:
        via_env = [np.asarray(x) for x in igg.update_halo(A, B)]
    finally:
        del os.environ["IGG_HALO_WIRE_DTYPE"]
    for x, y in zip(explicit, via_env):
        assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# Quantized wire (int8/int4 per-slab-scale payloads, per-axis policy)
# ---------------------------------------------------------------------------

@pytest.mark.quant
def test_quantized_wire_bounded_error_and_boundary_exact():
    """int8 wire: every received halo stays within scale/(2*127) of the
    exact exchange per slab (loose global bound below), the rounding
    actually happens, PROC_NULL boundary halos never cross the wire and
    stay exact, and the coalesced and per-field-buffer paths quantize
    identically (each slab carries its own scale in both layouts)."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, quiet=True)
    rng = np.random.default_rng(31)
    A = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    B = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    exact = [np.asarray(x) for x in igg.update_halo(A, B)]
    co = [np.asarray(x) for x in
          igg.update_halo(A, B, wire_dtype="int8", coalesce=True)]
    pf = [np.asarray(x) for x in
          igg.update_halo(A, B, wire_dtype="int8", coalesce=False)]
    for c, p in zip(co, pf):
        assert np.array_equal(c, p)  # packing never changes quantization
    for c, e in zip(co, exact):
        # |err| <= max_slab_scale/(2*127); slab maxima of N(0,1) draws sit
        # well under 5, and errors compound across the 3 sequential dims
        assert np.abs(c - e).max() < 3 * 5 / 254
        assert not np.array_equal(c, e)  # the quantization happened
        # physical-boundary halos (PROC_NULL, non-periodic): exact (same
        # cell selection as the bf16 test above)
        assert np.array_equal(c[0, 1:5, 1:5], e[0, 1:5, 1:5])
        assert np.array_equal(c[-1, 7:11, 7:11], e[-1, 7:11, 7:11])


@pytest.mark.quant
def test_quantized_wire_per_axis_policy_quantizes_only_named_axis():
    """`wire_dtype="z:int8"`: payloads on the x/y axes stay EXACT while
    z-axis halos quantize — every differing cell lies in a z-halo plane
    of some local block (the x/y exchanges are bit-identical to the
    full-precision run away from the z seams their send slabs patch)."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=1, dimz=2, periodx=1,
                         periodz=1, quiet=True)
    rng = np.random.default_rng(32)
    A = igg.device_put_g(rng.standard_normal((12, 6, 12)).astype(np.float32))
    exact = np.asarray(igg.update_halo(A))
    mixed = np.asarray(igg.update_halo(A, wire_dtype="z:int8"))
    diff = mixed != exact
    assert diff.any()  # z quantization happened
    # local z blocks are 6 wide: halo planes sit at stacked z indices
    # {0, 5, 6, 11} (hw=1 each side of each block)
    z_halo = np.zeros_like(diff)
    z_halo[:, :, [0, 5, 6, 11]] = True
    assert not (diff & ~z_halo).any()  # x/y wire untouched
    # fully-mixed policy: int4 on z, exact-cast f32 on x — still only
    # z-plane differences
    mixed4 = np.asarray(igg.update_halo(A, wire_dtype="z:int4,x:f32"))
    d4 = mixed4 != exact
    assert d4.any() and not (d4 & ~z_halo).any()


@pytest.mark.quant
def test_quantized_wire_ignores_non_float_and_defaults_off():
    """int32 payloads never quantize (corruption), and the quantized mode
    is opt-in: the default exchange stays bit-identical."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=2, periodx=1,
                         quiet=True)
    rng = np.random.default_rng(33)
    A = igg.device_put_g(
        rng.integers(-1000, 1000, (12, 12, 12)).astype(np.int32))
    F = igg.device_put_g(rng.standard_normal((12, 12, 12)).astype(np.float32))
    rq = igg.update_halo(A, F, wire_dtype="int8")
    re_ = igg.update_halo(A, F)
    assert np.array_equal(np.asarray(rq[0]), np.asarray(re_[0]))  # int exact
    assert not np.array_equal(np.asarray(rq[1]), np.asarray(re_[1]))
    r_env_off = igg.update_halo(A, F, wire_dtype="off")
    for x, y in zip(re_, r_env_off):
        assert np.array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.quant
def test_quantized_policy_on_unpartitioned_axis_is_noop():
    """A policy naming only axes a field has no ppermute on (dimz=1 here:
    z is self-copy/no-neighbor) is a NO-OP: results bit-identical to the
    exact exchange, and the field keeps the fast combined/self kernel
    tiers (it is not evicted to per-dim exchanges for nothing)."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=2, dimz=1, periodx=1,
                         periodz=1, quiet=True)
    rng = np.random.default_rng(35)
    A = igg.device_put_g(rng.standard_normal((12, 12, 6)).astype(np.float32))
    exact = np.asarray(igg.update_halo(A))
    noop = np.asarray(igg.update_halo(A, wire_dtype="z:int8"))
    assert np.array_equal(noop, exact)
    # plan agrees: no int8 anywhere, bytes identical to exact
    pe = igg.halo_comm_plan(A)
    pq = igg.halo_comm_plan(A, wire_dtype="z:int8")
    assert pq["wire_bytes"] == pe["wire_bytes"]
    assert all("int8" not in r["by_dtype"] for r in pq["axes"].values())


@pytest.mark.quant
def test_quantized_wire_pallas_unpack_matches_dus():
    """The dequantized slabs feed the SAME delivery tiers as exact ones:
    the multi-field Pallas unpack (interpret mode) delivers bit-identical
    results to the `dynamic_update_slice` path under int8 wire."""
    import implicitglobalgrid_tpu.ops.halo as halo_mod

    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, periodx=1,
                         periody=1, periodz=1, quiet=True)
    rng = np.random.default_rng(34)
    fs = [igg.device_put_g(
        rng.standard_normal((16, 16, 16)).astype(np.float32))
        for _ in range(2)]
    try:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
        dus = [np.asarray(igg.gather(x))
               for x in igg.update_halo(*fs, wire_dtype="int8")]
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = True
        pal = [np.asarray(igg.gather(x))
               for x in igg.update_halo(*fs, wire_dtype="int8")]
    finally:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
    for d, p in zip(dus, pal):
        assert np.array_equal(d, p)


@pytest.mark.quant
def test_quantized_wire_propagates_nonfinite():
    """A NaN in a send slab poisons the received halo slab to non-finite
    values (slab-granular propagation): quantization may coarsen a NaN
    but can never launder it into a plausible finite halo."""
    igg.init_global_grid(6, 6, 6, dimx=2, dimy=1, dimz=1, periodx=1,
                         quiet=True)
    a = np.ones((12, 6, 6), np.float32)
    a[4, 3, 3] = np.nan  # inside shard 0's right send slab (ol=2, hw=1)
    A = igg.device_put_g(a)
    out = np.asarray(igg.update_halo(A, wire_dtype="int8"))
    # the right-neighbor shard's left halo (stacked x index 6) received
    # the poisoned slab: wholly non-finite
    assert not np.isfinite(out[6, :, :]).any()
    # the exact path keeps the NaN point-local
    out_exact = np.asarray(igg.update_halo(A))
    assert np.isnan(out_exact[6, 3, 3]) and np.isfinite(out_exact[6, 0, 0])


def test_pallas_halo_multi_field_matches_dus():
    import implicitglobalgrid_tpu.ops.halo as halo_mod

    igg.init_global_grid(16, 16, 128, periodx=1, periody=1, periodz=1,
                         quiet=True)
    rng = np.random.default_rng(1)
    A = igg.device_put_g(rng.standard_normal((16, 16, 128)).astype(np.float32))
    B = igg.device_put_g(rng.standard_normal((16, 16, 128)).astype(np.float32))
    try:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
        ra, rb = igg.update_halo(A, B)
        ra, rb = np.asarray(igg.gather(ra)), np.asarray(igg.gather(rb))
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = True
        pa, pb = igg.update_halo(A, B)
        pa, pb = np.asarray(igg.gather(pa)), np.asarray(igg.gather(pb))
    finally:
        halo_mod._FORCE_PALLAS_WRITE_INTERPRET = False
    assert np.array_equal(ra, pa)
    assert np.array_equal(rb, pb)
