"""Tests of `select_device` — analog of the reference's
`test/test_select_device.jl`.

The reference binds each MPI rank to a node-local GPU and returns its id;
with PJRT every addressable device is already bound, so `select_device` is an
API-parity shim returning the bound device id (`parallel/grid.py`). The
reference's functional/non-functional backend matrix maps to `device_type`
resolution against the platforms JAX actually exposes in this process
(CPU-only under the test harness).
"""

import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.utils.exceptions import (
    InvalidArgumentError, NotInitializedError, NotLoadedError,
)


def test_select_device_returns_bound_id():
    import jax

    igg.init_global_grid(3, 4, 5, quiet=True)
    dev_id = igg.select_device()
    assert isinstance(dev_id, int)
    assert dev_id in [d.id for d in jax.local_devices()]


def test_select_device_auto_device_type():
    igg.init_global_grid(3, 4, 5, quiet=True, device_type="auto")
    assert igg.select_device() >= 0


def test_select_device_explicit_cpu():
    igg.init_global_grid(3, 4, 5, quiet=True, device_type="cpu")
    assert igg.select_device() >= 0


def test_unavailable_backend_throws():
    # Reference: device_type="CUDA" without functional CUDA → error at
    # select_device time (test_select_device.jl "CUDA"/"AMDGPU" absent
    # branches). Here the backend check happens at init, which is stricter.
    with pytest.raises((NotLoadedError, InvalidArgumentError, RuntimeError)):
        igg.init_global_grid(3, 4, 5, quiet=True, device_type="tpu")
        igg.select_device()


def test_invalid_device_type_throws():
    with pytest.raises(InvalidArgumentError):
        igg.init_global_grid(3, 4, 5, quiet=True, device_type="Metal")


def test_select_device_before_init_throws():
    assert not igg.grid_is_initialized()
    with pytest.raises(NotInitializedError):
        igg.select_device()


def test_device_type_none_runs_on_cpu():
    # Reference "none" keeps the grid CPU-only and select_device errors;
    # here "none" resolves to host CPU devices and binding is a no-op shim,
    # so select_device still reports the bound device (documented divergence:
    # PJRT has no unbound state).
    me, dims, nprocs, coords, mesh = igg.init_global_grid(
        3, 4, 5, quiet=True, device_type="none"
    )
    assert igg.global_grid().device_type in ("none", "cpu")
    assert igg.select_device() >= 0


def test_node_local_rank_single_process():
    """Single-process node grouping is trivial (the Comm_split_type analog,
    reference `select_device.jl:26-32`): rank 0 of 1, all local devices."""
    import jax

    from implicitglobalgrid_tpu.parallel.grid import node_local_rank

    me_l, nprocs_node, dev_node = node_local_rank()
    assert me_l == 0 and nprocs_node == 1
    assert dev_node == len(jax.local_devices())
