"""Closed-loop auto-tuner tests (ISSUE 13): the search picks the knob
the machine profile says pays, the winning config round-trips through
JSON and the per-job application surface (`RunSpec(tuned=...)` →
`MeshScheduler` admission), and the measured-validation path never
returns a config slower than the default (the baseline is always in the
measured set)."""

import dataclasses
import json
import os

import numpy as np
import pytest

import implicitglobalgrid_tpu as igg
from implicitglobalgrid_tpu.telemetry.tune import (
    TunedConfig, resolve_tuned, tuned_config_path,
)
from implicitglobalgrid_tpu.utils.exceptions import InvalidArgumentError

pytestmark = pytest.mark.tune

_GRID = dict(nx=16, ny=16, nz=16, dimx=2, dimy=2, dimz=2,
             periodx=1, periody=1, periodz=1)


def _hier_profile(z_lat=5e-4):
    """ICI-fast x/y, DCN-slow z — the hierarchical mesh the per-axis
    cadence exists for."""
    return igg.MachineProfile(
        membw_GBps=800.0, flops_G=45000.0,
        axes={"gx": {"GBps": 45.0, "latency_s": 5e-6},
              "gy": {"GBps": 45.0, "latency_s": 5e-6},
              "gz": {"GBps": 2.0, "latency_s": z_lat}})


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

def test_search_picks_slow_axis_cadence():
    """On the ICI+DCN profile the model-only search must rank the z-only
    cadence ABOVE both the exchange-every-step default and the uniform
    deep cadence (which pays slab-width compute on the fast axes too) —
    the COMM_AVOID.json losing row turned into a win."""
    cfg = igg.tune_config("stokes3d", dict(_GRID), _hier_profile(),
                          measure=False,
                          comm_every_options=("1", "2", "z:2"))
    assert cfg.model == "stokes3d"
    assert cfg.comm_every == "z:2"
    ranked = [r["comm_every"] for r in cfg.meta["ranking"]]
    assert ranked.index("z:2") < ranked.index("2")
    assert ranked.index("z:2") < ranked.index("1")
    assert cfg.predicted_step_s and cfg.predicted_step_s > 0
    assert cfg.meta["priced"] >= 3


def test_search_keeps_default_on_flat_fast_mesh():
    """With negligible latency everywhere, deep halos only cost slab
    compute — the tuner must return the default cadence, not a
    regression."""
    prof = igg.MachineProfile(
        membw_GBps=800.0, flops_G=45000.0,
        axes={a: {"GBps": 100.0, "latency_s": 1e-9}
              for a in ("gx", "gy", "gz")})
    cfg = igg.tune_config("diffusion3d", dict(_GRID), prof,
                          measure=False,
                          comm_every_options=("1", "2", "z:2"))
    assert cfg.comm_every == "1"


def test_search_sweeps_ensemble_and_wire():
    """E rides the search like any other knob (scored PER MEMBER — the
    amortization makes E>1 win on a latency-priced profile), and the
    per-axis wire policy is searchable alongside the cadence."""
    cfg = igg.tune_config(
        "diffusion3d", dict(_GRID), _hier_profile(),
        measure=False, comm_every_options=("1",),
        wire_dtype_options=(None, "z:int8,x:f32"),
        ensemble_options=(None, 8))
    assert cfg.ensemble == 8
    assert cfg.wire_dtype == "z:int8,x:f32"


def test_infeasible_candidates_skipped_loudly():
    """A cadence the geometry cannot carry is a recorded skip, not a
    crash; an all-infeasible space raises."""
    small = dict(_GRID, nx=4, ny=4, nz=4)
    cfg = igg.tune_config("stokes3d", small, _hier_profile(),
                          measure=False,
                          comm_every_options=("1", "z:8"))
    assert cfg.comm_every == "1"
    assert any(s["comm_every"] == "z:8" for s in cfg.meta["skipped"])
    with pytest.raises(InvalidArgumentError, match="infeasible"):
        igg.tune_config("stokes3d", dict(small, nx=2, ny=2, nz=2),
                        _hier_profile(), measure=False,
                        comm_every_options=("z:8",))


def test_tune_preserves_callers_grid():
    """`tune_config` owns its candidate grids but must hand back the
    caller's live grid untouched (epoch retained across the swaps)."""
    igg.init_global_grid(8, 8, 8, dimx=2, dimy=2, dimz=2, quiet=True)
    try:
        epoch = igg.global_grid().epoch
        igg.tune_config("diffusion3d", dict(_GRID), _hier_profile(),
                        measure=False, comm_every_options=("1",))
        assert igg.grid_is_initialized()
        assert igg.global_grid().epoch == epoch
    finally:
        igg.finalize_global_grid()


# ---------------------------------------------------------------------------
# persistence + application
# ---------------------------------------------------------------------------

def test_tuned_config_json_roundtrip(tmp_path):
    cfg = TunedConfig(model="diffusion3d", comm_every="z:2",
                      wire_dtype="z:int8", coalesce=True, overlap=False,
                      ensemble=4, predicted_step_s=1e-3, speedup=1.2)
    path = tuned_config_path(tmp_path / "profile.json", "diffusion3d")
    assert path.endswith("tuned_diffusion3d.json")
    igg.save_tuned_config(cfg, path)
    back = igg.load_tuned_config(path)
    assert back.knobs() == cfg.knobs()
    assert back.env() == {"IGG_COMM_EVERY": "z:2",
                          "IGG_HALO_WIRE_DTYPE": "z:int8",
                          "IGG_HALO_COALESCE": "1"}
    # every accepted RunSpec.tuned form resolves
    assert resolve_tuned(None) is None
    assert resolve_tuned(cfg) is cfg
    assert resolve_tuned(cfg.to_json()).knobs() == cfg.knobs()
    assert resolve_tuned(path).knobs() == cfg.knobs()
    with pytest.raises(InvalidArgumentError):
        resolve_tuned(42)
    with pytest.raises(InvalidArgumentError):
        igg.load_tuned_config(tmp_path / "missing.json")


def test_tune_runspec_scheduler_roundtrip(tmp_path):
    """ISSUE 13 acceptance: tune_config → persisted TunedConfig →
    `RunSpec(tuned=path)` → `MeshScheduler` load-and-apply on admission.
    The tuned job runs the deep super-step on the tuned geometry, the
    scheduler journals ``job_tuned``, the driver records the ``tuned``
    flight event, and the result is bit-identical to the solo deep
    run."""
    from implicitglobalgrid_tpu.models import init_diffusion3d, \
        run_diffusion
    from implicitglobalgrid_tpu.service import JobSpec, MeshScheduler, \
        builtin_setup

    path = os.path.join(tmp_path, "tuned_diffusion3d.json")
    cfg = igg.tune_config("diffusion3d",
                          dict(_GRID, nx=12, ny=12, nz=12),
                          _hier_profile(), measure=False,
                          comm_every_options=("1", "z:2"), path=path)
    assert cfg.comm_every == "z:2" and os.path.exists(path)
    grid_kw = dict(cfg.grid["winner"])

    # the reference trajectory: solo deep run of the same knobs
    igg.init_global_grid(**grid_kw)
    try:
        T, Cp, p = init_diffusion3d(dtype=np.float32, comm_every="z:2")
        ref = np.asarray(run_diffusion(T, Cp, p, 4, nt_chunk=2))
    finally:
        igg.finalize_global_grid()

    flight = os.path.join(tmp_path, "flight")
    with MeshScheduler(flight_dir=flight) as sched:
        sched.submit(JobSpec(
            name="tuned", setup=builtin_setup("diffusion3d", tuned=path),
            nt=2,  # super-steps: 2 cycles x cycle 2 = 4 physical steps
            grid=grid_kw,
            run=igg.RunSpec(nt_chunk=1, key=("tuned-rt",), tuned=path)))
        sched.run()
        job = sched.job("tuned")
        assert job.state == "done", job.error
        assert np.array_equal(np.asarray(job.result["T"]), ref)
    journal = [json.loads(line) for line in
               open(os.path.join(flight, "scheduler.jsonl"))]
    tuned_ev = [e for e in journal if e.get("kind") == "job_tuned"]
    assert tuned_ev and tuned_ev[0]["comm_every"] == "z:2"
    flight_ev = [json.loads(line) for line in
                 open(os.path.join(flight, "job_tuned.jsonl"))]
    assert any(e.get("kind") == "tuned" for e in flight_ev)


def test_builtin_setup_rejects_model_mismatch(tmp_path):
    from implicitglobalgrid_tpu.service import builtin_setup

    cfg = TunedConfig(model="stokes3d", comm_every="z:2")
    with pytest.raises(InvalidArgumentError, match="refusing"):
        builtin_setup("diffusion3d", tuned=cfg)


def test_tuned_ensemble_fills_runspec(tmp_path):
    """A tuned ensemble becomes the job's batch size when the RunSpec
    left it unset — the scheduler's `ResilientRun` then vmaps the chunk
    and the per-member guard surface engages."""
    from implicitglobalgrid_tpu.service import JobSpec, MeshScheduler, \
        builtin_setup

    cfg = TunedConfig(model="diffusion3d", comm_every="1", ensemble=2)
    with MeshScheduler() as sched:
        sched.submit(JobSpec(
            name="batched",
            setup=builtin_setup("diffusion3d", tuned=cfg),
            nt=2, grid=dict(nx=8, ny=8, nz=8, dimx=2, dimy=2, dimz=2),
            run=igg.RunSpec(nt_chunk=2, key=("tuned-ens",), tuned=cfg)))
        sched.run()
        job = sched.job("batched")
        assert job.state == "done", job.error
        assert job.run.ensemble == 2
        assert int(job.result["T"].shape[0]) == 2


@pytest.mark.slow
def test_measured_tune_never_regresses(tmp_path):
    """The measured path: baseline (all defaults) is always in the
    measured set, so the returned speedup is >= 1.0 by construction and
    the winner's measured step time is the set's minimum."""
    cfg = igg.tune_config(
        "diffusion3d", dict(_GRID, nx=12, ny=12, nz=12), None,
        measure=True, top_k=2, comm_every_options=("1", "2", "z:2"),
        path=os.path.join(tmp_path, "tuned.json"))
    assert cfg.measured_step_s is not None
    assert cfg.baseline_step_s is not None
    assert cfg.speedup >= 1.0
    assert cfg.meta["measured"] >= 2


@pytest.mark.slow
def test_tune_cli_smoke(tmp_path):
    """`tools tune` produce + show round-trip in a subprocess (the
    operator surface)."""
    import subprocess
    import sys

    out = os.path.join(tmp_path, "tuned_diffusion3d.json")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "-m", "implicitglobalgrid_tpu.tools", "tune",
         "diffusion3d", "--cpu", "--nx", "12", "--no-measure",
         "--comm-every-options", "1;z:2", "--out", out],
        capture_output=True, text=True, env=env, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout)
    assert rec["model"] == "diffusion3d"
    assert os.path.exists(out)
    r2 = subprocess.run(
        [sys.executable, "-m", "implicitglobalgrid_tpu.tools", "tune",
         "show", out],
        capture_output=True, text=True, env=env, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r2.returncode == 0 and json.loads(r2.stdout)["model"] \
        == "diffusion3d"
