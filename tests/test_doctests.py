"""Execute the API docstring examples — parity with the reference's
doctested API docs (`/root/reference/src/tools.jl:67-96`; its CI doctest
job, `docs/make.jl`). Each example is self-contained (inits and finalizes
its own grid) so the suite's grid hygiene holds."""

import doctest

import pytest

import implicitglobalgrid_tpu.ops.halo as halo
import implicitglobalgrid_tpu.tools as tools
import implicitglobalgrid_tpu.utils.checkpoint as checkpoint


@pytest.mark.parametrize("module,min_examples", [
    (tools, 4), (halo, 2), (checkpoint, 6),
])
def test_docstring_examples(module, min_examples):
    res = doctest.testmod(module, verbose=False)
    assert res.failed == 0, f"{module.__name__}: {res.failed} doctest failures"
    assert res.attempted >= min_examples, (
        f"{module.__name__}: expected >= {min_examples} doctest examples, "
        f"found {res.attempted}")
